"""The front door: one SUBMIT surface over the primary + N replicas.

Placement policy (read spreading):

1. refresh each replica's health (a background poll, or lazily when the
   poll is off) — ``/healthz``-shaped probes yield ``healthy`` plus the
   advertised ``replication_lag``;
2. order healthy replicas by advertised lag FIRST (freshness is the
   caller-visible contract), then break lag ties by a load score read
   from the same ``/healthz`` body — admission ``queue_depth`` plus a
   weighted penalty for a non-closed serve breaker (``breaker_worst``)
   — and round-robin within the equally-lagged-and-loaded group, so
   equally-fresh replicas share load instead of the first one eating it
   all while a deep-queued or degraded replica sheds placement to an
   idle sibling (ROADMAP 3c);
3. skip replicas whose per-replica circuit breaker gate is OPEN — a
   dead replica costs ``breaker_threshold`` failed probes ONCE, then
   its load re-routes without paying a timeout per request until the
   cooldown releases a half-open probe. A health poll that sees the
   replica answering again RESETS the gate (immediate re-admission on
   rejoin);
4. the primary is the exact-answer fallback: any request no replica
   could serve (all dead, all gated past their lag bound, typed
   refusals) lands there — degraded placement, zero caller-visible
   errors for in-budget requests.

Typed refusals that re-route: transport errors, 5xx, timeouts,
:class:`~hypergraphdb_tpu.serve.AdmissionGated` (the replica's lag
gate), :class:`~hypergraphdb_tpu.serve.QueueFull`. Permanent request
errors (:class:`~hypergraphdb_tpu.serve.Unservable`, malformed
payloads) and an expired deadline propagate immediately — no backend
could do better, and burning the breaker on them would punish a healthy
replica for a caller bug.

Backends are duck-typed (``id`` / ``submit(payload, timeout)`` /
``health()``): :class:`LocalBackend` wraps an in-process runtime (tests,
single-host tiers), :class:`HTTPBackend` speaks to a
:class:`~hypergraphdb_tpu.replica.httpd.SubmitServer` over real sockets.

**Standing queries** route through the same door: the FrontDoor owns a
door-side subscription id (``dsub-<n>``) per standing query, places the
subscribe like a submit, and MIRRORS the subscription (original
payload, current match set, anchoring seq, digest) so a backend loss is
survivable — when a poll finds the owning backend gone, the door
re-subscribes the original payload on another healthy backend and
synthesizes the ONE delta notification between the mirror's anchor seq
and the new snapshot (no loss, no duplicates; seqs are comparable
across backends because every node's subscription manager anchors at
the replication log position). Consumers never see backend identity:
subscription ids are rewritten both ways.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from hypergraphdb_tpu.fault import (
    CLOSED,
    OPEN,
    CircuitBreaker,
    PermanentFault,
    TransientFault,
)
from hypergraphdb_tpu.serve.types import (
    AdmissionGated,
    DeadlineExceeded,
    Unservable,
)
from hypergraphdb_tpu.utils.metrics import Metrics

#: request errors no re-route can fix: propagate, never penalize the
#: backend's breaker for them
_PERMANENT = (Unservable, PermanentFault, KeyError, ValueError, TypeError)


def _request_ids(payload: dict) -> list:
    """The RAW atom handles a submit payload names (seed / anchors) —
    what shard-ownership placement compares against a backend's
    advertised gid coverage. Gid-addressed forms resolve per-backend and
    carry no global ordering, so they contribute nothing here."""
    ids = []
    if isinstance(payload.get("seed"), int):
        ids.append(int(payload["seed"]))
    anchors = payload.get("anchors")
    if isinstance(anchors, (list, tuple)):
        ids.extend(int(a) for a in anchors if isinstance(a, int))
    return ids


def submit_payload(runtime, payload: dict, timeout: float,
                   authoritative: bool = False,
                   node_id: Optional[str] = None) -> dict:
    """One wire-shaped request → the runtime → a wire-shaped response.
    The single serve-payload schema, shared by the local backend and the
    HTTP handler so both paths answer byte-identically::

        {"kind": "bfs", "seed": 7, "max_hops": 2, "deadline_s": 0.5}
        {"kind": "pattern", "anchors": [3, 9], "type_handle": 4}

    Response: ``{"kind", "count", "matches", "truncated", "epoch",
    "served_by"}``. ``authoritative`` marks the PRIMARY's source-of-truth
    view: a gid it doesn't know exists nowhere, which is the caller's
    error — on a replica the same miss is a replication race.

    ``{"explain": true}`` requests per-request cost attribution: the
    response carries the runtime's EXPLAIN record (serving lane,
    occupancy, device seconds, retries, breaker state, trace id —
    assembled from the request's own span tree) under ``"explain"``,
    stamped with ``node_id`` when the endpoint knows who it is. Needs
    tracing enabled on the answering node (400 otherwise, the
    :class:`~hypergraphdb_tpu.serve.Unservable` mapping)."""
    kind = payload.get("kind")
    deadline = payload.get("deadline_s")
    explain = bool(payload.get("explain"))

    def _resolve(gid: str) -> int:
        # gid-addressed requests are location-transparent: the SAME
        # payload serves on any backend, whatever local handles its
        # history assigned (raw-handle payloads remain for single-node
        # callers that never leave one handle space)
        from hypergraphdb_tpu.peer import transfer

        g = getattr(runtime, "graph", None)
        h = None if g is None else transfer.lookup_local(g, str(gid))
        if h is None:
            if authoritative:
                # the source of truth doesn't know it: the gid is wrong
                # (deleted or typo'd) — a permanent caller error, NOT a
                # retryable refusal, or a 503-retrying client would poll
                # an unanswerable request forever
                raise Unservable(f"unknown gid {gid!r}")
            # "not HERE (yet)" — a replica may simply trail the atom's
            # creation; AdmissionGated makes the router re-route (the
            # primary has it) without a breaker penalty, instead of
            # surfacing a caller error for a replication race
            raise AdmissionGated(f"unknown gid {gid!r} on this node")
        return int(h)

    if kind == "bfs":
        seed = (_resolve(payload["seed_gid"]) if "seed_gid" in payload
                else int(payload["seed"]))
        fut = runtime.submit_bfs(
            seed,
            max_hops=(None if payload.get("max_hops") is None
                      else int(payload["max_hops"])),
            deadline_s=deadline,
            include_seed=bool(payload.get("include_seed", True)),
            explain=explain,
        )
    elif kind == "pattern":
        anchors = ([_resolve(a) for a in payload["anchor_gids"]]
                   if "anchor_gids" in payload
                   else [int(a) for a in payload["anchors"]])
        fut = runtime.submit_pattern(
            anchors,
            type_handle=(None if payload.get("type_handle") is None
                         else int(payload["type_handle"])),
            deadline_s=deadline,
            explain=explain,
        )
    else:
        raise Unservable(f"unknown request kind {kind!r}")
    res = fut.result(timeout=timeout)
    out = {
        "kind": res.kind,
        "count": int(res.count),
        "matches": [int(m) for m in res.matches],
        "truncated": bool(res.truncated),
        "epoch": int(res.epoch),
        "served_by": res.served_by,
    }
    if explain:
        rec = getattr(fut, "explain", None)
        if rec is not None:
            if node_id is not None:
                rec = dict(rec, node=str(node_id))
            out["explain"] = rec
    if payload.get("gids"):
        # matches are LOCAL handles of the answering node; a caller
        # comparing answers across backends (or following up against a
        # different node) asks for the global-id view — replicated atoms
        # carry one gid everywhere, unreplicated ones map to None
        from hypergraphdb_tpu.peer import transfer

        g = getattr(runtime, "graph", None)
        out["match_gids"] = (
            None if g is None
            else [transfer.existing_gid(g, int(m)) for m in res.matches]
        )
    return out


class LocalBackend:
    """In-process backend over one serve runtime + optional health probe
    (a :class:`~hypergraphdb_tpu.replica.node.ReplicaNode` passes its
    :meth:`health_probe`; a primary passes ``runtime_health``)."""

    def __init__(self, backend_id: str, runtime, health=None,
                 role: str = "replica"):
        self.id = backend_id
        self.runtime = runtime
        self.role = role
        self._health = health

    def submit(self, payload: dict, timeout: float) -> dict:
        return submit_payload(self.runtime, payload, timeout,
                              authoritative=self.role == "primary",
                              node_id=self.id)

    def _sub_manager(self):
        m = getattr(self.runtime, "subscriptions", None)
        if m is None:
            raise Unservable(f"{self.id} has no subscription tier")
        return m

    def subscribe(self, payload: dict, timeout: float) -> dict:
        from hypergraphdb_tpu.sub.wire import subscribe_payload

        return subscribe_payload(self._sub_manager(), payload)

    def poll(self, params: dict, timeout: float) -> dict:
        from hypergraphdb_tpu.sub.wire import poll_payload

        return poll_payload(self._sub_manager(), params)

    def health(self):
        if self._health is None:
            return True, {"role": self.role}
        return self._health()


class HTTPBackend:
    """A backend behind a :class:`~.httpd.SubmitServer` URL. Non-2xx
    submit responses raise typed: 4xx → :class:`PermanentFault` (the
    request is the problem), everything else → :class:`TransientFault`
    (the backend is — re-route)."""

    def __init__(self, backend_id: str, url: str, role: str = "replica",
                 health_timeout_s: float = 5.0):
        self.id = backend_id
        self.url = url.rstrip("/")
        self.role = role
        self.health_timeout_s = health_timeout_s

    def submit(self, payload: dict, timeout: float) -> dict:
        return self._roundtrip(urllib.request.Request(
            self.url + "/submit",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        ), timeout)

    def subscribe(self, payload: dict, timeout: float) -> dict:
        return self._roundtrip(urllib.request.Request(
            self.url + "/subscribe",
            data=json.dumps(payload).encode("utf-8"),
            headers={"Content-Type": "application/json"},
            method="POST",
        ), timeout)

    def poll(self, params: dict, timeout: float) -> dict:
        qs = urllib.parse.urlencode({
            k: params[k] for k in ("id", "timeout_s", "max")
            if params.get(k) is not None
        })
        return self._roundtrip(urllib.request.Request(
            self.url + "/notifications?" + qs, method="GET",
        ), timeout)

    def _roundtrip(self, req, timeout: float) -> dict:
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            body = e.read().decode("utf-8", "replace")[:300]
            try:
                kind = json.loads(body).get("error")
            except Exception:  # noqa: BLE001 - non-JSON error body
                kind = None
            if kind == "AdmissionGated":
                # the replica's lag gate, not a failure: the router
                # re-routes WITHOUT a breaker penalty
                raise AdmissionGated(body) from e
            if kind == "DeadlineExceeded":
                # the CALLER's budget expired, not the backend: must
                # propagate un-struck (a 504 read as TransientFault
                # would burn the breaker of a healthy replica and
                # retry a dead-on-arrival request across the tier)
                raise DeadlineExceeded(body) from e
            if 400 <= e.code < 500:
                raise PermanentFault(
                    f"{self.id} rejected the request ({e.code}): {body}"
                ) from e
            raise TransientFault(
                f"{self.id} failed ({e.code}): {body}"
            ) from e
        except OSError as e:  # refused/reset/timeout — the wire's fault
            raise TransientFault(f"{self.id} unreachable: {e}") from e

    def health(self):
        try:
            with urllib.request.urlopen(self.url + "/healthz",
                                        timeout=self.health_timeout_s) as r:
                return True, json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read().decode("utf-8"))
            except Exception:  # noqa: BLE001 - non-JSON error body
                payload = {}
            return False, payload
        # plain OSError (dead socket) propagates: the caller counts it
        # as unreachable


@dataclass
class RouterConfig:
    """Front-door knobs."""

    breaker_threshold: int = 2      # consecutive failures → OPEN
    breaker_cooldown_s: float = 0.5
    #: health snapshots older than this refresh before placement
    health_refresh_s: float = 0.25
    #: background poll cadence (0 = poll only lazily at placement)
    poll_interval_s: float = 0.25
    #: distinct replicas tried before falling back to the primary
    max_attempts: int = 2
    #: load-score penalty per ``breaker_worst`` code unit (0 closed /
    #: 1 half-open / 2 open): a replica whose own serve breaker is
    #: degraded loses lag-tied placement to ``weight×code`` queued
    #: requests' worth of load
    load_breaker_weight: float = 16.0
    submit_timeout_s: float = 30.0
    clock: Optional[Callable[[], float]] = None


class FrontDoor:
    """The router. Thread-safe: requests may arrive from many HTTP
    handler threads; placement state is one small locked dict and the
    breaker locks itself."""

    def __init__(self, primary, replicas: Sequence, config:
                 Optional[RouterConfig] = None):
        self.config = config or RouterConfig()
        self.primary = primary
        self.replicas = list(replicas)
        self.clock = self.config.clock or time.monotonic
        self.breaker = CircuitBreaker(
            threshold=self.config.breaker_threshold,
            cooldown_s=self.config.breaker_cooldown_s,
            clock=self.clock,
        )
        self.metrics = Metrics()
        self._lock = threading.Lock()
        #: backend id → (healthy, lag, load score, advertised gid
        #: capacity or None, snapshot time)
        self._health: dict[str, tuple] = {}
        self._rr = 0
        self._poll_stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        #: one refresh at a time: a lazy-mode submit that finds a probe
        #: already in flight places with the snapshot it has instead of
        #: queueing another N-probe sweep behind it
        self._refresh_gate = threading.Lock()
        #: door sid → subscription mirror: the resume state that makes a
        #: backend loss survivable (original payload to re-subscribe,
        #: the match set + replication-anchored seq to diff against)
        self._subs: dict[str, dict] = {}
        self._sub_seq = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FrontDoor":
        self.refresh_health()
        t = None
        if self.config.poll_interval_s > 0:
            with self._lock:      # check-and-set: two start()s, one poll
                if self._poll_thread is None:
                    self._poll_stop.clear()
                    self._poll_thread = t = threading.Thread(
                        target=self._poll_loop, name="frontdoor-health",
                        daemon=True,
                    )
        if t is not None:
            t.start()
        return self

    def stop(self) -> None:
        self._poll_stop.set()
        with self._lock:
            t, self._poll_thread = self._poll_thread, None
        if t is not None:
            t.join(timeout=5)

    def __enter__(self) -> "FrontDoor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- health / placement ---------------------------------------------------
    def refresh_health(self) -> None:
        """Probe every replica's health once. A backend whose health
        TRANSITIONS unhealthy → healthy (the rejoin edge) is re-admitted
        immediately (breaker reset) — rejoin should not wait out a
        cooldown ladder the outage already paid for. Deliberately
        edge-triggered: a backend whose ``/healthz`` lies green while
        its submits fail must NOT be level-reset every poll, or the
        breaker could never bound its probes.

        Probes run CONCURRENTLY (one short-lived thread per replica) and
        at most one sweep at a time: the wait is bounded by the slowest
        single probe, not their sum, and a blackholed replica (SYN
        dropped — urlopen eats its whole timeout) cannot stack N×timeout
        onto a lazy-mode submit path nor fan one sweep per handler
        thread."""
        if not self._refresh_gate.acquire(blocking=False):
            return  # a sweep is in flight; place with the snapshot we have
        try:
            now = self.clock()
            results: dict[str, tuple] = {}
            w = self.config.load_breaker_weight

            def probe(be):
                try:
                    healthy, payload = be.health()
                    lag = int(payload.get("replication_lag", 0))
                    # the load-aware tiebreak inputs (ROADMAP 3c), from
                    # the SAME body operators scrape: queued admissions
                    # + a penalty while the serve breaker is not closed
                    load = (float(payload.get("queue_depth", 0))
                            + w * float(payload.get("breaker_worst", 0)))
                    # shard ownership: a multi-chip pod advertises its
                    # partition map; the covered id space bounds which
                    # raw-handle requests its device path can own. No
                    # advertisement = a full replica (covers everything).
                    cover = None
                    mesh = payload.get("mesh")
                    if isinstance(mesh, dict):
                        pm = mesh.get("partition_map") or {}
                        if pm.get("capacity") is not None:
                            cover = int(pm["capacity"])
                except Exception:  # noqa: BLE001 - unreachable == unhealthy
                    healthy, lag, load, cover = False, 0, 0.0, None
                results[be.id] = (healthy, lag, load, cover)

            if len(self.replicas) <= 1:
                for be in self.replicas:
                    probe(be)
            else:
                threads = [
                    threading.Thread(target=probe, args=(be,),
                                     name=f"frontdoor-probe-{be.id}",
                                     daemon=True)
                    for be in self.replicas
                ]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            for be in self.replicas:
                healthy, lag, load, cover = results.get(
                    be.id, (False, 0, 0.0, None))
                with self._lock:
                    prev = self._health.get(be.id)
                    self._health[be.id] = (healthy, lag, load, cover, now)
                if (healthy and prev is not None and not prev[0]
                        and self.breaker.state_of(be.id) != CLOSED):
                    self.breaker.reset(be.id)
                    self.metrics.incr("router.readmissions")
        finally:
            self._refresh_gate.release()

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.config.poll_interval_s):
            try:
                self.refresh_health()
            except Exception:  # noqa: BLE001 - the poll must survive
                import logging

                logging.getLogger("hypergraphdb_tpu.replica").warning(
                    "front-door health poll failed", exc_info=True
                )

    def _placement(self, payload: Optional[dict] = None) -> list:
        """Healthy replicas ordered by SHARD OWNERSHIP first (a backend
        whose advertised partition map covers the request's raw ids
        beats one that would have to host-correct or re-route), then
        least-lagged, then a load-score tiebreak within a lag tie (queue
        depth + breaker penalty from ``/healthz``), round-robin within
        the equal head group (the spread), breaker-OPEN gates
        skipped."""
        now = self.clock()
        with self._lock:
            stale = any(
                self._health.get(be.id, (False, 0, 0.0, None, -1e9))[4]
                < now - self.config.health_refresh_s
                for be in self.replicas
            )
        if stale and self.config.poll_interval_s <= 0:
            self.refresh_health()
        with self._lock:
            known = {
                be.id: self._health.get(be.id, (False, 0, 0.0, None, 0.0))
                for be in self.replicas
            }
            self._rr += 1
            rr = self._rr
        healthy = [be for be in self.replicas if known[be.id][0]]
        if not healthy:
            return []
        req_ids = _request_ids(payload) if payload else []
        req_hi = max(req_ids) if req_ids else None

        def score(be):
            # load is QUANTIZED for grouping: exact float equality would
            # let one queued request's jitter collapse the round-robin
            # spread onto a single replica per poll window (herding) —
            # a few requests of depth difference is noise, not signal
            cover = known[be.id][3]
            owns = (req_hi is None or cover is None or req_hi < cover)
            return (0 if owns else 1,
                    known[be.id][1], int(known[be.id][2]) // 8)

        healthy.sort(key=score)
        best = score(healthy[0])
        grp = [be for be in healthy if score(be) == best]
        rest = [be for be in healthy if score(be) != best]
        k = rr % len(grp)
        ordered = grp[k:] + grp[:k] + rest
        # peek, don't allow: placement ranks candidates the request may
        # never reach — consuming a half-open probe token here would
        # starve the backend's actual recovery probe (the submit loop
        # calls allow() right before dispatching)
        return [be for be in ordered if self.breaker.peek(be.id)]

    # -- submit ---------------------------------------------------------------
    def submit(self, payload: dict,
               timeout: Optional[float] = None) -> dict:
        """Route one request: replicas by placement order (bounded
        attempts), then the primary. The response's ``routed_to`` names
        the backend that answered."""
        timeout = timeout if timeout is not None \
            else self.config.submit_timeout_s
        self.metrics.incr("router.submitted")
        attempts = 0
        for be in self._placement(payload):
            if attempts >= self.config.max_attempts:
                break
            if not self.breaker.allow(be.id):
                # lost the race for a half-open probe token between
                # placement's peek and here — skip without burning an
                # attempt on a backend we never tried
                continue
            attempts += 1
            try:
                res = be.submit(payload, timeout)
            except (DeadlineExceeded, *_PERMANENT):
                # no other backend can answer this better — and the
                # breaker must not punish a replica for a caller bug
                self.metrics.incr("router.errors")
                raise
            except AdmissionGated:
                # the replica's lag gate refused: a typed, HEALTHY
                # refusal — re-route without a breaker penalty
                self.metrics.incr("router.lag_rerouted")
                continue
            except Exception:  # noqa: BLE001 - transport/timeout/5xx
                # the breaker (not the health cache) owns failure
                # memory: K consecutive failures OPEN the gate and bound
                # the probes; health stays the poll's own observation so
                # the rejoin edge (unhealthy → healthy) is unambiguous
                self.breaker.record_failure(be.id)
                self.metrics.incr("router.rerouted")
                continue
            self.breaker.record_success(be.id)
            self.metrics.incr("router.routed_replica")
            res["routed_to"] = be.id
            return res
        # exact-answer fallback: the primary
        self.metrics.incr("router.primary_fallbacks")
        try:
            res = self.primary.submit(payload, timeout)
        except Exception:
            self.metrics.incr("router.errors")
            raise
        res["routed_to"] = self.primary.id
        return res

    # -- standing queries ------------------------------------------------------
    def _backend_by_id(self, bid):
        for be in self.replicas:
            if be.id == bid:
                return be
        return self.primary if self.primary.id == bid else None

    def subscribe(self, payload: dict,
                  timeout: Optional[float] = None) -> dict:
        """Place one standing query (or relay an ``unsubscribe``): the
        same placement/breaker walk as :meth:`submit`, plus a door-side
        mirror so the subscription survives losing its backend. The
        response's ``id`` is the DOOR's (``dsub-<n>``); consumers poll
        and unsubscribe through the door only."""
        timeout = timeout if timeout is not None \
            else self.config.submit_timeout_s
        if payload.get("what") == "unsubscribe":
            return self._unsubscribe(payload, timeout)
        self.metrics.incr("router.sub_subscribes")
        attempts = 0
        for be in self._placement(payload):
            if attempts >= self.config.max_attempts:
                break
            if not self.breaker.allow(be.id):
                continue
            attempts += 1
            try:
                resp = be.subscribe(payload, timeout)
            except (DeadlineExceeded, *_PERMANENT):
                self.metrics.incr("router.errors")
                raise
            except AdmissionGated:
                self.metrics.incr("router.lag_rerouted")
                continue
            except Exception:  # noqa: BLE001 - transport/timeout/5xx
                self.breaker.record_failure(be.id)
                self.metrics.incr("router.rerouted")
                continue
            self.breaker.record_success(be.id)
            return self._adopt(be, payload, resp)
        try:
            resp = self.primary.subscribe(payload, timeout)
        except Exception:
            self.metrics.incr("router.errors")
            raise
        return self._adopt(self.primary, payload, resp)

    @staticmethod
    def _snapshot(resp: dict) -> dict:
        """Read one ``subscribed`` envelope into the mirror's fields."""
        return {
            "sid": resp.get("id"),
            "kind": resp.get("kind"),
            "window": resp.get("window"),
            "matches": {int(m) for m in resp.get("matches") or ()},
            "seq": int(resp.get("seq") or 0),
            "digest": resp.get("digest"),
        }

    def _adopt(self, be, payload: dict, resp: dict) -> dict:
        """Mirror one freshly-placed subscription and rewrite its id to
        the door's namespace."""
        if resp.get("what") == "subscribed":
            snap = self._snapshot(resp)
            with self._lock:
                self._sub_seq += 1
                dsid = f"dsub-{self._sub_seq}"
                self._subs[dsid] = dict(
                    snap, backend=be.id, payload=dict(payload))
            self.metrics.incr("router.subscriptions")
            out = dict(resp)
            out["id"] = dsid
            out["routed_to"] = be.id
            return out
        return resp  # relay odd shapes verbatim (future envelopes)

    def _unsubscribe(self, payload: dict, timeout: float) -> dict:
        dsid = payload.get("id")
        with self._lock:
            m = self._subs.pop(dsid, None) if isinstance(dsid, str) \
                else None
        if m is None:
            raise Unservable(f"unknown subscription {dsid!r}")
        be = self._backend_by_id(m["backend"])
        if be is not None:
            try:
                be.subscribe({"what": "unsubscribe", "id": m["sid"]},
                             timeout)
            except Exception:  # noqa: BLE001 - best effort: the door's
                # view is gone either way; an orphaned backend copy
                # sheds into its bounded queue until resource close
                self.metrics.incr("router.sub_orphaned")
        return {"what": "unsubscribed", "id": dsid}

    def poll(self, params: dict,
             timeout: Optional[float] = None) -> dict:
        """Long-poll one door subscription. The happy path relays to the
        owning backend and folds the answered deltas into the mirror;
        ANY backend failure (transport, 5xx, a restarted replica that
        lost its subscription state) triggers the resume path instead of
        surfacing an error: re-subscribe elsewhere, diff, synthesize."""
        dsid = params.get("id")
        with self._lock:
            m = self._subs.get(dsid) if isinstance(dsid, str) else None
        if m is None:
            raise Unservable(f"unknown subscription {dsid!r}")
        try:  # validate the caller's knobs HERE: past this point every
            # backend refusal is a backend-state problem → failover
            wait = float(params.get("timeout_s", 0.0) or 0.0)
            int(params.get("max", 32) or 32)
        except (TypeError, ValueError) as e:
            raise Unservable(f"bad poll parameter: {e}") from None
        self.metrics.incr("router.sub_polls")
        timeout = timeout if timeout is not None \
            else wait + self.config.submit_timeout_s
        be = self._backend_by_id(m["backend"])
        if be is not None:
            try:
                env = be.poll(dict(params, id=m["sid"]), timeout)
            except DeadlineExceeded:
                self.metrics.incr("router.errors")
                raise
            except Exception:  # noqa: BLE001 - the backend lost it (or
                # itself): resume, don't error
                self.breaker.record_failure(be.id)
                return self._sub_failover(dsid, m, timeout)
            return self._fold_poll(dsid, m, env)
        return self._sub_failover(dsid, m, timeout)

    def _fold_poll(self, dsid: str, m: dict, env: dict) -> dict:
        """Apply one backend poll answer to the mirror and rewrite ids.
        The mirror replays the consumer contract exactly: deltas chain
        seq→seq, a resync replaces the set wholesale."""
        what = env.get("what")
        if what == "resync":
            if env.get("id") != m["sid"]:
                # the backend answered for a DIFFERENT subscription —
                # never expected; count it rather than corrupt the mirror
                self.metrics.incr("router.sub_id_mismatches")
                return dict(env, id=dsid)
            with self._lock:
                m["matches"] = {int(x) for x in env.get("matches") or ()}
                m["seq"] = int(env.get("seq") or 0)
                m["digest"] = env.get("digest")
            return dict(env, id=dsid)
        if what == "notifications":
            if env.get("id") != m["sid"]:
                self.metrics.incr("router.sub_id_mismatches")
                return dict(env, id=dsid, notes=[], more=False)
            notes = []
            for note in env.get("notes") or ():
                if note.get("what") == "notification" \
                        and note.get("id") == m["sid"]:
                    added = [int(x) for x in note.get("added") or ()]
                    removed = [int(x) for x in note.get("removed") or ()]
                    with self._lock:
                        if int(note.get("seq_from") or 0) != m["seq"]:
                            # a broken chain the backend's own resync
                            # discipline should make impossible — count
                            # it and re-anchor at the note's far edge
                            self.metrics.incr("router.sub_chain_gaps")
                        m["matches"].difference_update(removed)
                        m["matches"].update(added)
                        m["seq"] = int(note.get("seq_to") or 0)
                        m["digest"] = note.get("digest")
                    notes.append(dict(note, id=dsid))
                else:
                    notes.append(note)
            return {"what": "notifications", "id": dsid, "notes": notes,
                    "more": bool(env.get("more"))}
        return dict(env, id=dsid)

    def _sub_failover(self, dsid: str, m: dict, timeout: float) -> dict:
        """The resume path: the owning backend is gone (or forgot the
        subscription), so re-place the ORIGINAL payload on another
        backend and answer the poll with the ONE synthesized delta
        between the mirror's anchor seq and the adopted snapshot — the
        consumer sees an ordinary chained notification, never a gap, a
        loss, or a duplicate."""
        self.metrics.incr("router.sub_failovers")
        old = set(m["matches"])
        old_seq = m["seq"]
        dead = m["backend"]
        candidates = [be for be in self._placement(m["payload"])
                      if be.id != dead]
        if self.primary.id != dead:
            candidates.append(self.primary)
        adopted, snap = None, None
        for be in candidates:
            try:
                r = be.subscribe(m["payload"], timeout)
            except Exception:  # noqa: BLE001 - keep walking the tier
                continue
            if r.get("what") == "subscribed":
                adopted, snap = be, self._snapshot(r)
                break
        if adopted is None:
            raise TransientFault(
                f"subscription {dsid} lost backend {dead!r} and no other "
                "backend could adopt it")
        new = snap["matches"]
        with self._lock:
            m.update(snap, backend=adopted.id)
            new_seq, digest = m["seq"], m["digest"]
        added = sorted(new - old)
        removed = sorted(old - new)
        notes = []
        if added or removed or new_seq != old_seq:
            notes = [{
                "what": "notification", "id": dsid,
                "seq_from": old_seq, "seq_to": new_seq,
                "added": added, "removed": removed, "digest": digest,
            }]
        return {"what": "notifications", "id": dsid, "notes": notes,
                "more": False}

    # -- fleet observability ---------------------------------------------------
    def fleet_source(self, node_id: str = "router"):
        """The router's OWN node source for a
        :class:`~hypergraphdb_tpu.obs.fleet.FleetCollector`: routing
        counters + the router health probe — the door reads itself the
        same way it reads its backends."""
        from hypergraphdb_tpu.obs.fleet import LocalNodeSource

        return LocalNodeSource(
            node_id, registries=[self.metrics.registry],
            health=self.health_probe(), role="router",
        )

    # -- health surface --------------------------------------------------------
    def health_probe(self):
        """The router's own ``/healthz``: per-backend health/lag/breaker
        plus the routing counters. Healthy while ANY backend (replica or
        primary) can take traffic — the tier is degraded-not-down by
        design."""

        def probe():
            with self._lock:
                snap = dict(self._health)
            backends = {}
            any_replica = False
            for be in self.replicas:
                healthy, lag, load, cover, t = snap.get(
                    be.id, (False, 0, 0.0, None, 0.0))
                state = self.breaker.state_of(be.id)
                if healthy and state != OPEN:
                    any_replica = True
                backends[be.id] = {
                    "healthy": healthy,
                    "replication_lag": lag,
                    "load_score": load,
                    "gid_capacity": cover,
                    "breaker": state,
                }
            primary_ok = True
            ph = getattr(self.primary, "health", None)
            if ph is not None:
                try:
                    primary_ok = bool(ph()[0])
                except Exception:  # noqa: BLE001 - unreachable == down
                    primary_ok = False
            payload = {
                "role": "router",
                "primary": self.primary.id,
                "primary_healthy": primary_ok,
                "backends": backends,
                "counters": dict(self.metrics.counters),
            }
            return any_replica or primary_ok, payload

        return probe
