"""The serving runtime: dispatch loop, device executor, lifecycle.

Request path::

    submit_*() → AdmissionQueue (bounded, deadline-shedding)
        → Batcher (coalesce + pad-to-bucket, flush on full/linger)
            → Executor.launch()  — pin view, assemble, async device dispatch
                → Executor.collect() — sync, LSM-correct, complete futures

The dispatch thread **double-buffers**: ``pump()`` launches batch N+1
BEFORE collecting batch N's results, so host-side assembly of the next
batch (numpy padding, anchor ordering, delta refresh) overlaps device
execution of the current one — JAX dispatch is asynchronous, the
``launch`` never blocks on the device.

Consistency: every batch is assembled from ONE
:class:`~hypergraphdb_tpu.ops.incremental.PinnedView` — base, device
delta, and the host memtable captured under a single manager lock — so a
background compaction swapping mid-batch cannot desync what the kernel
reads from what the host correction compensates. BFS requests see
base ∪ delta directly in the kernel (staleness bounded by
``max_lag_edges``); pattern requests run on the base and the memtable is
merged at collect time (the ``query/compiler.DeviceValueConjPlan`` LSM
read-merge) against candidate records CAPTURED when the batch launched —
never the live graph — so every answer in a batch reflects the pinned
view's single point in the manager's event stream, however long the
device ran.

Deterministic testing: ``ServeConfig(manual=True)`` starts no thread —
tests drive ``step()`` / ``pump()`` with an injected clock and a fake
executor, making deadline shedding, flush policy, and drains exactly
reproducible.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future
from contextlib import nullcontext
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import numpy as np

from hypergraphdb_tpu.fault import (
    OPEN,
    CircuitBreaker,
    global_faults,
    is_transient,
)
from hypergraphdb_tpu.obs import global_tracer
from hypergraphdb_tpu.obs.device import annotate, profiling
from hypergraphdb_tpu.obs.flight import global_flight

#: process flight recorder, bound once (the fault-registry singleton
#: discipline: one attribute read per site when quiet)
_FLIGHT = global_flight()

#: the no-annotation dispatch context — stateless, safe to re-enter, so
#: the common (un-profiled) path allocates nothing per dispatch
_NULL_CM = nullcontext()
from hypergraphdb_tpu.serve.admission import AdmissionQueue
from hypergraphdb_tpu.serve.batcher import BUCKETS, Batcher, MicroBatch
from hypergraphdb_tpu.serve.stats import ServeStats
from hypergraphdb_tpu.serve.types import (
    BFSRequest,
    Clock,
    JoinRequest,
    JoinResult,
    PatternRequest,
    RangeRequest,
    ServeResult,
    Ticket,
    Unservable,
)


@dataclass
class ServeConfig:
    """Knobs of one runtime; defaults suit the streaming-bench scale."""

    buckets: Sequence[int] = BUCKETS        # pad-to-bucket request widths
    max_queue: int = 4096                   # admission queue bound
    policy: str = "block"                   # backpressure: "block" | "fail"
    max_linger_s: float = 0.002             # flush latency bound
    default_deadline_s: Optional[float] = None
    max_lag_edges: int = 0                  # delta staleness bound (BFS)
    top_r: int = 128                        # compact result window
    pattern_pad: int = 128                  # base-row budget per pattern
    default_max_hops: int = 2
    clock: Optional[Clock] = None           # injectable time source
    manual: bool = False                    # no thread; tests call step()
    latency_window: int = 4096
    #: pre-admission fitness gate: a callable returning None (admit) or
    #: a reason string (refuse with AdmissionGated). The replica tier
    #: wires its replication-lag bound here, so a lagging replica sheds
    #: to the router instead of answering past its staleness contract.
    admission_gate: Optional[Callable[[], Optional[str]]] = None
    tracer: Optional[object] = None         # hgobs Tracer; None → global
    device_timing: bool = False             # launch→ready deltas per batch
    #: hgperf sentinel (``obs.perf.PerfSentinel``): every completed
    #: request feeds its rolling per-lane digests, and the completion
    #: path drives its rate-limited evaluation (``maybe_tick``). The
    #: device-seconds digest additionally needs ``device_timing=True``
    #: AND an enabled tracer (``block_timed`` measurement rides the
    #: trace clock). Give the sentinel the SAME clock as the runtime —
    #: samples are stamped on it. None disables (zero cost: one
    #: attribute read per completion).
    perf: Optional[object] = None
    # -- self-healing (hgfault) ----------------------------------------------
    max_retries: int = 2                    # transient launch re-attempts
    retry_base_s: float = 0.005             # backoff seed: base * 2^(n-1)
    retry_max_s: float = 0.25               # backoff cap
    retry_jitter: float = 0.5               # multiplicative jitter frac
    retry_seed: int = 0                     # deterministic jitter stream
    breaker_threshold: int = 3              # consecutive failures → OPEN
    breaker_cooldown_s: float = 0.25        # OPEN → HALF_OPEN probe delay
    transient_errors: tuple = ()            # extra types to retry
    sleep: Optional[Callable] = None        # injectable backoff sleeper
    faults: Optional[object] = None         # fault registry; None → global
    # -- raw speed (pallas_bfs + aot_cache) ----------------------------------
    use_pallas_bfs: bool = True             # fused kernel when it preflights
    aot_cache_dir: Optional[str] = None     # AOT compile cache; None → env
    prewarm_aot: bool = True                # compile K buckets at startup
    prewarm_hops: Optional[tuple] = None    # hops to warm; None → (default,)
    #: pattern anchor arities P to prewarm per bucket (ROADMAP 4d) —
    #: P is a device shape dim, one compiled program each; () disables
    prewarm_pattern_arities: tuple = (1, 2)
    #: build + upload the co-incidence CSR at startup (deployments that
    #: serve joins): the build is O(Σ arity²) — done lazily it would
    #: land on the dispatch thread inside the first join batch's
    #: deadline window after every compaction. Opt-in: BFS/pattern-only
    #: tiers should not pay it.
    prewarm_join_nbr: bool = False
    # -- join engine v2 (degree-split / factorized / partial correction) -----
    #: build the prefix-grouped (trie) encoding of the co/tgt relations
    #: once per (signature-cache miss, base epoch) at plan time — K
    #: lanes probing equal rows then touch one HBM copy. The build is
    #: O(E log E) host work per epoch; joins-light tiers can switch it
    #: off and keep the flat CSRs.
    join_factorized: bool = True
    #: degree-split plans: lanes whose const-keyed rows exceed the hub
    #: threshold run the chunked dense-frontier chain instead of
    #: truncating onto the host path (``ops/join.join_hub_expand``)
    join_hub_split: bool = True
    #: hub threshold override (row width); None = the executor's pad cap
    join_hub_threshold: Optional[int] = None
    #: executor shape caps for the join lane (``ops/join`` defaults:
    #: 2^15 pooled binding rows, 2^10 expansion pad) — a deployment
    #: serving hub-anchored joins device-exact raises join_row_cap to
    #: hold the hub's full binding set
    join_row_cap: int = 1 << 15
    join_pad_cap: int = 1 << 10
    #: per-lane memtable correction (ROADMAP 2d): while the dirty set —
    #: new links plus their targets — stays at most this many atoms,
    #: join batches keep dispatching on device and collect merges the
    #: host-enumerated tuples touching the dirty set
    #: (``join/host.host_join_touching``); past it (or on any tombstone/
    #: revalue) the whole batch takes the exact host path as before.
    #: 0 disables the partial path.
    join_dirty_max: int = 16
    #: value DIMENSIONS (kind bytes, e.g. ``(ord("i"),)``) whose sorted
    #: index columns build + upload at startup, with the range-lane
    #: executables warmed per bucket when an AOT cache is configured —
    #: the hgindex half of the cold-start story (done lazily, the
    #: O(N log N) column sort + compile land on the dispatch thread
    #: inside the first range batch's deadline window; they still do
    #: after each compaction epoch, the same accepted cost class as the
    #: sharded base re-shard). Opt-in like ``prewarm_join_nbr``.
    prewarm_range_dims: tuple = ()
    # -- multi-chip serving (serve/sharded + ops/sharded_serving) ------------
    #: True routes serve buckets through the mesh-sharded executor;
    #: False pins single-chip; None = AUTO — sharded exactly when more
    #: than one device is visible AND the pinned base's device footprint
    #: exceeds ``hbm_budget_bytes`` (a snapshot one chip can hold serves
    #: faster without collective hops)
    sharded: Optional[bool] = None
    #: per-chip HBM budget the AUTO pick compares the base snapshot's
    #: estimated device bytes against; None disables the auto upgrade
    #: (only ``sharded=True`` shards then)
    hbm_budget_bytes: Optional[int] = None
    #: cap on mesh devices (None = every visible device)
    mesh_devices: Optional[int] = None


def _dummy_inc_csr():
    """The anchor-free range dispatch's stand-in incidence CSR: empty
    segments whatever index the (masked-off) probe clamps to."""
    import jax.numpy as jnp

    return jnp.zeros((2,), jnp.int32), jnp.zeros((8,), jnp.int32)


@dataclass
class LaunchedBatch:
    """An in-flight batch: the async device handles plus everything
    ``collect`` needs to turn them into per-ticket results."""

    batch: MicroBatch
    view: object = None                  # ops.incremental.PinnedView
    dev_out: object = None               # async (counts, first_r) handles
    lane_tickets: list = field(default_factory=list)   # [(lane, Ticket)]
    host_tickets: list = field(default_factory=list)   # exact-fallback path
    #: pattern batches: {handle: (target_set, type_handle)} of memtable
    #: candidates, captured AT LAUNCH (pin time ± µs) so collect-time
    #: corrections never read the live graph mid-ingest
    cand_records: dict = field(default_factory=dict)
    #: (t_launch, t_ready) in the tracer's clock once collect blocked —
    #: the batch's device-execution attribution (ServeConfig.device_timing)
    t_device: object = None
    _t_launch: object = None
    #: join batches: the ``join/planner.JoinPlan`` the lanes executed —
    #: collect needs its column order to permute tuples back into the
    #: request's variable order
    join_plan: object = None
    #: join batches dispatched under a SMALL pure-add dirty memtable:
    #: the sorted touched-atom list (new links + their targets, captured
    #: at launch) the per-lane collect correction enumerates against —
    #: None when the memtable was clean at pin (ROADMAP 2d)
    join_dirty: object = None
    #: join batches: real lanes this dispatch routed through the
    #: degree-split dense-frontier hub chain, and collect-side partial
    #: memtable corrections merged — batch-level EXPLAIN attribution
    #: (the per-request record reports the batch it rode)
    join_hub_lanes: int = 0
    join_partials: int = 0
    #: range batches: how many leading entries of the view's
    #: ``new_atoms`` the dispatched delta column covered — the collect
    #: residual (``new_atoms[covered:]``) the host correction owes
    range_covered: int = 0
    #: double-buffer slot of this dispatch (dispatch sequence mod 2) —
    #: rides the ``device`` span and the profiler annotation so device
    #: time is attributable per pipeline slot
    slot: int = -1


class DeviceExecutor:
    """The real executor: batched kernels over a pinned snapshot view.

    Requests the fixed-shape kernels cannot serve exactly — seeds/anchors
    beyond the base's id space (atoms newer than the last compaction),
    base rows wider than ``pattern_pad``, or a snapshot without ELL
    targets — fall back to exact host execution at collect time, counted
    in ``stats.host_fallbacks``."""

    #: which lane family a device-served result counts under (the
    #: sharded executor overrides with "sharded") — see stats.LANE_PATHS
    device_lane = "device"

    def __init__(self, graph, config: ServeConfig,
                 stats: Optional[ServeStats] = None):
        if graph is None:
            raise ValueError("DeviceExecutor needs a graph")
        self.graph = graph
        self.config = config
        self.stats = stats or ServeStats()
        self.tracer = config.tracer or global_tracer()
        self.faults = config.faults or global_faults()
        # serving implies ingest-concurrent reads: the incremental
        # (base, delta) pair IS the consistency mechanism
        self.mgr = graph.incremental or graph.enable_incremental()
        #: real device dispatches so far — slot = seq mod 2 names which
        #: half of the double buffer a batch rode (span + profiler attr)
        self._dispatch_seq = 0
        #: persistent AOT compile cache (ops/aot_cache): explicit dir from
        #: config, else $HG_AOT_CACHE, else off. content_key pins entries
        #: to this graph generation (quiet rebuild on mismatch).
        self.aot = self._open_aot_cache()
        self._aot_failed = False
        #: (epoch, new_atoms scanned, touched set | "full") —
        #: _join_dirty_info's memo
        self._join_dirty_memo: tuple = (-1, 0, frozenset())

    def _open_aot_cache(self):
        import os

        from hypergraphdb_tpu.ops.aot_cache import (
            CACHE_ENV,
            AOTCache,
            default_cache,
        )

        if not self.config.aot_cache_dir and not os.environ.get(CACHE_ENV):
            # no cache configured — decide BEFORE the content fingerprint
            # (an O(E) CRC over the full CSR at benchmark scale)
            return None
        try:
            fp = self._content_key()
            if self.config.aot_cache_dir:
                return AOTCache(root=self.config.aot_cache_dir,
                                content_key=fp)
            return default_cache(content_key=fp)
        except Exception:  # pragma: no cover - unwritable dir etc.
            return None

    def _content_key(self) -> str:
        """Snapshot content fingerprint of the graph at executor birth —
        the ``snapshot_fingerprint`` half of the AOT cache key. The
        executables themselves depend only on shapes, so the fingerprint
        is a conservative pin: restarting over the same data warm-hits,
        restarting over different data rebuilds quietly."""
        from hypergraphdb_tpu.ops.ellbfs import snapshot_fingerprint

        try:
            return snapshot_fingerprint(self.mgr.base)
        except Exception:  # pragma: no cover - exotic base states
            return ""

    # -- AOT-compiled dispatch + prewarm -------------------------------------
    def _aot_dispatch(self, entry: str, jit_fn, args: tuple,
                      statics: dict):
        """The cached executable for one dispatch, or None → the caller
        falls back to plain jit. ONE failure policy for every entry: a
        cache malfunction logs once and disables the cache for this
        executor's lifetime (the cache accelerates, never gates), while
        EXECUTION errors of the returned executable propagate to the
        retry/breaker ladder like any device failure. Dispatch-time
        compiles do not persist (``persist=False``): only the prewarm
        writes disk entries, so shape churn (resized delta buckets)
        cannot mint superseded multi-MB files on a serving thread."""
        if self.aot is None or self._aot_failed:
            return None
        try:
            return self.aot.get_or_compile(entry, jit_fn, args, statics,
                                           persist=False)
        except Exception:  # noqa: BLE001 - shapes the AOT path rejects
            import logging

            logging.getLogger("hypergraphdb_tpu.serve").warning(
                "aot dispatch failed for %s; falling back to jit", entry,
                exc_info=True,
            )
            self._aot_failed = True
            return None

    def _serve_bfs(self, view, seeds_dev, max_hops: int, top_r: int):
        """One BFS batch dispatch through the AOT cache when configured
        (first dispatch of a warmed bucket reuses the persisted
        executable instead of recompiling); plain jit otherwise."""
        from hypergraphdb_tpu.ops.serving import bfs_serve_batch

        args = (view.device, view.delta, seeds_dev)
        statics = {"max_hops": max_hops, "top_r": top_r}
        compiled = self._aot_dispatch("ops.serving.bfs_serve_batch",
                                      bfs_serve_batch, args, statics)
        if compiled is not None:
            return compiled(*args)
        return bfs_serve_batch(*args, **statics)

    def _serve_bfs_fused(self, kw: dict, seeds_dev, max_hops: int,
                         top_r: int):
        """The fused-kernel dispatch, through the AOT cache when the
        batch carries no overlay (the steady read-heavy shape prewarm
        covers); overlay batches take the plain jit — their array shapes
        change per delta refresh, which would churn even the in-process
        memo for executables jit retraces anyway."""
        from hypergraphdb_tpu.ops.serving import bfs_serve_batch_fused

        statics = {
            "geom": kw["geom"], "kwp": kw["kwp"], "max_hops": max_hops,
            "top_r": top_r, "widths1": kw["widths1"],
            "widths2": kw["widths2"],
        }
        if kw["overlay"] is None:
            args = (kw["fused"], seeds_dev, kw["n_atoms"])
            compiled = self._aot_dispatch(
                "ops.serving.bfs_serve_batch_fused",
                bfs_serve_batch_fused, args, statics,
            )
            if compiled is not None:
                return compiled(*args)
        return bfs_serve_batch_fused(kw["fused"], seeds_dev,
                                     kw["n_atoms"], kw["overlay"],
                                     **statics)

    def _serve_pattern(self, view, ell, anchors, type_vec):
        """One pattern batch dispatch through the AOT cache when
        configured (the prewarmed (bucket, P) executables — ROADMAP 4d:
        join/pattern traffic in a fresh process must not pay
        dispatch-thread compiles); plain jit otherwise. ``anchors`` and
        ``type_vec`` arrive as host numpy (the launch loop builds them);
        subclasses routing to other kernels reassemble from those."""
        import jax.numpy as jnp

        from hypergraphdb_tpu.ops.serving import pattern_serve_batch

        args = (view.device, ell, jnp.asarray(anchors),
                jnp.asarray(type_vec))
        statics = {"pad_len": self.config.pattern_pad,
                   "top_r": self.config.top_r}
        compiled = self._aot_dispatch("ops.serving.pattern_serve_batch",
                                      pattern_serve_batch, args, statics)
        if compiled is not None:
            return compiled(*args)
        return pattern_serve_batch(*args, **statics)

    def _serve_range(self, view, bcol, dcol, bounds: dict):
        """One range batch dispatch (``ops/value_index.ordered_topk_batch``
        over the base + delta value columns), through the AOT cache when
        configured. ``bounds`` carries the per-lane host numpy arrays the
        launch loop assembled."""
        import jax.numpy as jnp

        from hypergraphdb_tpu.ops.value_index import ordered_topk_batch
        from hypergraphdb_tpu.storage.value_index import (
            inc_csr_device,
            type_of_device,
        )

        if (bounds["anchor"] >= 0).any():
            inc_off, inc_links = inc_csr_device(view.base)
        else:
            # anchor-free batch (the steady shape): never materialize the
            # O(E) incidence CSR on device just to satisfy the kernel
            # signature — a tiny dummy CSR yields empty segments, and
            # every anchor_vec<0 lane masks the probe out anyway (a
            # second shape-keyed program, warmed as THE range program)
            inc_off, inc_links = _dummy_inc_csr()
        args = (
            bcol.rank_hi, bcol.rank_lo, bcol.rank2_hi, bcol.rank2_lo,
            bcol.gids, jnp.int32(bcol.n),
            dcol.rank_hi, dcol.rank_lo, dcol.rank2_hi, dcol.rank2_lo,
            dcol.gids, jnp.int32(dcol.n),
            type_of_device(view.base), inc_off, inc_links,
            jnp.asarray(bounds["lo_hi"]), jnp.asarray(bounds["lo_lo"]),
            jnp.asarray(bounds["lo_hi2"]), jnp.asarray(bounds["lo_lo2"]),
            jnp.asarray(bounds["lo_right"]),
            jnp.asarray(bounds["hi_hi"]), jnp.asarray(bounds["hi_lo"]),
            jnp.asarray(bounds["hi_hi2"]), jnp.asarray(bounds["hi_lo2"]),
            jnp.asarray(bounds["hi_right"]),
            jnp.asarray(bounds["type_vec"]), jnp.asarray(bounds["anchor"]),
            jnp.asarray(bounds["desc"]),
        )
        statics = {"win_pad": self._range_win_pad(),
                   "top_r": self.config.top_r}
        compiled = self._aot_dispatch("ops.value_index.ordered_topk_batch",
                                      ordered_topk_batch, args, statics)
        if compiled is not None:
            return compiled(*args)
        return ordered_topk_batch(*args, **statics)

    def _range_win_pad(self) -> int:
        """Candidate gather width per column: the smallest power-of-two
        bucket holding ``top_r`` (the kernel's prefix-dominance floor)."""
        from hypergraphdb_tpu.ops.setops import _bucket

        return _bucket(self.config.top_r, minimum=8)

    def _pattern_gate(self, view):
        """The pattern lanes' device-path gate: an opaque handle the
        dispatch needs (the base's ELL targets here), or None → every
        lane takes the exact host path."""
        from hypergraphdb_tpu.ops.setops import ell_targets

        return ell_targets(view.base)

    def _pin_view(self, kind: str, host_only: bool = False):
        """Pin the batch's consistent read unit — the ONE override point
        for executors that read a different device layout (the sharded
        executor pins mesh twins here)."""
        return self.mgr.pinned_view(
            self.config.max_lag_edges,
            sync_delta=(kind == "bfs") and not host_only,
        )

    def _execute_join(self, view, plan, consts, n_real: int):
        """One join batch through the single-chip lane executor
        (subclass override point — the sharded executor routes the same
        plan through the mesh's lane-sharded program)."""
        from hypergraphdb_tpu.ops.join import execute_join

        cfg = self.config
        # the view's epoch-cached trie encodings (built at plan time /
        # prewarm when join_factorized): present → serve through them,
        # absent (or disabled) → flat CSRs; never build on the dispatch
        # hot path
        fact = (view.factorized_join_rels()
                if cfg.join_factorized else None)
        return execute_join(view.base, plan, consts,
                            top_r=cfg.top_r, n_real=n_real,
                            row_cap=cfg.join_row_cap,
                            pad_cap=cfg.join_pad_cap,
                            hub_split=cfg.join_hub_split,
                            hub_threshold=cfg.join_hub_threshold,
                            factorized=(None if fact is not None
                                        else False))

    def prewarm(self, buckets, max_hops: Optional[int] = None) -> int:
        """Compile (or load from the AOT cache) the BFS serving
        executables for every bucket width against the current pinned
        view — the deploy-time half of the cold-start story. Warms the
        unfused entry always (it serves tombstone/overlay windows and
        every non-Pallas backend) and the fused entry wherever the fused
        gates would route the first dispatch. Runs even with NO cache
        configured: the fused host plan build (O(composed adjacency) —
        seconds at benchmark scale) and the backend probe compile are
        unrelated to AOT and must not land inside the first live
        request's deadline window. Returns the number of executables
        served from cache."""
        import jax.numpy as jnp

        from hypergraphdb_tpu.ops import pallas_bfs as _pbfs
        from hypergraphdb_tpu.ops.serving import (
            bfs_serve_batch,
            bfs_serve_batch_fused,
        )

        if self.config.prewarm_join_nbr:
            # the join lane's co-incidence CSR: built + uploaded at
            # deploy time (in-budget snapshots only — over budget it
            # raises and the serve path declines to host anyway), plus
            # the factorized trie encoding when the v2 path will use it
            from hypergraphdb_tpu.ops.join import (
                factorized_relations_device,
                neighbor_csr_device,
            )

            try:
                neighbor_csr_device(self.mgr.base)
                if self.config.join_factorized:
                    factorized_relations_device(self.mgr.base)
            except Exception:  # noqa: BLE001 - never block startup
                import logging

                logging.getLogger("hypergraphdb_tpu.serve").warning(
                    "join prewarm failed; first join dispatch builds the "
                    "CSR cold", exc_info=True,
                )
        range_dims = tuple(self.config.prewarm_range_dims or ())
        if range_dims:
            # the range lane's sorted columns (+ per-bucket executables
            # below): first dispatch must not pay the O(N log N) column
            # sort on the dispatch thread
            from hypergraphdb_tpu.storage.value_index import (
                value_index_column,
            )

            for dim in range_dims:
                try:
                    value_index_column(self.mgr.base, int(dim))
                except Exception:  # noqa: BLE001 - never block startup
                    import logging

                    logging.getLogger("hypergraphdb_tpu.serve").warning(
                        "range-column prewarm failed for dim %d; first "
                        "range dispatch sorts it cold", int(dim),
                        exc_info=True,
                    )
        if self.aot is None and not (self.config.use_pallas_bfs
                                     and _pbfs.pallas_bfs_ok()):
            # nothing to warm: no cache to load, and the fused path (the
            # owner of the plan-build/probe cost) can never engage — skip
            # the pinned_view so cache-less CPU construction stays free
            return 0

        # the hops SET to warm: a deployment serving more than the default
        # (ServeConfig.prewarm_hops) would otherwise compile the missing
        # statics synchronously on the dispatch thread in every fresh
        # process — dispatch-time compiles never persist
        hops_list = ((int(max_hops),) if max_hops is not None
                     else tuple(self.config.prewarm_hops or ())
                     or (self.config.default_max_hops,))
        view = self.mgr.pinned_view(self.config.max_lag_edges,
                                    sync_delta=True)
        n = view.base.num_atoms
        top_r = min(self.config.top_r + 1, n + 1)
        # the pattern lane's ELL targets + executables (ROADMAP 4d):
        # without this, join/pattern traffic in a fresh process pays its
        # (bucket, P) compiles on the dispatch thread at first flush
        arities = (tuple(self.config.prewarm_pattern_arities or ())
                   if self.aot is not None else ())
        ell = None
        if arities:
            from hypergraphdb_tpu.ops.setops import ell_targets

            ell = ell_targets(view.base)
        warm_dims = range_dims if self.aot is not None else ()
        if warm_dims:
            from hypergraphdb_tpu.storage.value_index import (
                build_delta_column,
                type_of_device,
            )

            # one empty delta column serves every warmed (dim, bucket):
            # the executable depends on shapes, not contents
            empty_delta = build_delta_column(self.graph, [], 0, epoch=-1)
        warm = 0
        for b in buckets:
            seeds = jnp.full((int(b),), n, dtype=jnp.int32)
            # plan build + backend probe happen HERE regardless of cache
            fkw = self._fused_bfs_kwargs(view, int(b))
            if self.aot is None:
                continue
            if ell is not None:
                from hypergraphdb_tpu.ops.serving import (
                    NO_TYPE,
                    pattern_serve_batch,
                )

                tvec = jnp.full((int(b),), NO_TYPE, dtype=jnp.int32)
                for P in arities:
                    anchors = jnp.full((int(b), int(P)), n,
                                       dtype=jnp.int32)
                    try:
                        warm += self.aot.warm(
                            "ops.serving.pattern_serve_batch",
                            pattern_serve_batch,
                            (view.device, ell, anchors, tvec),
                            {"pad_len": self.config.pattern_pad,
                             "top_r": self.config.top_r},
                        )
                    except Exception:  # noqa: BLE001 - never block startup
                        continue
            for dim in warm_dims:
                from hypergraphdb_tpu.ops.value_index import (
                    ordered_topk_batch,
                )
                from hypergraphdb_tpu.storage.value_index import (
                    value_index_column,
                )

                try:
                    bcol = value_index_column(view.base, int(dim))
                    # warm the ANCHOR-FREE program — the steady shape
                    # (anchored batches carry the real incidence CSR and
                    # compile on first use, like overlay BFS batches)
                    inc_off, inc_links = _dummy_inc_csr()
                    zu = jnp.zeros((int(b),), jnp.uint32)
                    zb = jnp.zeros((int(b),), bool)
                    neg = jnp.full((int(b),), -1, jnp.int32)
                    warm += self.aot.warm(
                        "ops.value_index.ordered_topk_batch",
                        ordered_topk_batch,
                        (bcol.rank_hi, bcol.rank_lo,
                         bcol.rank2_hi, bcol.rank2_lo, bcol.gids,
                         jnp.int32(bcol.n),
                         empty_delta.rank_hi, empty_delta.rank_lo,
                         empty_delta.rank2_hi, empty_delta.rank2_lo,
                         empty_delta.gids, jnp.int32(0),
                         type_of_device(view.base), inc_off, inc_links,
                         zu, zu, zu, zu, zb, zu, zu, zu, zu, zb,
                         neg, neg, zb),
                        {"win_pad": self._range_win_pad(),
                         "top_r": self.config.top_r},
                    )
                except Exception:  # noqa: BLE001 - never block startup
                    continue
            for hops in hops_list:
                # independent try blocks: a bucket whose unfused lowering
                # fails must not forfeit the fused warm (or vice versa) —
                # whichever entry the first dispatch routes to should be
                # hot
                try:
                    warm += self.aot.warm(
                        "ops.serving.bfs_serve_batch", bfs_serve_batch,
                        (view.device, view.delta, seeds),
                        {"max_hops": hops, "top_r": top_r},
                    )
                except Exception:  # noqa: BLE001 - never block startup
                    import logging

                    logging.getLogger("hypergraphdb_tpu.serve").warning(
                        "aot warm failed (bfs_serve_batch, hops=%d); "
                        "first dispatch compiles cold", hops,
                        exc_info=True,
                    )
                if fkw is None or fkw["overlay"] is not None:
                    continue
                try:
                    warm += self.aot.warm(
                        "ops.serving.bfs_serve_batch_fused",
                        bfs_serve_batch_fused,
                        (fkw["fused"], seeds, fkw["n_atoms"]),
                        {"geom": fkw["geom"], "kwp": fkw["kwp"],
                         "max_hops": hops, "top_r": top_r,
                         "widths1": fkw["widths1"],
                         "widths2": fkw["widths2"]},
                    )
                except Exception:  # noqa: BLE001
                    continue
        return warm

    def _fused_bfs_kwargs(self, view, bucket: int):
        """Route this batch through the fused Pallas kernel? None keeps
        the unfused chain. Gates, in order: config, backend preflight,
        pending tombstones (the composed adjacency cannot neutralize a
        dead link — bounded by the next compaction), plan budgets /
        overlay planability."""
        if not self.config.use_pallas_bfs:
            return None
        from hypergraphdb_tpu.ops import pallas_bfs as _pbfs

        if not _pbfs.pallas_bfs_ok():
            return None
        if view.dead:
            return None
        try:
            return _pbfs.serve_fused_kwargs(view.base, view.delta, bucket)
        except Exception:  # noqa: BLE001 - any plan surprise → fallback
            return None

    def _dispatch_cm(self, kind: str, bucket: int, statics: int):
        """The per-dispatch profiler annotation, active only when device
        timing is on or an ``obs.profile`` session is running — the
        common un-profiled path pays two attribute reads and re-enters
        the shared null context (no allocation)."""
        if self.config.device_timing or profiling():
            slot = self._dispatch_seq % 2
            return annotate(
                f"hg.serve.{kind}[K={bucket},s={statics},slot={slot}]"
            )
        return _NULL_CM

    # -- launch (async: never blocks on the device) --------------------------
    def launch(self, batch: MicroBatch) -> LaunchedBatch:
        import jax.numpy as jnp

        kind = batch.key[0]
        if getattr(batch, "force_host", False):
            # breaker-degraded mode: the WHOLE batch takes the exact host
            # path under the pinned epoch — no device work, no delta sync
            view = self._pin_view(kind, host_only=True)
            out = LaunchedBatch(batch=batch, view=view)
            out.host_tickets = list(batch.tickets)
            return out
        if self.faults.enabled:  # the ONE gate read on the disabled path
            # models the DEVICE dispatch failing — deliberately after the
            # force_host branch, so breaker-degraded batches stay immune
            self.faults.check("serve.launch", kind=kind)
        # pattern batches read base + HOST corrections only — don't pay a
        # device-delta upload on their hot path
        view = self._pin_view(kind)
        out = LaunchedBatch(batch=batch, view=view)
        if kind == "bfs":
            max_hops = batch.key[1]
            n = view.base.num_atoms
            seeds = np.full(batch.bucket, n, dtype=np.int32)  # pad → dummy
            lane = 0
            for t in batch.tickets:
                if t.request.seed >= n or t.request.seed < 0:
                    out.host_tickets.append(t)
                    continue
                seeds[lane] = t.request.seed
                out.lane_tickets.append((lane, t))
                lane += 1
            if out.lane_tickets:
                # one slot beyond top_r: an include_seed=False request
                # drops its seed from the window, and the spare slot keeps
                # the remaining prefix full-width (see _bfs_result)
                top_r = min(self.config.top_r + 1, n + 1)
                fused_kw = self._fused_bfs_kwargs(view, batch.bucket)
                with self._dispatch_cm("bfs", batch.bucket, max_hops):
                    if fused_kw is not None:
                        out.dev_out = self._serve_bfs_fused(
                            fused_kw, jnp.asarray(seeds), max_hops, top_r,
                        )
                    else:
                        out.dev_out = self._serve_bfs(
                            view, jnp.asarray(seeds), max_hops, top_r,
                        )
        elif kind == "pattern":
            from hypergraphdb_tpu.ops.serving import NO_TYPE

            P = batch.key[1]
            n = view.base.num_atoms
            ell = self._pattern_gate(view)
            off = view.base.inc_offsets
            anchors = np.full((batch.bucket, P), n, dtype=np.int32)
            type_vec = np.full(batch.bucket, NO_TYPE, dtype=np.int32)
            lane = 0
            for t in batch.tickets:
                req = t.request
                a = np.asarray(req.anchors, dtype=np.int64)
                if ell is None or a.min() < 0 or a.max() >= n:
                    out.host_tickets.append(t)
                    continue
                lens = off[a + 1].astype(np.int64) - off[a]
                order = np.argsort(lens, kind="stable")
                if lens[order[0]] > self.config.pattern_pad:
                    out.host_tickets.append(t)  # base row over budget
                    continue
                anchors[lane] = a[order]
                if req.type_handle is not None:
                    type_vec[lane] = int(req.type_handle)
                out.lane_tickets.append((lane, t))
                lane += 1
            if out.lane_tickets:
                out.cand_records = self._capture_candidates(view)
                with self._dispatch_cm("pattern", batch.bucket, P):
                    out.dev_out = self._serve_pattern(
                        view, ell, anchors, type_vec,
                    )
        elif kind == "range":
            from hypergraphdb_tpu.storage.value_index import (
                FIXED_WIDTH_KINDS,
                value_index_column,
            )

            dim = batch.key[1]
            n = view.base.num_atoms
            K = batch.bucket
            U32 = np.uint32(0xFFFFFFFF)
            bounds = {
                # pad-lane default: lo and hi both leftmost of rank 0 —
                # an empty window, well-defined garbage by construction
                "lo_hi": np.zeros(K, np.uint32),
                "lo_lo": np.zeros(K, np.uint32),
                "lo_hi2": np.zeros(K, np.uint32),
                "lo_lo2": np.zeros(K, np.uint32),
                "lo_right": np.zeros(K, bool),
                "hi_hi": np.zeros(K, np.uint32),
                "hi_lo": np.zeros(K, np.uint32),
                "hi_hi2": np.zeros(K, np.uint32),
                "hi_lo2": np.zeros(K, np.uint32),
                "hi_right": np.zeros(K, bool),
                "type_vec": np.full(K, -1, np.int32),
                "anchor": np.full(K, -1, np.int32),
                "desc": np.zeros(K, bool),
            }
            # columns build lazily: a variable-width batch must consult
            # their device_exact verdicts BEFORE routing lanes, but an
            # all-host batch (every bound ambiguous) must not pay the
            # build/upload at all
            cols = []

            def _cols():
                if not cols:
                    cols.append(value_index_column(view.base, dim))
                    cols.append(self.mgr.value_delta(
                        view, dim, self.config.max_lag_edges))
                return cols

            lane = 0
            for t in batch.tickets:
                req = t.request
                if (not req.exact
                        or (req.limit is not None
                            and req.limit > self.config.top_r)
                        or (req.anchor is not None
                            and (req.anchor < 0 or req.anchor >= n))
                        or (dim not in FIXED_WIDTH_KINDS
                            and not all(c.device_exact for c in _cols()))):
                    # ambiguous variable-width bounds (ties past the
                    # 128-bit rank pair), columns holding any ambiguous
                    # key, over-window limits, and anchors outside the
                    # base (a memtable anchor has no base incidence row
                    # to probe) all serve exactly on host. Anchored lanes
                    # under fresh ingest stay on device: the base-row
                    # probe can only mask fresh links OUT (never falsely
                    # in), and the collect re-offers the full memtable
                    # candidate set through the live-incidence host
                    # probe.
                    out.host_tickets.append(t)
                    continue
                lo, hi = req.lo_rank, req.hi_rank
                if lo is not None:
                    bounds["lo_hi"][lane] = np.uint32(lo >> 32)
                    bounds["lo_lo"][lane] = np.uint32(lo & 0xFFFFFFFF)
                    bounds["lo_hi2"][lane] = np.uint32(req.lo_rank2 >> 32)
                    bounds["lo_lo2"][lane] = np.uint32(
                        req.lo_rank2 & 0xFFFFFFFF)
                    bounds["lo_right"][lane] = req.lo_op == "gt"
                if hi is not None:
                    bounds["hi_hi"][lane] = np.uint32(hi >> 32)
                    bounds["hi_lo"][lane] = np.uint32(hi & 0xFFFFFFFF)
                    bounds["hi_hi2"][lane] = np.uint32(req.hi_rank2 >> 32)
                    bounds["hi_lo2"][lane] = np.uint32(
                        req.hi_rank2 & 0xFFFFFFFF)
                    bounds["hi_right"][lane] = req.hi_op == "lte"
                else:
                    bounds["hi_hi"][lane] = U32
                    bounds["hi_lo"][lane] = U32
                    bounds["hi_hi2"][lane] = U32
                    bounds["hi_lo2"][lane] = U32
                    bounds["hi_right"][lane] = True
                if req.type_handle is not None:
                    bounds["type_vec"][lane] = int(req.type_handle)
                if req.anchor is not None:
                    bounds["anchor"][lane] = int(req.anchor)
                bounds["desc"][lane] = bool(req.desc)
                out.lane_tickets.append((lane, t))
                lane += 1
            if out.lane_tickets:
                bcol, dcol = _cols()
                out.range_covered = dcol.covered
                self.stats.record_range_dispatch()
                with self._dispatch_cm("range", batch.bucket, dim):
                    out.dev_out = self._serve_range(view, bcol, dcol,
                                                    bounds)
        elif kind == "join":
            sig = batch.key[1]
            n = view.base.num_atoms
            # a memtable LINK can mint bindings anywhere in the tuple
            # space — not correctable against a compact device prefix.
            # Exact-at-collect discipline, join edition: while the dirty
            # set stays SMALL and pure-add, the batch still dispatches
            # on device and collect merges the per-lane correction
            # (tuples touching the dirty atoms — ROADMAP 2d); tombstones,
            # revalues, or a dirty set past ``join_dirty_max`` take the
            # whole batch to the exact host path as before (bounded by
            # the next compaction).
            dirty = self._join_dirty_info(view)
            plan = (None if dirty == "full"
                    else self._join_plan(sig, batch.tickets[0].request,
                                         view.base))
            if plan is None:
                out.host_tickets = list(batch.tickets)
            else:
                consts = np.zeros((batch.bucket, sig.n_consts),
                                  dtype=np.int32)
                lane = 0
                for t in batch.tickets:
                    cv = np.asarray(t.request.consts, dtype=np.int64)
                    if len(cv) and (cv.min() < 0 or cv.max() >= n):
                        out.host_tickets.append(t)  # beyond the base
                        continue
                    consts[lane] = cv
                    out.lane_tickets.append((lane, t))
                    lane += 1
                if out.lane_tickets:
                    out.join_plan = plan
                    out.join_dirty = dirty
                    with self._dispatch_cm("join", batch.bucket,
                                           len(plan.steps)):
                        with self.tracer.span("join.execute",
                                              sig=str(sig.atoms)):
                            ex = self._execute_join(view, plan, consts,
                                                    n_real=lane)
                    if ex.hub_lanes:
                        self.stats.record_join_hub_dispatch(ex.hub_lanes)
                    out.join_hub_lanes = int(ex.hub_lanes)
                    out.dev_out = (ex.counts, ex.trunc, ex.tuples)
        else:  # pragma: no cover - batch keys come from our own requests
            raise Unservable(f"unknown batch kind {kind!r}")
        if out.dev_out is not None:
            out.slot = self._dispatch_seq % 2
            self._dispatch_seq += 1
            self.stats.record_device_dispatch()
            if self.config.device_timing and self.tracer.enabled:
                out._t_launch = self.tracer.clock()
        return out

    def _capture_candidates(self, view) -> dict:
        """Memtable candidates' (targets, type), read ONCE per batch right
        after the view is pinned: collect-time corrections then evaluate
        pin-time state, not whatever the live graph mutated into while the
        device ran. A candidate whose record vanished inside the µs-wide
        pin→capture window is treated as dead — equivalent to having
        pinned a moment later. Node candidates (no targets) can never
        match a pattern and drop out here too."""
        g = self.graph
        recs = {}
        for h in (set(view.new_atoms) | view.revalued) - view.dead:
            try:
                ts = {int(t) for t in g.get_targets(h)}
                th = int(g.get_type_handle_of(h))
            except Exception:
                continue
            recs[h] = (ts, th)
        return recs

    # -- collect (sync: downloads compact results, corrects, resolves) -------
    def collect(self, launched: LaunchedBatch) -> list:
        from hypergraphdb_tpu.ops.setops import SENTINEL

        out = []
        view = launched.view
        if launched.dev_out is not None:
            if self.faults.enabled:
                # models the device RESULT download failing — host-only
                # batches (breaker-degraded / all-fallback) stay immune
                self.faults.check("serve.collect",
                                  kind=launched.batch.key[0])
            if launched._t_launch is not None:
                # opt-in device attribution: block on the async handles and
                # record the launch→ready wall delta for the batch's span
                from hypergraphdb_tpu.obs.device import block_timed

                _, t_ready = block_timed(launched.dev_out,
                                         self.tracer.clock)
                launched.t_device = (launched._t_launch, t_ready)
            kind = launched.batch.key[0]
            if kind == "join":
                return self._collect_join(launched)
            if kind == "range":
                return self._collect_range(launched)
            counts, first_r = (np.asarray(x) for x in launched.dev_out)
            if kind == "pattern":
                # batch-invariant memtable views, hoisted off the
                # per-lane path (a 1024-lane batch over a deep memtable
                # would otherwise rebuild these sets 1024×)
                drop = view.dead | view.revalued
                drop_arr = (np.fromiter(drop, dtype=np.int64)
                            if drop else np.empty(0, dtype=np.int64))
            for lane, ticket in launched.lane_tickets:
                row = first_r[lane]
                matches = row[row != SENTINEL].astype(np.int64)
                count = int(counts[lane])
                if kind == "bfs":
                    res = self._bfs_result(ticket.request, count, matches,
                                           view)
                else:
                    res = self._pattern_result(ticket.request, count,
                                               matches, view, drop_arr,
                                               launched.cand_records)
                out.append((ticket, res))
        out.extend(self._serve_host(launched.host_tickets, view.epoch))
        return out

    def _collect_join(self, launched: LaunchedBatch) -> list:
        """Join-batch result assembly: download the compact per-lane
        windows, permute tuple columns from the plan's elimination order
        back to the request's variable order, and re-serve any
        truncation-flagged lane exactly on host (a flagged count is a
        LOWER bound — honest, but not what a caller asked for).

        Batches dispatched under a small pure-add dirty memtable
        (``launched.join_dirty``) merge the per-lane correction here:
        the host enumerates exactly the tuples touching the dirty atoms
        (``join/host.host_join_touching`` — sound because a new link
        only ever mints tuples containing itself or its targets) and
        unions them into the device answer. Lanes whose device window is
        a PREFIX (count beyond top_r) re-serve on host instead — a
        prefix cannot absorb corrections, the pattern lane's rule."""
        view = launched.view
        sig = launched.batch.key[1]
        plan = launched.join_plan
        dirty = launched.join_dirty
        counts, trunc, tuples = (np.asarray(x) for x in launched.dev_out)
        perm = [plan.order.index(v) for v in sig.vars]
        top_r = self.config.top_r
        out = []
        for lane, ticket in launched.lane_tickets:
            try:
                rows = tuples[lane]
                rows = rows[rows[:, 0] >= 0][:, perm].astype(np.int64)
                count = int(counts[lane])
                if trunc[lane] or (dirty and count > len(rows)):
                    self.stats.record_host_fallback()
                    out.append((ticket,
                                self._host_join(ticket.request,
                                                view.epoch)))
                    continue
                if dirty:
                    from hypergraphdb_tpu.join.host import (
                        host_join_touching,
                    )

                    try:
                        extra = host_join_touching(
                            self.graph, sig.bind(ticket.request.consts),
                            dirty,
                        )
                    except Exception:  # noqa: BLE001 - odd shape → exact
                        self.stats.record_host_fallback()
                        out.append((ticket,
                                    self._host_join(ticket.request,
                                                    view.epoch)))
                        continue
                    if extra:
                        merged = sorted(
                            {tuple(int(x) for x in r) for r in rows}
                            | set(extra)
                        )
                        rows = np.asarray(merged, dtype=np.int64)
                        rows = rows.reshape(-1, len(sig.vars))[:top_r]
                        count = len(merged)
                    self.stats.record_join_partial_correction()
                    launched.join_partials += 1
                out.append((ticket, JoinResult(
                    "join", count, rows, sig.vars,
                    count > len(rows), view.epoch,
                )))
            except Exception as e:  # surface, don't kill the batch
                out.append((ticket, e))
        out.extend(self._serve_host(launched.host_tickets, view.epoch))
        return out

    def _collect_range(self, launched: LaunchedBatch) -> list:
        """Range-batch result assembly: download the compact per-lane
        windows and apply the LSM memtable correction — drop
        dead/revalued gids, host-evaluate the residual memtable
        candidates (atoms past the delta column's coverage, plus every
        revalued atom), merge in VALUE order. Prefix lanes (count beyond
        the compact window) with a non-empty correction set re-serve
        exactly on host — a prefix cannot absorb corrections, the
        pattern lane's rule."""
        from hypergraphdb_tpu.ops.setops import SENTINEL

        view = launched.view
        counts_f, first_r, covered, total = (
            np.asarray(x) for x in launched.dev_out
        )
        residual = view.new_atoms[launched.range_covered:]
        drop = view.dead | view.revalued
        # batch-invariant drop array, hoisted off the per-lane path (the
        # pattern collect's discipline: a 1024-lane batch over a deep
        # memtable must not rebuild this conversion 1024×)
        drop_arr = (np.fromiter(drop, dtype=np.int64)
                    if drop else np.empty(0, dtype=np.int64))
        cands = (set(residual) | view.revalued) - view.dead
        # filtered lanes need the FULL memtable candidate set: the
        # kernel's type filter reads the BASE type_of column (a
        # delta-column gid is -1 there) and the anchor filter probes the
        # BASE incidence row (a memtable link incident to the anchor is
        # not in it) — such atoms are masked out on device (never
        # falsely in), so the host merge must re-offer every fresh atom
        # through the live-graph predicate, not just the uncovered
        # residual. Built only when some lane actually carries a filter
        # (an unfiltered range-heavy batch must not pay O(|memtable|)
        # per collect).
        cands_full = (
            (set(view.new_atoms) | view.revalued) - view.dead
            if any(t.request.type_handle is not None
                   or t.request.anchor is not None
                   for _, t in launched.lane_tickets)
            else cands
        )
        out = []
        for lane, ticket in launched.lane_tickets:
            try:
                req = ticket.request
                out.append((ticket, self._range_result(
                    req, int(counts_f[lane]),
                    first_r[lane][first_r[lane] != SENTINEL],
                    bool(covered[lane]), int(total[lane]), view,
                    drop_arr,
                    cands_full
                    if (req.type_handle is not None
                        or req.anchor is not None) else cands,
                )))
            except Exception as e:  # surface, don't kill the batch
                out.append((ticket, e))
        out.extend(self._serve_host(launched.host_tickets, view.epoch))
        return out

    def _range_result(self, req: RangeRequest, count_f: int,
                      matches: np.ndarray, covered: bool, total: int,
                      view, drop_arr: np.ndarray, cands: set):
        filtered = req.type_handle is not None or req.anchor is not None
        if filtered and not covered:
            # the window outran the gather pad under a filter: neither
            # count nor prefix is reconstructible on device
            self.stats.record_host_fallback()
            return self._host_range(req, view.epoch)
        count = count_f if filtered else total
        top_r = self.config.top_r
        upto = min(req.limit if req.limit is not None else top_r, top_r)
        if count <= len(matches):
            # the complete filtered set is in hand: corrections merge
            # exactly (the LSM read-merge, value edition)
            matches = matches.astype(np.int64)
            if len(drop_arr) and len(matches):
                matches = matches[~np.isin(matches, drop_arr)]
            keys = self._range_keys(req) if cands else None
            fresh = [h for h in cands
                     if self._range_matches_host(req, h, keys)]
            if fresh:
                matches = self._range_order(
                    req, np.union1d(matches,
                                    np.asarray(fresh, dtype=np.int64))
                )
            count = len(matches)
            matches = matches[:upto]
            return ServeResult("range", count, matches,
                               count > len(matches), view.epoch)
        # prefix shape: count exact, matches an honest value-ordered
        # prefix — but only while the memtable is quiet for this view
        if len(drop_arr) or cands:
            self.stats.record_host_fallback()
            return self._host_range(req, view.epoch)
        return ServeResult("range", count,
                           matches[:upto].astype(np.int64),
                           count > upto, view.epoch)

    # -- range lane helpers ---------------------------------------------------
    def _range_keys(self, req: RangeRequest) -> tuple:
        """(lo_key, hi_key) order-preserving byte bounds of one request —
        the host comparison unit (exact for every kind, unlike the
        64-bit ranks). None = open."""
        ts = self.graph.typesystem

        def key_of(v):
            if v is None:
                return None
            vt = ts.infer(v)
            if vt is None:
                raise Unservable(f"value {v!r} has no registered type")
            return vt.to_key(v)

        return key_of(req.values[0]), key_of(req.values[1])

    def _range_matches_host(self, req: RangeRequest, h: int,
                            keys: Optional[tuple] = None) -> bool:
        """Does live atom ``h`` satisfy the FULL request predicate —
        kind, bounds, type, anchor? The memtable-correction evaluator.
        ``keys`` lets per-candidate loops pass the request's bound keys
        computed ONCE (``_range_keys`` runs the typesystem) instead of
        re-deriving them per atom."""
        from hypergraphdb_tpu.storage.value_index import value_key_of

        g = self.graph
        if not g.contains(h):
            return False
        key = value_key_of(g, h)
        if key is None or key[0] != req.dim:
            return False
        lo_key, hi_key = keys if keys is not None else self._range_keys(req)
        payload = key[1:]
        if lo_key is not None:
            lo = lo_key[1:]
            if payload < lo or (payload == lo and req.lo_op == "gt"):
                return False
        if hi_key is not None:
            hi = hi_key[1:]
            if payload > hi or (payload == hi and req.hi_op == "lt"):
                return False
        if req.type_handle is not None and int(
            g.get_type_handle_of(h)
        ) != int(req.type_handle):
            return False
        if req.anchor is not None:
            try:
                if int(req.anchor) not in {
                    int(t) for t in g.get_targets(h)
                }:
                    return False
            except Exception:  # noqa: BLE001 - node candidate: no targets
                return False
        return True

    def _range_order(self, req: RangeRequest, gids: np.ndarray
                     ) -> np.ndarray:
        """Sort gids into the request's value order via their live keys
        (bounded work: only complete—≤ top_r—windows are ever merged)."""
        from hypergraphdb_tpu.storage.value_index import value_key_of

        g = self.graph
        keyed = []
        for h in gids.tolist():
            key = value_key_of(g, int(h))
            if key is not None:
                keyed.append((key[1:], int(h)))
        keyed.sort(key=lambda kv: (kv[0], kv[1]))
        if req.desc:
            # descending by value, gid-ascending within ties (the
            # kernel's complemented-rank order)
            keyed.sort(key=lambda kv: kv[1])
            keyed.sort(key=lambda kv: kv[0], reverse=True)
        return np.asarray([h for _, h in keyed], dtype=np.int64)

    def _host_range(self, req: RangeRequest, epoch: int) -> ServeResult:
        """Exact host oracle: walk the by-value system index in key
        order (the scan the device lane replaces), filter, and shape the
        result under the same order/limit/truncation contract."""
        from hypergraphdb_tpu.core.graph import IDX_BY_VALUE

        g = self.graph
        idx = g.store.get_index(IDX_BY_VALUE)
        kb = bytes([req.dim])
        lo_key, hi_key = self._range_keys(req)
        start = lo_key if lo_key is not None else kb
        matched: list[int] = []
        for key, handles in idx.bulk_items(lo=start):
            if key[:1] != kb:
                break  # past the dimension's key family
            if lo_key is not None and key == lo_key and req.lo_op == "gt":
                continue
            if hi_key is not None:
                if key > hi_key or (key == hi_key and req.hi_op == "lt"):
                    break
            for h in np.asarray(handles).tolist():
                h = int(h)
                if req.type_handle is not None and (
                    not g.contains(h)
                    or int(g.get_type_handle_of(h)) != int(req.type_handle)
                ):
                    continue
                if req.anchor is not None:
                    try:
                        if int(req.anchor) not in {
                            int(t) for t in g.get_targets(h)
                        }:
                            continue
                    except Exception:  # noqa: BLE001 - node candidate
                        continue
                matched.append(h)
        arr = self._range_order(req, np.asarray(matched, dtype=np.int64))
        top_r = self.config.top_r
        upto = min(req.limit if req.limit is not None else top_r, top_r)
        return ServeResult("range", len(arr), arr[:upto],
                           len(arr) > upto, epoch, served_by="host")

    def collect_host(self, launched: LaunchedBatch) -> list:
        """Exact host re-serve of the WHOLE batch — the collect-failure
        recovery path: the device handles are poisoned but the pinned
        epoch is still the right consistency label, so every ticket is
        answered by the exact host executors instead of erroring."""
        view = launched.view
        return self._serve_host(launched.batch.tickets,
                                0 if view is None else view.epoch)

    def _serve_host(self, tickets, epoch: int) -> list:
        """The ONE exact host-serving loop (fallback lanes, degraded
        batches, collect recovery): per-ticket dispatch with per-ticket
        exception capture — one failing request surfaces, never kills
        its batch."""
        out = []
        for ticket in tickets:
            self.stats.record_host_fallback()
            try:
                kind = ticket.request.kind
                if kind == "bfs":
                    out.append((ticket, self._host_bfs(ticket.request,
                                                       epoch)))
                elif kind == "join":
                    out.append((ticket, self._host_join(ticket.request,
                                                        epoch)))
                elif kind == "range":
                    out.append((ticket, self._host_range(ticket.request,
                                                         epoch)))
                else:
                    out.append((ticket, self._host_pattern(ticket.request,
                                                           epoch)))
            except Exception as e:  # surface, don't kill the batch
                out.append((ticket, e))
        return out

    # -- per-request result assembly -----------------------------------------
    def _bfs_result(self, req: BFSRequest, count: int,
                    matches: np.ndarray, view) -> ServeResult:
        if not req.include_seed and count > 0:
            # a live seed is always in its own visited set
            count -= 1
            matches = matches[matches != req.seed]
        matches = matches[: self.config.top_r]  # trim the spare slot
        truncated = count > len(matches)
        return ServeResult("bfs", count, matches, truncated, view.epoch)

    def _pattern_result(self, req: PatternRequest, count: int,
                        matches: np.ndarray, view, drop_arr: np.ndarray,
                        cand_records: dict) -> ServeResult:
        truncated = count > len(matches)
        if truncated and (len(drop_arr) or cand_records):
            # corrections against a prefix we cannot see past are not
            # reconstructible (a tombstone beyond the window would
            # overcount, a fresh link would punch a hole in the prefix) —
            # serve this rare shape exactly on host instead of bending
            # the count/prefix contract
            self.stats.record_host_fallback()
            return self._host_pattern(req, view.epoch)
        if truncated:
            # memtable quiet (checked above): device numbers are exact
            return ServeResult("pattern", count, matches, True, view.epoch)
        # LSM read-merge over the COMPLETE result set: drop links
        # tombstoned/revalued since the pack, evaluate the pattern over
        # the captured memtable records (pin-time state — never the live
        # graph) — exact at any delta lag.
        if len(drop_arr) and len(matches):
            matches = matches[~np.isin(matches, drop_arr)]
        fresh = [
            h for h, (ts, th) in cand_records.items()
            if all(a in ts for a in req.anchors)
            and (req.type_handle is None or th == int(req.type_handle))
        ]
        if fresh:
            matches = np.union1d(matches,
                                 np.asarray(fresh, dtype=np.int64))
        count = len(matches)
        top_r = self.config.top_r
        if count > top_r:
            # the merge pushed the full set past the compact window:
            # same shape contract as every other truncated result
            return ServeResult("pattern", count, matches[:top_r], True,
                               view.epoch)
        return ServeResult("pattern", count, matches, False, view.epoch)

    # -- join lane helpers ----------------------------------------------------
    def _join_dirty_info(self, view):
        """What the memtable holds that a join answer could see.
        Returns ``None`` — clean, device lane open with no correction;
        a sorted touched-atom list — small pure-ADD dirty set (every new
        link plus its targets, ≤ ``join_dirty_max`` atoms): the batch
        still dispatches on device and collect merges the per-lane
        correction (ROADMAP 2d); ``"full"`` — tombstones/revalues (a
        vanished witness is not correctable against a compact window)
        or a dirty set past the bound: the whole batch takes the exact
        host path. Fresh NODES alone never dirty anything (nothing in
        the base points at them).

        Memoized per epoch with incremental suffix scans — ``new_atoms``
        only grows within an epoch and the touched set only accumulates
        (the ``"full"`` verdict is sticky), so a bulk ingest costs each
        batch only the atoms that arrived since the last one, not an
        O(memtable) store walk on the dispatch thread."""
        if view.dead or view.revalued:
            return "full"
        epoch, n_seen, dirty = self._join_dirty_memo
        if epoch != view.epoch:
            n_seen, dirty = 0, frozenset()
        limit = self.config.join_dirty_max
        if dirty != "full" and len(view.new_atoms) > n_seen:
            g = self.graph
            acc = set(dirty)
            for h in view.new_atoms[n_seen:]:
                try:
                    ts = g.get_targets(h)
                except Exception:  # noqa: BLE001 - racing delete
                    continue
                if ts:
                    acc.add(int(h))
                    acc.update(int(t) for t in ts)
                    if len(acc) > limit:
                        acc = "full"
                        break
            dirty = acc if acc == "full" else frozenset(acc)
        self._join_dirty_memo = (view.epoch, len(view.new_atoms), dirty)
        if dirty == "full":
            return "full"
        return sorted(dirty) if dirty else None

    def _join_plan(self, sig, req0: JoinRequest, base):
        """The signature's compiled decomposition, planned once per
        (signature, base snapshot): the plan's statics ARE the program
        identity, so a cache hit here is a jit cache hit downstream. The
        first request's constants seed the cardinality estimates; the
        structure stays valid for every constant vector of the
        signature. None → the planner declined (host path)."""
        cache = getattr(base, "_join_plan_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(base, "_join_plan_cache", cache)
        if sig not in cache:
            from hypergraphdb_tpu.join.ir import JoinUnsupported
            from hypergraphdb_tpu.join.planner import plan_join
            from hypergraphdb_tpu.ops.join import (
                NBR_MAX_PAIRS,
                nbr_pair_count,
            )

            try:
                if any(a[0] == "co" for a in sig.atoms) and \
                        nbr_pair_count(base) > NBR_MAX_PAIRS:
                    # the co-incidence CSR would be gigabytes — decline
                    # BEFORE launch ever asks execute_join to build it
                    # on the dispatch thread
                    cache[sig] = None
                else:
                    with self.tracer.span("join.plan",
                                          sig=str(sig.atoms)):
                        cache[sig] = plan_join(
                            base, sig.bind(req0.consts), sig,
                            req0.consts,
                        )
                    if cache[sig] is not None and \
                            self.config.join_factorized:
                        # the trie encoding, built HERE (plan time, once
                        # per base epoch — the _nbr_csr discipline) so
                        # the O(E log E) grouping never lands inside a
                        # steady-state dispatch; execute_join picks it
                        # up via the snapshot cache. Its OWN failure
                        # (the closed-co build re-checks the pair
                        # budget, which a co-free signature never
                        # tripped above) must not poison the cached
                        # plan — the flat CSRs still serve it.
                        from hypergraphdb_tpu.ops.join import (
                            factorized_relations,
                        )

                        try:
                            with self.tracer.span("join.factorize"):
                                factorized_relations(base)
                        except Exception:  # noqa: BLE001 - flat serves
                            import logging

                            logging.getLogger(
                                "hypergraphdb_tpu.serve"
                            ).warning(
                                "trie factorization failed; join plan "
                                "serves from the flat CSRs",
                                exc_info=True,
                            )
            except JoinUnsupported:
                cache[sig] = None
        return cache[sig]

    def _host_join(self, req: JoinRequest, epoch: int) -> JoinResult:
        from hypergraphdb_tpu.join.host import host_join

        rows = host_join(self.graph, req.sig.bind(req.consts))
        V = len(req.sig.vars)
        arr = (np.asarray(rows, dtype=np.int64) if rows
               else np.empty((0, V), dtype=np.int64))
        top_r = self.config.top_r
        return JoinResult("join", len(arr), arr[:top_r], req.sig.vars,
                          len(arr) > top_r, epoch, served_by="host")

    # -- exact host fallbacks -------------------------------------------------
    def _host_bfs(self, req: BFSRequest, epoch: int) -> ServeResult:
        from hypergraphdb_tpu.algorithms.traversals import (
            HGBreadthFirstTraversal,
        )

        reached = {
            int(atom) for _, atom in HGBreadthFirstTraversal(
                self.graph, req.seed, max_distance=req.max_hops
            )
        }
        if req.include_seed:
            reached.add(int(req.seed))
        else:
            reached.discard(int(req.seed))
        arr = np.asarray(sorted(reached), dtype=np.int64)
        top_r = self.config.top_r
        return ServeResult("bfs", len(arr), arr[:top_r],
                           len(arr) > top_r, epoch, served_by="host")

    def _host_pattern(self, req: PatternRequest, epoch: int) -> ServeResult:
        from hypergraphdb_tpu.query import conditions as c

        clauses = [c.Incident(a) for a in req.anchors]
        if req.type_handle is not None:
            clauses.append(c.AtomType(int(req.type_handle)))
        cond = clauses[0] if len(clauses) == 1 else c.And(*clauses)
        arr = np.asarray(sorted(int(h) for h in self.graph.find_all(cond)),
                         dtype=np.int64)
        top_r = self.config.top_r
        return ServeResult("pattern", len(arr), arr[:top_r],
                           len(arr) > top_r, epoch, served_by="host")


def _make_executor(graph, config: ServeConfig, stats):
    """Pick the executor for one runtime: the mesh-sharded executor when
    ``ServeConfig(sharded=True)``, or — AUTO mode (``sharded=None``) —
    when more than one device is visible and the pinned base snapshot's
    estimated device footprint exceeds ``hbm_budget_bytes`` (the
    one-chip-cannot-hold-it trigger). Everything else stays on the
    single-chip :class:`DeviceExecutor`."""
    if config.sharded is False or graph is None:
        return DeviceExecutor(graph, config, stats)
    use = config.sharded is True
    if not use and config.hbm_budget_bytes is not None:
        import jax

        n_dev = len(jax.devices())
        if config.mesh_devices is not None:
            n_dev = min(n_dev, int(config.mesh_devices))
        if n_dev > 1:
            from hypergraphdb_tpu.serve.sharded import snapshot_device_bytes

            mgr = graph.incremental or graph.enable_incremental()
            use = snapshot_device_bytes(mgr.base) > config.hbm_budget_bytes
    if not use:
        return DeviceExecutor(graph, config, stats)
    from hypergraphdb_tpu.serve.sharded import ShardedExecutor

    return ShardedExecutor(graph, config, stats)


class ServeRuntime:
    """The serving front door. Threaded by default; ``manual=True`` for
    deterministic stepping (tests). Context manager: ``close(drain=True)``
    on exit."""

    def __init__(self, graph=None, config: Optional[ServeConfig] = None,
                 executor=None):
        self.config = config or ServeConfig()
        self.clock: Clock = self.config.clock or time.monotonic
        self.tracer = self.config.tracer or global_tracer()
        self.stats = ServeStats(self.config.latency_window)
        self.perf = self.config.perf
        self.faults = self.config.faults or global_faults()
        # per-batch-key breaker: a flaky device bucket trips to the exact
        # host-fallback path and recovers via half-open probes; the
        # per-key callbacks feed the labelled serve.breaker.* family
        # (the worst-state gauge alone cannot say WHICH bucket degraded)
        self.breaker = CircuitBreaker(
            threshold=self.config.breaker_threshold,
            cooldown_s=self.config.breaker_cooldown_s,
            clock=self.clock,
            on_state=self.stats.set_breaker_state,
            on_trip=self.stats.record_breaker_trip,
            on_key_state=self.stats.set_breaker_key_state,
            on_key_trip=self.stats.record_breaker_key_trip,
        )
        self._sleep: Callable = self.config.sleep or time.sleep
        # seeded jitter: retries are reproducible under a fixed seed
        self._retry_rng = random.Random(self.config.retry_seed)
        self.queue = AdmissionQueue(
            self.config.max_queue, self.config.policy, self.clock,
            self.stats,
        )
        self.batcher = Batcher(self.queue, self.config.buckets,
                               self.config.max_linger_s)
        self.executor = (
            executor if executor is not None
            else _make_executor(graph, self.config, self.stats)
        )
        self.graph = graph
        # deploy-time compile: load-or-build the serving executables for
        # every bucket BEFORE the dispatch thread takes traffic, so a
        # warm AOT cache reaches first dispatch without recompiling.
        # Runs with no cache too — the fused plan build + backend probe
        # must not wait for the first live request (injected executors
        # without a prewarm hook are skipped)
        if (self.config.prewarm_aot and graph is not None
                and callable(getattr(self.executor, "prewarm", None))):
            try:
                self.executor.prewarm(self.config.buckets)
            except Exception:  # pragma: no cover - never block startup
                import logging

                logging.getLogger("hypergraphdb_tpu.serve").warning(
                    "aot prewarm failed", exc_info=True,
                )
        #: in-flight batch: (tickets, executor token, batch key,
        #: device_attempted) — what _finalize needs, incl. the breaker's
        #: success/failure bookkeeping
        self._pending: Optional[tuple] = None
        #: attached hgsub SubscriptionManager (``attach_subscriptions``):
        #: the dispatch cycle drives its evaluator rounds, so standing
        #: queries re-fire on the SAME thread that forms batches — their
        #: evals coalesce with ad-hoc traffic by bucket key. Set before
        #: the thread starts; read with getattr-free attribute access on
        #: every cycle (None = one comparison)
        self.subscriptions = None
        #: attached hgplan ``QueryPlanner`` (``attach_planner``): the
        #: cost-based chooser behind ``submit_planned``. None = the
        #: planned entry point is simply unavailable
        self.planner = None
        self._closed = False
        self._close_started = False
        self._draining = False
        self._close_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        if not self.config.manual:
            self._thread = threading.Thread(
                target=self._loop, name="hgdb-serve", daemon=True
            )
            self._thread.start()

    # -- submit --------------------------------------------------------------
    def submit(self, request, deadline_s: Optional[float] = None,
               priority: int = 0, explain: bool = False) -> Future:
        """Admit one request; returns its future. Raises
        :class:`~.types.QueueFull` under fail-fast backpressure,
        :class:`~.types.RuntimeClosed` after close; a deadline that expires
        while blocked lands ON the future as DeadlineExceeded. A higher
        ``priority`` class pops first at batch formation (FIFO within a
        class); shedding and backpressure are priority-blind. An
        ``admission_gate`` refusal raises
        :class:`~.types.AdmissionGated` BEFORE any queue state is
        touched (routers re-route; the request costs this node
        nothing).

        ``explain=True`` requests per-request COST ATTRIBUTION: the
        request's trace is force-sampled and, at resolve time, an
        ``obs.fleet.explain_record`` (serving lane, bucket/pad
        occupancy, device seconds, retries, breaker state, trace id —
        assembled from the ticket's own span tree) is attached to the
        returned future as ``future.explain`` BEFORE the result is
        delivered. Requires tracing (the span tree IS the record's
        source): raises :class:`~.types.Unservable` when the runtime's
        tracer is disabled."""
        gate = self.config.admission_gate
        if gate is not None:
            reason = gate()
            if reason:
                self.stats.record_gated()
                from hypergraphdb_tpu.serve.types import AdmissionGated

                raise AdmissionGated(str(reason))
        if explain and not self.tracer.enabled:
            raise Unservable(
                "explain=True needs tracing: enable the runtime's tracer "
                "(obs.enable(), or ServeConfig(tracer=Tracer().enable()))"
            )
        now = self.clock()
        dl = (deadline_s if deadline_s is not None
              else self.config.default_deadline_s)
        ticket = Ticket(
            request=request, submit_t=now,
            deadline_t=None if dl is None else now + dl,
            priority=int(priority), explain=bool(explain),
        )
        if self.tracer.enabled:  # the ONE gate read on the disabled path
            self._trace_submit(ticket)
            if explain and ticket.trace is not None:
                # the record is built from the FINISHED trace — an
                # explain request must survive any head sampling rate
                ticket.trace.force_sample()
        try:
            self.queue.submit(ticket)
        except Exception as e:
            ticket._close_trace("error", error=type(e).__name__)
            raise
        tr = ticket.trace
        if tr is not None:
            # ending is race-safe: if the dispatch thread already finished
            # the trace, the first end (finish's) won
            tr.marks["submit"].end()
        return ticket.future

    def _trace_submit(self, ticket: Ticket) -> None:
        """Open the request's trace: ``request`` root + ``submit`` and
        ``queue_wait`` spans. BOTH open before the ticket becomes visible
        to the dispatch thread — the thread may form, launch, and resolve
        the batch before ``queue.submit`` even returns to the caller, so
        every mark it pops must already exist (under ``block``
        backpressure ``queue_wait`` therefore includes the blocked-in-
        submit time)."""
        tr = self.tracer.start_trace(
            "serve.request", kind=ticket.request.kind,
            priority=ticket.priority,
        )
        if tr is None:
            return
        root = tr.start_span("request")
        tr.marks["root"] = root
        tr.marks["submit"] = tr.start_span("submit", parent=root)
        tr.marks["queue_wait"] = tr.start_span("queue_wait", parent=root)
        ticket.trace = tr

    def submit_bfs(self, seed: int, max_hops: Optional[int] = None,
                   deadline_s: Optional[float] = None,
                   include_seed: bool = True, priority: int = 0,
                   explain: bool = False) -> Future:
        return self.submit(
            BFSRequest(int(seed),
                       max_hops if max_hops is not None
                       else self.config.default_max_hops,
                       include_seed),
            deadline_s, priority, explain,
        )

    def submit_pattern(self, anchors: Sequence[int],
                       type_handle: Optional[int] = None,
                       deadline_s: Optional[float] = None,
                       priority: int = 0, explain: bool = False) -> Future:
        return self.submit(
            PatternRequest(tuple(int(a) for a in anchors),
                           None if type_handle is None
                           else int(type_handle)),
            deadline_s, priority, explain,
        )

    def submit_join(self, spec, distinct: bool = True,
                    deadline_s: Optional[float] = None,
                    priority: int = 0, explain: bool = False) -> Future:
        """Admit a conjunctive-pattern JOIN: ``spec`` is either a
        prebuilt :class:`~.types.JoinRequest` or a ``{var: condition}``
        mapping with ``query.variables.Var`` cross-references
        (``query/bridge.to_join_request`` does the extraction). Raises
        :class:`~.types.Unservable` for specs outside the pattern
        vocabulary. Resolves to a :class:`~.types.JoinResult`."""
        if not isinstance(spec, JoinRequest):
            from hypergraphdb_tpu.query.bridge import to_join_request

            spec = to_join_request(self.graph, spec, distinct=distinct)
        return self.submit(spec, deadline_s, priority, explain)

    def submit_range(self, lo=None, hi=None, *, lo_op: str = "gte",
                     hi_op: str = "lte", type_handle: Optional[int] = None,
                     anchor: Optional[int] = None, desc: bool = False,
                     limit: Optional[int] = None,
                     deadline_s: Optional[float] = None,
                     priority: int = 0, explain: bool = False) -> Future:
        """Admit a value RANGE / ordered / top-k request (the hgindex
        lane): atoms whose value lies in the ``[lo, hi]`` window of the
        bounds' kind, in value order (``desc=True`` flips it),
        optionally type-filtered / ``anchor``-incident / ``limit``-ed.
        Resolves to a :class:`~.types.ServeResult` with kind
        ``"range"``. Raises :class:`~.types.Unservable` for unbounded or
        mixed-kind windows."""
        from hypergraphdb_tpu.query.bridge import to_range_request

        return self.submit(
            to_range_request(self.graph, lo, hi, lo_op=lo_op, hi_op=hi_op,
                             type_handle=type_handle, anchor=anchor,
                             desc=desc, limit=limit),
            deadline_s, priority, explain,
        )

    def submit_query(self, condition,
                     deadline_s: Optional[float] = None,
                     priority: int = 0) -> Future:
        """Admit a query CONDITION (the batchable subset — see
        ``query/bridge``). Raises :class:`~.types.Unservable` for
        conditions outside it."""
        from hypergraphdb_tpu.query.bridge import to_request

        return self.submit(
            to_request(self.graph, condition,
                       default_max_hops=self.config.default_max_hops),
            deadline_s, priority,
        )

    # -- planned submission (hgplan) -----------------------------------------
    def attach_planner(self, planner) -> None:
        """Wire an hgplan ``QueryPlanner`` into this runtime: the
        planner's telemetry binds to THIS runtime's ``ServeStats``
        (``plan.*`` metrics ride the serving registry) and — unless the
        planner already carries one — its sentinel guard binds to this
        runtime's perf sentinel (a learned correction may never steer
        the argmin onto a lane currently listed in the sentinel's
        ``violating`` set). ``submit_planned`` is refused until this is
        called."""
        with self._close_lock:
            planner.stats = self.stats
            if planner.lane_degraded is None and self.perf is not None:
                perf = self.perf

                def _lane_degraded(kind: str) -> bool:
                    try:
                        return kind in perf.health_summary().get(
                            "violating", ())
                    except Exception:
                        return False  # a perf fault must not veto plans

                planner.lane_degraded = _lane_degraded
            self.planner = planner

    def submit_planned(self, condition, deadline_s: Optional[float] = None,
                       priority: int = 0, explain: bool = False,
                       force_shape: Optional[str] = None) -> Future:
        """Admit a query CONDITION through the attached cost-based
        planner: enumerate the candidate lane strategies, dispatch the
        cheapest (``force_shape`` overrides — the differential suite's
        hook), host-filter the residual clauses, and resolve to a
        ``plan.PlannedResult`` whose ``plan`` dict carries
        ``est_rows`` / ``actual_rows`` / the chosen shape. With
        ``explain=True`` the future's ``.explain`` record grows the same
        ``plan`` sub-dict beside the lane attribution (the host shape
        synthesizes a minimal record — no lane, no trace).

        Exactness contract matches ``graph.find_all(condition)``: a
        truncated lane window is re-served brute-force on the host, so
        the planner can be WRONG about cost but never about results."""
        planner = self.planner
        if planner is None:
            raise Unservable(
                "no planner attached: build a plan.QueryPlanner and "
                "attach_planner() it before submit_planned"
            )
        choice = planner.plan(condition, force_shape=force_shape)
        if choice.request is None:
            return self._planned_host(planner, choice, explain)
        inner = self.submit(choice.request, deadline_s, priority, explain)
        outer: Future = Future()

        def _done(f: Future) -> None:
            try:
                res = f.result()
            except Exception as e:
                outer.set_exception(e)
                return
            try:
                out = self._finish_planned(planner, choice, res)
                if explain:
                    ex = dict(getattr(f, "explain", None) or {})
                    ex["plan"] = out.plan
                    outer.explain = ex
            except Exception as e:  # residual/feedback fault → caller
                outer.set_exception(e)
                return
            outer.set_result(out)

        inner.add_done_callback(_done)
        return outer

    def _planned_host(self, planner, choice, explain: bool) -> Future:
        """The host shape: no lane, no queue — the exact scan the
        brute-force oracle defines, executed inline on the caller."""
        matches = tuple(sorted(
            int(h) for h in self.graph.find_all(choice.condition)))
        planner.observe(choice, len(matches))
        plan_rec = choice.explain()
        plan_rec["actual_rows"] = len(matches)
        from hypergraphdb_tpu.plan.planner import PlannedResult

        res = PlannedResult(
            kind="planned", count=len(matches), matches=matches,
            truncated=False, epoch=choice.epoch, lane_kind="host",
            served_by="host", plan=plan_rec,
        )
        fut: Future = Future()
        if explain:
            fut.explain = {"lane": {"kind": "host", "path": "host"},
                           "plan": plan_rec}
        fut.set_result(res)
        return fut

    def _finish_planned(self, planner, choice, res):
        """Turn one lane result into the planned answer: close the
        feedback loop on the PRE-residual row count, then either apply
        the residual filter or — when the lane window truncated — fall
        back to the exact host scan (truncation-honest results have an
        exact ``count`` but only a prefix of ``matches``; filtering a
        prefix would silently drop rows)."""
        actual = int(res.count)
        planner.observe(choice, actual)
        plan_rec = choice.explain()
        plan_rec["actual_rows"] = actual
        truncated = bool(res.truncated)
        if truncated:
            matches = tuple(sorted(
                int(h) for h in self.graph.find_all(choice.condition)))
            served_by = "host"
        else:
            if getattr(res, "kind", None) == "join":
                # single-variable condition join: project the "x" column
                # and dedupe — distinct=False keeps one row per
                # WITNESSING binding (auxiliary link vars), not per atom
                col = res.vars.index("x") if "x" in res.vars else 0
                rows = {int(t[col]) for t in res.tuples}
            else:
                rows = {int(h) for h in res.matches}
            g = self.graph
            matches = tuple(sorted(
                h for h in rows
                if all(cl.satisfies(g, h) for cl in choice.residual)))
            served_by = res.served_by
        from hypergraphdb_tpu.plan.planner import PlannedResult

        return PlannedResult(
            kind="planned", count=len(matches), matches=matches,
            truncated=False, epoch=getattr(res, "epoch", choice.epoch),
            lane_kind=res.kind, served_by=served_by, plan=plan_rec,
        )

    # -- dispatch ------------------------------------------------------------
    def attach_subscriptions(self, manager) -> None:
        """Wire an hgsub ``SubscriptionManager`` into the dispatch
        cycle: every ``step``/``pump`` runs one evaluator round before
        batch formation (dirty standing queries re-enter the admission
        queue and coalesce with ad-hoc lanes) and one after finalize
        (completed evals notify within the same wake)."""
        with self._close_lock:
            self.subscriptions = manager

    def _pump_subs(self) -> None:
        m = self.subscriptions
        if m is None:
            return
        try:
            m.pump()
        except Exception:  # the evaluator must never stall dispatch
            import logging

            logging.getLogger("hypergraphdb_tpu.serve").exception(
                "subscription pump error (continuing)"
            )

    def step(self, drain: bool = False) -> bool:
        """ONE synchronous collect→launch→finalize cycle (manual mode /
        tests). Returns whether a batch was dispatched."""
        self._pump_subs()
        t_form = self.tracer.clock() if self.tracer.enabled else None
        batch = self.batcher.next_batch(self.clock(), drain=drain)
        if batch is None:
            return False
        inflight = self._launch_guarded(batch, t_form)
        if inflight is not None:
            self.stats.record_batch(len(inflight[0]), batch.bucket)
            self._finalize(*inflight)
            self._pump_subs()
        return True

    def pump(self, drain: bool = False) -> bool:
        """One PIPELINED cycle: launch the next batch (if any), THEN
        finalize the previously launched one — host assembly of batch N+1
        overlaps device execution of batch N. Returns whether a new batch
        was consumed."""
        self._pump_subs()
        t_form = self.tracer.clock() if self.tracer.enabled else None
        batch = self.batcher.next_batch(self.clock(), drain=drain)
        inflight = None
        if batch is not None:
            inflight = self._launch_guarded(batch, t_form)
            if inflight is not None:
                self.stats.record_batch(len(inflight[0]), batch.bucket)
        prev = self._take_pending()
        if prev is not None:
            self._finalize(*prev)
            self._pump_subs()
        with self._close_lock:
            self._pending = inflight
        return batch is not None

    def _launch_guarded(self, batch, t_form=None):
        """Launch with the self-healing ladder, converting executor
        errors into per-ticket outcomes instead of a dead dispatch
        thread: transient failures get bounded exponential backoff +
        seeded jitter that respects each ticket's remaining deadline
        (a ticket whose deadline falls inside the next sleep is shed NOW,
        never parked past it); permanent failures surface typed to every
        caller; K consecutive device failures trip the batch key's
        circuit breaker, and a tripped/OPEN key re-routes the batch —
        including the one that tripped it — to the exact host-fallback
        path. Returns ``(tickets, token, key, device_attempted)`` for
        ``_finalize``, or None when every ticket was already completed.

        Traced tickets get their ``queue_wait`` closed and
        ``batch_form``/``launch`` spans here — the whole block is behind
        one ``tracer.enabled`` read; the ``launch`` span covers ALL
        attempts. ``t_form`` is the caller's pre-``next_batch``
        timestamp, so ``batch_form`` covers the REAL formation work."""
        tracer = self.tracer
        traced = tracer.enabled
        if traced:
            if t_form is None:
                t_form = tracer.clock()
            n_real = len(batch.tickets)
            pending = []
            for t in batch.tickets:
                tr = t.trace
                if tr is not None and not tr.finished:
                    qw = tr.marks.pop("queue_wait", None)
                    # clamp per ticket: a request submitted AFTER the
                    # caller's t_form capture but in time for take() must
                    # not get a negative queue_wait / a batch_form that
                    # predates its own birth
                    t0_i = t_form
                    if qw is not None:
                        t0_i = max(t_form, qw.t0)
                        qw.end(t0_i)
                    pending.append((tr, t0_i))
            t_l0 = tracer.clock()
            for tr, t0_i in pending:
                if not tr.finished:
                    tr.add_span(
                        "batch_form", t0_i, max(t_l0, t0_i),
                        parent=tr.marks.get("root"), bucket=batch.bucket,
                        n_real=n_real, n_pad=batch.bucket - n_real,
                    )
        key = batch.key
        cfg = self.config
        attempt = 0
        while True:
            device = not batch.force_host and self.breaker.allow(key)
            batch.force_host = not device
            try:
                launched = self.executor.launch(batch)
            except Exception as e:
                if not device:
                    # the DEGRADED path itself failed: no ladder left
                    self._fail_batch(batch.tickets, e)
                    return None
                self.breaker.record_failure(key)
                if not is_transient(e, cfg.transient_errors):
                    self._fail_batch(batch.tickets, e)
                    return None
                attempt += 1
                if self.breaker.state_of(key) == OPEN:
                    # this failure tripped the breaker: serve THIS batch
                    # on host immediately — degraded throughput, not a
                    # batch of errors (and no backoff: host is local).
                    # The tripping batch's traces are always-sample: a
                    # trip is exactly the window an operator replays
                    for t in batch.tickets:
                        if t.trace is not None:
                            t.trace.force_sample()
                    continue
                if attempt > cfg.max_retries:
                    self._fail_batch(batch.tickets, e)
                    return None
                self.stats.record_retry()
                if _FLIGHT.enabled:
                    _FLIGHT.record("serve.retry", key=str(key),
                                   attempt=attempt,
                                   error=type(e).__name__)
                if not self._backoff(batch, attempt):
                    return None  # every ticket's deadline < next attempt
                continue
            break
        if traced:
            t_l1 = tracer.clock()
            for t in batch.tickets:
                tr = t.trace
                if tr is not None and not tr.finished:
                    # retries = transient re-attempts this batch paid
                    # (0 on the clean path) — the EXPLAIN record's
                    # retry attribution reads it off this span
                    tr.add_span("launch", t_l0, t_l1,
                                parent=tr.marks.get("root"),
                                retries=attempt)
        return batch.tickets, launched, key, device

    def _backoff(self, batch, attempt: int) -> bool:
        """Sleep the capped exponential backoff (seeded jitter) before
        re-attempting a transient launch failure — deadline-aware:
        tickets whose deadline falls inside the sleep are shed NOW (the
        retry could never answer them), and with none left the batch is
        abandoned. Returns whether anything is left to retry."""
        cfg = self.config
        dt = min(cfg.retry_base_s * (2.0 ** (attempt - 1)), cfg.retry_max_s)
        dt *= 1.0 + cfg.retry_jitter * self._retry_rng.random()
        now = self.clock()
        wake = now + dt
        live = []
        for t in batch.tickets:
            if t.expired(wake):
                t.shed(now)
                self.stats.record_shed()
            else:
                live.append(t)
        batch.tickets = live
        if not live:
            return False
        self._sleep(dt)
        return True

    def _fail_batch(self, tickets, exc: BaseException) -> None:
        if tickets and _FLIGHT.enabled:
            # a typed serve error is an incident: the recorder dumps the
            # window that led here (rate-limited; counting is always on)
            _FLIGHT.incident("serve_error", error=type(exc).__name__,
                             tickets=len(tickets))
        for t in tickets:
            if t.fail(exc):
                self.stats.record_error()

    def _take_pending(self):
        """Swap the in-flight (tickets, token) pair out under the state
        lock (the lock covers only the pointer — finalize's blocking
        download runs outside it)."""
        with self._close_lock:
            prev, self._pending = self._pending, None
            return prev

    def _pending_empty(self) -> bool:
        with self._close_lock:
            return self._pending is None

    def _finalize(self, tickets, token, key=None, device=False) -> None:
        tracer = self.tracer
        traced = tracer.enabled
        t_c0 = tracer.clock() if traced else 0.0
        try:
            results = self.executor.collect(token)
        except Exception as e:
            results = self._recover_collect(tickets, token, key, device, e)
            if results is None:
                return
        else:
            if device and key is not None:
                self.breaker.record_success(key)
        if traced:
            t_c1 = tracer.clock()
            t_dev = getattr(token, "t_device", None)
            slot = getattr(token, "slot", -1)
            if t_dev is not None:
                # one histogram observation per measured batch — the
                # device-time distribution BENCH_C6 summarizes
                self.stats.record_device_time(t_dev[1] - t_dev[0])
                if self.perf is not None and key is not None:
                    # the perf sentinel's device-seconds/request digest
                    # (guarded like EXPLAIN: a sentinel bug must degrade
                    # observability, never the batch)
                    try:
                        self.perf.observe_batch(
                            key[0], t_dev[1] - t_dev[0],
                            n_real=len(getattr(token, "lane_tickets",
                                               ()) or ()),
                            n_total=getattr(getattr(token, "batch", None),
                                            "bucket", 0) or 0,
                            t=self.clock(),
                        )
                    except Exception:  # noqa: BLE001
                        self.stats.record_perf_error()
            for ticket, res in results:
                tr = ticket.trace
                if tr is None or tr.finished:
                    continue
                root = tr.marks.get("root")
                served_by = getattr(res, "served_by", None)
                if t_dev is not None and served_by == "device":
                    tr.add_span("device", t_dev[0], t_dev[1], parent=root,
                                slot=slot)
                tr.add_span("collect", t_c0, t_c1, parent=root)
                if served_by == "host":
                    tr.add_span("host_fallback", t_c0, t_c1, parent=root)
        now = self.clock()
        device_lane = getattr(self.executor, "device_lane", "device")
        for ticket, res in results:
            if isinstance(res, BaseException):
                if ticket.fail(res):
                    self.stats.record_error()
            else:
                path = ("host"
                        if getattr(res, "served_by", None) == "host"
                        else device_lane)
                if ticket.explain:
                    self._attach_explain(ticket, res, key, path, token)
                if ticket.resolve(res):
                    # a cancel()ed future neither raises out of the
                    # dispatch thread nor counts as a completion
                    self.stats.record_complete(now - ticket.submit_t)
                    self.stats.record_lane(res.kind, path)
                    if self.perf is not None:
                        try:
                            self.perf.observe(res.kind,
                                              now - ticket.submit_t,
                                              path=path, t=now)
                        except Exception:  # noqa: BLE001
                            self.stats.record_perf_error()
        if self.perf is not None:
            # rate-limited drift evaluation rides the completion path —
            # the sentinel has no thread of its own. Guarded: an
            # evaluation bug raising out of _finalize would unwind
            # pump() before the NEXT batch's pending handoff and strand
            # its tickets — observability must never cost a request
            try:
                self.perf.maybe_tick()
            except Exception:  # noqa: BLE001
                import logging

                logging.getLogger("hypergraphdb_tpu.serve").warning(
                    "perf sentinel tick failed (continuing)",
                    exc_info=True,
                )

    def _attach_explain(self, ticket, res, key, path: str,
                        token=None) -> None:
        """The EXPLAIN resolve path: finish the ticket's trace EARLY
        (terminal ``resolve`` — ``Ticket.resolve``'s own close then
        no-ops, first-end-wins) and attach the cost-attribution record
        to the future BEFORE the result is delivered, so a caller
        reading ``fut.result()`` then ``fut.explain`` never races this
        thread. The record is assembled FROM the finished span tree
        (``obs.fleet.explain_record``) — the one source of truth the
        fleet trace view also serves. Join requests additionally carry
        the batch's plan-shape/hub/correction attribution read off the
        launched token (``_join_explain``)."""
        tr = ticket.trace
        if tr is None:
            return
        tr.finish_terminal("resolve", parent=tr.marks.get("root"))
        from hypergraphdb_tpu.obs.fleet import explain_record

        try:
            ticket.future.explain = explain_record(
                tr, result=res, lane_path=path,
                breaker_state=(None if key is None
                               else self.breaker.state_of(key)),
                shard_owner=self._shard_owner(ticket.request),
                join=self._join_explain(res, path, token),
            )
        except Exception:  # noqa: BLE001 - never fail a resolve over EXPLAIN
            ticket.future.explain = None

    @staticmethod
    def _join_explain(res, path: str, token):
        """Join-engine attribution for the EXPLAIN record (ROADMAP: the
        PR-13 records predate join engine v2): the chosen plan shape —
        ``bushy`` (GHD bag decomposition) / ``hub`` (degree-split
        dense-frontier lanes in this batch) / ``flat`` (the PR-10 step
        chain) / ``host`` (exact host path, no device plan) — plus the
        batch's ``hub_dispatches`` and collect-side
        ``partial_corrections`` (batch-level counts: the request reports
        the dispatch it rode, the per-batch twin of the
        ``serve.join.*`` counters). None for non-join requests."""
        if getattr(res, "kind", None) != "join":
            return None
        plan = getattr(token, "join_plan", None)
        hub = int(getattr(token, "join_hub_lanes", 0) or 0)
        if path == "host" or plan is None:
            shape = "host"
        elif type(plan).__name__ == "BushyJoinPlan":
            shape = "bushy"
        elif hub:
            shape = "hub"
        else:
            shape = "flat"
        return {
            "plan": shape,
            "hub_dispatches": hub,
            "partial_corrections": int(
                getattr(token, "join_partials", 0) or 0),
        }

    def _shard_owner(self, request):
        """The mesh partition that owns this request's primary id (the
        EXPLAIN record's placement attribution), or None off the sharded
        executor / for gid-addressed shapes with no raw ids."""
        ex = self.executor
        if getattr(ex, "mesh", None) is None:
            return None
        sbase = getattr(getattr(ex, "mgr", None), "_sharded_base", None)
        pmap = getattr(sbase, "partition_map", None)
        if pmap is None:
            return None
        rid = getattr(request, "seed", None)
        if rid is None:
            anchors = getattr(request, "anchors", None)
            if not anchors:
                return None
            rid = max(anchors)
        try:
            return int(pmap.owner_of(int(rid)))
        except Exception:  # noqa: BLE001 - ids beyond the map: unowned
            return None

    def _recover_collect(self, tickets, token, key, device,
                         exc: BaseException):
        """A collect failure poisons the whole batch's device handles;
        the recovery is an exact host re-serve under the same pinned
        epoch (the executor's ``collect_host`` hook), not a device retry
        — the async results are gone either way. Feeds the breaker like
        any other device failure. Returns replacement results, or None
        after failing every ticket typed."""
        if device and key is not None:
            self.breaker.record_failure(key)
        host = getattr(self.executor, "collect_host", None)
        if host is not None and is_transient(exc,
                                             self.config.transient_errors):
            self.stats.record_retry()
            try:
                return host(token)
            except Exception as e2:
                exc = e2
        self._fail_batch(tickets, exc)
        return None

    def _loop(self) -> None:
        import logging

        log = logging.getLogger("hypergraphdb_tpu.serve")
        while True:
            try:
                if self._closed and not self._draining:
                    prev = self._take_pending()
                    if prev is not None:
                        self._finalize(*prev)
                    self.queue.cancel_all()
                    return
                worked = self.pump(drain=self._draining)
                if worked:
                    continue  # keep forming batches while the device runs
                # exit only once _closed is set (which happens AFTER
                # admission closed): no submit can land behind our back
                if (self._closed and self._draining
                        and self.queue.depth() == 0
                        and self._pending_empty()):
                    return
                ttf = self.batcher.time_to_flush(self.clock())
                if ttf is None:
                    # empty queue: wait_for_work's non-empty pre-check
                    # makes the submit-before-wait race safe for an
                    # unbounded park
                    self.queue.wait_for_work(None)
                else:
                    # items queued but linger remaining: sleep the
                    # remainder (a submit filling the bucket notifies and
                    # wakes us early; a missed wakeup costs at most
                    # max_linger_s)
                    self.queue.park(ttf)
            except Exception:
                # the per-batch paths already route errors onto tickets;
                # anything landing here is a runtime bug — log it and
                # keep serving rather than stranding every future caller
                log.exception("serve dispatch loop error (continuing)")
                time.sleep(0.01)  # no hot-spin on a persistent fault

    # -- lifecycle -----------------------------------------------------------
    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admitting and shut down. ``drain=True`` flushes and
        completes everything queued and in flight; ``drain=False``
        completes only the in-flight batch and fails queued tickets with
        RuntimeClosed."""
        with self._close_lock:
            already = self._close_started
            self._close_started = True
            if not already:
                self._draining = drain
        if not already:
            # admission closes BEFORE the thread sees _closed: a submit
            # racing close() either lands while the thread still serves or
            # raises RuntimeClosed — never a silently stranded ticket
            self.queue.close()
            with self._close_lock:
                self._closed = True
        if self._thread is not None:
            self._thread.join(timeout)
            return
        if already:
            return
        # manual mode: run the shutdown inline, deterministically
        prev = self._take_pending()
        if prev is not None:
            self._finalize(*prev)
        if drain:
            while self.step(drain=True):
                pass
        else:
            self.queue.cancel_all()

    def __enter__(self) -> "ServeRuntime":
        return self

    def __exit__(self, *exc) -> None:
        self.close(drain=True)

    def stats_snapshot(self) -> dict:
        out = self.stats.snapshot(queue_depth=self.queue.depth())
        aot = getattr(self.executor, "aot", None)
        if aot is not None:
            out["aot"] = aot.stats.as_dict()
        return out
