"""Serving vocabulary: requests, results, errors, tickets.

No jax imports here — the deterministic tier-1 runtime tests drive the
whole admission/batching machinery with a fake executor and never touch a
device.
"""

from __future__ import annotations

from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

#: injectable time source (seconds, monotonic) — tests pass a fake
Clock = Callable[[], float]


class ServeError(Exception):
    """Base class of every serving-runtime error."""


class DeadlineExceeded(ServeError):
    """The request's deadline expired before a device dispatch — shed in
    the admission queue (load shedding), the dispatch was never paid."""

    def __init__(self, waited_s):
        # the router re-raises this across an HTTP hop with the server's
        # error body as the message — only a local shed knows the wait
        if isinstance(waited_s, (int, float)):
            super().__init__(
                f"deadline exceeded after {waited_s * 1e3:.1f} ms "
                "in the admission queue")
            self.waited_s = float(waited_s)
        else:
            super().__init__(str(waited_s))
            self.waited_s = None


class QueueFull(ServeError):
    """Fail-fast admission: the bounded queue was full (backpressure)."""


class RuntimeClosed(ServeError):
    """Submitted to (or cancelled by) a closed runtime."""


class Unservable(ServeError):
    """The condition/request is outside the batchable subset — run it
    through ``graph.find_all`` instead."""


class AdmissionGated(ServeError):
    """The runtime's ``admission_gate`` refused this request — the node
    is temporarily unfit to answer within its contract (e.g. a replica
    whose replication lag exceeds its staleness bound). Retry elsewhere:
    a router treats this as "re-route", never as a caller error."""


# ---------------------------------------------------------------- requests


@dataclass(frozen=True)
class BFSRequest:
    """K-batchable BFS: atoms reachable from ``seed`` within ``max_hops``.

    Matches ``query.conditions.BFS`` semantics when ``include_seed`` is
    False (the condition's default excludes the start atom)."""

    seed: int
    max_hops: int
    include_seed: bool = True

    @property
    def kind(self) -> str:
        return "bfs"

    @property
    def batch_key(self) -> tuple:
        # max_hops is a static kernel arg — one compiled program per value
        return ("bfs", self.max_hops)


@dataclass(frozen=True)
class PatternRequest:
    """Conjunctive incident pattern: links incident to ALL ``anchors``,
    optionally restricted to ``type_handle``. The per-request type rides a
    traced (K,) vector, so typed and untyped requests share one batch."""

    anchors: tuple[int, ...]
    type_handle: Optional[int] = None

    def __post_init__(self):
        if not self.anchors:
            raise Unservable("pattern request needs at least one anchor")
        object.__setattr__(
            self, "anchors", tuple(int(a) for a in self.anchors)
        )

    @property
    def kind(self) -> str:
        return "pattern"

    @property
    def batch_key(self) -> tuple:
        # anchor arity P is a device shape dim — one program per P
        return ("pattern", len(self.anchors))


@dataclass(frozen=True)
class JoinRequest:
    """A conjunctive-pattern join: the structural half is a hashable
    ``join/ir.PatternSignature`` (``sig``) and the per-request half the
    constant vector (``consts``) — the split_constants factoring, which
    is exactly the batch-key/payload discipline: requests sharing one
    signature ride one compiled multiway-intersection program
    (``ops/join.execute_join``) as K lanes of one batch, however
    different their anchor atoms.

    Build via ``query.bridge.to_join_request`` (condition-spec front
    door) or directly from ``join.split_constants``."""

    sig: object                 # join/ir.PatternSignature (kept untyped:
    consts: tuple[int, ...]     # this module stays jax/join-import-free)

    def __post_init__(self):
        object.__setattr__(
            self, "consts", tuple(int(x) for x in self.consts)
        )
        n = getattr(self.sig, "n_consts", None)
        if n is not None and n != len(self.consts):
            raise Unservable(
                f"signature expects {n} constants, got {len(self.consts)}"
            )

    @property
    def kind(self) -> str:
        return "join"

    @property
    def batch_key(self) -> tuple:
        # the signature IS the compiled program's identity: elimination
        # order, step statics, filter layout all derive from it
        return ("join", self.sig)


@dataclass(frozen=True)
class RangeRequest:
    """A value range / ordered / top-k query over one indexed dimension
    (the hgindex serve lane): atoms whose value of ``kind`` falls in the
    ``[lo, hi]`` rank window, optionally type-filtered, optionally
    constrained incident to ``anchor``, returned in value order
    (``desc`` flips it) with an optional ``limit`` (top-k).

    ``dim`` is the value kind byte (the indexed DIMENSION — requests of
    one dimension share a sorted device column and a batch); ``lo_rank``
    / ``hi_rank`` are 64-bit order-preserving payload ranks
    (``utils/ordered_bytes.rank64``), ``None`` = open bound;
    ``lo_rank2`` / ``hi_rank2`` the matching SECOND rank words (payload
    bytes 8..16, ``rank128`` — 0 for fixed-width kinds and short keys).
    ``lo_op`` ∈ {"gt", "gte"}, ``hi_op`` ∈ {"lt", "lte"}. ``exact``
    records whether the 128-bit rank pair decides the request exactly:
    True for fixed-width kinds (rank order == value order, tie-free) and
    for variable-width bounds that are CLEAN (≤16 payload bytes, no NUL
    among them); lanes with ``exact=False`` are served on the exact host
    path — honest scoping, the device window cannot see ties past the
    pair. Even an ``exact`` variable-width request falls back to host
    when a consulted column is not ``device_exact`` (the runtime checks
    at dispatch). ``values`` keeps the ORIGINAL (lo, hi) python values
    so host execution and memtable correction compare real keys, never
    coarse ranks.

    Build via ``query.bridge.to_range_request`` (which derives the
    dimension and ranks through the typesystem) rather than by hand."""

    dim: int
    lo_rank: Optional[int]
    hi_rank: Optional[int]
    lo_op: str = "gte"
    hi_op: str = "lte"
    lo_rank2: int = 0
    hi_rank2: int = 0
    values: tuple = (None, None)
    type_handle: Optional[int] = None
    anchor: Optional[int] = None
    desc: bool = False
    limit: Optional[int] = None
    exact: bool = True

    def __post_init__(self):
        if self.lo_op not in ("gt", "gte") or self.hi_op not in ("lt", "lte"):
            raise Unservable(
                f"bad range ops ({self.lo_op}, {self.hi_op}); lower must "
                "be gt/gte, upper lt/lte"
            )
        if self.limit is not None and self.limit < 1:
            raise Unservable("range limit must be >= 1")

    @property
    def kind(self) -> str:
        return "range"

    @property
    def batch_key(self) -> tuple:
        # one sorted device column (and one compiled program) per value
        # dimension: the dimension IS the statics key
        return ("range", int(self.dim))


# ---------------------------------------------------------------- results


@dataclass(frozen=True, eq=False)  # ndarray field: dataclass eq would
class ServeResult:                 # raise on >1-element comparisons
    """One request's answer.

    ``matches`` holds the first ``top_r`` matching atom ids ascending —
    except for ``kind == "range"`` results, where they come in the
    request's VALUE order (ascending rank, or descending under
    ``desc=True``; rank ties break toward the smaller gid) and the
    window is additionally capped by the request's ``limit``;
    ``truncated`` flags a result set larger than the compact window (then
    ``count`` is exact but ``matches`` is a prefix). ``epoch`` is the
    compaction epoch of the pinned view that served the request;
    ``served_by`` is ``"device"`` for the batched path or ``"host"`` for
    the exact fallback (oversized rows / anchors beyond the base's id
    space)."""

    kind: str               # "bfs" | "pattern" | "range"
    count: int
    matches: np.ndarray     # int64, ascending
    truncated: bool
    epoch: int
    served_by: str = "device"


@dataclass(frozen=True, eq=False)
class JoinResult:
    """One join request's answer: the first ``top_r`` binding tuples.

    ``tuples`` is ``(n, V)`` int64, columns in the REQUEST's variable
    order (``vars``), rows ascending lexicographically; ``truncated``
    flags a binding set larger than the compact window (``count`` stays
    exact — truncation-honest device lanes are re-served on the exact
    host path before they get here, see ``DeviceExecutor.collect``)."""

    kind: str               # always "join"
    count: int
    tuples: np.ndarray      # (n, V) int64, lexicographic ascending
    vars: tuple             # column names, request order
    truncated: bool
    epoch: int
    served_by: str = "device"


# ---------------------------------------------------------------- tickets


@dataclass
class Ticket:
    """A queued request + its completion future and deadline bookkeeping
    (absolute times per the runtime's injected clock).

    ``priority`` orders admission pops: a higher class pops first, FIFO
    within a class (deadline shedding and backpressure are
    priority-blind). ``trace`` is the request's hgobs trace handle —
    ``None`` whenever tracing is off, so the disabled path allocates
    nothing and every terminal helper gates on one attribute read. The
    terminal span (``resolve``/``shed``/``error``) is emitted HERE so
    every completion path — dispatch, cancel_all, executor failure —
    closes the trace exactly once."""

    request: object
    future: Future = field(default_factory=Future)
    submit_t: float = 0.0
    deadline_t: Optional[float] = None
    priority: int = 0
    trace: object = None
    #: per-request cost attribution: the runtime finishes the trace
    #: EARLY at resolve time and attaches an ``obs.fleet.explain_record``
    #: to the future (``future.explain``) BEFORE the result is delivered,
    #: so a caller reading ``fut.result()`` then ``fut.explain`` never
    #: races the dispatch thread
    explain: bool = False

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now >= self.deadline_t

    def _close_trace(self, terminal: str, **attrs) -> None:
        tr = self.trace
        if tr is not None:
            tr.finish_terminal(terminal, **attrs)

    # Completion goes through these tolerant helpers everywhere: a caller
    # may have cancel()ed the future, and an InvalidStateError out of the
    # dispatch thread would kill the whole service for one dead request.
    def resolve(self, result) -> bool:
        try:
            self.future.set_result(result)
            ok = True
        except Exception:
            ok = False  # cancelled/already-done: nobody is listening
        self._close_trace("resolve", delivered=ok)
        return ok

    def fail(self, exc: BaseException) -> bool:
        try:
            self.future.set_exception(exc)
            ok = True
        except Exception:
            ok = False
        if not isinstance(exc, DeadlineExceeded):  # shed() emits its own
            self._close_trace("error", error=type(exc).__name__)
        return ok

    def shed(self, now: float) -> None:
        self.fail(DeadlineExceeded(now - self.submit_t))
        self._close_trace("shed", waited_s=now - self.submit_t)

    @property
    def batch_key(self) -> tuple:
        return self.request.batch_key
