"""Bounded admission queue: backpressure, deadline shedding, priorities.

The queue is the runtime's ONLY synchronization point between submitters
and the dispatch thread: one condition variable guards a deque of
:class:`~.types.Ticket`. Pops are PRIORITY-ordered: ``front()`` (which
picks the key the next micro-batch is formed around) returns the oldest
ticket of the highest priority class present, and ``take`` hands tickets
out highest-class-first, FIFO within a class — so a latency-critical
class jumps the batch-formation line while same-class requests keep
strict arrival order. Capacity, deadline shedding, and the ``block`` /
``fail`` policies are priority-blind: a high-priority request that
arrives at a full queue still waits or fails like any other. A lingered
lower class still forces flushes (the batcher's linger clock is
``oldest()``, priority-blind), so only genuinely saturating
higher-priority load — dispatch never finding the queue clear of higher
classes — delays lower ones, and deadlines bound how long a delayed
request waits.

Backpressure policy is per-queue:

- ``"block"`` — ``submit`` waits for space (bounded by the request's own
  deadline when it has one: a request that would expire while waiting is
  shed immediately, with the queue untouched);
- ``"fail"``  — ``submit`` raises :class:`~.types.QueueFull` at once.

Deadline shedding happens at pop time (``shed_expired``): an expired
ticket's future completes with a typed :class:`~.types.DeadlineExceeded`
and the ticket never reaches a batch — a dead request costs zero device
work.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from hypergraphdb_tpu.serve.stats import ServeStats
from hypergraphdb_tpu.serve.types import (
    Clock,
    QueueFull,
    RuntimeClosed,
    Ticket,
)


class AdmissionQueue:
    """Bounded FIFO of tickets with deadline shedding.

    All mutation happens under one condition variable; the dispatch thread
    waits on the same cv (``wait_for_work``) so a submit wakes it without
    polling."""

    def __init__(self, capacity: int, policy: str = "block",
                 clock: Clock = None, stats: Optional[ServeStats] = None):
        if policy not in ("block", "fail"):
            raise ValueError(f"unknown admission policy {policy!r}")
        if capacity < 1:
            raise ValueError("queue capacity must be >= 1")
        import time

        self.capacity = capacity
        self.policy = policy
        self.clock = clock or time.monotonic
        self.stats = stats or ServeStats()
        self._cv = threading.Condition()
        self._dq: deque[Ticket] = deque()
        # priority class -> queued count (zero entries removed): with a
        # single class present — the overwhelmingly common shape —
        # front() stays the O(1) deque head instead of an O(n) scan
        self._prio_counts: dict[int, int] = {}
        self._closed = False

    # -- submit side ---------------------------------------------------------
    def submit(self, ticket: Ticket) -> Ticket:
        """Enqueue (or shed / reject) one ticket; returns it either way —
        a shed ticket's future already carries ``DeadlineExceeded``."""
        with self._cv:
            while True:
                if self._closed:
                    raise RuntimeClosed("runtime is closed")
                if len(self._dq) < self.capacity:
                    self._dq.append(ticket)
                    p = ticket.priority
                    self._prio_counts[p] = self._prio_counts.get(p, 0) + 1
                    self.stats.record_submit()
                    self.stats.set_queue_depth(len(self._dq))
                    self._cv.notify_all()
                    return ticket
                if self.policy == "fail":
                    self.stats.record_reject()
                    raise QueueFull(
                        f"admission queue full ({self.capacity})"
                    )
                # block policy: wait for space, bounded by the request's
                # own deadline — expiring in THIS wait is still "expired
                # in the queue", shed the same way
                now = self.clock()
                if ticket.expired(now):
                    # counts as submitted-then-shed so the accounting
                    # identity holds: submitted == completed + shed +
                    # cancelled + in-flight
                    self.stats.record_submit()
                    ticket.shed(now)
                    self.stats.record_shed()
                    return ticket
                timeout = (
                    None if ticket.deadline_t is None
                    else max(ticket.deadline_t - now, 0.0)
                )
                self._cv.wait(timeout)

    # -- dispatch side -------------------------------------------------------
    def shed_expired(self, now: float) -> int:
        """Complete every expired ticket with DeadlineExceeded and drop it
        from the queue. Returns the shed count."""
        shed = 0
        with self._cv:
            live = deque()
            for t in self._dq:
                if t.expired(now):
                    t.shed(now)
                    self.stats.record_shed()
                    self._prio_dec(t.priority)
                    shed += 1
                else:
                    live.append(t)
            if shed:
                self._dq = live
                self.stats.set_queue_depth(len(self._dq))
                self._cv.notify_all()  # space freed: wake blocked submits
        return shed

    def take(self, batch_key: tuple, max_n: int) -> list:
        """Remove and return up to ``max_n`` tickets with ``batch_key``,
        highest priority class first, FIFO within a class; other keys
        stay queued in arrival order."""
        with self._cv:
            match = [t for t in self._dq if t.batch_key == batch_key]
            if len(self._prio_counts) > 1:
                # stable sort: equal priorities keep queue (arrival)
                # order. Skipped entirely on the common single-class
                # queue, where arrival order IS the answer.
                match.sort(key=lambda t: -t.priority)
            out = match[:max_n]
            if out:
                chosen = {id(t) for t in out}
                self._dq = deque(
                    t for t in self._dq if id(t) not in chosen
                )
                for t in out:
                    self._prio_dec(t.priority)
                self.stats.set_queue_depth(len(self._dq))
                self._cv.notify_all()
            return out

    def oldest(self) -> Optional[Ticket]:
        """The globally-oldest queued ticket regardless of priority — the
        LINGER clock. Keeping linger on this (while ``front()`` picks
        which key flushes) guarantees progress for every class: a
        lingered low-priority group forces a flush, draining whatever
        class is ahead of it until it reaches the front itself."""
        with self._cv:
            return self._dq[0] if self._dq else None

    def _prio_dec(self, p: int) -> None:
        """Drop one queued ticket from priority class ``p`` (caller holds
        the cv)."""
        n = self._prio_counts.get(p, 0) - 1
        if n > 0:
            self._prio_counts[p] = n
        else:
            self._prio_counts.pop(p, None)

    def front(self) -> Optional[Ticket]:
        """The oldest ticket of the highest priority class present — the
        ticket whose key defines the next micro-batch. O(1) with one
        class queued; a full scan only while classes actually mix."""
        with self._cv:
            if not self._dq:
                return None
            if len(self._prio_counts) <= 1:
                return self._dq[0]
            best = None
            for t in self._dq:
                if best is None or t.priority > best.priority:
                    best = t
            return best

    def count_key(self, batch_key: tuple) -> int:
        with self._cv:
            return sum(1 for t in self._dq if t.batch_key == batch_key)

    def depth(self) -> int:
        with self._cv:
            return len(self._dq)

    def wait_for_work(self, timeout: Optional[float] = None) -> bool:
        """Dispatch-thread parking: returns True when the queue is
        non-empty or closed (else after ``timeout``)."""
        with self._cv:
            if self._dq or self._closed:
                return True
            self._cv.wait(timeout)
            return bool(self._dq) or self._closed

    def park(self, timeout: float) -> None:
        """Sleep up to ``timeout`` seconds, waking early on any queue
        event (submit/close) — the dispatch thread's linger wait when
        requests are already queued but the flush policy says not yet."""
        with self._cv:
            if self._closed:
                return
            self._cv.wait(timeout)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        """Stop admitting; queued tickets stay for draining."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def cancel_all(self) -> int:
        """Fail every queued ticket with RuntimeClosed (non-drain close)."""
        with self._cv:
            n = len(self._dq)
            for t in self._dq:
                t.fail(RuntimeClosed("runtime closed"))
                self.stats.record_cancel()
            self._dq.clear()
            self._prio_counts.clear()
            self.stats.set_queue_depth(0)
            self._cv.notify_all()
            return n

    def wake(self) -> None:
        """Nudge any waiter (used on close and by fake-clock tests)."""
        with self._cv:
            self._cv.notify_all()
