"""Bounded admission queue: backpressure + in-queue deadline shedding.

The queue is the runtime's ONLY synchronization point between submitters
and the dispatch thread: one condition variable guards a deque of
:class:`~.types.Ticket`. Backpressure policy is per-queue:

- ``"block"`` — ``submit`` waits for space (bounded by the request's own
  deadline when it has one: a request that would expire while waiting is
  shed immediately, with the queue untouched);
- ``"fail"``  — ``submit`` raises :class:`~.types.QueueFull` at once.

Deadline shedding happens at pop time (``shed_expired``): an expired
ticket's future completes with a typed :class:`~.types.DeadlineExceeded`
and the ticket never reaches a batch — a dead request costs zero device
work.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from hypergraphdb_tpu.serve.stats import ServeStats
from hypergraphdb_tpu.serve.types import (
    Clock,
    QueueFull,
    RuntimeClosed,
    Ticket,
)


class AdmissionQueue:
    """Bounded FIFO of tickets with deadline shedding.

    All mutation happens under one condition variable; the dispatch thread
    waits on the same cv (``wait_for_work``) so a submit wakes it without
    polling."""

    def __init__(self, capacity: int, policy: str = "block",
                 clock: Clock = None, stats: Optional[ServeStats] = None):
        if policy not in ("block", "fail"):
            raise ValueError(f"unknown admission policy {policy!r}")
        if capacity < 1:
            raise ValueError("queue capacity must be >= 1")
        import time

        self.capacity = capacity
        self.policy = policy
        self.clock = clock or time.monotonic
        self.stats = stats or ServeStats()
        self._cv = threading.Condition()
        self._dq: deque[Ticket] = deque()
        self._closed = False

    # -- submit side ---------------------------------------------------------
    def submit(self, ticket: Ticket) -> Ticket:
        """Enqueue (or shed / reject) one ticket; returns it either way —
        a shed ticket's future already carries ``DeadlineExceeded``."""
        with self._cv:
            while True:
                if self._closed:
                    raise RuntimeClosed("runtime is closed")
                if len(self._dq) < self.capacity:
                    self._dq.append(ticket)
                    self.stats.record_submit()
                    self._cv.notify_all()
                    return ticket
                if self.policy == "fail":
                    self.stats.record_reject()
                    raise QueueFull(
                        f"admission queue full ({self.capacity})"
                    )
                # block policy: wait for space, bounded by the request's
                # own deadline — expiring in THIS wait is still "expired
                # in the queue", shed the same way
                now = self.clock()
                if ticket.expired(now):
                    # counts as submitted-then-shed so the accounting
                    # identity holds: submitted == completed + shed +
                    # cancelled + in-flight
                    self.stats.record_submit()
                    ticket.shed(now)
                    self.stats.record_shed()
                    return ticket
                timeout = (
                    None if ticket.deadline_t is None
                    else max(ticket.deadline_t - now, 0.0)
                )
                self._cv.wait(timeout)

    # -- dispatch side -------------------------------------------------------
    def shed_expired(self, now: float) -> int:
        """Complete every expired ticket with DeadlineExceeded and drop it
        from the queue. Returns the shed count."""
        shed = 0
        with self._cv:
            live = deque()
            for t in self._dq:
                if t.expired(now):
                    t.shed(now)
                    self.stats.record_shed()
                    shed += 1
                else:
                    live.append(t)
            if shed:
                self._dq = live
                self._cv.notify_all()  # space freed: wake blocked submits
        return shed

    def take(self, batch_key: tuple, max_n: int) -> list:
        """Remove and return up to ``max_n`` tickets with ``batch_key``,
        preserving FIFO order; other keys stay queued in order."""
        with self._cv:
            out, rest = [], deque()
            for t in self._dq:
                if len(out) < max_n and t.batch_key == batch_key:
                    out.append(t)
                else:
                    rest.append(t)
            self._dq = rest
            if out:
                self._cv.notify_all()
            return out

    def front(self) -> Optional[Ticket]:
        with self._cv:
            return self._dq[0] if self._dq else None

    def count_key(self, batch_key: tuple) -> int:
        with self._cv:
            return sum(1 for t in self._dq if t.batch_key == batch_key)

    def depth(self) -> int:
        with self._cv:
            return len(self._dq)

    def wait_for_work(self, timeout: Optional[float] = None) -> bool:
        """Dispatch-thread parking: returns True when the queue is
        non-empty or closed (else after ``timeout``)."""
        with self._cv:
            if self._dq or self._closed:
                return True
            self._cv.wait(timeout)
            return bool(self._dq) or self._closed

    def park(self, timeout: float) -> None:
        """Sleep up to ``timeout`` seconds, waking early on any queue
        event (submit/close) — the dispatch thread's linger wait when
        requests are already queued but the flush policy says not yet."""
        with self._cv:
            if self._closed:
                return
            self._cv.wait(timeout)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        """Stop admitting; queued tickets stay for draining."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def cancel_all(self) -> int:
        """Fail every queued ticket with RuntimeClosed (non-drain close)."""
        with self._cv:
            n = len(self._dq)
            for t in self._dq:
                t.fail(RuntimeClosed("runtime closed"))
                self.stats.record_cancel()
            self._dq.clear()
            self._cv.notify_all()
            return n

    def wake(self) -> None:
        """Nudge any waiter (used on close and by fake-clock tests)."""
        with self._cv:
            self._cv.notify_all()
