"""Query-serving runtime: async micro-batching with admission control.

The kernels under ``ops/`` are batch-native (K seeds / K queries per
dispatch) but every caller-facing entry point so far was one-shot — each
caller paid a full device dispatch alone (BENCH_r05 ``c5_streaming``:
p99 = 4.4 s under concurrent ingest). This package turns the kernel
library into a service using the continuous-batching shape of inference
stacks:

- requests enter a **bounded admission queue** (``admission.py``) with
  per-request deadlines and optional **priorities** (a higher class pops
  first at batch formation, FIFO within a class; shedding and
  backpressure stay priority-blind); expired requests are shed IN the
  queue with a typed :class:`DeadlineExceeded` — never a wasted device
  dispatch;
- a batcher (``batcher.py``) coalesces compatible requests and flushes
  **shape-bucketed micro-batches** (pad-to-bucket K ∈ {64, 256, 1024}) on
  batch-full or max-linger timeout;
- a dedicated dispatch thread (``runtime.py``) double-buffers: host-side
  assembly of batch N+1 overlaps device execution of batch N;
- every batch pins a consistent read view via
  ``SnapshotManager.pinned_view(max_lag_edges=...)`` so no request ever
  straddles a compaction swap;
- ``stats.py`` records queue depth, batch occupancy, shed counts, and
  latency percentiles into one hgobs registry (``serve.*`` namespace),
  and with tracing on (``obs.enable()``, or an injected, **enabled**
  tracer: ``ServeConfig(tracer=Tracer().enable())`` — injection alone
  does not flip the gate) every request carries a
  ``submit → queue_wait → batch_form → launch [→ device] → collect →
  resolve`` span chain — see README "Observability".

Entry point::

    from hypergraphdb_tpu.serve import ServeRuntime, ServeConfig

    with ServeRuntime(graph, ServeConfig(max_lag_edges=0)) as rt:
        fut = rt.submit_bfs(seed, max_hops=2, deadline_s=0.1)
        res = fut.result()          # ServeResult | raises DeadlineExceeded
"""

from hypergraphdb_tpu.serve.types import (
    AdmissionGated,
    BFSRequest,
    Clock,
    DeadlineExceeded,
    JoinRequest,
    JoinResult,
    PatternRequest,
    QueueFull,
    RuntimeClosed,
    ServeError,
    ServeResult,
    Unservable,
)
from hypergraphdb_tpu.serve.stats import ServeStats
from hypergraphdb_tpu.serve.admission import AdmissionQueue
from hypergraphdb_tpu.serve.batcher import Batcher, MicroBatch, bucket_for
from hypergraphdb_tpu.serve.runtime import (
    DeviceExecutor,
    ServeConfig,
    ServeRuntime,
)
from hypergraphdb_tpu.serve.sharded import ShardedExecutor

__all__ = [
    "AdmissionGated",
    "AdmissionQueue",
    "Batcher",
    "BFSRequest",
    "Clock",
    "DeadlineExceeded",
    "DeviceExecutor",
    "JoinRequest",
    "JoinResult",
    "MicroBatch",
    "PatternRequest",
    "QueueFull",
    "RuntimeClosed",
    "ServeConfig",
    "ServeError",
    "ServeResult",
    "ServeRuntime",
    "ServeStats",
    "ShardedExecutor",
    "Unservable",
    "bucket_for",
]
