"""Serving metrics: a façade over one hgobs registry.

Pre-hgobs this module owned its own counters and a private latency ring —
a second metrics surface disjoint from ``utils.metrics``. Every
instrument now lives in an :class:`hypergraphdb_tpu.obs.Registry` under
the ``serve.*`` dotted namespace (:data:`DOTTED_NAMES`); the latency ring
became the shared histogram's bounded exact-percentile window. The public
API is UNCHANGED — counter attributes (``stats.submitted``), the
``record_*`` methods, and the legacy flat ``snapshot()`` keys all keep
working; the legacy-key ↔ dotted-name mapping is committed as
:data:`LEGACY_TO_DOTTED` (the compat shim) and ``snapshot_namespaced()``
returns the dotted view. Prometheus rendering:
``obs.export.prometheus_text(stats.registry)``.

No jax — safe to call from the submit path, the dispatch thread, and
test assertions concurrently.
"""

from __future__ import annotations

import threading
from typing import Optional

from hypergraphdb_tpu.obs.registry import Registry

#: legacy ``snapshot()`` key -> dotted registry name (the compat shim;
#: derived keys map to the instruments they are computed from)
LEGACY_TO_DOTTED = {
    "submitted": "serve.submitted",
    "completed": "serve.completed",
    "shed_deadline": "serve.shed_deadline",
    "rejected_queue_full": "serve.rejected_queue_full",
    "gated": "serve.gated",
    "cancelled": "serve.cancelled",
    "errors": "serve.errors",
    "host_fallbacks": "serve.host_fallbacks",
    "batches": "serve.batches",
    "device_dispatches": "serve.device_dispatches",
    "sharded_dispatches": "serve.sharded_dispatches",
    "range_dispatches": "serve.range_dispatches",
    "retries": "serve.retries",
    "breaker_trips": "serve.breaker_trips",
    "breaker_state": "serve.breaker_state",
    "batch_occupancy": "serve.lanes_real",     # ÷ serve.lanes_padded
    "latency_ms": "serve.latency_seconds",
    "queue_depth": "serve.queue_depth",
}

#: every request KIND the runtime serves — grows with each new lane
#: (PR 10 join, PR 12 range); the lane drift gate in tests/test_obs.py
#: holds this against the executors' dispatch vocabulary
LANE_KINDS = ("bfs", "pattern", "join", "range")

#: every executor PATH a request can resolve through: the single-chip
#: device lane, the mesh-sharded device lane, the exact host lane
LANE_PATHS = ("device", "sharded", "host")

#: the per-lane served-request counter family, registered EAGERLY (the
#: full kind × path cross product, so a scrape — and the drift gate —
#: sees every lane's counter even before its first request; lanes a
#: deployment never routes legitimately sit at 0). Attribution is by
#: the ANSWERING executor: a device-served result under the sharded
#: executor counts ``sharded`` whatever kernel shape it rode.
LANE_NAMES = tuple(
    f"serve.lane.{kind}.{path}" for kind in LANE_KINDS
    for path in LANE_PATHS
)

#: every FIXED ``serve.*`` name this façade registers (drift-tested: the
#: registry holds exactly these — no orphans, no duplicates). Per-key
#: breaker instruments are the one DYNAMIC family on top:
#: ``serve.breaker.state.<key>`` / ``serve.breaker.trips.<key>``
#: (:data:`BREAKER_KEY_PREFIX`), created on a key's first transition.
#: BOTH names are load-bearing for static checking: hglint HG1105
#: evaluates ``DOTTED_NAMES`` (and any ``*_PREFIX`` constant) by AST and
#: flags literal metric sites outside the registry — renaming either
#: constant silently drops that coverage.
#: every plan SHAPE the hgplan planner can choose (``plan/planner.py``'s
#: candidate vocabulary: the four lanes' strategies plus the exact host
#: scan). Spelled here — not imported — because the dependency edge
#: runs plan → serve; the planner differential suite holds the two
#: vocabularies against each other instead.
PLAN_SHAPES = ("range_first", "pattern", "join", "bfs", "host")

#: every FIXED ``plan.*`` name (the hgplan planner's telemetry, recorded
#: through this façade so planned traffic shares the serving registry,
#: the drift gate, and the HG1105 vocabulary). Eager like the lane
#: family: per-shape choice counters cover all of :data:`PLAN_SHAPES`
#: from construction. NOTE: appended into :data:`DOTTED_NAMES` as one
#: expression — the HG1105 AST evaluator resolves a registry from its
#: single binding; re-assignment would make it self-referential and
#: silently drop governance of BOTH namespaces.
PLAN_NAMES = tuple(f"plan.choice.{shape}" for shape in PLAN_SHAPES) + (
    "plan.requests",
    "plan.est_rows",
    "plan.actual_rows",
    "plan.cost_seconds",
    "plan.abs_rel_error",
    "plan.feedback_updates",
    "plan.feedback_clamped",
    "plan.guard_vetoes",
)

DOTTED_NAMES = LANE_NAMES + PLAN_NAMES + (
    "serve.join.hub_dispatches",
    "serve.join.partial_corrections",
    "serve.submitted",
    "serve.completed",
    "serve.shed_deadline",
    "serve.rejected_queue_full",
    "serve.gated",
    "serve.cancelled",
    "serve.errors",
    "serve.host_fallbacks",
    "serve.perf_observe_errors",
    "serve.batches",
    "serve.device_dispatches",
    "serve.sharded_dispatches",
    "serve.range_dispatches",
    "serve.device_seconds",
    "serve.retries",
    "serve.breaker_trips",
    "serve.breaker_state",
    "serve.lanes_real",
    "serve.lanes_padded",
    "serve.latency_seconds",
    "serve.queue_depth",
)

#: name prefix of the per-batch-key breaker family (the labelled view
#: the one-gauge worst-state ``serve.breaker_state`` was too coarse
#: for — ``/healthz`` shows WHICH bucket is degraded, these let a
#: Prometheus scrape do the same)
BREAKER_KEY_PREFIX = "serve.breaker."


class ServeStats:
    """Thread-safe metrics surface for one :class:`~.runtime.ServeRuntime`.

    Counters: ``submitted``, ``completed``, ``shed_deadline`` (expired in
    queue), ``rejected_queue_full`` (fail-fast backpressure),
    ``cancelled`` (runtime closed without drain), ``host_fallbacks``
    (requests served exactly on host instead of the batched device path),
    ``batches`` (formed micro-batches), ``device_dispatches`` (real kernel
    launches). Occupancy is the fraction of real (non-padding) lanes per
    dispatched bucket."""

    def __init__(self, latency_window: int = 4096,
                 registry: Optional[Registry] = None):
        self.registry = registry if registry is not None else Registry()
        # coherence lock: each instrument locks itself, but the accounting
        # identity (submitted == completed + shed + cancelled + in-flight)
        # spans SEVERAL counters — record_* and snapshot() serialize on
        # this so a snapshot can never observe a torn multi-counter update
        self._lock = threading.Lock()
        r = self.registry
        self._submitted = r.counter("serve.submitted")
        self._completed = r.counter("serve.completed")
        self._shed = r.counter("serve.shed_deadline")
        self._rejected = r.counter("serve.rejected_queue_full")
        self._gated = r.counter("serve.gated")
        self._cancelled = r.counter("serve.cancelled")
        self._errors = r.counter("serve.errors")
        self._host_fallbacks = r.counter("serve.host_fallbacks")
        self._batches = r.counter("serve.batches")
        self._device_dispatches = r.counter("serve.device_dispatches")
        self._sharded_dispatches = r.counter("serve.sharded_dispatches")
        self._range_dispatches = r.counter("serve.range_dispatches")
        self._retries = r.counter("serve.retries")
        self._perf_errors = r.counter("serve.perf_observe_errors")
        self._join_hub = r.counter("serve.join.hub_dispatches")
        self._join_partial = r.counter("serve.join.partial_corrections")
        self._breaker_trips = r.counter("serve.breaker_trips")
        self._breaker_state = r.gauge("serve.breaker_state")
        self._lanes_real = r.counter("serve.lanes_real")
        self._lanes_padded = r.counter("serve.lanes_padded")
        self._latency = r.histogram("serve.latency_seconds",
                                    window=latency_window)
        self._device_seconds = r.histogram("serve.device_seconds")
        self._queue_depth = r.gauge("serve.queue_depth")
        # the per-lane served-request family, EAGER over the full
        # kind × path cross product (the drift gate's contract): which
        # lane answered each completed request, the EXPLAIN aggregate
        self._lanes = {
            (kind, path): r.counter(f"serve.lane.{kind}.{path}")
            for kind in LANE_KINDS for path in LANE_PATHS
        }
        # the hgplan planner's telemetry, eager over PLAN_SHAPES (same
        # drift-gate contract as the lane family)
        self._plan_choices = {
            shape: r.counter(f"plan.choice.{shape}") for shape in PLAN_SHAPES
        }
        self._plan_requests = r.counter("plan.requests")
        self._plan_est_rows = r.histogram("plan.est_rows")
        self._plan_actual_rows = r.histogram("plan.actual_rows")
        self._plan_cost = r.histogram("plan.cost_seconds")
        self._plan_abs_rel_error = r.histogram("plan.abs_rel_error")
        self._plan_fb_updates = r.counter("plan.feedback_updates")
        self._plan_fb_clamped = r.counter("plan.feedback_clamped")
        self._plan_guard_vetoes = r.counter("plan.guard_vetoes")
        # per-batch-key breaker family, lazily registered on a key's
        # first transition (label -> instrument; _key_instruments makes
        # reset() cover them too)
        self._key_states: dict = {}
        self._key_trips: dict = {}
        self._own = tuple(self._lanes.values()) + tuple(
            self._plan_choices.values()) + (
            self._plan_requests, self._plan_est_rows, self._plan_actual_rows,
            self._plan_cost, self._plan_abs_rel_error, self._plan_fb_updates,
            self._plan_fb_clamped, self._plan_guard_vetoes,
        ) + (
            self._submitted, self._completed, self._shed, self._rejected,
            self._gated, self._cancelled, self._errors, self._host_fallbacks,
            self._batches, self._device_dispatches,
            self._sharded_dispatches, self._range_dispatches,
            self._device_seconds,
            self._join_hub, self._join_partial,
            self._retries, self._perf_errors,
            self._breaker_trips, self._breaker_state,
            self._lanes_real, self._lanes_padded, self._latency,
            self._queue_depth,
        )

    def reset(self) -> None:
        """Zero every counter and the latency/occupancy windows — the
        bench's post-warmup cut so compile-time latencies never pollute
        steady-state percentiles. Resets only THIS façade's instruments
        (including the per-key breaker family): on a shared registry,
        foreign subsystems' counters (graph/tx/compact) must survive a
        serving-stats cut."""
        with self._lock:
            for m in self._own:
                m.reset()
            for m in list(self._key_states.values()):
                m.reset()
            for m in list(self._key_trips.values()):
                m.reset()

    # -- recording (serialized on the coherence lock) ------------------------
    def record_submit(self) -> None:
        with self._lock:
            self._submitted.inc()

    def record_shed(self) -> None:
        with self._lock:
            self._shed.inc()

    def record_reject(self) -> None:
        with self._lock:
            self._rejected.inc()

    def record_gated(self) -> None:
        """An admission-gate refusal (e.g. a replica past its lag
        bound): the request was never admitted, so it is outside the
        submitted/completed identity — counted on its own."""
        with self._lock:
            self._gated.inc()

    def record_cancel(self) -> None:
        with self._lock:
            self._cancelled.inc()

    def record_host_fallback(self) -> None:
        with self._lock:
            self._host_fallbacks.inc()

    def record_error(self) -> None:
        """A request failed with a typed non-deadline error (executor
        fault surfaced to the caller) — the accounting identity's fifth
        terminal: submitted == completed + shed + cancelled + errors +
        in-flight."""
        with self._lock:
            self._errors.inc()

    def record_retry(self) -> None:
        """One transient-failure re-attempt (device launch retry or a
        collect-failure host re-serve)."""
        with self._lock:
            self._retries.inc()

    def record_perf_error(self) -> None:
        """The hgperf sentinel's ``observe``/``observe_batch`` raised on
        the completion path. The dispatch loop swallows it (a perf
        evaluation bug must not fail the request) — this counter is the
        evidence that observations are being dropped."""
        with self._lock:
            self._perf_errors.inc()

    def record_join_hub_dispatch(self, n_lanes: int = 1) -> None:
        """``n_lanes`` real join lanes dispatched through the
        degree-split dense-frontier hub chain (join engine v2) — the
        lanes PR 10 routed to the exact host path. The live gate
        (``tools/join.sh``) asserts this moves on a hub-anchored
        smoke."""
        with self._lock:
            self._join_hub.inc(n_lanes)

    def record_join_partial_correction(self) -> None:
        """One join request answered device-side under a SMALL dirty
        memtable with the per-lane correction merged in (ROADMAP 2d) —
        a request the previous whole-batch rule would have re-routed to
        host."""
        with self._lock:
            self._join_partial.inc()

    # -- hgplan telemetry ----------------------------------------------------
    def record_plan_request(self, shape: str, est_rows: float,
                            cost_s: float) -> None:
        """One planner verdict: which shape won, what it estimated, what
        the costing priced it at. Unknown shapes (a planner this façade
        predates) drop like unknown lanes — never raise on a serve
        thread."""
        with self._lock:
            self._plan_requests.inc()
            c = self._plan_choices.get(shape)
            if c is not None:
                c.inc()
            self._plan_est_rows.observe(float(est_rows))
            self._plan_cost.observe(float(cost_s))

    def record_plan_actual(self, est_rows: float, actual_rows: float) -> None:
        """The execution side of one planned request: the actual row
        count and the |est − actual| / max(actual, 1) relative error the
        feedback digest learns from."""
        with self._lock:
            self._plan_actual_rows.observe(float(actual_rows))
            err = abs(float(est_rows) - float(actual_rows))
            self._plan_abs_rel_error.observe(err / max(float(actual_rows),
                                                       1.0))

    def record_plan_feedback_update(self, clamped: bool = False) -> None:
        """One ratio admitted into the drift digest (``clamped`` when
        the stored ratio hit the digest's clamp bounds)."""
        with self._lock:
            self._plan_fb_updates.inc()
            if clamped:
                self._plan_fb_clamped.inc()

    def record_plan_guard_veto(self) -> None:
        """The sentinel guard kept the uncorrected plan because the
        learned correction would have steered onto a lane currently
        breaching its perf baseline."""
        with self._lock:
            self._plan_guard_vetoes.inc()

    def plan_choice_counts(self) -> dict:
        """{shape: chosen count} over the planner's vocabulary."""
        return {shape: c.value for shape, c in self._plan_choices.items()}

    def record_breaker_trip(self) -> None:
        with self._lock:
            self._breaker_trips.inc()

    def set_breaker_state(self, code: int) -> None:
        """Pushed by the circuit breaker on every state change (worst
        state across batch keys: 0 closed, 1 half-open, 2 open) — a
        single instrument write, deliberately outside the coherence lock
        (the breaker calls this from its own callback path)."""
        self._breaker_state.set(code)

    @staticmethod
    def _key_label(key) -> str:
        """Stable metric label for a batch key: ``("bfs", 2)`` → ``bfs_2``.
        Delegates to the ONE canonical labeller (``obs.http``'s, which
        ``/healthz`` also uses) so the documented join-by-name between
        the healthz view and the ``serve.breaker.*`` family cannot
        drift. Late import: rare path (breaker transitions only), and it
        keeps the serve→obs.http edge out of module import time."""
        from hypergraphdb_tpu.obs.http import breaker_key_label

        return breaker_key_label(key)

    def set_breaker_key_state(self, key, code: int) -> None:
        """Per-batch-key breaker gauge (``serve.breaker.state.<key>``),
        pushed on every transition of THAT key — the labelled view the
        worst-state gauge summarizes. Same callback discipline as
        :meth:`set_breaker_state`: a leaf instrument write, no coherence
        lock (dict get/set is GIL-atomic; a racing first transition just
        resolves the same instrument twice)."""
        label = self._key_label(key)
        g = self._key_states.get(label)
        if g is None:
            g = self._key_states[label] = self.registry.gauge(
                BREAKER_KEY_PREFIX + "state." + label
            )
        g.set(code)

    def record_breaker_key_trip(self, key) -> None:
        """Per-batch-key trip counter (``serve.breaker.trips.<key>``)."""
        label = self._key_label(key)
        c = self._key_trips.get(label)
        if c is None:
            c = self._key_trips[label] = self.registry.counter(
                BREAKER_KEY_PREFIX + "trips." + label
            )
        c.inc()

    def breaker_key_states(self) -> dict:
        """{label: current gauge code} for every key that ever
        transitioned — the scrape-side mirror of ``breaker.states()``."""
        return {label: g.value for label, g in self._key_states.items()}

    def record_batch(self, n_real: int, bucket: int) -> None:
        """One successfully launched micro-batch; occupancy measures the
        ADMISSION layer's coalescing (real requests / padded lanes)."""
        with self._lock:
            self._batches.inc()
            self._lanes_real.inc(n_real)
            self._lanes_padded.inc(bucket)

    def record_device_dispatch(self) -> None:
        """One real device kernel launch (a batch whose every lane fell
        back to host, or whose launch raised, dispatches none)."""
        with self._lock:
            self._device_dispatches.inc()

    def record_sharded_dispatch(self) -> None:
        """One kernel dispatch routed through the mesh-sharded executor
        (a subset of ``device_dispatches``-adjacent work: counted at the
        kernel-call site, so an all-host batch counts neither)."""
        with self._lock:
            self._sharded_dispatches.inc()

    def record_range_dispatch(self) -> None:
        """One kernel dispatch of the hgindex range lane (a subset of
        ``device_dispatches``-adjacent work, counted at the kernel-call
        site like ``sharded_dispatches`` — an all-host range batch
        counts neither)."""
        with self._lock:
            self._range_dispatches.inc()

    def record_lane(self, kind: str, path: str) -> None:
        """One request RESOLVED through lane ``(kind, path)`` — counted
        at completion (beside ``record_complete``), so the family's sum
        over paths equals ``completed``. Unknown combinations (a future
        lane this façade predates) are dropped rather than raised: a
        metrics façade must never fail a serving thread."""
        c = self._lanes.get((kind, path))
        if c is not None:
            c.inc()

    def lane_counts(self) -> dict:
        """{(kind, path): served count} for every registered lane."""
        return {k: c.value for k, c in self._lanes.items()}

    def record_device_time(self, seconds: float) -> None:
        """One batch's launch→ready device wall delta (only measured
        under ``ServeConfig(device_timing=True)`` — the histogram stays
        empty otherwise)."""
        self._device_seconds.observe(seconds)

    def record_complete(self, latency_s: float) -> None:
        with self._lock:
            self._completed.inc()
            self._latency.observe(latency_s)

    def set_queue_depth(self, depth: int) -> None:
        """Pushed by the admission queue on every depth change, so a
        direct Prometheus scrape of the registry sees a live gauge
        without anyone calling ``snapshot()`` first."""
        self._queue_depth.set(depth)

    # -- counter attributes (pre-hgobs public surface) -----------------------
    @property
    def submitted(self) -> int:
        return self._submitted.value

    @property
    def completed(self) -> int:
        return self._completed.value

    @property
    def shed_deadline(self) -> int:
        return self._shed.value

    @property
    def rejected_queue_full(self) -> int:
        return self._rejected.value

    @property
    def gated(self) -> int:
        return self._gated.value

    @property
    def cancelled(self) -> int:
        return self._cancelled.value

    @property
    def errors(self) -> int:
        return self._errors.value

    @property
    def retries(self) -> int:
        return self._retries.value

    @property
    def breaker_trips(self) -> int:
        return self._breaker_trips.value

    @property
    def join_hub_dispatches(self) -> int:
        return self._join_hub.value

    @property
    def join_partial_corrections(self) -> int:
        return self._join_partial.value

    @property
    def plan_requests(self) -> int:
        return self._plan_requests.value

    @property
    def plan_guard_vetoes(self) -> int:
        return self._plan_guard_vetoes.value

    @property
    def plan_feedback_updates(self) -> int:
        return self._plan_fb_updates.value

    @property
    def host_fallbacks(self) -> int:
        return self._host_fallbacks.value

    @property
    def batches(self) -> int:
        return self._batches.value

    @property
    def device_dispatches(self) -> int:
        return self._device_dispatches.value

    @property
    def sharded_dispatches(self) -> int:
        return self._sharded_dispatches.value

    @property
    def range_dispatches(self) -> int:
        return self._range_dispatches.value

    # -- reading -------------------------------------------------------------
    def occupancy(self) -> Optional[float]:
        """Mean real-lane fraction over every dispatched bucket slot."""
        with self._lock:
            padded = self._lanes_padded.value
            if not padded:
                return None
            return self._lanes_real.value / padded

    def latency_percentiles_ms(self) -> dict:
        """{"p50": ..., "p95": ..., "p99": ...} over the latency window
        (milliseconds), or Nones before any completion. One locked read
        of the window — concurrent completions can't tear the triple
        (p50 > p99 impossible)."""
        p50, p95, p99 = self._latency.percentiles((0.50, 0.95, 0.99))
        return {
            "p50": None if p50 is None else p50 * 1e3,
            "p95": None if p95 is None else p95 * 1e3,
            "p99": None if p99 is None else p99 * 1e3,
        }

    def snapshot(self, queue_depth: Optional[int] = None) -> dict:
        """One COHERENT metrics dict under the LEGACY flat keys (the
        bench's reporting unit; see :data:`LEGACY_TO_DOTTED`): taken under
        the coherence lock, so multi-counter identities hold in every
        snapshot even under concurrent recording."""
        with self._lock:
            padded = self._lanes_padded.value
            out = {
                "submitted": self._submitted.value,
                "completed": self._completed.value,
                "shed_deadline": self._shed.value,
                "rejected_queue_full": self._rejected.value,
                "gated": self._gated.value,
                "cancelled": self._cancelled.value,
                "errors": self._errors.value,
                "host_fallbacks": self._host_fallbacks.value,
                "batches": self._batches.value,
                "device_dispatches": self._device_dispatches.value,
                "sharded_dispatches": self._sharded_dispatches.value,
                "range_dispatches": self._range_dispatches.value,
                "retries": self._retries.value,
                "breaker_trips": self._breaker_trips.value,
                "breaker_state": self._breaker_state.value,
                "batch_occupancy": (
                    self._lanes_real.value / padded if padded else None
                ),
            }
        out["latency_ms"] = self.latency_percentiles_ms()
        if queue_depth is not None:
            self._queue_depth.set(queue_depth)
            out["queue_depth"] = queue_depth
        return out

    def snapshot_namespaced(self, queue_depth: Optional[int] = None) -> dict:
        """The same snapshot under the dotted registry names (plus the
        derived ``serve.batch_occupancy``) — what new consumers key on.
        Latency percentiles ride under ``serve.latency_seconds`` in
        SECONDS, matching the histogram that name denotes everywhere else
        (only the legacy ``latency_ms`` key carries milliseconds)."""
        legacy = self.snapshot(queue_depth)
        out = {
            LEGACY_TO_DOTTED[k]: v for k, v in legacy.items()
            if k not in ("batch_occupancy", "latency_ms")
        }
        out["serve.batch_occupancy"] = legacy["batch_occupancy"]
        out["serve.latency_seconds"] = {
            k: (None if v is None else v / 1e3)
            for k, v in legacy["latency_ms"].items()
        }
        return out
