"""Serving metrics: counters, occupancy, latency percentiles.

One lock, no jax — safe to call from the submit path, the dispatch
thread, and test assertions concurrently. Latencies live in a bounded
ring buffer so a long-lived server's stats stay O(window), not O(total
requests served).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional


class ServeStats:
    """Thread-safe metrics surface for one :class:`~.runtime.ServeRuntime`.

    Counters: ``submitted``, ``completed``, ``shed_deadline`` (expired in
    queue), ``rejected_queue_full`` (fail-fast backpressure),
    ``cancelled`` (runtime closed without drain), ``host_fallbacks``
    (requests served exactly on host instead of the batched device path),
    ``batches`` (device dispatches). Occupancy is the fraction of real
    (non-padding) lanes per dispatched bucket."""

    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._lat = deque(maxlen=latency_window)
        self.submitted = 0
        self.completed = 0
        self.shed_deadline = 0
        self.rejected_queue_full = 0
        self.cancelled = 0
        self.host_fallbacks = 0
        self.batches = 0
        self.device_dispatches = 0
        self._real_lanes = 0
        self._padded_lanes = 0

    def reset(self) -> None:
        """Zero every counter and the latency/occupancy windows — the
        bench's post-warmup cut so compile-time latencies never pollute
        steady-state percentiles."""
        with self._lock:
            self._lat.clear()
            self.submitted = 0
            self.completed = 0
            self.shed_deadline = 0
            self.rejected_queue_full = 0
            self.cancelled = 0
            self.host_fallbacks = 0
            self.batches = 0
            self.device_dispatches = 0
            self._real_lanes = 0
            self._padded_lanes = 0

    # -- recording (each a single locked update) ----------------------------
    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed_deadline += 1

    def record_reject(self) -> None:
        with self._lock:
            self.rejected_queue_full += 1

    def record_cancel(self) -> None:
        with self._lock:
            self.cancelled += 1

    def record_host_fallback(self) -> None:
        with self._lock:
            self.host_fallbacks += 1

    def record_batch(self, n_real: int, bucket: int) -> None:
        """One successfully launched micro-batch; occupancy measures the
        ADMISSION layer's coalescing (real requests / padded lanes)."""
        with self._lock:
            self.batches += 1
            self._real_lanes += n_real
            self._padded_lanes += bucket

    def record_device_dispatch(self) -> None:
        """One real device kernel launch (a batch whose every lane fell
        back to host, or whose launch raised, dispatches none)."""
        with self._lock:
            self.device_dispatches += 1

    def record_complete(self, latency_s: float) -> None:
        with self._lock:
            self.completed += 1
            self._lat.append(latency_s)

    # -- reading -------------------------------------------------------------
    def occupancy(self) -> Optional[float]:
        """Mean real-lane fraction over every dispatched bucket slot."""
        with self._lock:
            if not self._padded_lanes:
                return None
            return self._real_lanes / self._padded_lanes

    def latency_percentiles_ms(self) -> dict:
        """{"p50": ..., "p95": ..., "p99": ...} over the latency window
        (milliseconds), or Nones before any completion."""
        with self._lock:
            lat = sorted(self._lat)
        if not lat:
            return {"p50": None, "p95": None, "p99": None}

        def pct(p: float) -> float:
            i = min(len(lat) - 1, int(round(p * (len(lat) - 1))))
            return lat[i] * 1e3

        return {"p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99)}

    def snapshot(self, queue_depth: Optional[int] = None) -> dict:
        """One coherent metrics dict (the bench's reporting unit)."""
        with self._lock:
            out = {
                "submitted": self.submitted,
                "completed": self.completed,
                "shed_deadline": self.shed_deadline,
                "rejected_queue_full": self.rejected_queue_full,
                "cancelled": self.cancelled,
                "host_fallbacks": self.host_fallbacks,
                "batches": self.batches,
                "device_dispatches": self.device_dispatches,
                "batch_occupancy": (
                    self._real_lanes / self._padded_lanes
                    if self._padded_lanes else None
                ),
            }
        out["latency_ms"] = self.latency_percentiles_ms()
        if queue_depth is not None:
            out["queue_depth"] = queue_depth
        return out
