"""Shape-bucketed micro-batch formation + flush policy.

Pure decision logic — no threads, no device code — so tier-1 tests drive
it deterministically with a fake clock. The batcher owns two decisions:

- WHEN to flush: a compatible group reaching the LARGEST bucket flushes
  immediately (batch-full); otherwise the oldest queued ticket's linger
  reaching ``max_linger_s`` flushes whatever is pending (latency bound).
  ``drain=True`` (shutdown) flushes unconditionally.
- WHAT shape to pay for: the flushed group pads up to the smallest
  configured bucket that fits (K ∈ {64, 256, 1024} by default) —
  power-of-two-style buckets bound the number of distinct compiled
  programs while keeping padding waste ≤ the bucket ratio.

Groups are keyed by ``Ticket.batch_key`` (kernel statics + shape dims:
``("bfs", max_hops)`` / ``("pattern", P)``) — requests with different
keys cannot share a dispatch. The group is formed from the OLDEST queued
ticket's key, so no key starves: whichever request has waited longest
defines the next batch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

from hypergraphdb_tpu.serve.admission import AdmissionQueue

#: default seed/query bucket widths (pad-to-bucket device shapes)
BUCKETS = (64, 256, 1024)


def bucket_for(n: int, buckets: Sequence[int] = BUCKETS) -> int:
    """Smallest configured bucket that fits ``n`` (``n`` above the largest
    bucket is a caller bug — the batcher never collects more than max)."""
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} requests exceed the largest bucket {buckets[-1]}")


@dataclass
class MicroBatch:
    """One flushed group: the tickets plus the padded device shape.

    ``force_host`` is set by the runtime when the batch key's circuit
    breaker is OPEN (or a degraded re-route is needed): the executor then
    serves every ticket on the exact host path and never touches the
    device."""

    key: tuple
    tickets: list
    bucket: int
    force_host: bool = False

    @property
    def occupancy(self) -> float:
        return len(self.tickets) / self.bucket


class Batcher:
    """Flush-policy head on an :class:`AdmissionQueue`."""

    def __init__(self, queue: AdmissionQueue,
                 buckets: Sequence[int] = BUCKETS,
                 max_linger_s: float = 0.002):
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError("buckets must be sorted, unique, non-empty")
        self.queue = queue
        self.buckets = tuple(int(b) for b in buckets)
        self.max_batch = self.buckets[-1]
        self.max_linger_s = max_linger_s

    def next_batch(self, now: float, drain: bool = False
                   ) -> Optional[MicroBatch]:
        """Shed expired tickets, then flush if the policy says so; None
        when nothing is ready yet. Two separate decisions: WHETHER to
        flush is keyed to the GLOBALLY-oldest ticket's linger (so no
        class can be starved past its linger by a trickle of
        higher-priority arrivals — every lingered group forces flushes
        until it reaches the front itself), WHICH key flushes follows
        ``front()`` (the highest priority class's oldest ticket)."""
        self.queue.shed_expired(now)
        head = self.queue.front()
        if head is None:
            return None
        key = head.batch_key
        pending = self.queue.count_key(key)
        full = pending >= self.max_batch
        oldest = self.queue.oldest()
        lingered = (
            oldest is not None
            and (now - oldest.submit_t) >= self.max_linger_s
        )
        if not (full or lingered or drain):
            return None
        tickets = self.queue.take(key, self.max_batch)
        if not tickets:  # raced with another consumer (single-thread: no-op)
            return None
        return MicroBatch(key=key, tickets=tickets,
                          bucket=bucket_for(len(tickets), self.buckets))

    def time_to_flush(self, now: float) -> Optional[float]:
        """Seconds until the OLDEST ticket's linger expires (the dispatch
        thread's wait timeout — the same clock ``next_batch`` flushes
        on); None with an empty queue."""
        oldest = self.queue.oldest()
        if oldest is None:
            return None
        return max(self.max_linger_s - (now - oldest.submit_t), 0.0)
