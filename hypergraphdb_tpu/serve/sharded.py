"""The multi-chip serving executor: serve buckets over the device mesh.

:class:`ShardedExecutor` is :class:`~.runtime.DeviceExecutor` with every
kernel dispatch rerouted through the ``ops/sharded_serving`` shard_map
programs — batches pin the manager's SHARDED (base, delta) twins
(``SnapshotManager.attach_mesh`` + ``pinned_view(sharded=True)``), BFS
frontiers exchange packed words over ICI, pattern candidates split along
the candidate axis, and join lanes split across chips. Everything else —
admission, batching, breakers, retries, AND the host-side memtable
corrections at collect — is inherited unchanged: the sharded kernels keep
the single-chip ``(counts, first_r)`` / ``JoinExecution`` contracts
bit-for-bit, so exactness guarantees are identical.

When it engages (see ``runtime._make_executor``): ``ServeConfig(
sharded=True)`` forces it; ``sharded=None`` + ``hbm_budget_bytes`` set
upgrades automatically once the pinned base snapshot no longer fits one
chip's budget. ``/healthz`` advertises the pod's mesh shape, gid-range
partition map, and per-shard HBM occupancy via :meth:`mesh_report`.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from hypergraphdb_tpu.serve.runtime import DeviceExecutor, ServeConfig
from hypergraphdb_tpu.serve.stats import ServeStats


def snapshot_device_bytes(base) -> int:
    """Estimated single-chip HBM footprint of one packed base snapshot
    (the per-row columns + both CSR relations) — what the AUTO shard
    trigger compares against ``ServeConfig.hbm_budget_bytes``."""
    n1 = base.num_atoms + 1
    per_row = 4 + 1 + 4 + 4 + 4 + 1      # type/is_link/arity/rank hi+lo/kind
    per_rel = 4 * 2                      # flat + src, int32 each
    return int(
        2 * (n1 + 1) * 4                 # the two offset arrays
        + n1 * per_row
        + base.n_edges_inc * per_rel
        + base.n_edges_tgt * per_rel
    )


class ShardedExecutor(DeviceExecutor):
    """Serve-batch execution over a ``jax.sharding.Mesh``.

    Construction attaches the mesh to the graph's snapshot manager; the
    first pinned view pays the one-time base repartition + upload (or
    :meth:`prewarm` does, at deploy time). ``mesh=None`` meshes every
    visible device (capped by ``ServeConfig.mesh_devices``)."""

    #: device-served results count under the mesh lane family
    #: (``serve.lane.<kind>.sharded``)
    device_lane = "sharded"

    def __init__(self, graph, config: ServeConfig,
                 stats: Optional[ServeStats] = None, mesh=None):
        super().__init__(graph, config, stats)
        if mesh is None:
            import jax

            from hypergraphdb_tpu.parallel.sharded import make_mesh

            devices = jax.devices()
            if config.mesh_devices is not None:
                devices = devices[: int(config.mesh_devices)]
            mesh = make_mesh(devices)
        self.mesh = mesh
        self.n_dev = int(mesh.devices.size)
        self.mgr.attach_mesh(mesh)

    # -- pinning ---------------------------------------------------------------
    def _pin_view(self, kind: str, host_only: bool = False):
        # BFS reads the sharded (base ∪ delta) twins; pattern/join lanes
        # read the base host-side (assembly) + host corrections — they
        # pay no delta partition on their hot path, exactly as the
        # single-chip pattern path pays no delta upload
        return self.mgr.pinned_view(
            self.config.max_lag_edges,
            sync_delta=False,
            sharded=(kind == "bfs") and not host_only,
        )

    # -- BFS -------------------------------------------------------------------
    def _fused_bfs_kwargs(self, view, bucket: int):
        return None  # the fused Pallas chain is single-chip only

    def _serve_bfs(self, view, seeds_dev, max_hops: int, top_r: int):
        from hypergraphdb_tpu.ops.sharded_serving import (
            bfs_serve_batch_sharded,
        )

        self.stats.record_sharded_dispatch()
        args = (view.sharded_base, view.sharded_delta, seeds_dev)
        statics = {"max_hops": max_hops, "top_r": top_r}
        compiled = self._aot_dispatch(
            "ops.sharded_serving.bfs_serve_batch_sharded",
            bfs_serve_batch_sharded, args, statics,
        )
        if compiled is not None:
            return compiled(*args)
        return bfs_serve_batch_sharded(*args, **statics)

    # -- patterns --------------------------------------------------------------
    def _pattern_gate(self, view):
        from hypergraphdb_tpu.ops.sharded_serving import pattern_sharded_ok

        # truthy sentinel: host-assembled candidate rows need no
        # device-resident ELL matrix, only the arity cap
        return True if pattern_sharded_ok(view.base) else None

    def _serve_pattern(self, view, ell, anchors, type_vec):
        import jax.numpy as jnp

        from hypergraphdb_tpu.ops.sharded_serving import (
            pattern_host_rows,
            pattern_serve_batch_sharded,
        )

        from hypergraphdb_tpu.ops.sharded_serving import mesh_carrier

        rows0, row0_types, tgt = pattern_host_rows(
            view.base, anchors, self.config.pattern_pad, self.n_dev
        )
        sdev = mesh_carrier(self.mesh)
        self.stats.record_sharded_dispatch()
        args = (sdev, jnp.asarray(rows0), jnp.asarray(row0_types),
                jnp.asarray(tgt), jnp.asarray(anchors, dtype=jnp.int32),
                jnp.asarray(type_vec))
        statics = {"top_r": self.config.top_r}
        compiled = self._aot_dispatch(
            "ops.sharded_serving.pattern_serve_batch_sharded",
            pattern_serve_batch_sharded, args, statics,
        )
        if compiled is not None:
            return compiled(*args)
        return pattern_serve_batch_sharded(*args, **statics)

    # -- joins -----------------------------------------------------------------
    def _execute_join(self, view, plan, consts, n_real: int):
        from hypergraphdb_tpu.ops.sharded_serving import (
            execute_join_sharded,
        )

        from hypergraphdb_tpu.ops.sharded_serving import mesh_carrier

        K = int(consts.shape[0])
        if K % self.n_dev or getattr(plan, "bags", None):
            # bucket not splittable over this mesh, or a bushy plan (the
            # sharded lane program runs one flat chain — sharding bag
            # materialization is the ROADMAP follow-up): exact
            # single-chip execution through the BASE executor, so the
            # join-v2 config knobs (caps, hub split, factorized) are
            # honored identically to the non-sharded tier
            return super()._execute_join(view, plan, consts, n_real)
        self.stats.record_sharded_dispatch()
        return execute_join_sharded(
            view.base, mesh_carrier(self.mesh), plan, consts,
            top_r=self.config.top_r, n_real=n_real,
        )

    # -- deploy-time prewarm ---------------------------------------------------
    def prewarm(self, buckets, max_hops: Optional[int] = None) -> int:
        """Compile (or AOT-load) the SHARDED bucket programs before the
        dispatch thread takes traffic — the multi-chip half of the
        cold-start story: the one-time base repartition + upload also
        happens here instead of inside the first request's deadline
        window. Returns executables served from cache."""
        import jax.numpy as jnp

        from hypergraphdb_tpu.ops.sharded_serving import (
            bfs_serve_batch_sharded,
            mesh_carrier,
            pattern_host_rows,
            pattern_serve_batch_sharded,
            pattern_sharded_ok,
        )

        hops_list = ((int(max_hops),) if max_hops is not None
                     else tuple(self.config.prewarm_hops or ())
                     or (self.config.default_max_hops,))
        view = self._pin_view("bfs")
        n = view.base.num_atoms
        top_r = min(self.config.top_r + 1, n + 1)
        arities = (tuple(self.config.prewarm_pattern_arities or ())
                   if self.aot is not None and pattern_sharded_ok(view.base)
                   else ())
        warm = 0
        if self.aot is None:
            return 0
        for b in buckets:
            seeds = jnp.full((int(b),), n, dtype=jnp.int32)
            for hops in hops_list:
                try:
                    warm += self.aot.warm(
                        "ops.sharded_serving.bfs_serve_batch_sharded",
                        bfs_serve_batch_sharded,
                        (view.sharded_base, view.sharded_delta, seeds),
                        {"max_hops": hops, "top_r": top_r},
                    )
                except Exception:  # noqa: BLE001 - never block startup
                    continue
            for P in arities:
                anchors = np.full((int(b), int(P)), n, dtype=np.int32)
                tvec = np.full(int(b), -1, dtype=np.int32)
                rows0, rtypes, tgt = pattern_host_rows(
                    view.base, anchors, self.config.pattern_pad,
                    self.n_dev,
                )
                try:
                    warm += self.aot.warm(
                        "ops.sharded_serving.pattern_serve_batch_sharded",
                        pattern_serve_batch_sharded,
                        (mesh_carrier(self.mesh), jnp.asarray(rows0),
                         jnp.asarray(rtypes), jnp.asarray(tgt),
                         jnp.asarray(anchors), jnp.asarray(tvec)),
                        {"top_r": self.config.top_r},
                    )
                except Exception:  # noqa: BLE001 - never block startup
                    continue
        return warm

    # -- health ----------------------------------------------------------------
    def mesh_report(self) -> dict:
        """The pod topology ``/healthz`` advertises: mesh shape, the
        gid-range partition map (what shard-aware routing places by),
        and MEASURED per-shard HBM occupancy (empty per-device stats on
        backends without allocator stats, e.g. CPU)."""
        from hypergraphdb_tpu.parallel.sharded import (
            AXIS,
            device_memory_stats,
        )
        from hypergraphdb_tpu.storage.partitioned import PartitionMap

        with self.mgr._lock:
            sbase = self.mgr._sharded_base
            base = self.mgr.base
        pmap = (sbase.partition_map if sbase is not None
                else PartitionMap.for_mesh(base.num_atoms + 1, self.n_dev))
        stats = device_memory_stats()
        shards = []
        for part, dev in enumerate(self.mesh.devices.flat):
            lo, hi = pmap.range_of(part)
            rec = {"device": int(dev.id), "gid_lo": int(lo),
                   "gid_hi": int(hi)}
            mem = stats.get(str(dev.id))
            if mem:
                rec["hbm_bytes_in_use"] = mem["bytes_in_use"]
            shards.append(rec)
        return {
            "axis": AXIS,
            "devices": self.n_dev,
            "partition_map": pmap.to_dict(),
            "sharded_epoch": self.mgr._sharded_epoch,
            "shards": shards,
        }
