"""The ``hg`` query DSL.

Mirror of the reference's ``hg`` expression namespace
(``core/src/java/org/hypergraphdb/HGQuery.java:364`` — ``hg.type(...)``,
``hg.value(...)``, ``hg.incident(...)``, ``hg.and(...)``, ``hg.findAll``).

    from hypergraphdb_tpu.query import dsl as hg
    hg.find_all(graph, hg.and_(hg.type("string"), hg.incident(h)))
"""

from __future__ import annotations

from typing import Any, Optional

from hypergraphdb_tpu.query import conditions as c

# condition constructors ------------------------------------------------------

all_atoms = c.AnyAtom
nothing = c.Nothing


def and_(*clauses) -> c.And:
    return c.And(*clauses)


def or_(*clauses) -> c.Or:
    return c.Or(*clauses)


def not_(clause) -> c.Not:
    return c.Not(clause)


def _h(x):
    """Handle coercion that lets Var placeholders pass through (bound later
    by query.variables.substitute)."""
    from hypergraphdb_tpu.query.variables import Var

    return x if isinstance(x, Var) else int(x)


def is_(handle) -> c.Is:
    return c.Is(_h(handle))


def type_(t) -> c.AtomType:
    return c.AtomType(t)


# keep reference-style aliases too
type = type_  # noqa: A001
typePlus = type_plus = lambda t: c.TypePlus(t)  # noqa: E731


def value(v, op: str = "eq") -> c.AtomValue:
    return c.AtomValue(v, op)


def eq(v) -> c.AtomValue:
    return c.AtomValue(v, "eq")


def lt(v) -> c.AtomValue:
    return c.AtomValue(v, "lt")


def lte(v) -> c.AtomValue:
    return c.AtomValue(v, "lte")


def gt(v) -> c.AtomValue:
    return c.AtomValue(v, "gt")


def gte(v) -> c.AtomValue:
    return c.AtomValue(v, "gte")


def typed_value(t, v, op: str = "eq") -> c.TypedValue:
    return c.TypedValue(v, t, op)


def part(path: str, v, op: str = "eq") -> c.AtomPart:
    return c.AtomPart(path, v, op)


def incident(target) -> c.Incident:
    return c.Incident(_h(target))


def co_incident(other) -> c.CoIncident:
    """Atoms sharing at least one link with ``other`` — the pattern-edge
    relation of conjunctive joins (``join/``); irreflexive."""
    return c.CoIncident(_h(other))


def typed_incident(target, t) -> c.TypedIncident:
    """Links of type ``t`` incident to ``target`` (the bdb-native
    typed-incidence query as a first-class condition)."""
    return c.TypedIncident(_h(target), t)


def incident_at(target, position: int) -> c.PositionedIncident:
    return c.PositionedIncident(_h(target), position)


def link(*targets) -> c.Link:
    return c.Link(*targets)


def ordered_link(*targets) -> c.OrderedLink:
    return c.OrderedLink(*targets)


def value_regex(pattern: str, flags: int = 0) -> c.ValueRegex:
    """String-value regex predicate (``AtomValueRegExPredicate``)."""
    return c.ValueRegex(pattern, flags)


def part_regex(path: str, pattern: str, flags: int = 0) -> c.PartRegex:
    """Record-projection regex predicate (``AtomPartRegExPredicate``)."""
    return c.PartRegex(path, pattern, flags)


def target_at(graph, condition, position: int):
    """Map each result link to its target at ``position`` — the
    LinkProjectionMapping form of ``ResultMapQuery``."""
    from hypergraphdb_tpu.query.compiler import (
        LinkProjectionMapping,
        result_map,
    )

    return result_map(graph, condition, LinkProjectionMapping(position))


def deref(graph, condition):
    """Map each result handle to its value (``DerefMapping``)."""
    from hypergraphdb_tpu.query.compiler import DerefMapping, result_map

    return result_map(graph, condition, DerefMapping())


def pipe(graph, producer_condition, key_condition):
    """``PipeQuery``: each producer result keys a dependent condition;
    returns the union of the keyed queries' results."""
    from hypergraphdb_tpu.query.compiler import pipe as _pipe

    return _pipe(graph, producer_condition, key_condition)


def mapped(condition, mapping=None, position: Optional[int] = None
           ) -> c.MapCondition:
    """First-class ``MapCondition`` — composable inside and_/or_ (the
    ``result_map`` API is top-level only). ``position=n`` is shorthand for
    the LinkProjectionMapping at target position n."""
    if mapping is None:
        if position is None:
            raise ValueError("mapped() needs a mapping or a position")
        from hypergraphdb_tpu.query.compiler import LinkProjectionMapping

        mapping = LinkProjectionMapping(position)
    return c.MapCondition(mapping, condition)


def subsumes(specific) -> c.Subsumes:
    """Atoms more general than ``specific`` (``SubsumesCondition``)."""
    return c.Subsumes(_h(specific))


def subsumed(general) -> c.Subsumed:
    """Atoms more specific than ``general`` (``SubsumedCondition``)."""
    return c.Subsumed(_h(general))


def target(link_handle) -> c.Target:
    return c.Target(_h(link_handle))


def arity(n: int, op: str = "eq") -> c.Arity:
    return c.Arity(n, op)


is_link = c.IsLink
is_node = c.IsNode


def in_index(name: str, key: bytes, op: str = "eq") -> c.IndexCondition:
    return c.IndexCondition(name, key, op)


def bfs(start, max_distance: Optional[int] = None, include_start: bool = False) -> c.BFS:
    return c.BFS(int(start), max_distance, include_start)


def dfs(start, max_distance: Optional[int] = None, include_start: bool = False) -> c.DFS:
    return c.DFS(int(start), max_distance, include_start)


def member_of(subgraph) -> c.SubgraphMember:
    return c.SubgraphMember(int(subgraph))


def contains(atom) -> c.SubgraphContains:
    return c.SubgraphContains(int(atom))


def predicate(fn) -> c.Predicate:
    return c.Predicate(fn)


# execution helpers (hg.findAll / hg.getAll / hg.count) -----------------------


def find_all(graph, condition) -> list[int]:
    return graph.find_all(condition)


def find_one(graph, condition) -> Optional[int]:
    return graph.find_one(condition)


def get_all(graph, condition) -> list[Any]:
    return [graph.get(h) for h in graph.find_all(condition)]


def get_one(graph, condition) -> Any:
    h = graph.find_one(condition)
    return None if h is None else graph.get(h)


def count(graph, condition) -> int:
    return graph.count(condition)
