"""Query condition vocabulary.

Re-expression of the reference's 41-file condition package
(``core/src/java/org/hypergraphdb/query/`` — SURVEY §2.1 "Query
conditions"): ``And/Or/Not/Nothing``, ``AtomTypeCondition``,
``TypePlusCondition``, ``AtomValueCondition``, ``AtomPartCondition``,
``TypedValueCondition``, ``IncidentCondition``,
``PositionedIncidentCondition``, ``LinkCondition``,
``OrderedLinkCondition``, ``TargetCondition``, ``ArityCondition``,
``BFSCondition``/``DFSCondition``, ``SubgraphMemberCondition``,
``IndexCondition``, ``MapCondition`` (here: ``Predicate``), ``IsCondition``,
``AnyAtomCondition``.

Conditions are frozen dataclasses — pure values the compiler rewrites.
Every condition can also act as a per-atom predicate via ``satisfies``
(the ``HGAtomPredicate.satisfies(graph, handle)`` contract), which is the
fallback execution mode when no index applies.
"""

from __future__ import annotations

import operator
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from hypergraphdb_tpu.core.handles import HGHandle

_OPS = {
    "eq": operator.eq,
    "lt": operator.lt,
    "lte": operator.le,
    "gt": operator.gt,
    "gte": operator.ge,
}


def _coerce_handle(t):
    """int-coerce a target handle, letting non-integer placeholders (query
    Vars, bound later by ``variables.substitute``) pass through."""
    try:
        return int(t)
    except (TypeError, ValueError):
        return t


class HGQueryCondition:
    """Base class; every condition is also an atom predicate."""

    def satisfies(self, graph, h: HGHandle) -> bool:
        raise NotImplementedError


# ---------------------------------------------------------------- trivial


@dataclass(frozen=True)
class AnyAtom(HGQueryCondition):
    def satisfies(self, graph, h):
        return graph.contains(h)


@dataclass(frozen=True)
class Nothing(HGQueryCondition):
    def satisfies(self, graph, h):
        return False


# ---------------------------------------------------------------- boolean


@dataclass(frozen=True)
class And(HGQueryCondition):
    clauses: tuple[HGQueryCondition, ...]

    def __init__(self, *clauses: HGQueryCondition):
        object.__setattr__(self, "clauses", tuple(clauses))

    def satisfies(self, graph, h):
        return all(c.satisfies(graph, h) for c in self.clauses)


@dataclass(frozen=True)
class Or(HGQueryCondition):
    clauses: tuple[HGQueryCondition, ...]

    def __init__(self, *clauses: HGQueryCondition):
        object.__setattr__(self, "clauses", tuple(clauses))

    def satisfies(self, graph, h):
        return any(c.satisfies(graph, h) for c in self.clauses)


@dataclass(frozen=True)
class Not(HGQueryCondition):
    clause: HGQueryCondition

    def satisfies(self, graph, h):
        return not self.clause.satisfies(graph, h)


# ---------------------------------------------------------------- identity


@dataclass(frozen=True)
class Is(HGQueryCondition):
    """Identity (``IsCondition``)."""

    handle: HGHandle

    def satisfies(self, graph, h):
        return int(h) == int(self.handle)


# ---------------------------------------------------------------- type


@dataclass(frozen=True)
class AtomType(HGQueryCondition):
    """Exact type (``AtomTypeCondition.java:38``). ``type`` is a type name
    or a type-atom handle."""

    type: Any

    def type_handle(self, graph) -> HGHandle:
        if isinstance(self.type, str):
            return graph.typesystem.handle_of(self.type)
        return int(self.type)

    def satisfies(self, graph, h):
        return graph.get_type_handle_of(h) == self.type_handle(graph)


@dataclass(frozen=True)
class TypePlus(HGQueryCondition):
    """Type or any of its subtypes (``TypePlusCondition``); expanded to an
    ``Or`` of ``AtomType`` during compilation."""

    type: Any

    def satisfies(self, graph, h):
        ts = graph.typesystem
        name = self.type if isinstance(self.type, str) else ts.name_of(self.type)
        closure = {ts.handle_of(n) for n in ts.subtypes_closure(name)}
        return graph.get_type_handle_of(h) in closure


# ---------------------------------------------------------------- value


def _key_compare(graph, atom_key: bytes, query_key: bytes, op: str) -> bool:
    """Compare two order-preserving value keys. Cross-kind comparisons are
    always False (the reference's Java ``equals``/comparator is likewise
    type-strict), which keeps the predicate path bit-identical to the
    by-value index path."""
    if atom_key[:1] != query_key[:1]:
        return False
    return _OPS[op](atom_key, query_key)


@dataclass(frozen=True)
class AtomValue(HGQueryCondition):
    """Value comparison (``AtomValueCondition``); ``op`` one of
    eq/lt/lte/gt/gte — non-eq ops require an ordered value kind.

    Comparison is type-strict via order-preserving keys, so predicate
    evaluation and index lookup agree exactly."""

    value: Any
    op: str = "eq"

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.core.graph import HGLink

        v = graph.get(h)
        if isinstance(v, HGLink):
            v = v.value
        at = graph.typesystem.get_type(graph.get_type_handle_of(h))
        qt = graph.typesystem.infer(self.value)
        if qt is None:
            return False
        try:
            return _key_compare(graph, at.to_key(v), qt.to_key(self.value), self.op)
        except Exception:
            return False


@dataclass(frozen=True)
class TypedValue(HGQueryCondition):
    """Value + type (``TypedValueCondition``)."""

    value: Any
    type: Any
    op: str = "eq"

    def satisfies(self, graph, h):
        return AtomType(self.type).satisfies(graph, h) and AtomValue(
            self.value, self.op
        ).satisfies(graph, h)


@dataclass(frozen=True)
class AtomPart(HGQueryCondition):
    """Projection-path comparison on record values (``AtomPartCondition``)."""

    path: str
    value: Any
    op: str = "eq"

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.core.graph import HGLink

        v = graph.get(h)
        if isinstance(v, HGLink):
            v = v.value
        th = graph.get_type_handle_of(h)
        atype = graph.typesystem.get_type(th)
        try:
            part = atype.project(v, self.path)
        except Exception:
            return False
        if part is None:
            return False
        pt = graph.typesystem.infer(part)
        qt = graph.typesystem.infer(self.value)
        if pt is None or qt is None:
            return False
        try:
            return _key_compare(
                graph, pt.to_key(part), qt.to_key(self.value), self.op
            )
        except Exception:
            return False


# ---------------------------------------------------------------- structure


@dataclass(frozen=True)
class Incident(HGQueryCondition):
    """Links pointing at ``target`` (``IncidentCondition``) — i.e. membership
    in the target's incidence set. THE building block of graph patterns."""

    target: HGHandle

    def satisfies(self, graph, h):
        return int(h) in graph.get_incidence_set(self.target)


@dataclass(frozen=True)
class CoIncident(HGQueryCondition):
    """Atoms sharing at least one link with ``other`` — the binary
    adjacency view of the hypergraph (two atoms are co-incident when some
    link's target tuple contains both). This is the edge relation of
    conjunctive PATTERN queries (triangles, paths, stars — ``join/``):
    a pattern edge between two variables lowers to one CoIncident clause.

    By definition an atom is never co-incident with itself (a link
    containing ``a`` twice does not make ``a`` its own neighbour) — the
    relation is irreflexive and symmetric. ``other`` may be a query
    ``Var`` inside a pattern spec; as a standalone condition it must be
    a concrete handle."""

    other: HGHandle

    def satisfies(self, graph, h):
        if int(h) == int(self.other):
            return False
        mine = graph.get_incidence_set(h)
        theirs = graph.get_incidence_set(self.other)
        # probe the smaller incidence set against the larger
        a, b = (mine, theirs) if len(mine) <= len(theirs) else (theirs, mine)
        return any(int(l) in b for l in a)


@dataclass(frozen=True)
class TypedIncident(HGQueryCondition):
    """Links of a given TYPE pointing at ``target`` — the first-class form
    of the reference's bdb-native typed-incidence query
    (``storage/incidence/TypedIncidentCondition.java`` answered by
    ``QueryByTypedIncident`` off the annotated incidence index alone).
    Expanded to ``And(Incident, AtomType)`` at compile time, which the
    planner fuses onto the hot host type column
    (``compiler.TypedIncidencePlan``) — same no-record-loads execution."""

    target: HGHandle
    type: Any  # type name or type-atom handle

    def satisfies(self, graph, h):
        # compose the two primitives, mirroring the expand() rewrite —
        # type resolution lives in ONE place (AtomType.type_handle)
        return Incident(self.target).satisfies(graph, h) and AtomType(
            self.type
        ).satisfies(graph, h)


@dataclass(frozen=True)
class PositionedIncident(HGQueryCondition):
    """Links having ``target`` at position ``position``
    (``PositionedIncidentCondition``)."""

    target: HGHandle
    position: int

    def satisfies(self, graph, h):
        try:
            ts = graph.get_targets(h)
        except Exception:
            return False
        return self.position < len(ts) and ts[self.position] == int(self.target)


@dataclass(frozen=True)
class Link(HGQueryCondition):
    """Links containing ALL the given targets, any positions
    (``LinkCondition``); expanded to ``And`` of ``Incident``."""

    targets: tuple[HGHandle, ...]

    def __init__(self, *targets: HGHandle):
        object.__setattr__(self, "targets", tuple(_coerce_handle(t) for t in targets))

    def satisfies(self, graph, h):
        try:
            ts = set(graph.get_targets(h))
        except Exception:
            return False
        return set(self.targets) <= ts


@dataclass(frozen=True)
class OrderedLink(HGQueryCondition):
    """Links whose target tuple starts with exactly these targets in order
    (``OrderedLinkCondition``)."""

    targets: tuple[HGHandle, ...]

    def __init__(self, *targets: HGHandle):
        object.__setattr__(self, "targets", tuple(_coerce_handle(t) for t in targets))

    def satisfies(self, graph, h):
        try:
            ts = graph.get_targets(h)
        except Exception:
            return False
        return ts[: len(self.targets)] == self.targets


@dataclass(frozen=True)
class ValueRegex(HGQueryCondition):
    """Atoms whose (string) value matches a regular expression — the
    reference's ``AtomValueRegExPredicate``. A predicate (P class): it
    narrows other conditions' results, never produces a set by itself."""

    pattern: str
    flags: int = 0

    def _rx(self):
        import re

        return re.compile(self.pattern, self.flags)

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.core.graph import HGLink

        v = graph.get(h)
        if isinstance(v, HGLink):
            v = v.value
        return isinstance(v, str) and self._rx().search(v) is not None


@dataclass(frozen=True)
class PartRegex(HGQueryCondition):
    """Record-projection regex (``AtomPartRegExPredicate``): the value's
    ``path`` projection matches the pattern."""

    path: str
    pattern: str
    flags: int = 0

    def satisfies(self, graph, h):
        import re

        from hypergraphdb_tpu.core.graph import HGLink

        v = graph.get(h)
        if isinstance(v, HGLink):
            v = v.value
        try:
            atype = graph.typesystem.get_type(graph.get_type_handle_of(h))
            part = atype.project(v, self.path)
        except Exception:
            return False
        return isinstance(part, str) and re.search(
            self.pattern, part, self.flags
        ) is not None


def _subsumption_holds(graph, general: int, specific: int) -> bool:
    """Reference subsumption check (``query/impl/SubsumesImpl.java``):
    a DECLARED ``HGSubsumes`` link ``(general, specific)`` wins outright;
    otherwise both atoms must share a type whose ``subsumes`` relation
    accepts the value pair."""
    from hypergraphdb_tpu.atom.utilities import subsumes_declared

    if subsumes_declared(graph, general, specific):
        return True
    try:
        gt = int(graph.get_type_handle_of(general))
        st = int(graph.get_type_handle_of(specific))
    except Exception:
        return False
    if gt != st:
        return False
    try:
        atype = graph.typesystem.get_type(gt)
    except Exception:
        return False
    from hypergraphdb_tpu.core.graph import HGLink

    def val(h):
        v = graph.get(h)
        return v.value if isinstance(v, HGLink) else v

    return bool(atype.subsumes(val(general), val(specific)))


@dataclass(frozen=True)
class Subsumes(HGQueryCondition):
    """Atoms that subsume ``specific`` — i.e. are more general than it
    (``SubsumesCondition.java``: declared ``HGSubsumes`` links first, then
    same-type value subsumption)."""

    specific: HGHandle

    def satisfies(self, graph, h):
        return _subsumption_holds(graph, int(h), int(self.specific))


@dataclass(frozen=True)
class Subsumed(HGQueryCondition):
    """Atoms subsumed by ``general`` — more specific than it
    (``SubsumedCondition.java``)."""

    general: HGHandle

    def satisfies(self, graph, h):
        return _subsumption_holds(graph, int(self.general), int(h))


@dataclass(frozen=True)
class Target(HGQueryCondition):
    """Atoms that are targets of the given link (``TargetCondition``)."""

    link: HGHandle

    def satisfies(self, graph, h):
        try:
            return int(h) in graph.get_targets(self.link)
        except Exception:
            return False


@dataclass(frozen=True)
class Arity(HGQueryCondition):
    """Link arity comparison (``ArityCondition``)."""

    arity: int
    op: str = "eq"

    def satisfies(self, graph, h):
        try:
            n = graph.arity(h)
        except Exception:
            return False
        return _OPS[self.op](n, self.arity)


@dataclass(frozen=True)
class IsLink(HGQueryCondition):
    def satisfies(self, graph, h):
        try:
            return graph.is_link(h)
        except Exception:
            return False


@dataclass(frozen=True)
class IsNode(HGQueryCondition):
    def satisfies(self, graph, h):
        try:
            return not graph.is_link(h)
        except Exception:
            return False


# ---------------------------------------------------------------- index


@dataclass(frozen=True)
class IndexCondition(HGQueryCondition):
    """Direct lookup in a registered user index (``IndexCondition`` /
    ``IndexedPartCondition``): key comparison against index ``name``."""

    name: str
    key: bytes
    op: str = "eq"

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.indexing.manager import get_index

        idx = get_index(graph, self.name)
        if self.op == "eq":
            return int(h) in idx.find(self.key)
        rs = {
            "lt": idx.find_lt,
            "lte": idx.find_lte,
            "gt": idx.find_gt,
            "gte": idx.find_gte,
        }[self.op](self.key)
        return int(h) in rs


# ---------------------------------------------------------------- traversal


@dataclass(frozen=True)
class BFS(HGQueryCondition):
    """Atoms reachable breadth-first from ``start`` (``BFSCondition``)."""

    start: HGHandle
    max_distance: Optional[int] = None
    include_start: bool = False

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.algorithms.traversals import HGBreadthFirstTraversal

        if self.include_start and int(h) == int(self.start):
            return True
        for _, atom in HGBreadthFirstTraversal(
            graph, self.start, max_distance=self.max_distance
        ):
            if atom == int(h):
                return True
        return False


@dataclass(frozen=True)
class DFS(HGQueryCondition):
    """Atoms reachable depth-first from ``start`` (``DFSCondition``)."""

    start: HGHandle
    max_distance: Optional[int] = None
    include_start: bool = False

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.algorithms.traversals import HGDepthFirstTraversal

        if self.include_start and int(h) == int(self.start):
            return True
        for _, atom in HGDepthFirstTraversal(
            graph, self.start, max_distance=self.max_distance
        ):
            if atom == int(h):
                return True
        return False


# ---------------------------------------------------------------- subgraph


@dataclass(frozen=True)
class SubgraphMember(HGQueryCondition):
    """Members of a named subgraph (``SubgraphMemberCondition``)."""

    subgraph: HGHandle

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.atom.subgraph import HGSubgraph

        return HGSubgraph.of(graph, self.subgraph).is_member(h)


@dataclass(frozen=True)
class SubgraphContains(HGQueryCondition):
    """Subgraphs containing the given atom (``SubgraphContainsCondition``)."""

    atom: HGHandle

    def satisfies(self, graph, h):
        from hypergraphdb_tpu.atom.subgraph import HGSubgraph

        try:
            return HGSubgraph.of(graph, h).is_member(self.atom)
        except Exception:
            return False


# ---------------------------------------------------------------- arbitrary


@dataclass(frozen=True)
class MapCondition(HGQueryCondition):
    """First-class result-mapping condition (``query/MapCondition.java``):
    the result set of ``condition`` passed through ``mapping`` (an object
    with ``apply(graph, np.ndarray) -> np.ndarray``, e.g.
    ``LinkProjectionMapping``). COMPOSABLE inside And/Or — the mapped set
    intersects/unions like any other set — which the ``result_map`` API
    (top-level only) could not do. Inside a composition the mapping must
    return handles; value-producing mappings (Deref) stay top-level."""

    mapping: Any
    condition: Any

    def satisfies(self, graph, h):
        # membership of h in a mapped set has no per-handle form (the
        # mapping is not invertible in general) — same stance as the
        # reference's MapCondition, which only exists as a query
        from hypergraphdb_tpu.core.errors import QueryError

        raise QueryError(
            "MapCondition has no per-atom satisfies(); use it as a query"
        )


@dataclass(frozen=True)
class Predicate(HGQueryCondition):
    """Arbitrary predicate over (graph, handle) (``MapCondition`` /
    user ``HGAtomPredicate``). Opaque to the planner: always a filter."""

    fn: Callable[[Any, HGHandle], bool]

    def satisfies(self, graph, h):
        return self.fn(graph, h)
