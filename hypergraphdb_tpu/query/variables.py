"""Parameterized queries: ``Var`` placeholders bound at execution time.

Re-expression of the reference's query-variable machinery (``util/Var``,
``VarContext``, ``Ref``/``Constant`` and ``HGQuery.var`` — precompile a
query once, run it many times with different bindings). Conditions are
frozen dataclasses, so substitution is a pure tree rewrite::

    pq = prepare(graph, q.and_(q.type_("string"), q.value(var("v"))))
    pq.execute(v="hello")
    pq.execute(v="world")
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any

from hypergraphdb_tpu.core.errors import QueryError
from hypergraphdb_tpu.query import conditions as c


@dataclass(frozen=True)
class Var:
    """A named placeholder usable anywhere a condition field takes a value."""

    name: str


def var(name: str) -> Var:
    return Var(name)


def variables_of(cond: c.HGQueryCondition) -> set[str]:
    out: set[str] = set()

    def visit(v: Any) -> None:
        if isinstance(v, Var):
            out.add(v.name)
        elif isinstance(v, c.HGQueryCondition):
            for f in dataclasses.fields(v):
                visit(getattr(v, f.name))
        elif isinstance(v, tuple):
            for x in v:
                visit(x)

    visit(cond)
    return out


def substitute(cond: c.HGQueryCondition, bindings: dict[str, Any]
               ) -> c.HGQueryCondition:
    """Rewrite the condition tree, replacing every ``Var`` with its binding."""

    def sub(v: Any) -> Any:
        if isinstance(v, Var):
            if v.name not in bindings:
                raise QueryError(f"unbound query variable {v.name!r}")
            return bindings[v.name]
        if isinstance(v, (c.And, c.Or)):
            return type(v)(*[sub(x) for x in v.clauses])
        if isinstance(v, (c.Link, c.OrderedLink)):  # variadic ctors too
            return type(v)(*[sub(t) for t in v.targets])
        if isinstance(v, c.HGQueryCondition):
            kw = {f.name: sub(getattr(v, f.name))
                  for f in dataclasses.fields(v)}
            return type(v)(**kw)
        if isinstance(v, tuple):
            return tuple(sub(x) for x in v)
        return v

    return sub(cond)


class PreparedQuery:
    """A reusable query template (``HGQuery`` with variables)."""

    def __init__(self, graph, condition: c.HGQueryCondition):
        self.graph = graph
        self.condition = condition
        self.variables = variables_of(condition)

    def execute(self, **bindings) -> list[int]:
        missing = self.variables - bindings.keys()
        if missing:
            raise QueryError(f"unbound query variables: {sorted(missing)}")
        return self.graph.find_all(substitute(self.condition, bindings))

    def count(self, **bindings) -> int:
        return len(self.execute(**bindings))


def prepare(graph, condition: c.HGQueryCondition) -> PreparedQuery:
    return PreparedQuery(graph, condition)
