"""Condition ↔ JSON round-trip for remote queries and interest predicates.

The analogue of the reference's query/atom JSON serialization used by the
p2p layer (``peer/serializer/HGPeerJsonFactory.java``, exercised by
``p2p/test/java/hgtest/p2p/QueryToJsonTests``): a peer ships a query
condition to another peer, which compiles and executes it locally
(``peer/cact/RemoteQueryExecution.java:34``).

Conditions are frozen dataclasses, so the codec is generic: class name +
field dict, recursing into nested conditions and condition tuples. ``bytes``
fields travel base64. ``Predicate`` (an arbitrary Python callable) is
explicitly NOT serializable — remote peers must never execute foreign code.
"""

from __future__ import annotations

import base64
import dataclasses
from typing import Any

from hypergraphdb_tpu.core.errors import QueryError
from hypergraphdb_tpu.query import conditions as c

#: serializable condition classes, by name (the remote-queryable vocabulary)
VOCABULARY: dict[str, type] = {
    cls.__name__: cls
    for cls in vars(c).values()
    if isinstance(cls, type)
    and issubclass(cls, c.HGQueryCondition)
    and cls is not c.HGQueryCondition
    and dataclasses.is_dataclass(cls)
    and cls.__name__ != "Predicate"
}


def to_json(cond: c.HGQueryCondition) -> dict:
    cls = type(cond)
    if cls.__name__ not in VOCABULARY:
        raise QueryError(
            f"condition {cls.__name__} is not remotely serializable"
        )
    out: dict[str, Any] = {"c": cls.__name__}
    for f in dataclasses.fields(cond):
        out[f.name] = _enc(getattr(cond, f.name))
    return out


def from_json(obj: dict) -> c.HGQueryCondition:
    name = obj.get("c")
    cls = VOCABULARY.get(name)
    if cls is None:
        raise QueryError(f"unknown condition class {name!r}")
    kwargs = {k: _dec(v) for k, v in obj.items() if k != "c"}
    if name in ("And", "Or"):  # variadic constructors
        return cls(*kwargs["clauses"])
    return cls(**kwargs)


def _enc(v: Any) -> Any:
    if isinstance(v, c.HGQueryCondition):
        return to_json(v)
    if isinstance(v, tuple):
        return {"t": [_enc(x) for x in v]}
    if isinstance(v, bytes):
        return {"b64": base64.b64encode(v).decode("ascii")}
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    raise QueryError(f"value {v!r} is not remotely serializable")


def _dec(v: Any) -> Any:
    if isinstance(v, dict):
        if "c" in v:
            return from_json(v)
        if "t" in v:
            return tuple(_dec(x) for x in v["t"])
        if "b64" in v:
            return base64.b64decode(v["b64"])
    return v
