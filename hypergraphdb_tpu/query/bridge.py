"""Condition → batched serving request: the supported subset.

The serving runtime batches three device shapes — K-seed BFS, K
conjunctive incident patterns, and K same-signature conjunctive-pattern
JOINS (triangles, paths, stars, anchored multi-variable conjunctions —
the hgjoin subsystem). This module maps the query-condition vocabulary
onto them:

==========================================  ================================
condition                                   request
==========================================  ================================
``BFS(start, max_distance=d)``              ``BFSRequest(start, d)``
``Incident(t)``                             ``PatternRequest((t,))``
``TypedIncident(t, T)``                     ``PatternRequest((t,), T)``
``Link(t1, .., tn)``                        ``PatternRequest((t1, .., tn))``
``And(Incident.., [AtomType])``             ``PatternRequest(anchors, T)``
``And(CoIncident.., ..)``                   ``JoinRequest(sig, consts)``
multi-variable spec (``to_join_request``)   ``JoinRequest(sig, consts)``
==========================================  ================================

A single condition whose ``And`` mixes ``CoIncident`` with the incident
vocabulary becomes a one-variable join; a *spec* — ``{var: condition}``
with ``query.variables.Var`` cross-references — becomes a multi-variable
join via :func:`to_join_request` (``extract_pattern`` → signature/
constant split; see the README "Pattern joins" table for the exact
vocabulary: CoIncident/Incident/Target/AtomType per variable).

Anything else — value predicates, Or/Not, regex, unbounded BFS — raises a
typed :class:`~hypergraphdb_tpu.serve.types.Unservable`: the caller runs
those through ``graph.find_all`` (the planner's host/one-shot device
paths stay exact and general; the serving subset is deliberately the
batch-native shapes). This is honest scoping, not a fallback-in-disguise:
a serving tier that silently degraded to one-shot execution would destroy
the latency contract it exists to provide.
"""

from __future__ import annotations

from typing import Mapping

from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.serve.types import (
    BFSRequest,
    JoinRequest,
    PatternRequest,
    Unservable,
)


def _type_handle(graph, type_cond: c.AtomType) -> int:
    if graph is None and isinstance(type_cond.type, str):
        raise Unservable(
            "type names need a graph to resolve; pass a type handle"
        )
    return int(type_cond.type_handle(graph)) if isinstance(
        type_cond.type, str
    ) else int(type_cond.type)


def to_request(graph, condition, *, default_max_hops: int = 2):
    """Translate ``condition`` into a batchable request, or raise
    :class:`Unservable` naming the unsupported shape."""
    if isinstance(condition, c.BFS):
        hops = condition.max_distance
        if hops is None:
            # fixed-shape kernels need a static hop count; an unbounded
            # traversal has no batchable device form
            raise Unservable(
                "unbounded BFS is not batchable; set max_distance (the "
                f"runtime default is {default_max_hops})"
            )
        return BFSRequest(int(condition.start), int(hops),
                          include_seed=bool(condition.include_start))
    if isinstance(condition, c.Incident):
        return PatternRequest((int(condition.target),))
    if isinstance(condition, c.TypedIncident):
        return PatternRequest(
            (int(condition.target),),
            _type_handle(graph, c.AtomType(condition.type)),
        )
    if isinstance(condition, c.Link):
        return PatternRequest(tuple(int(t) for t in condition.targets))
    if isinstance(condition, c.CoIncident):
        # distinct=False: a single-variable CONDITION has find_all
        # semantics — CoIncident is already irreflexive and Incident(a)
        # legitimately admits a self-targeting a (the same reasoning as
        # the compiler's try_single_var_join); distinct=True would
        # silently drop that atom on the serve path only
        return to_join_request(graph, {"x": condition}, distinct=False)
    if isinstance(condition, c.And):
        if any(isinstance(cl, c.CoIncident) for cl in condition.clauses):
            # adjacency conjunctions (common neighbours, anchored
            # patterns) are the join lane's one-variable shape;
            # distinct=False per the single-variable contract above
            return to_join_request(graph, {"x": condition},
                                   distinct=False)
        anchors: list[int] = []
        type_h = None
        for cl in condition.clauses:
            if isinstance(cl, c.Incident):
                anchors.append(int(cl.target))
            elif isinstance(cl, c.TypedIncident):
                anchors.append(int(cl.target))
                th = _type_handle(graph, c.AtomType(cl.type))
                if type_h is not None and type_h != th:
                    raise Unservable("conflicting type constraints")
                type_h = th
            elif isinstance(cl, c.AtomType):
                th = _type_handle(graph, cl)
                if type_h is not None and type_h != th:
                    raise Unservable("conflicting type constraints")
                type_h = th
            else:
                raise Unservable(
                    f"{type(cl).__name__} inside And is outside the "
                    "batchable subset (Incident/TypedIncident/AtomType)"
                )
        if not anchors:
            raise Unservable("And without an Incident anchor has no "
                             "batchable device form")
        return PatternRequest(tuple(anchors), type_h)
    raise Unservable(
        f"{type(condition).__name__} is outside the batchable subset; "
        "use graph.find_all"
    )


def to_join_request(graph, spec: Mapping[str, c.HGQueryCondition],
                    distinct: bool = True) -> JoinRequest:
    """Translate a multi-variable condition SPEC (``{var: condition}``,
    cross-references spelled with ``query.variables.Var``) into a
    batchable :class:`JoinRequest`, or raise :class:`Unservable`
    (``join/ir.JoinUnsupported`` is a subclass) naming the clause
    outside the pattern vocabulary. The signature/constant split means
    two requests for the same SHAPE — a triangle at atom 17, a triangle
    at atom 99 — share one batch key and ride one compiled program."""
    from hypergraphdb_tpu.join.ir import extract_pattern, split_constants

    pattern = extract_pattern(graph, spec, distinct=distinct)
    if not any(not a.key_is_var for a in pattern.atoms):
        raise Unservable(
            "a servable join needs at least one constant anchor; "
            "unanchored (whole-graph) patterns run through "
            "ops.join.execute_join's seeds mode instead"
        )
    sig, consts = split_constants(pattern)
    return JoinRequest(sig, consts)
