"""Condition → batched serving request: the supported subset.

The serving runtime batches four device shapes — K-seed BFS, K
conjunctive incident patterns, K same-signature conjunctive-pattern
JOINS (triangles, paths, stars, anchored multi-variable conjunctions —
the hgjoin subsystem), and K value RANGE / ordered / top-k probes over
one indexed dimension (the hgindex subsystem). This module maps the
query-condition vocabulary onto them:

==========================================  ================================
condition                                   request
==========================================  ================================
``BFS(start, max_distance=d)``              ``BFSRequest(start, d)``
``Incident(t)``                             ``PatternRequest((t,))``
``TypedIncident(t, T)``                     ``PatternRequest((t,), T)``
``Link(t1, .., tn)``                        ``PatternRequest((t1, .., tn))``
``And(Incident.., [AtomType])``             ``PatternRequest(anchors, T)``
``And(CoIncident.., ..)``                   ``JoinRequest(sig, consts)``
multi-variable spec (``to_join_request``)   ``JoinRequest(sig, consts)``
``AtomValue(v, op)``                        ``RangeRequest(dim, ...)``
``TypedValue(v, T, op)``                    ``RangeRequest(dim, ..., T)``
``And(AtomValue lo, AtomValue hi,           ``RangeRequest(dim, lo, hi,
[AtomType], [Incident])``                   [T], [anchor])``
==========================================  ================================

A single condition whose ``And`` mixes ``CoIncident`` with the incident
vocabulary becomes a one-variable join; a *spec* — ``{var: condition}``
with ``query.variables.Var`` cross-references — becomes a multi-variable
join via :func:`to_join_request` (``extract_pattern`` → signature/
constant split; see the README "Pattern joins" table for the exact
vocabulary: CoIncident/Incident/Target/AtomType per variable). Value
predicates batch by ``("range", dim)`` — one sorted device column per
value kind (``storage/value_index``); ordered/top-k shapes ride the same
lane via :func:`to_range_request`'s ``desc``/``limit``.

Anything else — Or/Not, regex, unbounded BFS, cross-kind value bounds —
raises a typed :class:`~hypergraphdb_tpu.serve.types.Unservable`: the
caller runs those through ``graph.find_all`` (the planner's host/one-shot
device paths stay exact and general; the serving subset is deliberately
the batch-native shapes). This is honest scoping, not a
fallback-in-disguise: a serving tier that silently degraded to one-shot
execution would destroy the latency contract it exists to provide.
"""

from __future__ import annotations

from typing import Mapping, Optional

from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.serve.types import (
    BFSRequest,
    JoinRequest,
    PatternRequest,
    RangeRequest,
    Unservable,
)


def _type_handle(graph, type_cond: c.AtomType) -> int:
    if graph is None and isinstance(type_cond.type, str):
        raise Unservable(
            "type names need a graph to resolve; pass a type handle"
        )
    return int(type_cond.type_handle(graph)) if isinstance(
        type_cond.type, str
    ) else int(type_cond.type)


def _value_key(graph, value) -> bytes:
    """The order-preserving key of one query value via the typesystem,
    or a typed :class:`Unservable` when the value has no key encoding."""
    if graph is None:
        raise Unservable("value predicates need a graph to derive the "
                         "indexed dimension and rank bounds")
    vt = graph.typesystem.infer(value)
    if vt is None:
        raise Unservable(f"value {value!r} has no registered type; no "
                         "indexed dimension to probe")
    return vt.to_key(value)


def to_range_request(graph, lo=None, hi=None, *, lo_op: str = "gte",
                     hi_op: str = "lte", type_handle: Optional[int] = None,
                     anchor: Optional[int] = None, desc: bool = False,
                     limit: Optional[int] = None) -> RangeRequest:
    """Build a :class:`RangeRequest` from VALUES (at least one bound):
    the typesystem derives the indexed dimension (the value kind byte)
    and the 128-bit rank-pair bounds; mixed-kind bounds are Unservable
    (ranks of different kinds are incomparable once the kind prefix is
    stripped). Variable-width kinds (str/bytes) produce ``exact=True``
    when every bound key is CLEAN (≤16 payload bytes, NUL-free — the
    zero-padded rank pair then orders the bound exactly against any
    column entry); ambiguous bounds produce ``exact=False`` requests —
    admitted, batched, and served on the exact host lane."""
    from hypergraphdb_tpu.storage.value_index import FIXED_WIDTH_KINDS
    from hypergraphdb_tpu.utils.ordered_bytes import rank128, rank_ambiguous

    if lo is None and hi is None:
        raise Unservable("a range request needs at least one bound "
                         "(an unbounded scan has no batchable window)")
    lo_rank = hi_rank = None
    lo_rank2 = hi_rank2 = 0
    dim = None
    bounds_clean = True
    if lo is not None:
        key = _value_key(graph, lo)
        dim = key[0]
        lo_rank, lo_rank2 = rank128(key[1:])
        bounds_clean = bounds_clean and not rank_ambiguous(key[1:])
    if hi is not None:
        key = _value_key(graph, hi)
        if dim is not None and key[0] != dim:
            raise Unservable(
                f"mixed-kind range bounds ({lo!r}, {hi!r}): ranks of "
                "different value kinds are incomparable"
            )
        dim = key[0]
        hi_rank, hi_rank2 = rank128(key[1:])
        bounds_clean = bounds_clean and not rank_ambiguous(key[1:])
    return RangeRequest(
        dim=int(dim), lo_rank=lo_rank, hi_rank=hi_rank,
        lo_op=lo_op, hi_op=hi_op,
        lo_rank2=lo_rank2, hi_rank2=hi_rank2, values=(lo, hi),
        type_handle=None if type_handle is None else int(type_handle),
        anchor=None if anchor is None else int(anchor),
        desc=bool(desc), limit=limit,
        exact=int(dim) in FIXED_WIDTH_KINDS or bounds_clean,
    )


def _value_to_range(graph, val: c.AtomValue,
                    type_handle: Optional[int] = None,
                    anchor: Optional[int] = None) -> RangeRequest:
    """One ``AtomValue`` as a window: eq collapses to [v, v]; ordered
    ops open the other side."""
    if val.op == "eq":
        return to_range_request(graph, lo=val.value, hi=val.value,
                                lo_op="gte", hi_op="lte",
                                type_handle=type_handle, anchor=anchor)
    if val.op in ("gt", "gte"):
        return to_range_request(graph, lo=val.value, lo_op=val.op,
                                type_handle=type_handle, anchor=anchor)
    if val.op in ("lt", "lte"):
        return to_range_request(graph, hi=val.value, hi_op=val.op,
                                type_handle=type_handle, anchor=anchor)
    raise Unservable(f"value op {val.op!r} has no range window")


def _try_range_and(graph, clauses) -> Optional[RangeRequest]:
    """``And(AtomValue{1,2}, [AtomType], [Incident])`` → one range
    window, or None when the conjunction is not range-shaped (the
    pattern/join translations then get their turn)."""
    vals: list[c.AtomValue] = []
    types: list[c.AtomType] = []
    incs: list[int] = []
    for cl in clauses:
        if isinstance(cl, c.AtomValue):
            vals.append(cl)
        elif isinstance(cl, c.AtomType):
            types.append(cl)
        elif isinstance(cl, c.Incident):
            incs.append(int(cl.target))
        else:
            return None
    if not vals or len(vals) > 2 or len(types) > 1 or len(incs) > 1:
        return None
    th = _type_handle(graph, types[0]) if types else None
    anchor = incs[0] if incs else None
    if len(vals) == 1:
        return _value_to_range(graph, vals[0], th, anchor)
    lo = next((v for v in vals if v.op in ("gt", "gte")), None)
    hi = next((v for v in vals if v.op in ("lt", "lte")), None)
    if lo is None or hi is None:
        return None
    return to_range_request(graph, lo=lo.value, hi=hi.value,
                            lo_op=lo.op, hi_op=hi.op,
                            type_handle=th, anchor=anchor)


def to_request(graph, condition, *, default_max_hops: int = 2):
    """Translate ``condition`` into a batchable request, or raise
    :class:`Unservable` naming the unsupported shape."""
    if isinstance(condition, c.AtomValue):
        return _value_to_range(graph, condition)
    if isinstance(condition, c.TypedValue):
        return _value_to_range(
            graph, c.AtomValue(condition.value, condition.op),
            _type_handle(graph, c.AtomType(condition.type)),
        )
    if isinstance(condition, c.BFS):
        hops = condition.max_distance
        if hops is None:
            # fixed-shape kernels need a static hop count; an unbounded
            # traversal has no batchable device form
            raise Unservable(
                "unbounded BFS is not batchable; set max_distance (the "
                f"runtime default is {default_max_hops})"
            )
        return BFSRequest(int(condition.start), int(hops),
                          include_seed=bool(condition.include_start))
    if isinstance(condition, c.Incident):
        return PatternRequest((int(condition.target),))
    if isinstance(condition, c.TypedIncident):
        return PatternRequest(
            (int(condition.target),),
            _type_handle(graph, c.AtomType(condition.type)),
        )
    if isinstance(condition, c.Link):
        return PatternRequest(tuple(int(t) for t in condition.targets))
    if isinstance(condition, c.CoIncident):
        # distinct=False: a single-variable CONDITION has find_all
        # semantics — CoIncident is already irreflexive and Incident(a)
        # legitimately admits a self-targeting a (the same reasoning as
        # the compiler's try_single_var_join); distinct=True would
        # silently drop that atom on the serve path only
        return to_join_request(graph, {"x": condition}, distinct=False)
    if isinstance(condition, c.And):
        if any(isinstance(cl, c.CoIncident) for cl in condition.clauses):
            # adjacency conjunctions (common neighbours, anchored
            # patterns) are the join lane's one-variable shape;
            # distinct=False per the single-variable contract above
            return to_join_request(graph, {"x": condition},
                                   distinct=False)
        if any(isinstance(cl, c.AtomValue) for cl in condition.clauses):
            # value-predicate conjunctions are the hgindex range lane's
            # shape: 1-2 bounds of ONE kind, optional type, optional
            # single incident anchor
            rr = _try_range_and(graph, condition.clauses)
            if rr is not None:
                return rr
            raise Unservable(
                "value conjunction outside the range lane's shape "
                "(need 1-2 same-kind bounds, at most one AtomType and "
                "one Incident)"
            )
        anchors: list[int] = []
        type_h = None
        for cl in condition.clauses:
            if isinstance(cl, c.Incident):
                anchors.append(int(cl.target))
            elif isinstance(cl, c.TypedIncident):
                anchors.append(int(cl.target))
                th = _type_handle(graph, c.AtomType(cl.type))
                if type_h is not None and type_h != th:
                    raise Unservable("conflicting type constraints")
                type_h = th
            elif isinstance(cl, c.AtomType):
                th = _type_handle(graph, cl)
                if type_h is not None and type_h != th:
                    raise Unservable("conflicting type constraints")
                type_h = th
            else:
                raise Unservable(
                    f"{type(cl).__name__} inside And is outside the "
                    "batchable subset (Incident/TypedIncident/AtomType)"
                )
        if not anchors:
            raise Unservable("And without an Incident anchor has no "
                             "batchable device form")
        return PatternRequest(tuple(anchors), type_h)
    raise Unservable(
        f"{type(condition).__name__} is outside the batchable subset; "
        "use graph.find_all"
    )


def to_join_request(graph, spec: Mapping[str, c.HGQueryCondition],
                    distinct: bool = True) -> JoinRequest:
    """Translate a multi-variable condition SPEC (``{var: condition}``,
    cross-references spelled with ``query.variables.Var``) into a
    batchable :class:`JoinRequest`, or raise :class:`Unservable`
    (``join/ir.JoinUnsupported`` is a subclass) naming the clause
    outside the pattern vocabulary. The signature/constant split means
    two requests for the same SHAPE — a triangle at atom 17, a triangle
    at atom 99 — share one batch key and ride one compiled program."""
    from hypergraphdb_tpu.join.ir import extract_pattern, split_constants

    pattern = extract_pattern(graph, spec, distinct=distinct)
    if not any(not a.key_is_var for a in pattern.atoms):
        raise Unservable(
            "a servable join needs at least one constant anchor; "
            "unanchored (whole-graph) patterns run through "
            "ops.join.execute_join's seeds mode instead"
        )
    sig, consts = split_constants(pattern)
    return JoinRequest(sig, consts)
