"""Query engine: condition vocabulary, compiler, DSL, serialization,
parameterized queries (SURVEY §2.1 "Query conditions/compiler/executors")."""

from hypergraphdb_tpu.query import conditions, dsl
from hypergraphdb_tpu.query.compiler import CompiledQuery, compile_query
from hypergraphdb_tpu.query.variables import PreparedQuery, Var, prepare, var

__all__ = [
    "CompiledQuery",
    "PreparedQuery",
    "Var",
    "compile_query",
    "conditions",
    "dsl",
    "prepare",
    "var",
]
