"""Query compiler: conditions → physical plans → (host|device) execution.

Re-expression of the reference's compile pipeline (``cond2qry/
ExpressionBasedQuery.java:853-875``): preprocess → expand → toDNF →
simplify → translate, with the cost-based conjunction planner of
``AndToQuery`` (``cond2qry/AndToQuery.java:102-306``: partition conjuncts
into set-producing vs predicate classes, sort by expected size, intersect
smallest-first, demote the rest to filters).

The execution model is deliberately different from the reference's lazy
cursor trees: every set-producing conjunct materializes as a **sorted int64
array** (they already live in that form in the storage layer), and
intersections/unions are vectorized merges — ``np.intersect1d`` is the
batched equivalent of the reference's ZigZag/SortedIntersection duality
(``impl/ZigZagIntersectionResult.java:23``). That same array form is what
the device executor consumes: large plans are pushed to TPU as sorted-set
kernels (``ops/setops.py``) while small ones stay on host — the planner
duality from SURVEY §7 ("hard parts" #4).
"""

from __future__ import annotations

import itertools
import logging
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from hypergraphdb_tpu.core.errors import QueryError
from hypergraphdb_tpu.obs import global_tracer
from hypergraphdb_tpu.query import conditions as c

logger = logging.getLogger("hypergraphdb_tpu.query")

# ============================================================ physical plans


class Plan:
    """A physical plan node. ``run(graph) -> sorted np.int64 array``."""

    def run(self, graph) -> np.ndarray:
        raise NotImplementedError

    def estimate(self, graph) -> float:
        """Expected result size (the reference's ``QueryMetaData`` expected
        size used for intersection ordering)."""
        return float("inf")

    def describe(self) -> str:
        return type(self).__name__


_EMPTY = np.empty(0, dtype=np.int64)


@dataclass
class EmptyPlan(Plan):
    def run(self, graph):
        return _EMPTY

    def estimate(self, graph):
        return 0.0

    def describe(self):
        return "∅"


@dataclass
class SingletonPlan(Plan):
    handle: int

    def run(self, graph):
        if graph.contains(self.handle):
            return np.asarray([self.handle], dtype=np.int64)
        return _EMPTY

    def estimate(self, graph):
        return 1.0

    def describe(self):
        return f"is({self.handle})"


@dataclass
class AllAtomsPlan(Plan):
    def run(self, graph):
        return np.fromiter(graph.atoms(), dtype=np.int64)

    def estimate(self, graph):
        # the dense-id high-water mark is an O(1) upper bound on live atoms
        # (real cardinality, not a magic constant — VERDICT r4 missing #3);
        # still the largest child of any conjunction it appears in
        try:
            return float(max(int(graph.handles.peek), 1))
        except Exception:
            return 1e12

    def describe(self):
        return "scan(*)"


@dataclass
class TypeSetPlan(Plan):
    """All atoms of a type — by-type system index lookup."""

    type_handle: int

    def run(self, graph):
        from hypergraphdb_tpu.core.graph import IDX_BY_TYPE, _type_key

        return graph.store.get_index(IDX_BY_TYPE).find(
            _type_key(self.type_handle)
        ).array()

    def estimate(self, graph):
        from hypergraphdb_tpu.core.graph import IDX_BY_TYPE, _type_key

        return float(
            graph.store.get_index(IDX_BY_TYPE).count(_type_key(self.type_handle))
        )

    def describe(self):
        return f"type({self.type_handle})"


def _capped_range_estimate(graph, idx, stats_name: str, bounds) -> float:
    """Shared range-scan cardinality policy (HGIndexStats.java:37
    semantics): cost-capped EXACT count where ordering decisions live; a
    saturated count falls back to the persisted whole-index stats so
    'big' ranges stay ordered among themselves. One implementation for
    the by-value system index and user indexes — the policy must not
    drift between them (review r5 finding 7)."""
    lo, hi, lo_inc, hi_inc = bounds
    cap = graph.config.query.range_estimate_cap
    n = idx.count_range(
        lo=lo, hi=hi, lo_inclusive=lo_inc, hi_inclusive=hi_inc, cap=cap,
    )
    if n >= cap:
        from hypergraphdb_tpu.indexing.manager import index_stats

        stats = index_stats(graph, stats_name)
        return float(max(cap, stats["entries"] // 2))
    return float(n)


@dataclass
class ValueSetPlan(Plan):
    """Atoms by value via the by-value system index; eq or ordered range."""

    key: bytes
    op: str = "eq"
    kind: bytes = b""  # kind prefix bounding range scans

    def _bounds(self) -> tuple:
        """(lo, hi, lo_inclusive, hi_inclusive) of the range scan — shared
        by run() and estimate() so the estimate counts exactly what the
        scan will read."""
        hi_kind = bytes([self.kind[0] + 1]) if self.kind else None
        if self.op == "lt":
            return self.kind, self.key, True, False
        if self.op == "lte":
            return self.kind, self.key, True, True
        if self.op == "gt":
            return self.key, hi_kind, False, False
        if self.op == "gte":
            return self.key, hi_kind, True, False
        raise QueryError(f"bad value op {self.op}")

    def _find(self, graph):
        from hypergraphdb_tpu.core.graph import IDX_BY_VALUE

        idx = graph.store.get_index(IDX_BY_VALUE)
        if self.op == "eq":
            return idx.find(self.key)
        lo, hi, lo_inc, hi_inc = self._bounds()
        return idx.find_range(
            lo=lo, hi=hi, lo_inclusive=lo_inc, hi_inclusive=hi_inc
        )

    def run(self, graph):
        return self._find(graph).array()

    def estimate(self, graph):
        from hypergraphdb_tpu.core.graph import IDX_BY_VALUE

        idx = graph.store.get_index(IDX_BY_VALUE)
        if self.op == "eq":
            return float(idx.count(self.key))
        return _capped_range_estimate(
            graph, idx, IDX_BY_VALUE, self._bounds()
        )

    def describe(self):
        return f"value[{self.op}]"


@dataclass
class IncidentPlan(Plan):
    """The incidence set of an atom — sorted by construction."""

    target: int

    def run(self, graph):
        return graph.get_incidence_set(self.target).array()

    def estimate(self, graph):
        return float(graph.store.incidence_count(self.target))

    def describe(self):
        return f"incident({self.target})"


@dataclass
class TypedIncidencePlan(Plan):
    """``And(Incident(t), AtomType(T))`` answered from the incidence set
    plus ONE vectorized gather into the hot host type column — no store
    record read per candidate link and no full type-set materialization
    (the reference's typed-incidence annotation,
    ``storage/bdb-native/.../TypeAndPositionIncidenceAnnotator.java``)."""

    target: int
    type_handle: int

    def run(self, graph):
        arr = graph.get_incidence_set(self.target).array()
        if not len(arr):
            return np.asarray(arr, dtype=np.int64)
        tcol = graph.type_column()
        return np.asarray(
            arr[tcol.types_of(arr) == self.type_handle], dtype=np.int64
        )

    def estimate(self, graph):
        from hypergraphdb_tpu.core.graph import IDX_BY_TYPE, _type_key

        inc = graph.store.incidence_count(self.target)
        tcnt = graph.store.get_index(IDX_BY_TYPE).count(
            _type_key(self.type_handle)
        )
        return float(min(inc, tcnt))

    def describe(self):
        return f"typed-incident({self.target}, type({self.type_handle}))"


@dataclass
class NeighborsPlan(Plan):
    """The co-incidence neighbourhood of an atom — every atom sharing at
    least one link with ``other`` (``conditions.CoIncident``): the union
    of the target tuples of ``other``'s incidence row, minus ``other``
    itself. The host leaf the join subsystem's ground truth runs on; the
    device twin is one row of ``ops/join.neighbor_csr``."""

    other: int

    def run(self, graph):
        links = graph.get_incidence_set(self.other).array()
        if not len(links):
            return _EMPTY
        snap = graph._snapshot_cache
        if snap is not None and snap.version == graph._mutations and (
            links < snap.num_atoms
        ).all():
            starts = snap.tgt_offsets[links].astype(np.int64)
            lens = snap.arity[links].astype(np.int64)
            idx = np.repeat(starts, lens) + (
                np.arange(int(lens.sum())) - np.repeat(
                    np.cumsum(lens) - lens, lens
                )
            )
            out = snap.tgt_flat[idx].astype(np.int64)
        else:
            ts: list[int] = []
            for l in links.tolist():
                try:
                    ts.extend(int(t) for t in graph.get_targets(l))
                except Exception:
                    continue
            out = np.asarray(ts, dtype=np.int64)
        out = np.unique(out)
        return out[out != int(self.other)]

    def estimate(self, graph):
        # each incident link contributes (arity - 1) co-targets; the
        # flat factor keeps the estimate O(1) (no row materialization)
        # while ordering correctly against sibling incidence estimates
        return 2.0 * float(graph.store.incidence_count(self.other))

    def describe(self):
        return f"neighbors({self.other})"


@dataclass
class TargetSetPlan(Plan):
    """The (sorted, deduped) targets of a link."""

    link: int

    def run(self, graph):
        try:
            ts = graph.get_targets(self.link)
        except Exception:
            return _EMPTY
        return np.unique(np.asarray(ts, dtype=np.int64)) if ts else _EMPTY

    def estimate(self, graph):
        try:
            return float(graph.arity(self.link))
        except Exception:
            return 0.0

    def describe(self):
        return f"targets({self.link})"


@dataclass
class IndexSetPlan(Plan):
    """Lookup in a registered user index."""

    name: str
    key: bytes
    op: str = "eq"

    def run(self, graph):
        from hypergraphdb_tpu.indexing.manager import get_index

        idx = get_index(graph, self.name)
        if self.op == "eq":
            return idx.find(self.key).array()
        return {
            "lt": idx.find_lt,
            "lte": idx.find_lte,
            "gt": idx.find_gt,
            "gte": idx.find_gte,
        }[self.op](self.key).array()

    def estimate(self, graph):
        from hypergraphdb_tpu.indexing.manager import get_index

        idx = get_index(graph, self.name)
        if self.op == "eq":
            return float(idx.count(self.key))
        bounds = {
            "lt": (None, self.key, True, False),
            "lte": (None, self.key, True, True),
            "gt": (self.key, None, False, False),
            "gte": (self.key, None, True, False),
        }[self.op]
        return _capped_range_estimate(graph, idx, self.name, bounds)

    def describe(self):
        return f"index({self.name})[{self.op}]"


@dataclass
class TraversalPlan(Plan):
    """Reachable-set materialization of a BFS/DFS condition (the reference's
    ``TraversalBasedQuery``). Device-accelerated for large graphs via the
    CSR snapshot BFS kernel."""

    start: int
    max_distance: Optional[int]
    include_start: bool
    depth_first: bool = False

    def run(self, graph):
        from hypergraphdb_tpu.algorithms.traversals import (
            HGBreadthFirstTraversal,
            HGDepthFirstTraversal,
        )

        cls = HGDepthFirstTraversal if self.depth_first else HGBreadthFirstTraversal
        out = [a for _, a in cls(graph, self.start, max_distance=self.max_distance)]
        if self.include_start:
            out.append(int(self.start))
        return np.unique(np.asarray(out, dtype=np.int64)) if out else _EMPTY

    def describe(self):
        return f"{'dfs' if self.depth_first else 'bfs'}({self.start})"


@dataclass
class IntersectPlan(Plan):
    """Sorted-set intersection of children + residual predicate filters —
    the vectorized AndToQuery output."""

    children: list[Plan]
    predicates: list[c.HGQueryCondition] = field(default_factory=list)

    def run(self, graph):
        ordered = sorted(self.children, key=lambda p: p.estimate(graph))
        cfg = graph.config.query
        # planner duality (SURVEY §7 hard part 4): small intersections stay
        # on host cursors; large ones amortize a device kernel launch
        use_device = (
            cfg.prefer_device
            and len(ordered) > 1
            and ordered[0].estimate(graph) >= cfg.device_min_batch
        )
        if use_device:
            arrays = [c.run(graph) for c in ordered]
            if any(len(a) == 0 for a in arrays):
                return _EMPTY
            try:
                from hypergraphdb_tpu.ops.setops import device_intersect_sorted

                arr = device_intersect_sorted(arrays)
            except Exception:
                # host merge reuses the already-materialized arrays — no
                # re-execution of child plans on fallback
                logger.warning(
                    "device intersection failed; host merge fallback",
                    exc_info=True,
                )
                arr = arrays[0]
                for a in arrays[1:]:
                    if len(arr) == 0:
                        break
                    arr = intersect_sorted(graph, arr, a)
            return filter_predicates(graph, arr, self.predicates)
        arr = ordered[0].run(graph)
        for child in ordered[1:]:
            if len(arr) == 0:
                return arr
            arr = intersect_sorted(graph, arr, child.run(graph))
        return filter_predicates(graph, arr, self.predicates)

    def estimate(self, graph):
        return min((p.estimate(graph) for p in self.children), default=0.0)

    def describe(self):
        inner = " ∩ ".join(p.describe() for p in self.children)
        if self.predicates:
            inner += " | " + ",".join(type(p).__name__ for p in self.predicates)
        return f"({inner})"


#: value kinds whose key payload is fixed-width ≤ 8 bytes — their 64-bit
#: payload rank IS the value order (device compares are exact, no ties);
#: the ONE definition lives at the storage layer beside the sorted
#: columns it governs (``storage/value_index``)
from hypergraphdb_tpu.storage.value_index import (  # noqa: E402
    FIXED_WIDTH_KINDS as _FIXED_WIDTH_KINDS,
)


@dataclass
class DeviceValueConjPlan(Plan):
    """``And(Incident..., AtomValue[range], [AtomType])`` pushed down to one
    device kernel that range-compares the snapshot's order-preserving value
    ranks (``ops/setops.incident_value_pattern``) — the TPU analogue of the
    reference's value-indexed conjunctions (``cond2qry/AndToQuery.java:
    102-306``). Fixed-width kinds run tie-free on device; variable-width
    kinds host-verify only rank ties. Falls back to the classic plan when
    the snapshot has no ELL targets (over-wide links) or the value type is
    not device-encodable."""

    targets: list[int]
    value: Any
    op: str
    type_handle: Optional[int]
    fallback: Plan
    #: optional SECOND bound: (value, op) is then the lower bound and
    #: (value2, op2) the upper — an ``And(gte lo, lt hi)`` range window runs
    #: as ONE fused launch (``ops/setops.incident_value_range``) instead of
    #: two full membership passes (VERDICT r4 item 4)
    value2: Any = None
    op2: Optional[str] = None

    def run(self, graph):
        from hypergraphdb_tpu.ops.setops import (
            _bucket,
            ell_targets,
            incident_value_pattern,
            incident_value_range,
        )
        from hypergraphdb_tpu.utils.ordered_bytes import rank64

        cfg = graph.config.query
        if self.estimate(graph) < cfg.device_min_batch:
            return self.fallback.run(graph)  # planner duality: small → host
        vt = graph.typesystem.infer(self.value)
        if vt is None:
            return self.fallback.run(graph)
        if self.op2 is not None:
            vt2 = graph.typesystem.infer(self.value2)
            if vt2 is not vt:
                return self.fallback.run(graph)  # mixed-kind bounds: host
        mgr = graph.incremental
        if mgr is not None:
            # ONE-lock read view: base + memtable captured together, so a
            # background compaction swapping mid-query cannot desync them
            snap, dead, new_atoms, revalued = mgr.read_view()
        else:
            snap = graph.snapshot()
            dead = new_atoms = revalued = None
        if any(t >= snap.num_atoms for t in self.targets):
            # anchor beyond the (stale) base's id space — host plan is fresh
            return self.fallback.run(graph)
        ell = ell_targets(snap)
        if ell is None:
            return self.fallback.run(graph)
        import jax.numpy as jnp

        key = vt.to_key(self.value)
        kind, payload = key[0], key[1:]
        exact = kind in _FIXED_WIDTH_KINDS
        rank = rank64(payload)
        # smallest incidence row is the gathered base (hub-proof)
        anchors = np.asarray(self.targets, dtype=np.int32)
        lens = snap.inc_offsets[anchors + 1] - snap.inc_offsets[anchors]
        anchors = anchors[np.argsort(lens, kind="stable")]
        pad = _bucket(int(lens.min()) if len(lens) else 1)
        th = None if self.type_handle is None else jnp.int32(self.type_handle)
        if self.op2 is not None:
            rank2 = rank64(vt.to_key(self.value2)[1:])
            rows, keep, tie, _ = incident_value_range(
                snap.device, ell, jnp.asarray(anchors[None, :]), pad,
                jnp.uint8(kind),
                jnp.uint32(rank >> 32), jnp.uint32(rank & 0xFFFFFFFF),
                jnp.uint32(rank2 >> 32), jnp.uint32(rank2 & 0xFFFFFFFF),
                self.op, self.op2, exact, th,
            )
        else:
            rows, keep, tie = incident_value_pattern(
                snap.device, ell, jnp.asarray(anchors[None, :]), pad,
                jnp.uint8(kind),
                jnp.uint32(rank >> 32), jnp.uint32(rank & 0xFFFFFFFF),
                self.op, exact, th,
            )
        rows = np.asarray(rows[0])
        arr = rows[np.asarray(keep[0])].astype(np.int64)
        ties = rows[np.asarray(tie[0])]
        if len(ties):
            vcs = [c.AtomValue(self.value, self.op)] + (
                [c.AtomValue(self.value2, self.op2)]
                if self.op2 is not None else []
            )
            verified = [
                int(h) for h in ties.tolist()
                if all(vc.satisfies(graph, h) for vc in vcs)
            ]
            if verified:
                arr = np.union1d(arr, np.asarray(verified, dtype=np.int64))
        if new_atoms is not None:
            # LSM read merge: the device result was computed on the BASE;
            # drop tombstoned/revalued handles and host-evaluate the
            # conjunction over the (small) memtable
            drop = dead | revalued
            if drop and len(arr):
                arr = arr[~np.isin(arr, np.fromiter(drop, dtype=np.int64))]
            cands = (set(new_atoms) | revalued) - dead
            fresh = [h for h in cands if self._matches_host(graph, h)]
            if fresh:
                arr = np.union1d(arr, np.asarray(fresh, dtype=np.int64))
        return arr

    def _matches_host(self, graph, h: int) -> bool:
        if not graph.contains(h):
            return False
        try:
            ts = {int(t) for t in graph.get_targets(h)}
        except Exception:
            return False
        if any(t not in ts for t in self.targets):
            return False
        if self.type_handle is not None and int(
            graph.get_type_handle_of(h)
        ) != self.type_handle:
            return False
        if not c.AtomValue(self.value, self.op).satisfies(graph, h):
            return False
        return self.op2 is None or c.AtomValue(
            self.value2, self.op2
        ).satisfies(graph, h)

    def estimate(self, graph):
        return float(
            min(graph.store.incidence_count(t) for t in self.targets)
        )

    def describe(self):
        t = f", type({self.type_handle})" if self.type_handle is not None else ""
        v = f"value[{self.op}]"
        if self.op2 is not None:
            v = f"value[{self.op}..{self.op2}]"
        return (
            f"device({v} ∩ "
            + " ∩ ".join(f"incident({x})" for x in self.targets)
            + t + ")"
        )


@dataclass
class UnionPlan(Plan):
    """Sorted union of children; the merge is vectorized (``np.unique``
    over the concatenated child arrays) regardless of ``parallel``.

    ``parallel`` mirrors ``OrToParellelQuery``/``UnionResultAsync`` for
    API parity but is OFF by default: index-read children are GIL-bound,
    and the measured thread-pool 'speedup' is 0.9× — a slight loss
    (CALIBRATION.md §3)."""

    children: list[Plan]
    parallel: bool = False

    def run(self, graph):
        if self.parallel and len(self.children) > 1:
            # OrToParellelQuery/UnionResultAsync analogue. The caller's
            # transaction lives in a thread-local stack, so each worker must
            # explicitly join it — otherwise branches read committed state
            # only and miss the tx's own writes.
            from concurrent.futures import ThreadPoolExecutor

            tx = graph.txman.current()

            def run_child(p):
                with graph.txman.scoped(tx):
                    return p.run(graph)

            with ThreadPoolExecutor(max_workers=min(8, len(self.children))) as ex:
                arrays = list(ex.map(run_child, self.children))
        else:
            arrays = [p.run(graph) for p in self.children]
        arrays = [a for a in arrays if len(a)]
        if not arrays:
            return _EMPTY
        return np.unique(np.concatenate(arrays))

    def estimate(self, graph):
        return sum(p.estimate(graph) for p in self.children)

    def describe(self):
        return "(" + " ∪ ".join(p.describe() for p in self.children) + ")"


@dataclass
class FilterScanPlan(Plan):
    """Full scan + predicates — the W class: no index narrows it."""

    predicates: list[c.HGQueryCondition]

    def run(self, graph):
        arr = np.fromiter(graph.atoms(), dtype=np.int64)
        return filter_predicates(graph, arr, self.predicates)

    def describe(self):
        return "scan|" + ",".join(type(p).__name__ for p in self.predicates)


# ============================================================ result mapping


@dataclass(frozen=True)
class LinkProjectionMapping:
    """Map each result LINK to its target at ``position``
    (``query/impl/LinkProjectionMapping``). Vectorized against the
    snapshot's target columns when fresh, per-handle otherwise."""

    position: int

    #: output is a handle set → composable inside MapCondition/And/Or
    returns_handles = True

    def __post_init__(self):
        if int(self.position) < 0:
            raise QueryError(
                "LinkProjectionMapping position must be >= 0 (negative "
                "indexing would mean different things on the columnar and "
                "per-handle paths)"
            )

    def apply(self, graph, arr: np.ndarray) -> np.ndarray:
        if len(arr) == 0:
            return arr
        cols = _columns_for_filter(graph, len(arr))
        pos = int(self.position)
        if cols is not None:
            snap, memtable = cols
            ok = (arr < snap.num_atoms)
            if memtable:
                ok &= ~np.isin(arr, np.fromiter(memtable, dtype=np.int64))
            out = []
            sel = arr[ok]
            good = snap.arity[sel] > pos
            offs = snap.tgt_offsets[sel[good]].astype(np.int64) + pos
            out.append(snap.tgt_flat[offs].astype(np.int64))
            for h in arr[~ok].tolist():
                try:
                    ts = graph.get_targets(h)
                except Exception:
                    continue
                if pos < len(ts):
                    out.append(np.asarray([int(ts[pos])], dtype=np.int64))
            return np.unique(np.concatenate(out)) if out else _EMPTY
        vals = []
        for h in arr.tolist():
            try:
                ts = graph.get_targets(h)
            except Exception:
                continue
            if pos < len(ts):
                vals.append(int(ts[pos]))
        return np.unique(np.asarray(vals, dtype=np.int64)) if vals else _EMPTY


@dataclass(frozen=True)
class DerefMapping:
    """Map each result handle to its VALUE (``query/impl/DerefMapping``);
    the output is a python list, not a handle set — top-level
    ``result_map``/``deref`` only, never inside MapCondition."""

    returns_handles = False

    def apply(self, graph, arr: np.ndarray) -> list:
        return [graph.get(int(h)) for h in arr.tolist()]


@dataclass
class ResultMapPlan(Plan):
    """``ResultMapQuery``: run the child, then map every result."""

    child: Plan
    mapping: Any

    def run(self, graph):
        return self.mapping.apply(graph, self.child.run(graph))

    def estimate(self, graph):
        return self.child.estimate(graph)

    def describe(self):
        return f"map[{type(self.mapping).__name__}]({self.child.describe()})"


@dataclass
class PipePlan(Plan):
    """``PipeQuery`` (``query/impl/PipeQuery.java:25``): every result of
    the producer becomes the KEY of a dependent query; the union of the
    keyed queries' results is the pipe's output. ``key_condition`` maps a
    produced handle to the downstream condition."""

    producer: Plan
    key_condition: Any  # Callable[[int], HGQueryCondition]

    def run(self, graph):
        keys = self.producer.run(graph)
        if len(keys) == 0:
            return _EMPTY
        outs = []
        for k in keys.tolist():
            # traced=False: these per-key compiles run their plans
            # directly, so a trace would never finish — a pipe over 10k
            # keys must not allocate 10k span trees that vanish
            sub = compile_query(graph, self.key_condition(int(k)),
                                traced=False)
            arr = sub.plan.run(graph)
            if len(arr):
                outs.append(arr)
        if not outs:
            return _EMPTY
        return np.unique(np.concatenate(outs))

    def describe(self):
        return f"pipe({self.producer.describe()} → ...)"


def result_map(graph, condition, mapping):
    """Compile + run ``condition`` and map results (the hg.apply DSL).
    Untraced: the plan runs through a wrapper plan, not ``execute()``, so
    an opened query trace would never finish/export."""
    q = compile_query(graph, condition, traced=False)

    def run():
        return ResultMapPlan(q.plan, mapping).run(graph)

    return graph.txman.ensure_transaction(run, readonly=True)


def pipe(graph, producer_condition, key_condition):
    """Compile + run a pipe: producer results keyed into a dependent
    condition builder (``PipeQuery`` semantics). Untraced — see
    :func:`result_map`."""
    q = compile_query(graph, producer_condition, traced=False)

    def run():
        return PipePlan(q.plan, key_condition).run(graph)

    return graph.txman.ensure_transaction(run, readonly=True)


# ============================================================ helpers


#: zig-zag/merge crossover, MEASURED (CALIBRATION.md §1): probing wins
#: from 4× size disparity at every tested small size (1K–100K over the
#: 10M id space); the old 32 made 4×–32× intersections pay the merge
ZIGZAG_RATIO = 4


def intersect_sorted(graph, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Vectorized sorted intersection. For different-enough sizes use
    searchsorted probing (the zig-zag/leapfrog analogue); otherwise a
    merge (``np.intersect1d``) — mirroring the reference's
    ZigZag-vs-SortedIntersection choice by size ratio."""
    if len(a) == 0 or len(b) == 0:
        return _EMPTY
    small, large = (a, b) if len(a) <= len(b) else (b, a)
    if len(large) > ZIGZAG_RATIO * len(small):
        pos = np.searchsorted(large, small)
        pos = np.minimum(pos, len(large) - 1)
        return small[large[pos] == small]
    return np.intersect1d(a, b, assume_unique=True)


#: conditions decidable from snapshot columns alone (no payload access)
_VECTOR_PREDICATES = (c.Arity, c.IsLink, c.IsNode, c.AtomType,
                      c.PositionedIncident)

_NP_OPS = {
    "eq": np.equal, "lt": np.less, "lte": np.less_equal,
    "gt": np.greater, "gte": np.greater_equal,
}


def _vector_predicate_mask(graph, snap, arr: np.ndarray,
                           pred: c.HGQueryCondition) -> np.ndarray:
    """Columnar evaluation of one residual predicate over handle array
    ``arr`` — the batched replacement for per-handle ``satisfies`` calls
    (VERDICT r2 item 7). ``arr`` values must be < snap.num_atoms."""
    if isinstance(pred, c.Arity):
        return _NP_OPS[pred.op](snap.arity[arr], pred.arity)
    if isinstance(pred, c.IsLink):
        return snap.is_link[arr].copy()
    if isinstance(pred, c.IsNode):
        return ~snap.is_link[arr]
    if isinstance(pred, c.AtomType):
        return snap.type_of[arr] == int(pred.type_handle(graph))
    if isinstance(pred, c.PositionedIncident):
        pos = int(pred.position)
        ok = snap.arity[arr] > pos
        off = snap.tgt_offsets[arr].astype(np.int64) + pos
        vals = snap.tgt_flat[np.where(ok, off, 0)]
        return ok & (vals == int(pred.target))
    raise QueryError(f"not a vectorizable predicate: {pred!r}")


def _columns_for_filter(graph, n_handles: int):
    """A snapshot usable for columnar filtering + the memtable handle set
    that must fall back to per-handle evaluation (exactness under
    incremental mode). None → no cheap columns; use the Python loop."""
    mgr = graph.incremental
    if mgr is not None:
        base, dead, new_atoms, revalued = mgr.read_view()
        return base, set(new_atoms) | revalued | dead
    snap = graph._snapshot_cache
    if snap is not None and snap.version == graph._mutations:
        return snap, set()
    # no fresh columns: packing amortizes only over big filter batches
    if n_handles >= 4096:
        return graph.snapshot(), set()
    return None


def filter_predicates(
    graph, arr: np.ndarray, predicates: Sequence[c.HGQueryCondition]
) -> np.ndarray:
    if not predicates or len(arr) == 0:
        return arr
    vec = [p for p in predicates if isinstance(p, _VECTOR_PREDICATES)]
    rest = [p for p in predicates if not isinstance(p, _VECTOR_PREDICATES)]
    if vec:
        cols = _columns_for_filter(graph, len(arr))
        if cols is None:
            rest = predicates  # no columns: everything via satisfies
        else:
            snap, memtable = cols
            in_cols = arr < snap.num_atoms
            if memtable and in_cols.any():
                mt = np.fromiter(memtable, dtype=np.int64)
                in_cols &= ~np.isin(arr, mt)
            mask = in_cols.copy()
            sel = arr[in_cols]
            keep = np.ones(len(sel), dtype=bool)
            for p in vec:
                keep &= _vector_predicate_mask(graph, snap, sel, p)
            mask[in_cols] = keep
            # memtable / out-of-range handles: exact per-handle evaluation
            outside = np.nonzero(~in_cols)[0]
            for i in outside.tolist():
                mask[i] = all(p.satisfies(graph, int(arr[i])) for p in vec)
            arr = arr[mask]
    if not rest or len(arr) == 0:
        return arr
    keep = [h for h in arr.tolist() if all(p.satisfies(graph, h) for p in rest)]
    return np.asarray(keep, dtype=np.int64)


# ============================================================ rewriting


def expand(graph, cond: c.HGQueryCondition) -> c.HGQueryCondition:
    """Expansion pass (``ExpressionBasedQuery.expand`` :603): rewrite sugar
    into primitive conditions + discover applicable user indices."""
    if isinstance(cond, c.And):
        return c.And(*(expand(graph, x) for x in cond.clauses))
    if isinstance(cond, c.Or):
        return c.Or(*(expand(graph, x) for x in cond.clauses))
    if isinstance(cond, c.Not):
        return c.Not(expand(graph, cond.clause))
    if isinstance(cond, c.TypePlus):
        ts = graph.typesystem
        name = cond.type if isinstance(cond.type, str) else ts.name_of(cond.type)
        closure = sorted(ts.subtypes_closure(name))
        return c.Or(*(c.AtomType(n) for n in closure))
    if isinstance(cond, c.Link):
        if not cond.targets:
            return c.IsLink()
        return c.And(*(c.Incident(t) for t in cond.targets))
    if isinstance(cond, c.OrderedLink):
        if not cond.targets:
            return c.IsLink()
        # incidence narrows; the order itself stays a predicate
        return c.And(*(c.Incident(t) for t in cond.targets), cond)
    if isinstance(cond, c.TypedValue):
        return c.And(c.AtomType(cond.type), c.AtomValue(cond.value, cond.op))
    if isinstance(cond, c.TypedIncident):
        return c.And(c.Incident(cond.target), c.AtomType(cond.type))
    return cond


def _find_part_index(graph, cond: c.AtomPart, type_handles: set[int]
                     ) -> Optional[c.IndexCondition]:
    """Index discovery (``ExpressionBasedQuery.findIndex`` :59): an
    ``AtomPart`` becomes a direct index lookup ONLY when the enclosing
    conjunction already constrains the atom type to one covered by a
    registered ByPartIndexer — an index must never change query answers by
    excluding other types."""
    from hypergraphdb_tpu.indexing.manager import ByPartIndexer, _registry

    pt = graph.typesystem.infer(cond.value)
    if pt is None:
        return None
    for type_handle, idxs in _registry(graph).items():
        if int(type_handle) not in type_handles:
            continue
        for ix in idxs:
            if isinstance(ix, ByPartIndexer) and ix.dimension == cond.path:
                return c.IndexCondition(ix.name, pt.to_key(cond.value), cond.op)
    return None


def _substitute_part_indices(graph, conj: c.And) -> c.And:
    """Within one conjunction, swap AtomPart conditions for index lookups
    where sound (the type is pinned and indexed on that dimension)."""
    type_handles = {
        x.type_handle(graph) for x in conj.clauses if isinstance(x, c.AtomType)
    }
    if not type_handles:
        return conj
    out = []
    for cl in conj.clauses:
        if isinstance(cl, c.AtomPart):
            sub = _find_part_index(graph, cl, type_handles)
            out.append(sub if sub is not None else cl)
        else:
            out.append(cl)
    return c.And(*out)


def to_dnf(cond: c.HGQueryCondition) -> c.HGQueryCondition:
    """DNF normalization (``ExpressionBasedQuery.toDNF`` :94) with negation
    pushed to the leaves."""
    cond = _push_not(cond, False)
    return _distribute(cond)


def _push_not(cond: c.HGQueryCondition, neg: bool) -> c.HGQueryCondition:
    if isinstance(cond, c.Not):
        return _push_not(cond.clause, not neg)
    if isinstance(cond, c.And):
        parts = [_push_not(x, neg) for x in cond.clauses]
        return c.Or(*parts) if neg else c.And(*parts)
    if isinstance(cond, c.Or):
        parts = [_push_not(x, neg) for x in cond.clauses]
        return c.And(*parts) if neg else c.Or(*parts)
    if neg:
        if isinstance(cond, c.AnyAtom):
            return c.Nothing()
        if isinstance(cond, c.Nothing):
            return c.AnyAtom()
        return c.Not(cond)
    return cond


def _distribute(cond: c.HGQueryCondition) -> c.HGQueryCondition:
    if isinstance(cond, c.Or):
        return c.Or(*(_distribute(x) for x in cond.clauses))
    if isinstance(cond, c.And):
        clauses = [_distribute(x) for x in cond.clauses]
        # flatten nested Ands
        flat: list = []
        for cl in clauses:
            if isinstance(cl, c.And):
                flat.extend(cl.clauses)
            else:
                flat.append(cl)
        or_idx = next((i for i, cl in enumerate(flat) if isinstance(cl, c.Or)), None)
        if or_idx is None:
            return c.And(*flat)
        the_or = flat[or_idx]
        rest = flat[:or_idx] + flat[or_idx + 1 :]
        return _distribute(
            c.Or(*(c.And(branch, *rest) for branch in the_or.clauses))
        )
    return cond


def _dedupe(items: list) -> list:
    """Order-preserving dedupe tolerant of unhashable condition payloads
    (e.g. AtomValue holding a non-frozen dataclass or a list)."""
    try:
        return list(dict.fromkeys(items))
    except TypeError:
        out: list = []
        for x in items:
            if not any(x == y for y in out):
                out.append(x)
        return out


def simplify(graph, cond: c.HGQueryCondition) -> c.HGQueryCondition:
    """Simplification (``ExpressionBasedQuery.simplify`` :219): flatten,
    dedupe, fold contradictions to Nothing, drop AnyAtom in conjunctions."""
    if isinstance(cond, c.Or):
        out = []
        for cl in cond.clauses:
            s = simplify(graph, cl)
            if isinstance(s, c.Nothing):
                continue
            if isinstance(s, c.AnyAtom):
                return c.AnyAtom()
            if isinstance(s, c.Or):
                out.extend(s.clauses)
            else:
                out.append(s)
        out = _dedupe(out)
        if not out:
            return c.Nothing()
        return out[0] if len(out) == 1 else c.Or(*out)
    if isinstance(cond, c.And):
        out = []
        for cl in cond.clauses:
            s = simplify(graph, cl)
            if isinstance(s, c.Nothing):
                return c.Nothing()
            if isinstance(s, c.AnyAtom):
                continue
            if isinstance(s, c.And):
                out.extend(s.clauses)
            else:
                out.append(s)
        out = _dedupe(out)
        # contradiction: two different exact types
        types = {
            x.type_handle(graph) for x in out if isinstance(x, c.AtomType)
        }
        if len(types) > 1:
            return c.Nothing()
        # contradiction: Is(h) conflicting with Is(h')
        handles = {x.handle for x in out if isinstance(x, c.Is)}
        if len(handles) > 1:
            return c.Nothing()
        if not out:
            return c.AnyAtom()
        return out[0] if len(out) == 1 else c.And(*out)
    if isinstance(cond, c.Not):
        inner = simplify(graph, cond.clause)
        if isinstance(inner, c.Nothing):
            return c.AnyAtom()
        if isinstance(inner, c.AnyAtom):
            return c.Nothing()
        return c.Not(inner)
    return cond


def _apply_index_substitution(graph, cond: c.HGQueryCondition) -> c.HGQueryCondition:
    """Per-conjunction index substitution (the reference folds this into
    ``simplify``, ``ExpressionBasedQuery.java:449-541``)."""
    if isinstance(cond, c.Or):
        return c.Or(*(_apply_index_substitution(graph, x) for x in cond.clauses))
    if isinstance(cond, c.And):
        return _substitute_part_indices(graph, cond)
    return cond


# ============================================================ translation


def _leaf_plan(graph, cond: c.HGQueryCondition) -> Optional[Plan]:
    """Set-producing translation of a leaf (the ORA/O classes of
    ``AndToQuery.java:114-149``); None means predicate-only (P class)."""
    if isinstance(cond, c.AtomType):
        return TypeSetPlan(cond.type_handle(graph))
    if isinstance(cond, c.AtomValue):
        vt = graph.typesystem.infer(cond.value)
        if vt is None:
            return None
        return ValueSetPlan(vt.to_key(cond.value), cond.op, kind=vt.kind)
    if isinstance(cond, c.Incident):
        return IncidentPlan(int(cond.target))
    if isinstance(cond, c.CoIncident):
        return NeighborsPlan(int(cond.other))
    if isinstance(cond, c.PositionedIncident):
        # incidence narrows, position check stays a predicate (cheap)
        return IncidentPlan(int(cond.target))
    if isinstance(cond, c.Target):
        return TargetSetPlan(int(cond.link))
    if isinstance(cond, c.Is):
        return SingletonPlan(int(cond.handle))
    if isinstance(cond, c.IndexCondition):
        return IndexSetPlan(cond.name, cond.key, cond.op)
    if isinstance(cond, c.BFS):
        return TraversalPlan(cond.start, cond.max_distance, cond.include_start, False)
    if isinstance(cond, c.DFS):
        return TraversalPlan(cond.start, cond.max_distance, cond.include_start, True)
    if isinstance(cond, c.SubgraphMember):
        from hypergraphdb_tpu.atom.subgraph import member_index_plan

        return member_index_plan(graph, cond.subgraph)
    if isinstance(cond, c.AnyAtom):
        return AllAtomsPlan()
    if isinstance(cond, c.Nothing):
        return EmptyPlan()
    if isinstance(cond, c.MapCondition):
        if not getattr(cond.mapping, "returns_handles", False):
            # a value-producing mapping (Deref) would feed a python list
            # into the surrounding set algebra — fail at compile time,
            # not deep inside an intersection (review r5 finding 6)
            raise QueryError(
                f"MapCondition mapping {type(cond.mapping).__name__} does "
                "not return handles; use result_map()/deref() at top level"
            )
        return ResultMapPlan(
            translate(graph, simplify(graph, expand(graph, cond.condition))),
            cond.mapping,
        )
    return None


# predicates that still narrow results when combined with a set: keep as filter
def _residual_predicate(cond: c.HGQueryCondition) -> Optional[c.HGQueryCondition]:
    if isinstance(cond, c.PositionedIncident):
        return cond  # set + this position filter
    return None


def _translate_and(graph, clauses: Sequence[c.HGQueryCondition]) -> Plan:
    clauses = list(clauses)
    # typed-incidence fusion: one AtomType + ≥1 Incident → answer the type
    # constraint from the hot type column over the SMALLEST incidence row
    # instead of materializing the whole type set (TypedIncidencePlan)
    types = [cl for cl in clauses if isinstance(cl, c.AtomType)]
    incs = [cl for cl in clauses if isinstance(cl, c.Incident)]
    fused: Optional[Plan] = None
    if len(types) == 1 and incs:
        try:
            th = int(types[0].type_handle(graph))
            best = min(
                incs,
                key=lambda i: graph.store.incidence_count(int(i.target)),
            )
            fused = TypedIncidencePlan(int(best.target), th)
            clauses = [
                cl for cl in clauses if cl is not types[0] and cl is not best
            ]
        except Exception:
            fused = None  # e.g. unknown type name: generic planning decides
    sets: list[Plan] = [fused] if fused is not None else []
    preds: list[c.HGQueryCondition] = []
    for cl in clauses:
        p = _leaf_plan(graph, cl)
        if p is None:
            preds.append(cl)
        else:
            sets.append(p)
            extra = _residual_predicate(cl)
            if extra is not None:
                preds.append(extra)
    if not sets:
        return FilterScanPlan(preds)
    if len(sets) == 1 and not preds:
        return sets[0]
    return IntersectPlan(sets, preds)


def _try_value_pushdown(graph, clauses: Sequence[c.HGQueryCondition]
                        ) -> Optional[Plan]:
    """Recognize ``And(Incident+, AtomValue, [AtomType])`` — exactly the
    conjunction shape the device value kernel serves. Any other clause
    present → None (the generic planner handles it)."""
    if not graph.config.query.prefer_device:
        return None
    incs: list[int] = []
    vals: list[c.AtomValue] = []
    types: list[c.AtomType] = []
    for cl in clauses:
        if isinstance(cl, c.Incident):
            incs.append(int(cl.target))
        elif isinstance(cl, c.AtomValue):
            vals.append(cl)
        elif isinstance(cl, c.AtomType):
            types.append(cl)
        else:
            return None
    if len(vals) not in (1, 2) or not incs or len(types) > 1:
        return None
    th = types[0].type_handle(graph) if types else None
    if len(vals) == 2:
        # a RANGE window: one lower bound (gt/gte) + one upper (lt/lte)
        # fuses into a single device launch (incident_value_range); any
        # other two-value shape goes to the generic planner
        lo = next((v for v in vals if v.op in ("gt", "gte")), None)
        hi = next((v for v in vals if v.op in ("lt", "lte")), None)
        if lo is None or hi is None:
            return None
        return DeviceValueConjPlan(
            targets=incs,
            value=lo.value,
            op=lo.op,
            type_handle=None if th is None else int(th),
            fallback=_translate_and(graph, clauses),
            value2=hi.value,
            op2=hi.op,
        )
    return DeviceValueConjPlan(
        targets=incs,
        value=vals[0].value,
        op=vals[0].op,
        type_handle=None if th is None else int(th),
        fallback=_translate_and(graph, clauses),
    )


def _try_join_pushdown(graph, clauses: Sequence[c.HGQueryCondition]
                       ) -> Optional[Plan]:
    """Recognize ``And(CoIncident+, [Incident*], [AtomType],
    [AtomValue{1,2}])`` — a single-variable conjunctive PATTERN (common
    neighbours, anchored adjacency), optionally VALUE-constrained — and
    hand it to the join planner's cost-based device plan
    (``join/planner.DeviceJoinPlan``). Value predicates ride the
    executor as rank-window filters on the intersection candidates
    (``ops/join.execute_join``'s ``value_windows`` — the hgindex planner
    hook), pruning binding rows instead of post-filtering. The join plan
    carries the classic host translation as its fallback and compares
    costs at run time, so ``translate()`` stays the one arbiter between
    the ``IntersectPlan``/``PipePlan`` host family and the multiway-
    intersection executor. Any clause outside the vocabulary → None
    (generic planning)."""
    if not graph.config.query.prefer_device:
        return None
    if not any(isinstance(cl, c.CoIncident) for cl in clauses):
        return None
    structural: list[c.HGQueryCondition] = []
    value_conds: list[c.AtomValue] = []
    for cl in clauses:
        if isinstance(cl, c.AtomValue):
            value_conds.append(cl)
            continue
        if not isinstance(cl, (c.CoIncident, c.Incident, c.AtomType)):
            return None
        if isinstance(cl, (c.CoIncident, c.Incident)):
            ref = cl.other if isinstance(cl, c.CoIncident) else cl.target
            try:
                int(ref)
            except (TypeError, ValueError):
                return None  # unbound Var: multi-variable specs go
                             # through join.extract_pattern, not here
        structural.append(cl)
    if len(value_conds) > 2:
        return None
    from hypergraphdb_tpu.join.planner import try_single_var_join

    return try_single_var_join(
        graph, structural, fallback=_translate_and(graph, clauses),
        value_conds=value_conds,
    )


def translate(graph, cond: c.HGQueryCondition, parallel_or: bool = False) -> Plan:
    """Translate a simplified DNF condition into a physical plan
    (``QueryCompile.translate`` → ``ToQueryMap`` dispatch)."""
    if isinstance(cond, c.Or):
        return UnionPlan(
            [translate(graph, x, parallel_or) for x in cond.clauses],
            parallel=parallel_or,
        )
    if isinstance(cond, c.And):
        pushed = _try_value_pushdown(graph, cond.clauses)
        if pushed is not None:
            return pushed
        pushed = _try_join_pushdown(graph, cond.clauses)
        if pushed is not None:
            return pushed
        return _translate_and(graph, cond.clauses)
    # single leaf
    p = _leaf_plan(graph, cond)
    if p is not None:
        extra = _residual_predicate(cond)
        if extra is not None:
            return IntersectPlan([p], [extra])
        return p
    return FilterScanPlan([cond])


# ============================================================ compiled query


@dataclass
class CompiledQuery:
    """The executable query handle (``HGQuery`` + ``AnalyzedQuery``
    introspection: ``plan.describe()`` is the plan dump).

    ``trace`` is the hgobs trace opened at compile time (None when
    tracing is off): ``compile`` and ``plan`` spans are already recorded;
    the FIRST ``execute()`` appends its span and finishes the trace —
    one ``compile → plan → execute`` tree per query lifecycle."""

    graph: Any
    condition: c.HGQueryCondition
    simplified: c.HGQueryCondition
    plan: Plan
    trace: Any = None

    def execute(self) -> Iterable[int]:
        def run():
            return self.plan.run(self.graph)

        with self.graph.metrics.timer("query.execute"):
            arr = self._run_traced(
                lambda: self.graph.txman.ensure_transaction(
                    run, readonly=True
                )
            )
        self.graph.metrics.incr("query.executed")
        return iter(arr.tolist())

    def _run_traced(self, runner) -> np.ndarray:
        """Run the plan under the query trace's ``execute`` span. The
        trace finishes on EVERY exit — a raising plan exports an ``error``
        terminal instead of silently dropping the trace (the failing
        query is exactly the one worth inspecting)."""
        tr = self.trace
        sp = (tr.start_span("execute", parent=tr.marks.get("root"))
              if tr is not None and not tr.finished else None)
        try:
            arr = runner()
        except BaseException as e:
            if sp is not None:
                sp.end()
                tr.finish_error(e)
            raise
        if sp is not None:
            sp.set(results=int(len(arr))).end()
            tr.finish()
        return arr

    def results(self) -> np.ndarray:
        return self._run_traced(lambda: self.plan.run(self.graph))

    def count(self) -> int:
        return int(len(self._run_traced(
            lambda: self.plan.run(self.graph)
        )))

    def analyze(self) -> str:
        """Plan dump (AnalyzedQuery: condition → simplified form → physical
        plan, ``QueryCompile.analyze`` ``query/QueryCompile.java:148``)."""
        return (
            f"condition:  {self.condition}\n"
            f"simplified: {self.simplified}\n"
            f"plan:       {self.plan.describe()}"
        )


def compile_query(graph, condition: c.HGQueryCondition,
                  traced: bool = True) -> CompiledQuery:
    """The full pipeline (``ExpressionBasedQuery.compileProcess`` :853).

    ``traced=False`` skips the query trace — for INTERNAL callers whose
    plans run outside ``execute()``/``results()``/``count()`` and would
    leave the trace forever unfinished (pipes, result maps)."""
    if not isinstance(condition, c.HGQueryCondition):
        raise QueryError(f"not a condition: {condition!r}")
    tracer = global_tracer()
    tr = (tracer.start_trace("query")
          if traced and tracer.enabled else None)
    root = None
    if tr is not None:
        root = tr.start_span("query")
        tr.marks["root"] = root
        sp = tr.start_span("compile", parent=root)
    try:
        expanded = expand(graph, condition)
        dnf = to_dnf(expanded)
        simplified = simplify(graph, dnf)
        simplified = _apply_index_substitution(graph, simplified)
        if tr is not None:
            sp.end()
            sp = tr.start_span("plan", parent=root)
        plan = translate(
            graph, simplified, parallel_or=graph.config.query.parallel_or
        )
    except BaseException as e:
        # same every-exit guarantee as _run_traced: a condition the
        # compiler rejects still exports its trace with an error terminal
        if tr is not None:
            tr.finish_error(e, parent=root)
        raise
    if tr is not None:
        sp.set(plan=type(plan).__name__).end()
    return CompiledQuery(graph, condition, simplified, plan, trace=tr)
