"""Record (composite) types with projections.

The analogue of the reference's ``RecordType`` (``type/RecordType.java:46``),
``HGCompositeType``/``HGProjection`` dimension paths and the Java-bean
binding (``JavaTypeFactory.java:37``, ``BonesOfBeans``). In Python the
natural binding is **dataclasses**: each dataclass becomes a record type
whose dimensions are its fields; nested paths ("part.subpart") power
by-part indexing and ``AtomPartCondition`` exactly like the reference's
projection paths.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import msgpack

from hypergraphdb_tpu.core.errors import TypeError_
from hypergraphdb_tpu.types.system import HGAtomType


def _pack_default(obj):
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {"__dc__": _qualname(type(obj)),
                "f": {f.name: getattr(obj, f.name) for f in dataclasses.fields(obj)}}
    raise TypeError(f"unpackable: {type(obj)}")


def _qualname(cls: type) -> str:
    return f"{cls.__module__}.{cls.__qualname__}"


class RecordType(HGAtomType):
    """A composite type over named dimensions, bound to a dataclass."""

    kind = b"r"

    def __init__(self, name: str, cls: Optional[type] = None,
                 fields: tuple[str, ...] = (),
                 supertype_names: tuple[str, ...] = ()):
        self.name = name
        self.cls = cls
        self.fields = fields
        self.supertype_names = supertype_names
        self._registry: dict[str, type] = {}
        if cls is not None:
            self._registry[_qualname(cls)] = cls

    # -- dataclass binding ------------------------------------------------------
    @staticmethod
    def for_dataclass(cls: type, ts=None) -> "RecordType":
        if not dataclasses.is_dataclass(cls):
            raise TypeError_(f"{cls} is not a dataclass")
        fields = tuple(f.name for f in dataclasses.fields(cls))
        supers = tuple(
            _qualname(b)
            for b in cls.__mro__[1:]
            if dataclasses.is_dataclass(b)
        )
        return RecordType(_qualname(cls), cls, fields, supers)

    # -- serialization ----------------------------------------------------------
    def store(self, value: Any) -> bytes:
        if isinstance(value, dict):
            # schema-only binding: a peer that installed this record type
            # over the wire (SyncTypes) has no dataclass class; values
            # round-trip as field dicts (the reference likewise degrades
            # when the Java class is off the classpath)
            d = {f: value.get(f) for f in self.fields} if self.fields else value
        else:
            d = {
                f.name: getattr(value, f.name)
                for f in dataclasses.fields(value)
            }
        return msgpack.packb(d, use_bin_type=True, default=_pack_default)

    def make(self, data: bytes) -> Any:
        d = msgpack.unpackb(data, raw=False)
        return self._revive(d)

    def _revive(self, d: Any) -> Any:
        if isinstance(d, dict) and "__dc__" in d:
            cls = self._registry.get(d["__dc__"])
            vals = {k: self._revive(v) for k, v in d["f"].items()}
            if cls is None:
                return vals
            return cls(**vals)
        if isinstance(d, dict):
            if self.cls is not None and set(d) >= set(self.fields):
                vals = {k: self._revive(v) for k, v in d.items() if k in self.fields}
                return self.cls(**vals)
            return {k: self._revive(v) for k, v in d.items()}
        if isinstance(d, list):
            return [self._revive(v) for v in d]
        return d

    # -- index key ---------------------------------------------------------------
    def to_key(self, value: Any) -> bytes:
        return self.kind + self.store(value)

    def handles_value(self, value: Any) -> bool:
        return self.cls is not None and isinstance(value, self.cls)

    # -- projections (HGCompositeType) -------------------------------------------
    def dimensions(self) -> list[str]:
        return list(self.fields)

    def project(self, value: Any, dimension: str) -> Any:
        """Resolve a (possibly dotted) projection path — the analogue of the
        reference's ``HGProjection`` dimension paths used by ``ByPartIndexer``
        and ``AtomPartCondition``."""
        obj = value
        for part in dimension.split("."):
            if obj is None:
                return None
            if isinstance(obj, dict):
                obj = obj.get(part)
            else:
                obj = getattr(obj, part, None)
        return obj

    # -- subsumption ----------------------------------------------------------------
    def subsumes(self, general: Any, specific: Any) -> bool:
        """Structural subsumption: every set field of `general` matches
        `specific` (reference ``RecordType.subsumes`` treats null parts as
        wildcards)."""
        if general is None:
            return True
        if specific is None:
            return False
        for f in self.fields:
            g = self.project(general, f)
            if g is None:
                continue
            if g != self.project(specific, f):
                return False
        return True
