"""Type system: types are atoms; values are typed, serialized, and indexable.

Re-expression of the reference's ``HGTypeSystem`` (``core/.../type/
HGTypeSystem.java:93``) and ``HGAtomType`` contract (``type/HGAtomType.java:40``
— make/store/release/subsumes), redesigned for the TPU build:

- Every type provides ``store(value) -> bytes`` / ``make(bytes) -> value``
  (serialization into the data store) and ``to_key(value) -> bytes`` — an
  **order-preserving index key** (the sort-order contract the reference
  expresses as ``HGPrimitiveType`` = ``ByteArrayConverter`` + comparator,
  ``type/HGPrimitiveType.java:28``). Keys carry a 1-byte kind prefix so keys
  of different primitive kinds never collide and sort deterministically.
- Types are themselves atoms: each registered type gets a type-atom in the
  graph (value = its symbolic name, type = the top type), so queries over
  types work exactly like queries over data (``HGTypeSystem.java:194``
  bootstrap equivalence).
- Python classes bind to types automatically: dataclasses become record
  types with projections (the ``JavaTypeFactory.java:37`` / bean
  introspection analogue lives in ``types/record.py``).
- Value payloads stay host-side; the device plane only ever sees the
  order-preserving key (or its 64-bit rank) — SURVEY §7 hard part 3.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from hypergraphdb_tpu.core.errors import TypeError_
from hypergraphdb_tpu.core.handles import HGHandle


class HGAtomType:
    """A type: serialization + index-key + subsumption for its values."""

    #: symbolic name, unique in a type system
    name: str = ""
    #: 1-byte kind prefix for index keys
    kind: bytes = b"?"

    def store(self, value: Any) -> bytes:
        raise NotImplementedError

    def make(self, data: bytes) -> Any:
        raise NotImplementedError

    def to_key(self, value: Any) -> bytes:
        """Order-preserving index key, including the kind prefix."""
        raise NotImplementedError

    def handles_value(self, value: Any) -> bool:
        """Can this type store the given runtime value?"""
        return False

    def subsumes(self, general: Any, specific: Any) -> bool:
        """Value-level subsumption (``HGAtomType.subsumes``); default: equality."""
        return general == specific

    def dimensions(self) -> list[str]:
        """Projection dimensions (``HGCompositeType`` analogue); empty for scalars."""
        return []

    def project(self, value: Any, dimension: str) -> Any:
        raise TypeError_(f"type {self.name} has no dimension {dimension!r}")


class TopType(HGAtomType):
    """The top type — the type of type atoms (``type/Top.java:25``).

    Its values are type names (strings)."""

    name = "top"
    kind = b"T"

    def store(self, value: Any) -> bytes:
        return str(value).encode("utf-8")

    def make(self, data: bytes) -> Any:
        return data.decode("utf-8")

    def to_key(self, value: Any) -> bytes:
        return self.kind + str(value).encode("utf-8")

    def handles_value(self, value: Any) -> bool:
        return False  # never inferred


class NullType(HGAtomType):
    """Type of ``None`` — used for valueless links (the reference stores a
    null value handle in that case, ``HyperGraph.java:1589``)."""

    name = "null"
    kind = b"0"

    def store(self, value: Any) -> bytes:
        return b""

    def make(self, data: bytes) -> Any:
        return None

    def to_key(self, value: Any) -> bytes:
        return self.kind

    def handles_value(self, value: Any) -> bool:
        return value is None


class HGTypeSystem:
    """Registry binding runtime classes ↔ types ↔ type atoms.

    The graph kernel calls ``get_type_handle(value)`` on every ``add`` —
    the analogue of ``HGTypeSystem.getTypeHandle`` at ``HyperGraph.java:651``.
    """

    def __init__(self, graph: "HyperGraph"):  # noqa: F821
        self.graph = graph
        self._by_name: dict[str, HGAtomType] = {}
        self._handle_by_name: dict[str, HGHandle] = {}
        self._name_by_handle: dict[HGHandle, str] = {}
        self._by_class: dict[type, str] = {}
        self._inference: list[Callable[[Any], Optional[HGAtomType]]] = []
        #: direct supertype edges: type name -> parent type names
        self._supertypes: dict[str, set[str]] = {}
        #: bumped on every hierarchy change; consumed by lookup caches
        self.hierarchy_version = 0
        self.top = TopType()
        self.null = NullType()

    # -- bootstrap ------------------------------------------------------------
    def bootstrap(self) -> None:
        """Create the predefined type atoms (``HGTypeSystem.java:194``)."""
        from hypergraphdb_tpu.types import primitive as prim

        self.register(self.top, classes=())
        self.register(self.null, classes=(type(None),))
        for t, classes in prim.PREDEFINED:
            self.register(t, classes=classes)

    # -- registration -----------------------------------------------------------
    def register(
        self,
        atype: HGAtomType,
        classes: tuple = (),
        supertypes: tuple[str, ...] = (),
    ) -> HGHandle:
        if atype.name in self._by_name:
            return self._handle_by_name[atype.name]
        self._by_name[atype.name] = atype
        # the type atom: value = type name, type = top. On a persistent
        # backend the atom may already exist from a previous open — adopt
        # its handle so stored atoms keep resolving (HGTypeSystem.java:97-98
        # class↔type index recovery).
        h = self.graph._find_type_atom(atype.name)
        if h is None:
            h = self.graph._add_type_atom(atype.name)
        self._handle_by_name[atype.name] = h
        self._name_by_handle[h] = atype.name
        for c in classes:
            self._by_class[c] = atype.name
        if supertypes:
            self._supertypes[atype.name] = set(supertypes)
            self.hierarchy_version += 1
        return h

    def add_inference(self, fn: Callable[[Any], Optional[HGAtomType]]) -> None:
        """Register a fallback value→type inference hook."""
        self._inference.append(fn)

    # -- lookup -------------------------------------------------------------------
    def get_type(self, name_or_handle) -> HGAtomType:
        if isinstance(name_or_handle, str):
            t = self._by_name.get(name_or_handle)
            if t is None:
                raise TypeError_(f"unknown type {name_or_handle!r}")
            return t
        name = self._name_by_handle.get(int(name_or_handle))
        if name is None:
            name = self._recover_type_name(int(name_or_handle))
        if name is None:
            raise TypeError_(f"handle {name_or_handle} is not a type atom")
        return self._by_name[name]

    def _type_atom_name(self, handle: int) -> Optional[str]:
        """If ``handle`` is a persisted type atom (typed by top), return its
        stored name — whether or not that type is registered this session."""
        rec = self.graph.store.get_link(handle)
        if rec is None or len(rec) < 3:
            return None
        # only atoms typed by top (or top itself) are type atoms
        top_h = self._handle_by_name.get("top")
        if top_h is not None and rec[0] != int(top_h) and handle != int(top_h):
            return None
        data = self.graph.store.get_data(rec[1]) if rec[1] >= 0 else None
        if data is None:
            return None
        return self.top.make(data)

    def _recover_type_name(self, handle: int) -> Optional[str]:
        """Reopen path: a persisted type atom whose name was registered this
        session under a different handle, or not yet touched. Read the name
        from the store and adopt the persisted handle if it matches a
        registered type."""
        name = self._type_atom_name(handle)
        if name is not None and name in self._by_name:
            self._handle_by_name.setdefault(name, handle)
            self._name_by_handle[handle] = name
            return name
        return None

    def adopt_type_atom(self, handle: int) -> Optional[str]:
        """Reopen path: bind a persisted type atom's name↔handle mapping
        WITHOUT requiring its HGAtomType implementation to be registered
        this session — enough for by-type/TypePlus queries to resolve
        (value decoding still needs the type registered, exactly like the
        reference needs the class on the classpath)."""
        name = self._type_atom_name(int(handle))
        if name is None:
            return None
        self._handle_by_name.setdefault(name, int(handle))
        self._name_by_handle.setdefault(int(handle), name)
        return name

    def handle_of(self, name: str) -> HGHandle:
        h = self._handle_by_name.get(name)
        if h is None:
            raise TypeError_(f"unknown type {name!r}")
        return h

    def name_of(self, handle: HGHandle) -> str:
        return self._name_by_handle[int(handle)]

    def is_type_handle(self, handle: HGHandle) -> bool:
        h = int(handle)
        # persisted-but-unregistered type atoms count too: the remove guard
        # must protect them across sessions, not just this session's registry
        return h in self._name_by_handle or self._type_atom_name(h) is not None

    def get_type_handle(self, value: Any) -> HGHandle:
        """Infer the type of a runtime value (``HyperGraph.add`` step 1).

        Unlike the reference this never creates types implicitly except for
        dataclasses, which auto-register as record types (the
        ``JavaTypeFactory`` behavior)."""
        t = self.infer(value)
        if t is None:
            raise TypeError_(f"no type for value of class {type(value).__name__}")
        return self._handle_by_name[t.name]

    def infer(self, value: Any) -> Optional[HGAtomType]:
        name = self._by_class.get(type(value))
        if name is not None:
            return self._by_name[name]
        for fn in self._inference:
            t = fn(value)
            if t is not None:
                if t.name not in self._by_name:
                    self.register(t, classes=(type(value),))
                return t
        # dataclass auto-binding
        import dataclasses

        if dataclasses.is_dataclass(value) and not isinstance(value, type):
            from hypergraphdb_tpu.types.record import RecordType

            t = RecordType.for_dataclass(type(value), self)
            if t.name not in self._by_name:
                self.register(t, classes=(type(value),),
                              supertypes=t.supertype_names)
            return self._by_name[t.name]
        return None

    # -- subsumption (type-level) ---------------------------------------------
    def declare_subtype(self, sub: str, sup: str) -> None:
        self._supertypes.setdefault(sub, set()).add(sup)
        self.hierarchy_version += 1

    def subtypes_closure(self, name: str) -> set[str]:
        """All type names subsumed by `name` (including itself) — powers
        ``TypePlusCondition`` expansion (``cond2qry/ExpressionBasedQuery.java:603``)."""
        out = {name}
        changed = True
        while changed:
            changed = False
            for sub, sups in self._supertypes.items():
                if sub not in out and (sups & out):
                    out.add(sub)
                    changed = True
        return out

    def supertypes_of(self, name: str) -> set[str]:
        out: set[str] = set()
        frontier = set(self._supertypes.get(name, ()))
        while frontier:
            out |= frontier
            nxt: set[str] = set()
            for n in frontier:
                nxt |= self._supertypes.get(n, set()) - out
            frontier = nxt
        return out
