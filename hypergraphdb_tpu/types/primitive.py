"""Primitive types.

The analogue of the reference's ``type/javaprimitive/`` package (26 files:
String, numerics, boolean, date/timestamp, enums, primitive arrays — SURVEY
§2.1). Each primitive is serialization + an order-preserving key, which is
the exact contract indices depend on (``type/HGPrimitiveType.java:28``).

Kind prefixes keep different primitives in disjoint, deterministic key
ranges: b(ool) < f(loat) < i(nt) < l(ist) < s(tr) < t(imestamp) < y(bytes).
Ints and floats get *distinct* kinds — unlike a unified numeric tower, an
index range scan over ints never has to skip float keys.
"""

from __future__ import annotations

import datetime
import struct
from typing import Any

import msgpack

from hypergraphdb_tpu.types.system import HGAtomType
from hypergraphdb_tpu.utils import ordered_bytes as ob


class IntType(HGAtomType):
    name = "int"
    kind = b"i"

    def store(self, value: Any) -> bytes:
        return ob.encode_int(int(value))

    def make(self, data: bytes) -> Any:
        return ob.decode_int(data)

    def to_key(self, value: Any) -> bytes:
        return self.kind + ob.encode_int(int(value))

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, int) and not isinstance(value, bool)

    def subsumes(self, general: Any, specific: Any) -> bool:
        return int(general) == int(specific)


class FloatType(HGAtomType):
    name = "float"
    kind = b"f"

    def store(self, value: Any) -> bytes:
        return struct.pack(">d", float(value))

    def make(self, data: bytes) -> Any:
        return struct.unpack(">d", data)[0]

    def to_key(self, value: Any) -> bytes:
        return self.kind + ob.encode_float(float(value))

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, float)


class StringType(HGAtomType):
    name = "string"
    kind = b"s"

    def store(self, value: Any) -> bytes:
        return str(value).encode("utf-8")

    def make(self, data: bytes) -> Any:
        return data.decode("utf-8")

    def to_key(self, value: Any) -> bytes:
        return self.kind + str(value).encode("utf-8")

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, str)


class BoolType(HGAtomType):
    name = "bool"
    kind = b"b"

    def store(self, value: Any) -> bytes:
        return ob.encode_bool(bool(value))

    def make(self, data: bytes) -> Any:
        return ob.decode_bool(data)

    def to_key(self, value: Any) -> bytes:
        return self.kind + ob.encode_bool(bool(value))

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, bool)


class BytesType(HGAtomType):
    name = "bytes"
    kind = b"y"

    def store(self, value: Any) -> bytes:
        return bytes(value)

    def make(self, data: bytes) -> Any:
        return data

    def to_key(self, value: Any) -> bytes:
        return self.kind + bytes(value)

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, (bytes, bytearray))


class TimestampType(HGAtomType):
    """Dates/timestamps (reference: ``DateType``/``TimestampType``/
    ``CalendarType`` in ``type/javaprimitive/``). Stored as epoch micros."""

    name = "timestamp"
    kind = b"t"

    def store(self, value: Any) -> bytes:
        return ob.encode_int(self._micros(value))

    def make(self, data: bytes) -> Any:
        us = ob.decode_int(data)
        return datetime.datetime.fromtimestamp(us / 1e6, tz=datetime.timezone.utc)

    def to_key(self, value: Any) -> bytes:
        return self.kind + ob.encode_int(self._micros(value))

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, (datetime.datetime, datetime.date))

    @staticmethod
    def _micros(value: Any) -> int:
        if isinstance(value, datetime.datetime):
            if value.tzinfo is None:
                value = value.replace(tzinfo=datetime.timezone.utc)
            return int(value.timestamp() * 1e6)
        if isinstance(value, datetime.date):
            dt = datetime.datetime(value.year, value.month, value.day,
                                   tzinfo=datetime.timezone.utc)
            return int(dt.timestamp() * 1e6)
        raise TypeError(f"not a date: {value!r}")


class ListType(HGAtomType):
    """Heterogeneous lists/tuples of primitives (reference: ``CollectionType``/
    ``ArrayType``). Serialized with msgpack; key = msgpack bytes (msgpack
    int/str encodings are not order-preserving across the whole domain, so
    list keys support equality lookups only — same restriction the reference
    has for collection values)."""

    name = "list"
    kind = b"l"

    def store(self, value: Any) -> bytes:
        return msgpack.packb(list(value), use_bin_type=True)

    def make(self, data: bytes) -> Any:
        return msgpack.unpackb(data, raw=False)

    def to_key(self, value: Any) -> bytes:
        return self.kind + msgpack.packb(list(value), use_bin_type=True)

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, (list, tuple))


class DictType(HGAtomType):
    """Free-form string-keyed maps (reference: ``MapType``)."""

    name = "dict"
    kind = b"m"

    def store(self, value: Any) -> bytes:
        return msgpack.packb(dict(value), use_bin_type=True)

    def make(self, data: bytes) -> Any:
        return msgpack.unpackb(data, raw=False)

    def to_key(self, value: Any) -> bytes:
        items = sorted(dict(value).items())
        return self.kind + msgpack.packb(items, use_bin_type=True)

    def handles_value(self, value: Any) -> bool:
        return isinstance(value, dict)

    def dimensions(self) -> list[str]:
        return []  # dynamic; use project() directly

    def project(self, value: Any, dimension: str) -> Any:
        return value.get(dimension)


#: (type instance, bound runtime classes) — the predefined-type manifest,
#: analogue of the ``core/src/config/org/hypergraphdb/types`` resource.
PREDEFINED: list[tuple[HGAtomType, tuple]] = [
    (BoolType(), (bool,)),          # bool BEFORE int: bool is an int subclass
    (IntType(), (int,)),
    (FloatType(), (float,)),
    (StringType(), (str,)),
    (BytesType(), (bytes, bytearray)),
    (TimestampType(), (datetime.datetime, datetime.date)),
    (ListType(), (list, tuple)),
    (DictType(), (dict,)),
]
