"""Standing-query evaluation: the incremental tier over the ingest delta.

A :class:`SubscriptionManager` turns the serve runtime into a streaming
view maintainer. Three moving parts:

**Dirty tracking** (ingest threads). Graph mutation events — dispatched
POST-commit, so listeners may read the graph — run a SOUND per-kind
relevance predicate and mark affected subscriptions dirty:

- *pattern*: a new/rewritten link whose target tuple covers every
  anchor, or any mutation of a current match;
- *range*: a new/revalued atom whose key falls in the window (probed
  against bound keys precomputed ONCE at subscribe), or any mutation of
  a current match;
- *BFS*: a link touching the reachable set (for removals, targets are
  captured at the pre-commit remove-request event — the atom is gone by
  the time the post-commit event fires), or any mutation of a member.

Soundness means: every event that can change a match set dirties it
(an already-dirty subscription skips the predicate — the pending full
re-fire covers everything until it runs). The predicates only ever
OVER-approximate, so a clean subscription's match set provably equals
its full re-evaluation — the property the soak asserts.

**Re-evaluation** (the dispatch thread). ``pump()`` — hooked into the
runtime's dispatch cycle — resubmits dirty subscriptions through the
ORDINARY serve lanes (``submit_pattern`` / ``submit_range`` /
``submit_bfs``), so thousands of standing queries coalesce by bucket
key into the same compiled device programs as ad-hoc traffic; a
standing query is just a lane that re-fires on its dirty set. The
eval-seq protocol makes results exact without ever pausing ingest: the
manager notes the ingest seq at submit (``S1``) and resolve (``S2``);
if the subscription was NOT re-dirtied in between, no relevant event
landed in ``(S1, S2]``, so the lane's answer — computed somewhere
within — equals the match set at ``S2`` and anchors a sound delta.
A re-dirtied result is discarded (the next round re-fires). Truncated
lane results fall back to an exact host oracle (``graph.find_all`` /
one traversal pass), counted ``sub.full_fallbacks``.

**Delivery** (HTTP handler threads). Notifications are set deltas
``(seq_from, seq_to, added, removed, digest)`` on a bounded
per-subscription queue (``window`` deep). Overflow or deadline expiry
sheds the WHOLE queue and arms a resync — a gap breaks the delta
chain, so the consumer's next poll gets the full current set instead
of a silently wrong one (shed-not-hang, counted ``sub.shed``).
Consumers must ignore any queued delta whose ``seq_to`` is <= the seq
of a resync they just applied.

Lock order: manager lock -> (registry lock | subscription cond |
admission cv); the stats lock is a leaf. ``poll`` takes the cond and
the manager lock strictly in sequence, never nested.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from hypergraphdb_tpu.core import events as ev
from hypergraphdb_tpu.serve.types import (
    PatternRequest,
    QueueFull,
    RangeRequest,
    RuntimeClosed,
    ServeError,
    Unservable,
)
from hypergraphdb_tpu.sub.registry import (
    Subscription,
    SubscriptionRegistry,
)
from hypergraphdb_tpu.sub.stats import SubStats

SUB_KINDS = ("pattern", "range", "bfs")

_log = logging.getLogger("hypergraphdb_tpu.sub")


@dataclass
class SubConfig:
    """Knobs of one manager."""

    default_window: int = 64        # per-sub notification queue bound
    default_deadline_s: Optional[float] = None  # notification TTL
    staleness_bound_s: float = 5.0  # health: dirty-age SLO bound
    max_subscriptions: int = 4096
    #: deadline on eval submissions: bounds how long the dispatch thread
    #: can block on a full admission queue (an eval shed by its deadline
    #: simply re-fires), and makes standing load yield to ad-hoc traffic.
    #: Generous by default — it must outlive a cold bucket compile ahead
    #: of the eval in the queue, or first-touch evals shed spuriously
    eval_deadline_s: Optional[float] = 30.0
    eval_priority: int = -1         # ad-hoc requests pop first
    #: admission headroom kept free when burst-submitting evals — the
    #: dispatch thread must never block itself out of draining its own
    #: queue
    submit_margin: int = 8
    retry_backoff_s: float = 0.05   # failed eval re-fire delay
    clock: Optional[Callable[[], float]] = None  # None -> runtime's


class SubscriptionManager:
    """Standing pattern / range / BFS queries over one graph + runtime.

    Construct, then ``runtime.attach_subscriptions(manager)`` so the
    dispatch cycle drives :meth:`pump`. ``seq_source`` injects an
    external replication seq (a replica's applied-op clock) as the
    notification anchor — the resume contract across failover; without
    it an internal per-event counter anchors notifications."""

    def __init__(self, graph, runtime, config: Optional[SubConfig] = None,
                 seq_source: Optional[Callable[[], int]] = None,
                 registry=None):
        self.graph = graph
        self.runtime = runtime
        self.config = config or SubConfig()
        self.stats = SubStats(registry)
        self.subs = SubscriptionRegistry()
        self._seq_source = seq_source
        self._clock = (self.config.clock
                       or getattr(runtime, "clock", None) or time.monotonic)
        self._lock = threading.Lock()
        self._seq = 0
        self._n_bfs = 0            # gates the pre-commit removal capture
        self._pending_rm: dict[int, frozenset] = {}
        self._listening = False
        self._closed = False
        self._seq_source_warned = False

    # -- seq ------------------------------------------------------------------
    def current_seq(self) -> int:
        """Monotone notification anchor: the external seq when injected
        (both clocks are monotone, so max() stays monotone), else the
        internal per-event counter."""
        s = self._seq
        if self._seq_source is not None:
            try:
                s = max(s, int(self._seq_source() or 0))
            except Exception:
                # a dying replication layer mid-shutdown: the internal
                # counter stays a sound (if coarser) anchor — log ONCE,
                # this runs on every pump
                if not self._seq_source_warned:
                    # benign once-flag race (callers may already hold
                    # the manager lock, so it cannot be taken here);
                    # worst case is a duplicate warning
                    self._seq_source_warned = True  # hglint: disable=HG402
                    _log.warning(
                        "subscription seq source failed; falling back "
                        "to the internal event counter", exc_info=True,
                    )
        return s

    # -- subscribe / unsubscribe ----------------------------------------------
    def subscribe(self, kind: str, params: dict,
                  window: Optional[int] = None,
                  deadline_s: Optional[float] = None) -> dict:
        """Register one standing query; returns the ``subscribed``
        envelope carrying the initial FULL match set and the seq it
        anchors (the client's resume base). Raises typed
        :class:`Unservable` for shapes outside the standing subset and
        :class:`QueueFull` at capacity."""
        if self._closed:
            raise RuntimeClosed("subscription manager is closed")
        if kind not in SUB_KINDS:
            raise Unservable(f"unknown subscription kind {kind!r}; "
                             f"expected one of {SUB_KINDS}")
        if len(self.subs) >= self.config.max_subscriptions:
            raise QueueFull(
                f"subscription capacity ({self.config.max_subscriptions})"
            )
        norm, request, range_keys = self._normalize(kind, params)
        self._ensure_listeners()
        w = int(window) if window is not None else self.config.default_window
        if w < 1:
            raise Unservable("window must be >= 1")
        ttl = (deadline_s if deadline_s is not None
               else self.config.default_deadline_s)
        sub = self.subs.add(kind, norm, w, ttl)
        sub.request = request
        sub.range_keys = range_keys
        if kind == "bfs":
            with self._lock:
                self._n_bfs += 1
        # initial snapshot: the sub is already listener-visible, so any
        # mutation landing DURING the eval marks it dirty and the first
        # pump re-fires; a seq movement across the eval is treated the
        # same way (conservative — the snapshot may be torn)
        s_before = self.current_seq()
        matches = self._full_eval(sub)
        with self._lock:
            s_after = self.current_seq()
            sub.matches = matches
            sub.last_seq = s_after
            sub.refresh_digest()
            if s_after != s_before:
                sub.dirty = True
                if sub.dirty_since is None:
                    sub.dirty_since = self._clock()
        self.stats.record_subscribe(len(self.subs))
        return {
            "what": "subscribed", "id": sub.sid, "kind": kind,
            "seq": sub.last_seq, "window": w,
            "matches": sorted(sub.matches), "digest": sub.digest,
        }

    def unsubscribe(self, sid: str) -> dict:
        sub = self.subs.remove(sid)
        if sub is None:
            raise Unservable(f"unknown subscription {sid!r}")
        if sub.kind == "bfs":
            with self._lock:
                self._n_bfs -= 1
        with sub.cond:
            sub.closed = True
            sub.cond.notify_all()
        self.stats.record_unsubscribe(len(self.subs))
        return {"what": "unsubscribed", "id": sid}

    def _normalize(self, kind: str, params: dict):
        """Validate + normalize one subscription's parameters; returns
        ``(normalized_params, prebuilt_request, range_keys)``."""
        if kind == "pattern":
            anchors = tuple(int(a) for a in params.get("anchors", ()))
            th = params.get("type_handle")
            req = PatternRequest(anchors,
                                 None if th is None else int(th))
            norm = {"anchors": list(req.anchors),
                    "type_handle": req.type_handle}
            return norm, req, None
        if kind == "range":
            if params.get("limit") is not None or params.get("desc"):
                raise Unservable(
                    "standing range queries are window-only: limit/desc "
                    "have no incremental delta semantics (a top-k's "
                    "membership depends on atoms outside it)"
                )
            from hypergraphdb_tpu.query.bridge import to_range_request

            req = to_range_request(
                self.graph, params.get("lo"), params.get("hi"),
                lo_op=params.get("lo_op", "gte"),
                hi_op=params.get("hi_op", "lte"),
                type_handle=params.get("type_handle"),
                anchor=params.get("anchor"),
            )
            norm = {"lo": params.get("lo"), "hi": params.get("hi"),
                    "lo_op": req.lo_op, "hi_op": req.hi_op,
                    "type_handle": req.type_handle, "anchor": req.anchor}
            return norm, req, self._bound_keys(req)
        seed = int(params["seed"])
        hops = params.get("max_hops")
        hops = (int(hops) if hops is not None
                else self.runtime.config.default_max_hops)
        if hops < 1:
            raise Unservable("bfs max_hops must be >= 1")
        include = bool(params.get("include_seed", False))
        norm = {"seed": seed, "max_hops": hops, "include_seed": include}
        return norm, None, None

    def _bound_keys(self, req: RangeRequest) -> tuple:
        """(lo_key, hi_key) order-preserving byte bounds, computed ONCE
        at subscribe so the per-event window probe never re-runs the
        typesystem (the runtime's ``_range_keys`` discipline)."""
        ts = self.graph.typesystem

        def key_of(v):
            if v is None:
                return None
            vt = ts.infer(v)
            if vt is None:
                raise Unservable(f"value {v!r} has no registered type")
            return vt.to_key(v)

        return key_of(req.values[0]), key_of(req.values[1])

    # -- dirty tracking (ingest threads) --------------------------------------
    def _ensure_listeners(self) -> None:
        """Attach graph listeners on first use — bulk ingest keeps its
        no-events fast path until someone actually subscribes."""
        with self._lock:
            if self._listening or self._closed:
                return
            self._listening = True
        e = self.graph.events
        e.add_listener(ev.HGAtomAddedEvent, self._on_added)
        e.add_listener(ev.HGAtomRemovedEvent, self._on_removed)
        e.add_listener(ev.HGAtomReplacedEvent, self._on_replaced)
        e.add_listener(ev.HGAtomRemoveRequestEvent, self._on_remove_request)

    def _detach_listeners(self) -> None:
        with self._lock:
            if not self._listening:
                return
            # flipped BEFORE the removals: _ensure_listeners is gated on
            # _closed, so nobody re-attaches concurrently
            self._listening = False
        e = self.graph.events
        e.remove_listener(ev.HGAtomAddedEvent, self._on_added)
        e.remove_listener(ev.HGAtomRemovedEvent, self._on_removed)
        e.remove_listener(ev.HGAtomReplacedEvent, self._on_replaced)
        e.remove_listener(ev.HGAtomRemoveRequestEvent,
                          self._on_remove_request)

    def _on_remove_request(self, graph, event) -> int:
        """PRE-commit capture: a removed link's targets are unreadable
        once the post-commit removed event fires, and BFS relevance
        needs them. Gated on BFS subscriptions existing at all."""
        try:
            if self._n_bfs:
                h = int(event.handle)
                try:
                    tgts = frozenset(
                        int(t) for t in graph.get_targets(h)
                    )
                except Exception:
                    tgts = frozenset()
                if tgts:
                    self._pending_rm[h] = tgts
        except Exception:
            # dirty tracking must never break a write — but a failure
            # here can mean a missed notification, so leave evidence
            _log.warning("subscription remove-capture failed",
                         exc_info=True)
        return ev.HGListener.CONTINUE

    def _on_added(self, graph, event) -> int:
        try:
            self._note(graph, int(event.handle), alive=True,
                       rm_targets=None)
        except Exception:
            _log.warning("subscription dirty tracking failed (add)",
                         exc_info=True)
        return ev.HGListener.CONTINUE

    def _on_replaced(self, graph, event) -> int:
        try:
            self._note(graph, int(event.handle), alive=True,
                       rm_targets=None)
        except Exception:
            _log.warning("subscription dirty tracking failed (replace)",
                         exc_info=True)
        return ev.HGListener.CONTINUE

    def _on_removed(self, graph, event) -> int:
        try:
            h = int(event.handle)
            self._note(graph, h, alive=False,
                       rm_targets=self._pending_rm.pop(h, frozenset()))
        except Exception:
            _log.warning("subscription dirty tracking failed (remove)",
                         exc_info=True)
        return ev.HGListener.CONTINUE

    def _note(self, graph, h: int, alive: bool, rm_targets) -> None:
        """One mutation: advance the seq, run the relevance predicates,
        nudge the dispatch loop if anything went dirty. ``alive`` means
        the atom is readable (add/replace); removals carry the
        pre-captured targets instead."""
        tgts: Optional[frozenset] = None if alive else rm_targets
        key = _UNSET if alive else None  # a dead atom has no value key

        def targets() -> frozenset:
            nonlocal tgts
            if tgts is None:
                try:
                    tgts = frozenset(
                        int(t) for t in graph.get_targets(h)
                    )
                except Exception:
                    tgts = frozenset()
            return tgts

        def value_key():
            nonlocal key
            if key is _UNSET:
                from hypergraphdb_tpu.storage.value_index import (
                    value_key_of,
                )

                try:
                    key = value_key_of(graph, h)
                except Exception:
                    key = None
            return key

        woke = False
        with self._lock:
            self._seq += 1
            now = None
            for sub in self.subs.all():
                if sub.dirty:
                    continue  # pending full re-fire already covers this
                if not self._relevant(graph, sub, h, alive,
                                      targets, value_key):
                    continue
                sub.dirty = True
                if sub.dirty_since is None:
                    if now is None:
                        now = self._clock()
                    sub.dirty_since = now
                woke = True
        if woke:
            try:
                self.runtime.queue.wake()  # un-park the dispatch loop
            except Exception:
                # a closing runtime: the next pump (or poll) catches up
                _log.debug("dispatch wake failed", exc_info=True)

    def _relevant(self, graph, sub: Subscription, h: int, alive: bool,
                  targets, value_key) -> bool:
        """SOUND per-kind relevance of one mutation to one clean
        subscription — may over-approximate, never under."""
        if sub.kind == "pattern":
            if h in sub.matches:
                return True
            if not alive:
                return False
            req = sub.request
            if not set(req.anchors).issubset(targets()):
                return False
            if req.type_handle is not None:
                try:
                    if int(graph.get_type_handle_of(h)) != int(
                        req.type_handle
                    ):
                        return False
                except Exception:
                    return True  # unreadable type: stay conservative
            return True
        if sub.kind == "range":
            if h in sub.matches:
                return True
            if not alive:
                return False
            return self._range_live_match(graph, sub.request, h,
                                          sub.range_keys, value_key())
        # bfs: anything touching the reachable set (members + seed)
        reach = sub.matches
        seed = sub.params["seed"]
        if h in reach or h == seed:
            return True
        t = targets()
        return bool(t) and (seed in t or not reach.isdisjoint(t))

    def _range_live_match(self, graph, req: RangeRequest, h: int,
                          keys: tuple, key) -> bool:
        """The full live range predicate — kind, bounds, type, anchor —
        against a precomputed value key (the runtime's
        ``_range_matches_host`` logic, listener edition)."""
        if key is None or key[0] != req.dim:
            return False
        lo_key, hi_key = keys
        payload = key[1:]
        if lo_key is not None:
            lo = lo_key[1:]
            if payload < lo or (payload == lo and req.lo_op == "gt"):
                return False
        if hi_key is not None:
            hi = hi_key[1:]
            if payload > hi or (payload == hi and req.hi_op == "lt"):
                return False
        try:
            if req.type_handle is not None and int(
                graph.get_type_handle_of(h)
            ) != int(req.type_handle):
                return False
            if req.anchor is not None and int(req.anchor) not in {
                int(t) for t in graph.get_targets(h)
            }:
                return False
        except Exception:
            return True  # torn read: stay conservative
        return True

    # -- re-evaluation (dispatch thread) --------------------------------------
    def pump(self) -> None:
        """One evaluator round, driven from the runtime's dispatch
        cycle: resolve finished evals, shed expired notifications,
        re-fire dirty subscriptions, refresh gauges. Cheap when idle."""
        now = self._clock()
        self._resolve_inflight()
        self._shed_expired(now)
        self._submit_dirty(now)
        self._gauges(now)

    def _submit_dirty(self, now: float) -> None:
        with self._lock:
            cands = [s for s in self.subs.all()
                     if s.dirty and s.inflight is None and not s.closed
                     and s.retry_at <= now]
        if not cands:
            return
        # headroom: never submit the dispatch thread into its own
        # backpressure (eval deadlines bound the residual race)
        cfg = self.runtime.config
        budget = (cfg.max_queue - self.runtime.queue.depth()
                  - self.config.submit_margin)
        submitted = 0
        for sub in cands[:max(0, budget)]:
            with self._lock:
                if not sub.dirty or sub.inflight is not None:
                    continue
                sub.dirty = False
                s1 = self.current_seq()
            try:
                fut = self._submit_eval(sub)
            except ServeError:
                # QueueFull / AdmissionGated (replica lag) / closed:
                # stay dirty, back off, staleness keeps score
                with self._lock:
                    sub.dirty = True
                    sub.retry_at = now + self.config.retry_backoff_s
                continue
            with self._lock:
                sub.inflight = (fut, s1)
            submitted += 1
        if submitted:
            self.stats.record_eval_round(
                submitted, max(0, len(self.subs) - submitted)
            )

    def _submit_eval(self, sub: Subscription):
        cfg = self.config
        if sub.kind == "pattern" or sub.kind == "range":
            return self.runtime.submit(sub.request, cfg.eval_deadline_s,
                                       cfg.eval_priority)
        p = sub.params
        return self.runtime.submit_bfs(
            p["seed"], p["max_hops"], deadline_s=cfg.eval_deadline_s,
            include_seed=p["include_seed"], priority=cfg.eval_priority,
        )

    def _resolve_inflight(self) -> None:
        with self._lock:
            done = [s for s in self.subs.all()
                    if s.inflight is not None and s.inflight[0].done()]
        for sub in done:
            fut, _s1 = sub.inflight
            new: Optional[set] = None
            failed = False
            try:
                res = fut.result()
                if res.truncated:
                    # the compact window cannot carry the full set: one
                    # exact host oracle pass instead
                    self.stats.record_full_fallback()
                    new = self._full_eval(sub)
                else:
                    new = {int(x) for x in res.matches}
            except ServeError:
                failed = True  # backpressure/shed: re-fire later
            except Exception:
                failed = True
                self.stats.record_eval_error()
            latency = None
            with self._lock:
                sub.inflight = None
                if failed:
                    sub.dirty = True
                    sub.retry_at = self._clock() + \
                        self.config.retry_backoff_s
                elif sub.dirty:
                    # re-dirtied mid-flight: the answer's seq anchor is
                    # unprovable — discard, the next round re-fires
                    self.stats.record_eval()
                else:
                    self.stats.record_eval()
                    latency = self._apply(sub, new, self.current_seq())
            if latency is not None:
                self._observe_sub_perf(latency)

    def _apply(self, sub: Subscription, new: set, s2: int) -> Optional[float]:
        """Commit one clean eval (caller holds the manager lock): diff,
        advance the seq anchor, push the delta. Returns the dirty→
        notified wall seconds when a delta was pushed (the ``sub``
        lane's perf-sentinel sample), else None."""
        added = new - sub.matches
        removed = sub.matches - new
        seq_from = sub.last_seq
        since = sub.dirty_since
        sub.matches = new
        sub.last_seq = s2
        sub.dirty_since = None
        if not added and not removed:
            return None  # no news: the anchor still advances (freshness)
        sub.refresh_digest()
        self._enqueue(sub, {
            "what": "notification", "id": sub.sid,
            "seq_from": seq_from, "seq_to": s2,
            "added": sorted(added), "removed": sorted(removed),
            "digest": sub.digest,
        })
        return (None if since is None
                else max(0.0, self._clock() - since))

    def _observe_sub_perf(self, latency_s: float) -> None:
        """Feed the runtime's perf sentinel (``ServeConfig(perf=...)``)
        one delivered notification on the ``sub`` lane: ingest-dirty →
        delta-enqueued wall seconds. This is the lane a seeded
        ``PERF_BASELINE.json`` entry named ``sub`` gates — a standing
        tier silently re-evaluating 3× slower alerts exactly like a
        slow serve lane."""
        perf = getattr(self.runtime, "perf", None)
        if perf is None:
            return
        try:
            perf.observe("sub", latency_s)
        except Exception:
            _log.debug("sub perf observe failed", exc_info=True)

    def _enqueue(self, sub: Subscription, env: dict) -> None:
        with sub.cond:
            if sub.needs_resync or sub.closed:
                return  # the armed resync supersedes queued deltas
            if len(sub.queue) >= sub.window:
                # overflow: a dropped delta breaks the chain — shed the
                # whole queue and resync instead of delivering a lie
                n = len(sub.queue)
                sub.queue.clear()
                sub.needs_resync = True
                self.stats.record_shed(n + 1)
            else:
                sub.queue.append((self._clock(), env))
                self.stats.record_notify()
            sub.cond.notify_all()

    def _shed_expired(self, now: float) -> None:
        for sub in self.subs.all():
            ttl = sub.deadline_s
            if ttl is None:
                continue
            with sub.cond:
                if not sub.queue or now - sub.queue[0][0] <= ttl:
                    continue
                # one expired delta gaps the chain: shed everything
                # queued and resync (shed-not-hang)
                n = len(sub.queue)
                sub.queue.clear()
                sub.needs_resync = True
                self.stats.record_shed(n)
                sub.cond.notify_all()

    def _gauges(self, now: float) -> None:
        depth = 0
        oldest: Optional[float] = None
        for sub in self.subs.all():
            with sub.cond:
                depth += len(sub.queue)
            ds = sub.dirty_since
            if ds is not None and (oldest is None or ds < oldest):
                oldest = ds
        self.stats.set_queue_depth(depth)
        self.stats.set_staleness(0.0 if oldest is None
                                 else max(0.0, now - oldest))

    # -- full-evaluation oracles ----------------------------------------------
    def _full_eval(self, sub: Subscription) -> set:
        """The exact host answer for one subscription, against the live
        graph: the initial snapshot, the truncation fallback, and the
        differential soak's ground truth all share this path."""
        g = self.graph
        p = sub.params
        from hypergraphdb_tpu.query import conditions as c

        if sub.kind == "pattern":
            cls = [c.Incident(a) for a in p["anchors"]]
            if p["type_handle"] is not None:
                cls.append(c.AtomType(p["type_handle"]))
            cond = cls[0] if len(cls) == 1 else c.And(*cls)
            return {int(h) for h in g.find_all(cond)}
        if sub.kind == "range":
            req = sub.request
            cls = []
            lo, hi = req.values
            if lo is not None:
                cls.append(c.AtomValue(lo, req.lo_op))
            if hi is not None:
                cls.append(c.AtomValue(hi, req.hi_op))
            if req.type_handle is not None:
                cls.append(c.AtomType(req.type_handle))
            if req.anchor is not None:
                cls.append(c.Incident(req.anchor))
            cond = cls[0] if len(cls) == 1 else c.And(*cls)
            return {int(h) for h in g.find_all(cond)}
        from hypergraphdb_tpu.algorithms.traversals import (
            HGBreadthFirstTraversal,
        )

        out: set = set()
        seed = p["seed"]
        try:
            if not g.contains(seed):
                return out
            if p["include_seed"]:
                out.add(seed)
            for _link, nbr in HGBreadthFirstTraversal(
                g, seed, max_distance=p["max_hops"]
            ):
                out.add(int(nbr))
        except Exception:
            # a seed racing removal mid-traversal: the partial set is
            # still anchored — the next dirty round settles it
            _log.debug("bfs full-eval raced a mutation", exc_info=True)
        return out

    # -- delivery (handler threads) -------------------------------------------
    def poll(self, sid: str, max_notes: int = 32,
             timeout_s: Optional[float] = None) -> dict:
        """Long-poll one subscription's queue. Returns a
        ``notifications`` envelope (possibly empty on timeout), or a
        ``resync`` envelope carrying the full current set after a shed
        — the consumer replaces its set and ignores queued deltas whose
        ``seq_to`` <= the resync's ``seq``."""
        sub = self.subs.get(sid)
        if sub is None:
            raise Unservable(f"unknown subscription {sid!r}")
        self.stats.record_poll()
        deadline = (None if timeout_s is None
                    else self._clock() + max(0.0, timeout_s))
        resync = False
        notes: list = []
        with sub.cond:
            while True:
                if sub.closed:
                    raise Unservable(f"subscription {sid!r} is closed")
                if sub.needs_resync:
                    sub.needs_resync = False
                    sub.queue.clear()  # superseded deltas
                    resync = True
                    break
                if sub.queue:
                    while sub.queue and len(notes) < max(1, max_notes):
                        notes.append(sub.queue.popleft()[1])
                    more = bool(sub.queue)
                    break
                remaining = (None if deadline is None
                             else deadline - self._clock())
                if remaining is not None and remaining <= 0:
                    more = False
                    break
                sub.cond.wait(remaining)
        if resync:
            # cond released; the manager lock gives a coherent
            # (matches, seq, digest) triple — any delta enqueued in the
            # gap has seq_to <= this seq and the client drops it
            with self._lock:
                matches = list(sub.matches)
                seq, digest = sub.last_seq, sub.digest
            self.stats.record_resync()
            return {"what": "resync", "id": sid, "seq": seq,
                    "matches": sorted(matches), "digest": digest}
        return {"what": "notifications", "id": sid, "notes": notes,
                "more": more}

    # -- observability / lifecycle --------------------------------------------
    def health_section(self) -> dict:
        """The ``sub`` healthz section: staleness (oldest un-notified
        dirty age) against the configured bound — what the
        ``sub_staleness`` fleet objective consumes."""
        now = self._clock()
        with self._lock:
            subs = self.subs.all()
            dirty = sum(1 for s in subs if s.dirty)
            inflight = sum(1 for s in subs if s.inflight is not None)
            oldest = min((s.dirty_since for s in subs
                          if s.dirty_since is not None), default=None)
        staleness = 0.0 if oldest is None else max(0.0, now - oldest)
        bound = self.config.staleness_bound_s
        return {
            "active": len(subs), "dirty": dirty, "inflight": inflight,
            "staleness_s": round(staleness, 6), "bound_s": bound,
            "violating": staleness > bound,
            "notified_total": self.stats.notified,
            "shed_total": self.stats.shed,
        }

    def close(self) -> None:
        """Detach from the graph and wake every parked poller; the
        runtime is NOT closed (it outlives its standing queries)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._detach_listeners()
        for sub in self.subs.all():
            with sub.cond:
                sub.closed = True
                sub.cond.notify_all()


class _Unset:
    __slots__ = ()


_UNSET = _Unset()
