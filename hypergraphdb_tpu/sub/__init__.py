"""hgsub: standing queries — a streaming subscription tier over the
ingest delta.

A standing query is a serve lane that re-fires on its dirty set:
registered pattern / range / BFS queries are incrementally re-evaluated
against graph mutations through the SAME bucketed device programs as
ad-hoc traffic, and set deltas stream to consumers over bounded
per-subscription queues with resume-seq anchoring and shed-not-hang
backpressure. See ``sub/manager.py`` for the evaluation model and
``sub/wire.py`` for the wire contract.
"""

from hypergraphdb_tpu.sub.manager import SubConfig, SubscriptionManager
from hypergraphdb_tpu.sub.registry import (
    Subscription,
    SubscriptionRegistry,
    match_digest,
)
from hypergraphdb_tpu.sub.stats import DOTTED_NAMES, SubStats
from hypergraphdb_tpu.sub.wire import poll_payload, subscribe_payload

__all__ = [
    "SubConfig",
    "SubscriptionManager",
    "Subscription",
    "SubscriptionRegistry",
    "match_digest",
    "DOTTED_NAMES",
    "SubStats",
    "poll_payload",
    "subscribe_payload",
]
