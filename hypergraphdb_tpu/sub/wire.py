"""Wire surface of the subscription tier: payload -> manager calls.

The JSON envelopes (all ``"what"``-discriminated — the tree's envelope
idiom, statically checked closed-world by hglint HG1102):

Requests (``POST /subscribe`` body; ``GET /notifications`` query)::

    {"what": "subscribe", "kind": "pattern", "anchors": [..],
     "type_handle": T?, "window": W?, "deadline_s": D?}
    {"what": "subscribe", "kind": "range", "lo": .., "hi": ..,
     "lo_op": "gte", "hi_op": "lte", "type_handle": T?, "anchor": A?,
     "window": W?, "deadline_s": D?}
    {"what": "subscribe", "kind": "bfs", "seed": S, "max_hops": H?,
     "include_seed": false, "window": W?, "deadline_s": D?}
    {"what": "unsubscribe", "id": "sub-1"}
    {"id": "sub-1", "timeout_s": 5, "max": 32}          # notifications

Responses::

    {"what": "subscribed", "id", "kind", "seq", "window",
     "matches": [..], "digest"}                          # resume base
    {"what": "unsubscribed", "id"}
    {"what": "notifications", "id", "notes": [..], "more": bool}
    {"what": "notification", "id", "seq_from", "seq_to",
     "added": [..], "removed": [..], "digest"}           # one note
    {"what": "resync", "id", "seq", "matches": [..], "digest"}

Contract: a notification's ``added``/``removed`` is EXACTLY the diff of
full evaluations at ``seq_from`` and ``seq_to``; consecutive notes
chain (``seq_from`` equals the previous ``seq_to``); after a ``resync``
the consumer replaces its set wholesale and drops any delta whose
``seq_to`` is <= the resync's ``seq``.

Errors ride the standard typed mapping (``replica/httpd._STATUS``):
unknown/closed subscription and malformed shapes are
:class:`~hypergraphdb_tpu.serve.types.Unservable` (400), capacity is
:class:`~hypergraphdb_tpu.serve.types.QueueFull` (503).
"""

from __future__ import annotations

from hypergraphdb_tpu.serve.types import Unservable


def subscribe_payload(manager, payload: dict) -> dict:
    """Decode one ``POST /subscribe`` body and run it against the
    manager: ``subscribe`` (the default when ``what`` is omitted) or
    ``unsubscribe``."""
    what = payload.get("what", "subscribe")
    if what == "unsubscribe":
        sid = payload.get("id")
        if not isinstance(sid, str):
            raise Unservable("unsubscribe needs a string 'id'")
        return manager.unsubscribe(sid)
    if what == "subscribe":
        kind = payload.get("kind")
        if not isinstance(kind, str):
            raise Unservable("subscribe needs a string 'kind' "
                             "(pattern | range | bfs)")
        params = {
            "anchors": payload.get("anchors"),
            "type_handle": payload.get("type_handle"),
            "lo": payload.get("lo"), "hi": payload.get("hi"),
            "lo_op": payload.get("lo_op", "gte"),
            "hi_op": payload.get("hi_op", "lte"),
            "anchor": payload.get("anchor"),
            "limit": payload.get("limit"),
            "desc": payload.get("desc"),
            "seed": payload.get("seed"),
            "max_hops": payload.get("max_hops"),
            "include_seed": payload.get("include_seed", False),
        }
        if kind == "pattern" and params["anchors"] is None:
            raise Unservable("pattern subscription needs 'anchors'")
        if kind == "bfs" and params["seed"] is None:
            raise Unservable("bfs subscription needs 'seed'")
        return manager.subscribe(
            kind, params, window=payload.get("window"),
            deadline_s=payload.get("deadline_s"),
        )
    raise Unservable(f"unknown subscribe action {what!r}")


def poll_payload(manager, payload: dict,
                 max_timeout_s: float = 25.0) -> dict:
    """Decode one ``GET /notifications`` request (query parameters as a
    dict) into a long-poll. ``timeout_s`` is clamped below the HTTP
    handler's own socket timeout so a parked poll always answers."""
    sid = payload.get("id")
    if not isinstance(sid, str) or not sid:
        raise Unservable("notifications poll needs a subscription 'id'")
    try:
        timeout = float(payload.get("timeout_s", 0.0) or 0.0)
        max_notes = int(payload.get("max", 32) or 32)
    except (TypeError, ValueError) as e:
        raise Unservable(f"bad poll parameter: {e}") from None
    return manager.poll(sid, max_notes=max_notes,
                        timeout_s=min(max(0.0, timeout), max_timeout_s))
