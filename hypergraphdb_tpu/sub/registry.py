"""Standing-query registry: subscriptions and their per-sub state.

A :class:`Subscription` is one standing pattern / range / BFS query with
the state the incremental evaluator and the delivery plane share:

- ``matches`` — the current FULL match set (atom handles), the thing
  deltas are diffed against;
- ``last_seq`` — the ingest seq the client is notified through (the
  resume anchor: a notification carries ``seq_from == last_seq`` before
  it advances);
- ``digest`` — order-independent 64-bit digest of ``matches`` (the
  residual match-set digest; rides every notification so a consumer can
  audit that its replayed set matches the server's);
- ``queue`` — the bounded per-subscription notification queue
  (``window`` deep) with its condition variable (long-poll parking);
- ``dirty`` / ``inflight`` — the evaluator's re-fire state.

The :class:`SubscriptionRegistry` is a locked id → subscription map;
evaluation policy lives in :class:`~hypergraphdb_tpu.sub.manager
.SubscriptionManager`, wire shapes in :mod:`hypergraphdb_tpu.sub.wire`.
"""

from __future__ import annotations

import itertools
import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable, Optional

_MASK64 = (1 << 64) - 1


def match_digest(matches: Iterable[int]) -> int:
    """Order-independent 64-bit digest of a match set: XOR of each
    handle's splitmix64 finalizer — O(n), incrementally updatable
    (XOR-in an added handle, XOR-out a removed one), and collision-safe
    enough for a drift AUDIT (the diff itself is always exact)."""
    d = 0
    for h in matches:
        x = (int(h) + 0x9E3779B97F4A7C15) & _MASK64
        x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
        x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _MASK64
        d ^= x ^ (x >> 31)
    return d & _MASK64


@dataclass
class Subscription:
    """One standing query. Mutable state is guarded by the owning
    manager's lock EXCEPT the notification queue, which the delivery
    plane guards with ``cond`` (enqueue from the dispatch thread, drain
    from HTTP handler threads)."""

    sid: str
    kind: str                        # "pattern" | "range" | "bfs"
    params: dict                     # normalized request parameters
    window: int                      # bounded queue depth (backpressure)
    deadline_s: Optional[float]      # notification TTL before shed
    # -- evaluator state (manager lock) --
    matches: set = field(default_factory=set)
    last_seq: int = 0
    digest: int = 0
    dirty: bool = False
    dirty_since: Optional[float] = None
    inflight: Optional[tuple] = None     # (future, eval_seq)
    retry_at: float = 0.0                # failed-eval backoff gate
    #: prebuilt serve request (PatternRequest / RangeRequest; None for
    #: bfs, whose request is rebuilt from params per submit)
    request: object = None
    # range acceleration: precomputed order-preserving bound keys
    # (dim, lo_key, hi_key) so the per-event window probe never re-runs
    # the typesystem
    range_keys: Optional[tuple] = None
    # -- delivery state (cond) --
    queue: deque = field(default_factory=deque)
    cond: threading.Condition = field(default_factory=threading.Condition)
    needs_resync: bool = False
    closed: bool = False

    def refresh_digest(self) -> None:
        self.digest = match_digest(self.matches)


class SubscriptionRegistry:
    """Locked id → :class:`Subscription` map. Ids are process-local
    (``sub-<n>``); cross-process identity is the front door's concern
    (it maps its own ids onto each backend's)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: dict[str, Subscription] = {}
        self._ids = itertools.count(1)

    def add(self, sub_kind: str, params: dict, window: int,
            deadline_s: Optional[float]) -> Subscription:
        with self._lock:
            sid = f"sub-{next(self._ids)}"
            sub = Subscription(sid=sid, kind=sub_kind, params=params,
                               window=window, deadline_s=deadline_s)
            self._subs[sid] = sub
            return sub

    def get(self, sid: str) -> Optional[Subscription]:
        with self._lock:
            return self._subs.get(sid)

    def remove(self, sid: str) -> Optional[Subscription]:
        with self._lock:
            return self._subs.pop(sid, None)

    def all(self) -> list:
        with self._lock:
            return list(self._subs.values())

    def __len__(self) -> int:
        with self._lock:
            return len(self._subs)
