"""Subscription metrics: the ``sub.*`` dotted namespace.

One façade over an :class:`hypergraphdb_tpu.obs.Registry` in the
``serve/stats.py`` mold: every fixed name is committed in
:data:`DOTTED_NAMES` (hglint HG1105 evaluates the tuple by AST and flags
any literal ``sub.*`` metric site outside it), counters are registered
eagerly so a scrape sees the whole family before the first
subscription, and the ``record_*`` methods serialize on one coherence
lock so the accounting identities (``notified + shed`` vs enqueued,
``evals + eval_errors`` vs rounds) hold in every snapshot.

No jax — safe from the dispatch thread, the graph-event listeners, and
HTTP handler threads concurrently.
"""

from __future__ import annotations

import threading
from typing import Optional

from hypergraphdb_tpu.obs.registry import Registry

#: every fixed ``sub.*`` name this façade registers. Load-bearing for
#: static checking: hglint HG1105 treats the first dotted segment as a
#: governed namespace — a ``sub.*`` literal outside this tuple is
#: metric-name drift.
DOTTED_NAMES = (
    "sub.subscribed",
    "sub.unsubscribed",
    "sub.active",
    "sub.eval_rounds",
    "sub.evals",
    "sub.eval_errors",
    "sub.dirty_skipped",
    "sub.full_fallbacks",
    "sub.notified",
    "sub.shed",
    "sub.resyncs",
    "sub.polls",
    "sub.queue_depth",
    "sub.staleness_seconds",
)


class SubStats:
    """Thread-safe metrics surface for one
    :class:`~hypergraphdb_tpu.sub.manager.SubscriptionManager`."""

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry if registry is not None else Registry()
        self._lock = threading.Lock()
        r = self.registry
        self._subscribed = r.counter("sub.subscribed")
        self._unsubscribed = r.counter("sub.unsubscribed")
        self._active = r.gauge("sub.active")
        self._eval_rounds = r.counter("sub.eval_rounds")
        self._evals = r.counter("sub.evals")
        self._eval_errors = r.counter("sub.eval_errors")
        self._dirty_skipped = r.counter("sub.dirty_skipped")
        self._full_fallbacks = r.counter("sub.full_fallbacks")
        self._notified = r.counter("sub.notified")
        self._shed = r.counter("sub.shed")
        self._resyncs = r.counter("sub.resyncs")
        self._polls = r.counter("sub.polls")
        self._queue_depth = r.gauge("sub.queue_depth")
        self._staleness = r.gauge("sub.staleness_seconds")
        self._own = (
            self._subscribed, self._unsubscribed, self._active,
            self._eval_rounds, self._evals, self._eval_errors,
            self._dirty_skipped, self._full_fallbacks, self._notified,
            self._shed, self._resyncs, self._polls, self._queue_depth,
            self._staleness,
        )

    def reset(self) -> None:
        """Zero this façade's instruments only — foreign subsystems on a
        shared registry survive (the serve-stats discipline)."""
        with self._lock:
            for m in self._own:
                m.reset()

    # -- recording ------------------------------------------------------------
    def record_subscribe(self, active: int) -> None:
        with self._lock:
            self._subscribed.inc()
            self._active.set(active)

    def record_unsubscribe(self, active: int) -> None:
        with self._lock:
            self._unsubscribed.inc()
            self._active.set(active)

    def record_eval_round(self, submitted: int, skipped: int) -> None:
        """One pump round: ``submitted`` dirty subscriptions re-entered
        the serve lanes, ``skipped`` clean ones did NOT re-evaluate —
        the incremental tier's whole point, so it is counted as
        evidence (``sub.dirty_skipped``)."""
        with self._lock:
            self._eval_rounds.inc()
            if skipped:
                self._dirty_skipped.inc(skipped)

    def record_eval(self) -> None:
        with self._lock:
            self._evals.inc()

    def record_eval_error(self) -> None:
        with self._lock:
            self._eval_errors.inc()

    def record_full_fallback(self) -> None:
        """A truncated lane result forced an exact full host
        re-evaluation for one subscription."""
        with self._lock:
            self._full_fallbacks.inc()

    def record_notify(self) -> None:
        with self._lock:
            self._notified.inc()

    def record_shed(self, n: int = 1) -> None:
        with self._lock:
            self._shed.inc(n)

    def record_resync(self) -> None:
        with self._lock:
            self._resyncs.inc()

    def record_poll(self) -> None:
        with self._lock:
            self._polls.inc()

    def set_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(depth)

    def set_staleness(self, seconds: float) -> None:
        self._staleness.set(seconds)

    # -- reading --------------------------------------------------------------
    @property
    def subscribed(self) -> int:
        return self._subscribed.value

    @property
    def active(self) -> int:
        return int(self._active.value)

    @property
    def evals(self) -> int:
        return self._evals.value

    @property
    def eval_rounds(self) -> int:
        return self._eval_rounds.value

    @property
    def dirty_skipped(self) -> int:
        return self._dirty_skipped.value

    @property
    def full_fallbacks(self) -> int:
        return self._full_fallbacks.value

    @property
    def notified(self) -> int:
        return self._notified.value

    @property
    def shed(self) -> int:
        return self._shed.value

    @property
    def resyncs(self) -> int:
        return self._resyncs.value

    def snapshot(self) -> dict:
        """One coherent dotted-name snapshot (the drift gate asserts its
        keys equal :data:`DOTTED_NAMES`)."""
        with self._lock:
            return {
                "sub.subscribed": self._subscribed.value,
                "sub.unsubscribed": self._unsubscribed.value,
                "sub.active": self._active.value,
                "sub.eval_rounds": self._eval_rounds.value,
                "sub.evals": self._evals.value,
                "sub.eval_errors": self._eval_errors.value,
                "sub.dirty_skipped": self._dirty_skipped.value,
                "sub.full_fallbacks": self._full_fallbacks.value,
                "sub.notified": self._notified.value,
                "sub.shed": self._shed.value,
                "sub.resyncs": self._resyncs.value,
                "sub.polls": self._polls.value,
                "sub.queue_depth": self._queue_depth.value,
                "sub.staleness_seconds": self._staleness.value,
            }
