"""Entry-point registry for jaxpr-level verification (``tools/hgverify``).

The kernels in ``ops/`` and ``parallel/`` publish their public jitted
entry points here with *shape exemplars* — small ``ShapeDtypeStruct``
pytrees a verifier can trace under ``JAX_PLATFORMS=cpu`` to obtain the
ground-truth jaxpr/HLO of what actually runs on the TPU. The decorator is
non-invasive: it records the function in a registry and returns it
UNCHANGED (no wrapper, no import-time tracing — exemplar builders are
zero-arg callables evaluated only when a verifier harvests them).

Usage, at a kernel definition site::

    from hypergraphdb_tpu import verify as hgverify

    @hgverify.entry(shapes=lambda: (hgverify.sds((8, 128), "uint32"),))
    @jax.jit
    def my_kernel(x): ...

Registered metadata feeds four verification families (see
``tools/hgverify``): HV1xx traced-graph purity (no host callbacks), HV2xx
collective/mesh consistency (``mesh=`` declares the deployment mesh axis
names the entry's collectives must match), HV3xx donation contracts
(``donate=True`` declares that the entry donates buffers), HV4xx static
cost budgets (FLOPs / bytes accessed / peak temp vs
``tools/hgverify/costs.json``).

This module deliberately imports nothing heavy at module scope so the
registry is importable from both the product package and the tools tree.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence


@dataclass(frozen=True)
class Entry:
    """One registered verification entry point."""

    name: str                      # registry key, e.g. "ops.frontier.bfs_levels"
    fn: Callable                   # the (possibly jitted) callable, unchanged
    shapes: Callable               # () -> tuple of exemplar args (SDS pytrees)
    statics: dict                  # static kwargs bound before tracing
    mesh: Optional[tuple]          # declared deployment mesh axis names
    donate: bool                   # entry declares buffer donation
    path: str                      # source file of the underlying function
    line: int                      # first line of the underlying function


class Registry:
    """Ordered, name-keyed entry collection. The module-level
    :data:`REGISTRY` holds the production entries; tests build private
    registries so fixture entries never pollute the cost-budget gate."""

    def __init__(self):
        self._entries: dict[str, Entry] = {}

    def entry(self, name: Optional[str] = None, *,
              shapes: Callable,
              statics: Optional[dict] = None,
              mesh: Optional[Sequence[str]] = None,
              donate: bool = False):
        """Decorator registering ``fn`` under ``name`` (default: the
        function's ``<module-tail>.<qualname>``). Returns ``fn`` as-is."""

        def deco(fn):
            path, line = _source_of(fn)
            key = name or _default_name(fn)
            if key in self._entries:
                raise ValueError(f"hgverify entry {key!r} registered twice")
            self._entries[key] = Entry(
                name=key, fn=fn, shapes=shapes,
                statics=dict(statics or {}),
                mesh=tuple(mesh) if mesh is not None else None,
                donate=bool(donate), path=path, line=line,
            )
            return fn

        return deco

    def names(self) -> list:
        return list(self._entries)

    def get(self, name: str) -> Entry:
        return self._entries[name]

    def __iter__(self):
        return iter(self._entries.values())

    def __len__(self):
        return len(self._entries)


#: the production registry ``tools/hgverify`` harvests
REGISTRY = Registry()

#: module-level decorator bound to the production registry, so kernel
#: modules spell ``@hgverify.entry(shapes=...)``
entry = REGISTRY.entry


def _unwrap(fn):
    """Innermost wrapped function — jit/partial wrappers carry
    ``__wrapped__``/``func`` chains back to real code."""
    seen = 0
    while seen < 8:
        nxt = getattr(fn, "__wrapped__", None) or getattr(fn, "func", None)
        if nxt is None or nxt is fn:
            break
        fn = nxt
        seen += 1
    return fn


def _source_of(fn) -> tuple:
    code = getattr(_unwrap(fn), "__code__", None)
    if code is None:
        return "<unknown>", 0
    return code.co_filename, code.co_firstlineno


def _default_name(fn) -> str:
    inner = _unwrap(fn)
    mod = getattr(inner, "__module__", "") or ""
    tail = mod.split("hypergraphdb_tpu.")[-1] if mod else "<mod>"
    return f"{tail}.{getattr(inner, '__qualname__', repr(inner))}"


# ---------------------------------------------------------------- exemplars
#
# Shared builders for the shape exemplars kernel modules register. All jax
# imports are deferred: nothing here touches a backend until a verifier
# actually evaluates a ``shapes=`` callable.


def sds(shape, dtype):
    """``jax.ShapeDtypeStruct`` shorthand for exemplar tuples."""
    import jax
    import jax.numpy as jnp

    return jax.ShapeDtypeStruct(tuple(shape), jnp.dtype(dtype))


def dev_snapshot_exemplar(n_atoms: int = 31, e_inc: int = 64,
                          e_tgt: int = 64):
    """A :class:`ops.snapshot.DeviceSnapshot` pytree of abstract leaves —
    31 atoms + the dummy row, 64-entry edge relations. Small enough that
    every traced program compiles in milliseconds on CPU."""
    from hypergraphdb_tpu.ops.snapshot import DeviceSnapshot

    n1 = n_atoms + 1
    return DeviceSnapshot(
        num_atoms=n_atoms,
        inc_offsets=sds((n1 + 1,), "int32"),
        inc_links=sds((e_inc,), "int32"),
        inc_src=sds((e_inc,), "int32"),
        tgt_offsets=sds((n1 + 1,), "int32"),
        tgt_flat=sds((e_tgt,), "int32"),
        tgt_src=sds((e_tgt,), "int32"),
        type_of=sds((n1,), "int32"),
        is_link=sds((n1,), "bool"),
        arity=sds((n1,), "int32"),
        value_rank_hi=sds((n1,), "uint32"),
        value_rank_lo=sds((n1,), "uint32"),
        value_kind=sds((n1,), "uint8"),
    )


def device_delta_exemplar(n_atoms: int = 31, d: int = 16):
    """A :class:`ops.incremental.DeviceDelta` overlay exemplar matching
    :func:`dev_snapshot_exemplar`'s id space."""
    from hypergraphdb_tpu.ops.incremental import DeviceDelta

    return DeviceDelta(
        inc_links=sds((d,), "int32"),
        inc_src=sds((d,), "int32"),
        tgt_flat=sds((d,), "int32"),
        tgt_src=sds((d,), "int32"),
        dead=sds((n_atoms + 1,), "bool"),
    )


def sharded_snapshot_exemplar(n_loc: int = 128, e_loc: int = 64):
    """A :class:`parallel.sharded.ShardedSnapshot` over the available CPU
    devices (capped at 8 — the count ``tools/verify.sh`` and the test
    harness force via ``xla_force_host_platform_device_count``). Edge/row
    arrays are abstract; only the mesh itself is concrete (shard_map needs
    a real Mesh object to trace, not real data)."""
    import jax
    import numpy as np

    from hypergraphdb_tpu.parallel.sharded import ShardedSnapshot
    from jax.sharding import Mesh

    devices = jax.devices()[:8]
    mesh = Mesh(np.asarray(devices), ("shard",))
    n_dev = len(devices)
    n_pad = n_dev * n_loc
    return ShardedSnapshot(
        mesh=mesh,
        num_atoms=n_pad - 28,     # a ragged tail exercises the valid mask
        n_loc=n_loc,
        edge_chunk=e_loc,
        inc_src=sds((n_dev * e_loc,), "int32"),
        inc_dst=sds((n_dev * e_loc,), "int32"),
        tgt_src=sds((n_dev * e_loc,), "int32"),
        tgt_dst=sds((n_dev * e_loc,), "int32"),
        type_of=sds((n_pad,), "int32"),
        is_link=sds((n_pad,), "bool"),
        arity=sds((n_pad,), "int32"),
        value_rank_hi=sds((n_pad,), "uint32"),
        value_rank_lo=sds((n_pad,), "uint32"),
    )


def sharded_delta_exemplar(n_loc: int = 128, d_loc: int = 16):
    """A :class:`parallel.sharded.ShardedDelta` overlay exemplar matching
    :func:`sharded_snapshot_exemplar`'s row layout (same n_loc, same
    device count cap): per-device delta edge slices of ``d_loc`` entries
    and the packed per-device tombstone words."""
    import jax

    from hypergraphdb_tpu.parallel.sharded import ShardedDelta

    n_dev = len(jax.devices()[:8])
    return ShardedDelta(
        epoch=0,
        edge_chunk=d_loc,
        inc_src=sds((n_dev * d_loc,), "int32"),
        inc_dst=sds((n_dev * d_loc,), "int32"),
        tgt_src=sds((n_dev * d_loc,), "int32"),
        tgt_dst=sds((n_dev * d_loc,), "int32"),
        dead=sds((n_dev * (n_loc // 32),), "uint32"),
    )
