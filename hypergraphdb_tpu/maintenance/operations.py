"""Resumable maintenance operations (see package docstring)."""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

from hypergraphdb_tpu.core.errors import HGException


class MaintenanceException(HGException):
    pass


@dataclass(frozen=True)
class MaintenanceOperation:
    """Base persisted operation state. Subclasses are dataclasses so they
    serialize as record atoms; ``execute`` runs ONE batch and returns the
    updated state, or None when finished (the MaintenanceOperation.execute
    contract, batched like ApplyNewIndexer.java:36-41)."""

    last_processed: int = -1
    batch_size: int = 100

    def execute_batch(self, graph) -> Optional["MaintenanceOperation"]:
        raise NotImplementedError


@dataclass(frozen=True)
class ApplyNewIndexer(MaintenanceOperation):
    """Offline population of a newly-registered indexer: walks the atom id
    space in batches, indexing atoms of the indexer's type; the cursor
    (``last_processed``) persists between batches so a crash resumes."""

    indexer_name: str = ""
    type_handle: int = -1
    #: frozen id-space bound, captured on the first batch: persisting the
    #: cursor itself allocates handles, so a live ``handles.peek`` bound
    #: would recede forever (atoms added after scheduling are indexed by
    #: the normal ``maybe_index`` add path anyway)
    end_bound: int = -1

    def execute_batch(self, graph) -> Optional["ApplyNewIndexer"]:
        from hypergraphdb_tpu.indexing.manager import get_index, indexers_of

        indexers = [
            ix for ix in indexers_of(graph, self.type_handle)
            if ix.name == self.indexer_name
        ]
        if not indexers:
            raise MaintenanceException(
                f"indexer {self.indexer_name!r} is not registered"
            )
        ix = indexers[0]
        if self.end_bound < 0:
            return replace(self, end_bound=int(graph.handles.peek))
        start = self.last_processed + 1
        end = min(start + self.batch_size, self.end_bound)
        if start >= end:
            return None
        idx = get_index(graph, ix.name)
        # subtype atoms are indexed too — same closure the online path and
        # rebuild() use, or the offline build silently disagrees with them
        applicable = {int(self.type_handle)}
        try:
            tname = graph.typesystem.name_of(self.type_handle)
            for sub in graph.typesystem.subtypes_closure(tname):
                applicable.add(int(graph.typesystem.handle_of(sub)))
        except KeyError:
            pass
        for h in range(start, end):
            rec = graph.store.get_link(h)
            if rec is None or len(rec) < 3 or int(rec[0]) not in applicable:
                continue
            try:
                value = graph.get(h)
                targets = getattr(value, "targets", None)
                value = getattr(value, "value", value)
            except Exception:
                continue
            for key in ix.keys(graph, h, value, targets):
                for v in ix.values(graph, h, value, targets):
                    idx.add_entry(key, v)
        return replace(self, last_processed=end - 1)


def schedule(graph, op: MaintenanceOperation) -> int:
    """Persist an operation atom; it runs at the next ``run_pending`` (the
    reference schedules them to run on open, ``HyperGraph.open`` step)."""
    return int(graph.add(op))


def run_pending(graph, max_batches: int = 1_000_000) -> int:
    """Run all persisted maintenance operations to completion, batch by
    batch, persisting the cursor after each batch (crash ⇒ resume). Returns
    the number of operations completed."""
    from hypergraphdb_tpu.query import dsl as q

    done = 0
    for cls in _operation_classes():
        t = graph.typesystem.infer(cls())
        if t is None:
            continue
        for h in list(q.find_all(graph, q.type_(t.name))):
            op = graph.get(h)
            op = getattr(op, "value", op)
            batches = 0
            try:
                while op is not None and batches < max_batches:
                    nxt = op.execute_batch(graph)
                    if nxt is None:
                        graph.remove(h)
                        done += 1
                        break
                    graph.replace(h, nxt)  # persist the cursor
                    op = nxt
                    batches += 1
            except MaintenanceException:
                # e.g. the indexer registry is per-session and hasn't been
                # re-registered after reopen: leave THIS op persisted for a
                # later run instead of aborting every pending operation
                import logging

                logging.getLogger("hypergraphdb_tpu.maintenance").warning(
                    "maintenance op %s deferred", h, exc_info=True
                )
                continue
    return done


def _operation_classes() -> list[type]:
    return [ApplyNewIndexer]
