"""Maintenance: resumable offline operations run at open.

Re-expression of the reference's ``maintenance/`` package
(``MaintenanceOperation``, ``ApplyNewIndexer`` with its batch-100
``lastProcessed`` cursor at ``maintenance/ApplyNewIndexer.java:36-41``,
``Migration``/``Upgrade``): a maintenance operation is persisted AS AN
ATOM, executes in batches with a persisted cursor, and — if the process
dies mid-run — resumes from the cursor on the next open.
"""

from hypergraphdb_tpu.maintenance.operations import (
    ApplyNewIndexer,
    MaintenanceException,
    MaintenanceOperation,
    run_pending,
    schedule,
)

__all__ = [
    "ApplyNewIndexer",
    "MaintenanceException",
    "MaintenanceOperation",
    "run_pending",
    "schedule",
]
