"""On-disk format versioning + migration hooks.

The reference's ``maintenance/`` package pairs resumable operations with
explicit database upgrades; here the store carries a persisted FORMAT
VERSION (``hg.sys.format``) checked at every open:

- a fresh database is stamped with :data:`FORMAT_VERSION`;
- an older database runs the registered migration chain, one step per
  version, stamping after each completed step (a crash mid-chain resumes
  at the first unapplied step);
- a NEWER database refuses to open (downgrade protection — the WAL magic
  alone could not distinguish "new layout" from "corrupt").

Migrations are plain callables ``fn(graph) -> None`` registered per
from-version with :func:`register_migration`; they run inside the open
path after the backend is up but before indexer/subsumption restore, so a
migration may rewrite registry formats the loaders then read.
"""

from __future__ import annotations

from typing import Callable, Optional

from hypergraphdb_tpu.core.errors import HGException

#: the CURRENT on-disk format this build reads and writes
FORMAT_VERSION = 1

#: version the pre-versioning databases are assumed to be at
_IMPLICIT_VERSION = 1

IDX_FORMAT = "hg.sys.format"
_KEY = b"version"

_MIGRATIONS: dict[int, Callable] = {}


class MigrationError(HGException):
    pass


def register_migration(from_version: int, fn: Callable) -> None:
    """Register the step migrating ``from_version`` → ``from_version + 1``.
    One step per version; re-registration replaces (tests)."""
    _MIGRATIONS[int(from_version)] = fn


def stored_format_version(graph) -> Optional[int]:
    idx = graph.store.get_index(IDX_FORMAT, create=False)
    if idx is None:
        return None
    vals = idx.find(_KEY).array()
    return int(vals.max()) if len(vals) else None


def stamp_format_version(graph, version: int) -> None:
    def run() -> None:
        idx = graph.store.get_index(IDX_FORMAT)
        for old in idx.find(_KEY).array().tolist():
            idx.remove_entry(_KEY, int(old))
        idx.add_entry(_KEY, int(version))

    graph.txman.ensure_transaction(run)


def migrate(graph, target: Optional[int] = None) -> int:
    """Bring the database to ``target`` (default :data:`FORMAT_VERSION`).
    Returns how many migration steps ran. Called from ``HyperGraph``'s
    open path; safe on every backend including memory."""
    target = FORMAT_VERSION if target is None else int(target)
    stored = stored_format_version(graph)
    if stored is None:
        # fresh database OR pre-versioning store: fresh stores (flagged by
        # the graph BEFORE bootstrap populated them) stamp the current
        # format; legacy populated ones sit at the implicit version and
        # may need the chain
        stored = (
            target if getattr(graph, "_fresh_store", False)
            else _IMPLICIT_VERSION
        )
        if stored >= target:
            stamp_format_version(graph, target)
            return 0
    if stored > target:
        raise MigrationError(
            f"database format {stored} is newer than this build's {target}: "
            "refusing to open (upgrade the library instead)"
        )
    steps = 0
    while stored < target:
        fn = _MIGRATIONS.get(stored)
        if fn is None:
            raise MigrationError(
                f"no migration registered for format {stored} → {stored + 1}"
            )
        fn(graph)
        stored += 1
        stamp_format_version(graph, stored)  # resumable: stamp per step
        steps += 1
    return steps
