"""Typed fault vocabulary: what can go wrong, and how callers classify it.

The reference survives faults by TYPING them — activities carry explicit
failure FSM states (``peer/workflow/WorkflowState.java``), storage errors
are transactional aborts, and everything else is a crash the BDB log
replays through. This module is the rebuild's equivalent vocabulary: every
self-healing layer (serve retries, peer redelivery, checkpoint recovery)
keys its decision — retry / degrade / surface / die — off these types
instead of string-matching exception messages.
"""

from __future__ import annotations


class FaultError(Exception):
    """Base of every injected fault (and the natural base for real fault
    types a deployment wants routed through the same classification)."""


class TransientFault(FaultError):
    """Retry-worthy: the operation may succeed if re-attempted (flaky
    device dispatch, dropped packet, momentarily busy resource)."""

    transient = True


class PermanentFault(FaultError):
    """Not retry-worthy: re-attempting burns the caller's deadline for
    nothing (malformed input, missing capability, poisoned state)."""

    transient = False


class InjectedCrash(BaseException):
    """Simulated process death at a registered crash point.

    Deliberately NOT an ``Exception``: the self-healing layers' generic
    ``except Exception`` recovery code must never swallow a *kill* — a
    crash drill's harness catches it at the very top and ``os._exit``\\ s,
    exactly like the reference's AbruptExit test."""


#: exception types classified transient by default (beyond the explicit
#: ``transient`` attribute): timeouts and connection drops are the
#: canonical retry-worthy failures of both the device and the peer planes
DEFAULT_TRANSIENT = (TransientFault, TimeoutError, ConnectionError)


def is_transient(exc: BaseException, extra: tuple = ()) -> bool:
    """Classify an error as transient (retry may help) vs permanent.

    Order matters: an explicit ``transient`` attribute on the exception
    wins (``PermanentFault.transient = False`` beats any isinstance
    check), then the default transient families plus the caller's
    ``extra`` types (``ServeConfig.transient_errors``)."""
    t = getattr(exc, "transient", None)
    if t is not None:
        return bool(t)
    return isinstance(exc, DEFAULT_TRANSIENT + tuple(extra))
