"""Per-key circuit breaker: trip to a degraded path, probe, recover.

The serving runtime keys one breaker gate per batch key (the "bucket" of
kernel statics): ``K`` consecutive device failures for a key trip its
gate OPEN, and while open every batch of that key routes to the exact
host-fallback path — a flaky device degrades *throughput*, never
*answers*. After ``cooldown_s`` the gate half-opens and releases ONE
probe batch to the device; a probe success closes the gate (device
serving resumes), a probe failure re-opens it for another cooldown. A
probe that never reports (lost batch) does not wedge the gate: another
probe is released once a further cooldown elapses.

States and the numeric codes the ``serve.breaker_state`` gauge exports::

    closed (0)  --K consecutive failures-->  open (2)
    open   (2)  --cooldown elapsed------->  half_open (1), one probe out
    half_open   --probe success---------->  closed (0)
    half_open   --probe failure---------->  open (2)

Lock discipline: one lock guards all gates; the ``on_state`` /
``on_trip`` / ``on_key_state`` / ``on_key_trip`` callbacks run UNDER it,
so state-change notifications are serialized in transition order — two
racing transitions can never apply their gauge writes reversed and leave
``serve.breaker_state`` stale. Callbacks must therefore be cheap
instrument writes (the wired ones are: a gauge set / counter inc, each
behind its own leaf lock; nothing takes the breaker lock while holding
an instrument lock, so the one-way nesting is HG401-clean) and must
never call back into the breaker.

Observability: every transition lands one event in the process flight
recorder; a trip is an **incident** (the recorder dumps its window —
rate-limited file IO on an already-degraded path, the one deliberate
exception to "callbacks are leaf instrument writes").
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from hypergraphdb_tpu.obs.flight import global_flight

_FLIGHT = global_flight()

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

#: state → exported gauge code (ordered by badness; the gauge publishes
#: the WORST code across keys, so "anything open?" is one scrape)
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class _Gate:
    __slots__ = ("state", "failures", "opened_t", "probe_t")

    def __init__(self) -> None:
        self.state = CLOSED
        self.failures = 0          # consecutive failures while closed
        self.opened_t = 0.0        # when the gate last opened
        self.probe_t: Optional[float] = None  # when a probe was released


class CircuitBreaker:
    """Keyed breaker gates; see module docstring for the state machine."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 0.25,
                 clock: Optional[Callable[[], float]] = None,
                 on_state: Optional[Callable[[int], None]] = None,
                 on_trip: Optional[Callable[[], None]] = None,
                 on_key_state: Optional[Callable] = None,
                 on_key_trip: Optional[Callable] = None):
        if threshold < 1:
            raise ValueError("breaker threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock or time.monotonic
        self.on_state = on_state      # worst STATE_CODES value, post-change
        self.on_trip = on_trip        # called on every -> OPEN transition
        #: per-key views (the labelled metrics): (key, STATE_CODES value)
        #: after every transition of THAT key / (key,) on its trips
        self.on_key_state = on_key_state
        self.on_key_trip = on_key_trip
        self._lock = threading.Lock()
        self._gates: dict = {}
        self._trips = 0

    # -- the dispatch-side queries -------------------------------------------
    def allow(self, key) -> bool:
        """May the next batch for ``key`` touch the device? OPEN gates say
        no (host fallback); a HALF_OPEN gate says yes exactly once per
        cooldown window (the probe)."""
        with self._lock:
            g = self._gates.get(key)
            if g is None or g.state == CLOSED:
                return True
            now = self.clock()
            if g.state == OPEN:
                if now - g.opened_t < self.cooldown_s:
                    return False
                g.state = HALF_OPEN
                g.probe_t = now
                self._notify_locked(key, g)
                return True
            # HALF_OPEN: one probe per cooldown window
            if g.probe_t is not None and now - g.probe_t < self.cooldown_s:
                return False
            g.probe_t = now
            return True

    def record_success(self, key) -> None:
        """A device batch for ``key`` completed: close the gate."""
        with self._lock:
            g = self._gates.get(key)
            if g is not None and (g.state != CLOSED or g.failures):
                notify = g.state != CLOSED
                g.state = CLOSED
                g.failures = 0
                g.probe_t = None
                if notify:
                    self._notify_locked(key, g)

    def reset(self, key) -> None:
        """Administratively close ``key``'s gate NOW — the rejoin path:
        a router whose health poll sees a previously-dead replica
        answering again re-admits it immediately instead of waiting out
        the cooldown + probe ladder. Notifies like any transition."""
        with self._lock:
            g = self._gates.get(key)
            if g is not None and (g.state != CLOSED or g.failures):
                notify = g.state != CLOSED
                g.state = CLOSED
                g.failures = 0
                g.probe_t = None
                if notify:
                    self._notify_locked(key, g)

    def record_failure(self, key) -> None:
        """A device batch for ``key`` failed (launch or collect)."""
        with self._lock:
            g = self._gates.get(key)
            if g is None:
                g = self._gates[key] = _Gate()
            if g.state == HALF_OPEN:
                # the probe failed: straight back to OPEN
                g.state = OPEN
                g.opened_t = self.clock()
                g.probe_t = None
                self._trips += 1
                self._notify_locked(key, g, tripped=True)
            elif g.state == CLOSED:
                g.failures += 1
                if g.failures >= self.threshold:
                    g.state = OPEN
                    g.opened_t = self.clock()
                    self._trips += 1
                    self._notify_locked(key, g, tripped=True)
            # OPEN: late failures from in-flight batches change nothing

    def _notify_locked(self, key, gate: _Gate,
                       tripped: bool = False) -> None:
        """State-change callbacks, serialized by the caller-held lock
        (see module docstring for why and what callbacks may do).
        Also the flight-recorder tap: one ring append per transition,
        incident (rate-limited dump) on every trip."""
        if _FLIGHT.enabled:
            _FLIGHT.record("breaker.transition", key=str(key),
                           state=gate.state)
        if self.on_state is not None:
            self.on_state(self._worst_locked())
        if self.on_key_state is not None:
            self.on_key_state(key, STATE_CODES[gate.state])
        if tripped:
            if self.on_trip is not None:
                self.on_trip()
            if self.on_key_trip is not None:
                self.on_key_trip(key)
            if _FLIGHT.enabled:
                _FLIGHT.incident("breaker_trip", key=str(key))

    # -- reading -------------------------------------------------------------
    def peek(self, key) -> bool:
        """Would :meth:`allow` admit ``key`` right now — WITHOUT
        consuming the half-open probe token or transitioning the gate?
        For placement-style callers that rank candidates they may never
        dispatch to: burning the one-probe-per-cooldown token on a
        backend the request doesn't reach would starve its actual
        recovery probe. The dispatcher calls :meth:`allow` immediately
        before committing."""
        with self._lock:
            g = self._gates.get(key)
            if g is None or g.state == CLOSED:
                return True
            now = self.clock()
            if g.state == OPEN:
                return now - g.opened_t >= self.cooldown_s
            # HALF_OPEN: a fresh probe window admits one
            return g.probe_t is None or now - g.probe_t >= self.cooldown_s

    def state_of(self, key) -> str:
        with self._lock:
            g = self._gates.get(key)
            return CLOSED if g is None else g.state

    def states(self) -> dict:
        """Every key's current gate state — the per-key ``/healthz``
        view (keys with no gate yet have implicitly closed gates and do
        not appear)."""
        with self._lock:
            return {k: g.state for k, g in self._gates.items()}

    def worst_code(self) -> int:
        with self._lock:
            return self._worst_locked()

    @property
    def trips(self) -> int:
        with self._lock:
            return self._trips

    def _worst_locked(self) -> int:
        return max(
            (STATE_CODES[g.state] for g in self._gates.values()),
            default=0,
        )
