"""Deterministic, seeded fault injection: named points with schedules.

The serving/peer/durability planes each carry named **fault points** —
one-line sites of the form::

    if _FAULTS.enabled:                  # ONE attribute read when off
        _FAULTS.check("serve.launch", kind=kind)

``check`` raises the armed error when the point's schedule fires and is a
counted no-op otherwise. The gate discipline is exactly
``obs.trace.Tracer.enabled``'s: with the registry disabled (the default)
every site costs one attribute read and allocates nothing — enforced by
the event-order differential + poisoned-``check`` regression in
``tests/test_serve_fault.py``.

Schedules are **deterministic by construction**: probability draws come
from a per-point ``random.Random`` seeded by ``(seed, point name)``, so a
point's fire/pass decision depends ONLY on its own hit index — never on
thread interleaving across points. Same seed → same fault sequence, which
is what makes the chaos soaks replayable.

Schedule kinds (first match wins: ``at`` > ``times`` > ``prob``):

- ``at={2, 5}``   — fire on exactly those 1-based hit indices;
- ``times=3``     — fire on the next 3 hits, then pass forever;
- ``prob=0.2``    — fire each hit with probability 0.2 (seeded);
- ``when=fn``     — additional ctx predicate; a hit failing it never
  fires, never draws, and does NOT consume a schedule index — ``at``/
  ``times``/``prob`` count only MATCHED hits, so a filter like "transfer
  chunks only" keeps unrelated traffic out of the schedule arithmetic.

Every fire appends ``(name, hit_index)`` to :attr:`FaultRegistry.journal`,
bumps the ``fault.injected`` counter in the process obs registry, and
lands a ``fault.fired`` event in the process flight recorder — so every
injected-fault test doubles as a flight-recorder fixture and an incident
dump always shows the faults that led up to it.
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Optional

from hypergraphdb_tpu.fault.errors import FaultError, TransientFault
from hypergraphdb_tpu.obs.flight import global_flight

_FLIGHT = global_flight()


class _Point:
    """One armed fault point's schedule + bookkeeping."""

    __slots__ = ("name", "error", "times", "prob", "at", "when", "rng",
                 "fired", "matched")

    def __init__(self, name: str, error, times: Optional[int],
                 prob: Optional[float], at: Optional[set],
                 when: Optional[Callable[[dict], bool]], rng: random.Random):
        self.name = name
        self.error = error
        self.times = times
        self.prob = prob
        self.at = at
        self.when = when
        self.rng = rng
        self.fired = 0
        self.matched = 0  # hits that passed `when` — the schedule index


class FaultRegistry:
    """Named fault points with seeded, deterministic schedules.

    ``enabled`` is the zero-cost gate (a plain attribute, same discipline
    as ``Tracer.enabled``); all other state lives behind one lock. One
    process-wide instance (:func:`global_faults`) serves the in-tree
    sites; tests inject private instances through ``ServeConfig(faults=)``
    where isolation matters."""

    def __init__(self) -> None:
        self.enabled = False
        self._lock = threading.Lock()
        self._seed = 0
        self._points: dict[str, _Point] = {}
        self._hits: dict[str, int] = {}
        #: (point name, 1-based hit index) per fire, in fire order — the
        #: reproducibility record chaos tests assert on
        self.journal: list[tuple[str, int]] = []

    # -- lifecycle -----------------------------------------------------------
    def enable(self, seed: int = 0) -> "FaultRegistry":
        """Turn injection on. ``seed`` keys every probabilistic schedule
        armed afterwards (re-arming an existing probabilistic point resets
        its stream)."""
        with self._lock:
            self._seed = int(seed)
            self.enabled = True
        return self

    def disable(self) -> "FaultRegistry":
        with self._lock:
            self.enabled = False
        return self

    def reset(self) -> "FaultRegistry":
        """Disarm everything and clear counters/journal (the enabled flag
        is left as-is — pair with :meth:`disable` for a full teardown)."""
        with self._lock:
            self._points.clear()
            self._hits.clear()
            self.journal.clear()
        return self

    # -- arming --------------------------------------------------------------
    def arm(self, name: str, *, times: Optional[int] = None,
            prob: Optional[float] = None, at=None,
            error=TransientFault,
            when: Optional[Callable[[dict], bool]] = None) -> None:
        """Arm ``name`` with one schedule (see module docstring). ``error``
        is the exception CLASS to raise (instantiated with a descriptive
        message), or a callable ``(name, hit_index) -> BaseException``."""
        if times is None and prob is None and at is None:
            raise ValueError(f"fault point {name!r}: no schedule given "
                             "(one of times=, prob=, at=)")
        if prob is not None and not 0.0 <= prob <= 1.0:
            raise ValueError(f"fault point {name!r}: prob {prob} not in "
                             "[0, 1]")
        with self._lock:
            # per-point stream: decisions depend only on this point's own
            # hit ordering, never on cross-point interleaving
            rng = random.Random(f"{self._seed}:{name}")
            self._points[name] = _Point(
                name, error, None if times is None else int(times),
                prob, None if at is None else {int(i) for i in at},
                when, rng,
            )

    def disarm(self, name: str) -> None:
        with self._lock:
            self._points.pop(name, None)

    # -- the site call -------------------------------------------------------
    def check(self, name: str, **ctx) -> None:
        """Count a hit at fault point ``name``; raise the armed error when
        its schedule fires. No-op while disabled (sites additionally gate
        on :attr:`enabled` so the disabled path never even gets here)."""
        if not self.enabled:
            return
        with self._lock:
            self._hits[name] = self._hits.get(name, 0) + 1
            pt = self._points.get(name)
            if pt is None:
                return
            if pt.when is not None and not pt.when(ctx):
                return
            pt.matched += 1
            idx = pt.matched
            if pt.at is not None:
                fire = idx in pt.at
            elif pt.times is not None:
                fire = pt.fired < pt.times
            elif pt.prob is not None:
                fire = pt.rng.random() < pt.prob
            else:  # pragma: no cover - arm() requires a schedule
                fire = False
            if not fire:
                return
            pt.fired += 1
            self.journal.append((name, idx))
            err = pt.error
        # construct + count + record outside the lock: error factories,
        # the metrics instrument, and the flight ring take their own paths
        exc = (err(name, idx) if not isinstance(err, type)
               else err(f"injected fault at {name!r} (hit {idx})"))
        from hypergraphdb_tpu.utils.metrics import global_metrics

        global_metrics.incr("fault.injected")
        if _FLIGHT.enabled:
            _FLIGHT.record("fault.fired", point=name, hit=idx,
                           error=type(exc).__name__)
        raise exc

    # -- reading -------------------------------------------------------------
    def hits(self, name: str) -> int:
        """How many times ``name`` was reached while enabled."""
        with self._lock:
            return self._hits.get(name, 0)

    def fired(self, name: str) -> int:
        """How many of those hits raised."""
        with self._lock:
            pt = self._points.get(name)
            return 0 if pt is None else pt.fired

    def armed(self) -> list[str]:
        with self._lock:
            names = list(self._points)
        return sorted(names)


#: the process-wide registry every in-tree site binds at import — a
#: singleton by contract (sites cache the reference in a module global,
#: so replacing it would silently disconnect them)
_GLOBAL = FaultRegistry()


def global_faults() -> FaultRegistry:
    return _GLOBAL


# re-exported for the common "catch anything injected" shape
__all__ = ["FaultError", "FaultRegistry", "global_faults"]
