"""hgfault — deterministic fault injection and the self-healing vocabulary.

The reference HyperGraphDB survives faults at every layer: transactional
MVCC storage, BDB checkpoint/replay, and P2P activities with explicit
failure FSM states. This package is the rebuild's equivalent spine, in
three parts:

- **errors** (:mod:`~hypergraphdb_tpu.fault.errors`): the typed fault
  vocabulary — :class:`TransientFault` (retry may help),
  :class:`PermanentFault` (it will not), :class:`InjectedCrash` (a
  simulated kill, deliberately a ``BaseException``), and the
  :func:`is_transient` classifier every retry ladder keys off;
- **registry** (:mod:`~hypergraphdb_tpu.fault.registry`): seeded,
  deterministic fault injection at named points
  (``serve.launch`` / ``serve.collect`` / ``peer.transport.send`` /
  ``ckpt.save_npz`` / ``ckpt.save_plans`` / ``tx.commit.pre`` /
  ``tx.commit.apply``) with per-point probability/count/index schedules.
  Zero-cost when disabled: one attribute read per site, nothing
  allocated — the ``Tracer.enabled`` discipline, regression-tested by an
  event-order differential with a poisoned ``check``;
- **breaker** (:mod:`~hypergraphdb_tpu.fault.breaker`): a per-key
  circuit breaker (closed → open → half-open probe → closed) the serving
  runtime uses to trip flaky device buckets onto the exact host-fallback
  path and recover automatically.

Wired consumers: ``serve/runtime.py`` (bounded deadline-aware retries +
breaker degradation), ``peer/`` (send retry, redelivery, resumable
snapshot transfer), ``ops/checkpoint.py`` (crash-atomic saves),
``tx/manager.py`` (the ingest crash drill). The chaos gate is
``tools/chaos.sh``; see README "Fault tolerance & degraded modes".
"""

from hypergraphdb_tpu.fault.breaker import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    STATE_CODES,
    CircuitBreaker,
)
from hypergraphdb_tpu.fault.errors import (
    DEFAULT_TRANSIENT,
    FaultError,
    InjectedCrash,
    PermanentFault,
    TransientFault,
    is_transient,
)
from hypergraphdb_tpu.fault.registry import FaultRegistry, global_faults

#: every fault point wired into the tree (name → where it fires) — the
#: README table and the crash-drill parameterization read this
WIRED_POINTS = {
    "serve.launch": "DeviceExecutor.launch, before any device work",
    "serve.collect": "DeviceExecutor.collect, before the result download",
    "peer.transport.send": "transport send (loopback + TCP): a fired "
                           "fault IS a dropped wire message",
    "peer.journal.save": "redelivery-journal save, after the tmp is "
                         "written, before os.replace publishes it",
    "ckpt.save_npz": "save_snapshot, after the tmp npz is written, "
                     "before os.replace publishes it",
    "ckpt.save_plans": "save_snapshot, after the tmp plans sidecar is "
                       "written, before os.replace publishes it",
    "tx.commit.pre": "HGTransactionManager.commit, top-level write "
                     "commit, before the commit lock",
    "tx.commit.apply": "HGTransactionManager.commit, inside the commit "
                       "lock, after conflict checks, before apply",
}

__all__ = [
    "CLOSED",
    "CircuitBreaker",
    "DEFAULT_TRANSIENT",
    "FaultError",
    "FaultRegistry",
    "HALF_OPEN",
    "InjectedCrash",
    "OPEN",
    "PermanentFault",
    "STATE_CODES",
    "TransientFault",
    "WIRED_POINTS",
    "global_faults",
    "is_transient",
]
