"""Peer transport SPI: pluggable message fabric between peers.

Re-expression of the reference's ``PeerInterface``
(``peer/PeerInterface.java:27``) — an async point-to-point message fabric
with presence — minus XMPP: the reference's only real transport is Smack
chat rooms (``peer/xmpp/XMPPPeerInterface.java:58``) and its tests need a
live XMPP server (SURVEY §4 calls this out as the gap to fix). Here:

- :class:`LoopbackNetwork` — in-process fabric; multi-peer tests run
  without any cluster or server (each peer still has its own graph).
- :class:`TCPPeerInterface` — newline-delimited JSON over TCP sockets for
  real multi-process/multi-host deployments (the DCN control plane of
  SURVEY §5; the device data plane is ``parallel/``).

Messages are JSON-serializable dicts. Delivery is async and at-most-once;
ordering is per-sender-pair (both transports preserve send order).

Fault story: both transports carry the ``peer.transport.send`` fault
point — a fired fault IS a dropped wire message (``send`` returns False,
nothing delivered), which is how the chaos tests model lossy networks
deterministically. The TCP transport additionally bounds every connect
and send with ``connect_timeout`` and retries a stale connection with
capped backoff; the layers above (replication retry/redelivery, transfer
resume) own end-to-end healing. ``metrics`` (an optional
``utils.metrics.Metrics``, wired to the graph's by ``HyperGraphPeer``)
records ``peer.transport_*`` counters.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
from typing import Callable, Optional

from hypergraphdb_tpu.fault import FaultError, global_faults

MessageHandler = Callable[[str, dict], None]  # (sender_id, message)

#: the process fault registry, bound once (module-global: the singleton
#: contract makes the enabled gate ONE attribute read per send)
_FAULTS = global_faults()


class PeerInterface:
    """Transport contract. Implementations deliver ``send()`` payloads to the
    target peer's registered handler on a receiver thread."""

    peer_id: str
    #: optional utils.metrics.Metrics surface (peer.transport_* counters);
    #: HyperGraphPeer.start() wires the graph's in
    metrics = None

    def start(self) -> None: ...
    def stop(self) -> None: ...

    def _dropped_by_fault(self, target: str, message: dict) -> bool:
        """Shared injection hook: True when the armed schedule ate this
        message (the wire dropped it)."""
        if not _FAULTS.enabled:
            return False
        try:
            _FAULTS.check(
                "peer.transport.send", target=target,
                performative=message.get("performative"),
                activity=message.get("activity_type"),
            )
        except FaultError:
            m = self.metrics
            if m is not None:
                m.incr("peer.transport_drops")
            return True
        return False

    def send(self, target: str, message: dict) -> bool:
        """Queue a message; False if the target is unknown/unreachable."""
        raise NotImplementedError

    def on_message(self, handler: MessageHandler) -> None:
        self._handler = handler

    def peers(self) -> list[str]:
        """Currently-present peer ids (roster/presence analogue)."""
        raise NotImplementedError


class LoopbackNetwork:
    """In-process message fabric: the test/loopback transport the reference
    lacks. Thread-safe; messages delivered on a single dispatcher thread per
    network (preserves global order, mimics a broker)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._peers: dict[str, "LoopbackPeerInterface"] = {}

    def interface(self, peer_id: str) -> "LoopbackPeerInterface":
        return LoopbackPeerInterface(self, peer_id)

    def _register(self, iface: "LoopbackPeerInterface") -> None:
        with self._lock:
            self._peers[iface.peer_id] = iface

    def _unregister(self, peer_id: str) -> None:
        with self._lock:
            self._peers.pop(peer_id, None)

    def _route(self, sender: str, target: str, message: dict) -> bool:
        with self._lock:
            iface = self._peers.get(target)
        if iface is None:
            return False
        iface._deliver(sender, message)
        return True

    def peer_ids(self) -> list[str]:
        with self._lock:
            return list(self._peers)


class LoopbackPeerInterface(PeerInterface):
    def __init__(self, network: LoopbackNetwork, peer_id: str):
        self.network = network
        self.peer_id = peer_id
        self._handler: Optional[MessageHandler] = None
        self._queue: list[tuple[str, dict]] = []
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._running = False

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self.network._register(self)
        self._thread = threading.Thread(
            target=self._pump, name=f"loopback-{self.peer_id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        self.network._unregister(self.peer_id)
        with self._cv:
            self._cv.notify_all()
        if self._thread:
            self._thread.join(timeout=5)

    def send(self, target: str, message: dict) -> bool:
        if self._dropped_by_fault(target, message):
            return False
        # serialize/deserialize to enforce the same wire constraints as TCP
        payload = json.loads(json.dumps(message))
        ok = self.network._route(self.peer_id, target, payload)
        m = self.metrics
        if m is not None:
            m.incr("peer.transport_sends" if ok
                   else "peer.transport_drops")
        return ok

    def peers(self) -> list[str]:
        return [p for p in self.network.peer_ids() if p != self.peer_id]

    def _deliver(self, sender: str, message: dict) -> None:
        with self._cv:
            self._queue.append((sender, message))
            self._cv.notify()

    def _pump(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._queue:
                    self._cv.wait(timeout=0.5)
                if not self._running and not self._queue:
                    return
                sender, msg = self._queue.pop(0)
            if self._handler is not None:
                try:
                    self._handler(sender, msg)
                except Exception:  # handler bugs must not kill the pump
                    import logging

                    logging.getLogger("hypergraphdb_tpu.peer").exception(
                        "message handler failed"
                    )


class _TCPHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        iface: "TCPPeerInterface" = self.server.iface  # type: ignore[attr-defined]
        for line in self.rfile:
            try:
                envelope = json.loads(line.decode("utf-8"))
                sender = envelope["from"]
                msg = envelope["msg"]
            except (ValueError, KeyError):
                continue
            if envelope.get("hello"):
                iface._learn(sender, tuple(envelope["addr"]))
            if msg is not None and iface._handler is not None:
                try:
                    iface._handler(sender, msg)
                except Exception:
                    import logging

                    logging.getLogger("hypergraphdb_tpu.peer").exception(
                        "message handler failed"
                    )


class _TCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class TCPPeerInterface(PeerInterface):
    """JSON-over-TCP transport: one listening socket per peer, one
    connection per outgoing peer (kept open, reconnected on failure)."""

    def __init__(self, peer_id: str, host: str = "127.0.0.1", port: int = 0,
                 connect_timeout: float = 5.0, send_attempts: int = 2,
                 retry_backoff_s: float = 0.05,
                 retry_backoff_max_s: float = 0.5):
        self.peer_id = peer_id
        #: bounds BOTH the connect and every subsequent sendall (the
        #: timeout sticks to the socket): a hung peer costs a bounded
        #: wait, never a wedged sender thread
        self.connect_timeout = float(connect_timeout)
        self.send_attempts = max(1, int(send_attempts))
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_max_s = float(retry_backoff_max_s)
        self._handler: Optional[MessageHandler] = None
        self._server = _TCPServer((host, port), _TCPHandler)
        self._server.iface = self  # type: ignore[attr-defined]
        self.addr: tuple[str, int] = self._server.server_address  # bound
        self._known: dict[str, tuple[str, int]] = {}
        self._conns: dict[str, socket.socket] = {}
        self._lock = threading.Lock()
        # one lock per target: sendall must not interleave two threads'
        # newline-framed messages on the same socket
        self._send_locks: dict[str, threading.Lock] = {}
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        # check-and-set under the lock: two concurrent start() calls would
        # otherwise both pass the None check and spawn two serve loops
        with self._lock:
            if self._thread:
                return
            self._thread = t = threading.Thread(
                target=self._server.serve_forever,
                name=f"tcp-{self.peer_id}", daemon=True,
            )
        t.start()

    def stop(self) -> None:
        self._server.shutdown()
        with self._lock:
            for s in self._conns.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._conns.clear()
        self._server.server_close()

    def connect(self, peer_id: str, addr: tuple[str, int]) -> None:
        """Bootstrap: learn another peer's address and say hello (so it
        learns ours — the identity handshake)."""
        self._learn(peer_id, addr)
        self._write(peer_id, {"from": self.peer_id, "msg": None,
                              "hello": True, "addr": list(self.addr)})

    def _learn(self, peer_id: str, addr: tuple[str, int]) -> None:
        with self._lock:
            self._known[peer_id] = addr

    def send(self, target: str, message: dict) -> bool:
        if self._dropped_by_fault(target, message):
            return False
        return self._write(target, {"from": self.peer_id, "msg": message})

    def _write(self, target: str, envelope: dict) -> bool:
        with self._lock:
            addr = self._known.get(target)
            send_lock = self._send_locks.setdefault(target, threading.Lock())
        if addr is None:
            return False
        data = (json.dumps(envelope) + "\n").encode("utf-8")
        m = self.metrics
        with send_lock:
            # reconnect-with-backoff on stale/refused connections; every
            # attempt's connect AND send are bounded by connect_timeout
            for attempt in range(self.send_attempts):
                if attempt:
                    if m is not None:
                        m.incr("peer.transport_reconnects")
                    time.sleep(min(
                        self.retry_backoff_s * (2.0 ** (attempt - 1)),
                        self.retry_backoff_max_s,
                    ))
                with self._lock:
                    conn = self._conns.get(target)
                try:
                    if conn is None:
                        conn = socket.create_connection(
                            addr, timeout=self.connect_timeout
                        )
                        with self._lock:
                            self._conns[target] = conn
                    conn.sendall(data)
                    if m is not None:
                        m.incr("peer.transport_sends")
                    return True
                except OSError:
                    with self._lock:
                        self._conns.pop(target, None)
                    conn = None
        if m is not None:
            m.incr("peer.transport_drops")
        return False

    def peers(self) -> list[str]:
        with self._lock:
            return list(self._known)
