"""Atom (sub)graph transfer: serialize atom closures between peers.

Re-expression of ``SubgraphManager`` (``peer/SubgraphManager.java:57``) —
atoms travel as (type name, value bytes, target refs) records and are
written through on the receiving side. Identity: local handles are dense
per-graph ints (not the reference's global UUIDs), so every transferred
atom carries a **global id** ``origin_peer:origin_handle``; each peer keeps
a persistent ``hg.peer.atommap`` index translating global ids to local
handles (created on first sight, updated on replace)."""

from __future__ import annotations

import base64
from typing import Optional

IDX_ATOM_MAP = "hg.peer.atommap"


def global_id(origin_peer: str, origin_handle: int) -> str:
    return f"{origin_peer}:{int(origin_handle)}"


def existing_gid(graph, h: int):
    """The atom's global id IF it ever crossed the replication boundary,
    else None — a pure lookup. Removal paths must use this: minting a
    fresh gid for a never-replicated atom would announce the death of an
    identity no peer has ever heard of AND permanently pollute the atom
    map with an entry for a now-gone handle (ADVICE r2)."""
    keys = _atom_map(graph).find_by_value(int(h))
    return keys[0].decode("utf-8") if keys else None


def gid_of(graph, h: int, origin_peer: str) -> str:
    """The atom's global id. Atoms that arrived FROM another peer (or were
    exported before) already have a mapping in the atom map — reuse it, so
    a replicated atom keeps ONE identity everywhere instead of being
    re-minted (and duplicated) on push-back. Fresh local atoms are assigned
    ``origin_peer:handle`` and recorded for the same reason.

    A handle→gid memo rides on the graph: a gid never changes once
    assigned and handles are never reused, so positive results cache
    forever (the push worker calls this for every target of every
    mutation — an index lookup each was the hottest line in the profile)."""
    h = int(h)
    cache = getattr(graph, "_gid_cache", None)
    if cache is None:
        cache = graph._gid_cache = {}
    hit = cache.get(h)
    if hit is not None:
        return hit
    cur = graph.txman.current()
    keys = _atom_map(graph).find_by_value(h)
    if keys:
        gid = keys[0].decode("utf-8")
        if cur is None:
            cache[h] = gid
        else:
            # find_by_value merges the tx OVERLAY: this gid may only be
            # STAGED (e.g. minted earlier in this very tx) — caching now
            # would poison the forever-cache if the tx aborts/conflicts
            cur.on_commit.append(lambda: cache.__setitem__(h, gid))
        return gid
    gid = global_id(origin_peer, h)
    graph.txman.ensure_transaction(
        lambda: _atom_map(graph).add_entry(gid.encode("utf-8"), h)
    )
    if cur is not None:
        cur.on_commit.append(lambda: cache.__setitem__(h, gid))
    else:
        cache[h] = gid  # ensure_transaction committed before returning
    return gid


# -- type schemas over the wire (SyncTypes, ref peer/cact/SyncTypes.java) -----


def describe_type(graph, name: str) -> Optional[dict]:
    """Wire schema of a registered type: record types travel with their
    full shape (fields, declared supertypes) so a peer WITHOUT the
    defining dataclass can still install, store, query and index atoms of
    the type; everything else is named only (builtins exist everywhere)."""
    from hypergraphdb_tpu.types.record import RecordType

    ts = graph.typesystem
    t = ts._by_name.get(name)
    if t is None:
        return None
    if isinstance(t, RecordType):
        return {
            "schema": "record",
            "name": name,
            "fields": list(t.fields),
            "supertype_names": list(t.supertype_names),
            "supertypes": sorted(ts._supertypes.get(name, ())),
        }
    return {"schema": "builtin", "name": name}


def install_type(graph, desc: dict) -> int:
    """Install a remote type schema locally (the receiving half of
    SyncTypes): record schemas register a class-less :class:`RecordType`
    (values revive as field dicts — the reference degrades the same way
    when the Java class is off the classpath); builtin names must already
    exist. Idempotent; returns the local type-atom handle."""
    from hypergraphdb_tpu.core.errors import TypeError_
    from hypergraphdb_tpu.types.record import RecordType

    ts = graph.typesystem
    name = desc["name"]
    if name in ts._by_name:
        return int(ts.handle_of(name))
    if desc.get("schema") != "record":
        raise TypeError_(
            f"cannot install remote type {name!r}: schema "
            f"{desc.get('schema')!r} has no local implementation"
        )
    rt = RecordType(
        name, None,
        tuple(desc.get("fields", ())),
        tuple(desc.get("supertype_names", ())),
    )
    return int(ts.register(rt, supertypes=tuple(desc.get("supertypes", ()))))


def serialize_atom(graph, h: int, origin_peer: str) -> dict:
    """One atom → wire dict; the atom and its targets are referenced by
    their global ids (existing mappings reused, see ``gid_of``). Record
    types ride along as schemas; type ATOMS are flagged so receivers map
    them onto their own type atoms instead of duplicating them."""
    h = int(h)
    rec = graph.store.get_link(h)
    if rec is None:
        raise KeyError(h)
    type_handle, value_handle, flags = rec[0], rec[1], rec[2]
    targets = rec[3:]
    data = graph.store.get_data(value_handle) if value_handle >= 0 else None
    ts = graph.typesystem
    type_name = ts.name_of(type_handle)
    wire = {
        "gid": gid_of(graph, h, origin_peer),
        "type": type_name,
        "value_b64": (
            base64.b64encode(data).decode("ascii") if data is not None else None
        ),
        "is_link": bool(flags & 1),
        "targets": [gid_of(graph, t, origin_peer) for t in targets],
    }
    schema = describe_type(graph, type_name)
    if schema is not None and schema["schema"] != "builtin":
        wire["type_schema"] = schema
    named = ts._type_atom_name(h)
    if named is not None:
        wire["is_type_atom"] = True
        atom_schema = describe_type(graph, named)
        if atom_schema is not None:
            wire["atom_schema"] = atom_schema
    return wire


def serialize_closure(graph, h: int, origin_peer: str) -> list[dict]:
    """The atom plus its transitive target closure, dependencies first."""
    out: list[dict] = []
    seen: set[int] = set()

    def visit(x: int) -> None:
        x = int(x)
        if x in seen:
            return
        seen.add(x)
        rec = graph.store.get_link(x)
        if rec is None:
            return
        for t in rec[3:]:
            visit(t)
        out.append(serialize_atom(graph, x, origin_peer))

    visit(h)
    return out


def _atom_map(graph):
    return graph.store.get_index(IDX_ATOM_MAP)


def lookup_local(graph, gid: str) -> Optional[int]:
    return _atom_map(graph).find_first(gid.encode("utf-8"))


def store_atom(graph, wire: dict) -> int:
    """Write one transferred atom (write-through, ``HGStore.attachOverlayGraph``
    analogue): create or replace the local twin of ``wire['gid']``.
    Targets must already be mapped (send closures dependencies-first).

    Type handling (SyncTypes semantics): a transferred TYPE ATOM maps onto
    the receiver's own type atom for that name (never duplicated — links
    targeting it, e.g. Subsumes, land on the local type atom); an atom
    whose record type is unknown locally installs the schema shipped in
    ``type_schema`` first."""
    from hypergraphdb_tpu.core.errors import TypeError_

    gid = wire["gid"]
    ts = graph.typesystem
    if wire.get("is_type_atom"):
        name = (
            ts.top.make(base64.b64decode(wire["value_b64"]))
            if wire.get("value_b64") is not None else None
        )
        if name is None:
            raise TypeError_(f"type atom {gid} carries no name")
        if wire.get("atom_schema") is not None:
            local_t = install_type(graph, wire["atom_schema"])
        elif name in ts._by_name:
            local_t = int(ts.handle_of(name))
        else:
            raise TypeError_(
                f"transferred type atom {name!r} has no local "
                "implementation and no wire schema"
            )
        prev = lookup_local(graph, gid)
        if prev is None:
            graph.txman.ensure_transaction(
                lambda: _atom_map(graph).add_entry(
                    gid.encode("utf-8"), local_t
                )
            )
        return local_t
    if wire["type"] not in ts._by_name and wire.get("type_schema") is not None:
        install_type(graph, wire["type_schema"])
    atype = graph.typesystem.get_type(wire["type"])
    value = (
        atype.make(base64.b64decode(wire["value_b64"]))
        if wire["value_b64"] is not None
        else None
    )
    targets = []
    for tg in wire["targets"]:
        lt = lookup_local(graph, tg)
        if lt is None:
            raise KeyError(f"unmapped target {tg}")
        targets.append(int(lt))

    local = lookup_local(graph, gid)
    if local is not None:
        if graph.contains(local):
            # explicit type: a dict-revived record value must not be
            # re-inferred as 'dict' (review r5 finding 1)
            graph.replace(local, value, type=wire["type"])
            return int(local)
        _atom_map(graph).remove_entry(gid.encode("utf-8"), local)
    if wire["is_link"]:
        h = graph.add_link(targets, value=value, type=wire["type"])
    else:
        h = graph.add_node(value, type=wire["type"])
    _atom_map(graph).add_entry(gid.encode("utf-8"), int(h))
    return int(h)


def store_closure(graph, atoms: list[dict]) -> list[int]:
    return [store_atom(graph, w) for w in atoms]


def content_digest(graph) -> str:
    """Order-insensitive digest of the graph's REPLICATED content: every
    LIVE atom with a global id hashes as (gid, type name, value bytes,
    sorted target gids), and the per-atom hashes combine by modular sum —
    so local handle assignment, atom-map iteration order, and the path an
    atom took here (push vs catch-up vs snapshot transfer) cannot change
    the digest. Two peers whose digests match hold identical replicated
    universes; the differential convergence tests and the chaos soaks
    assert exactly this (atoms that never crossed the replication
    boundary have no gid and are deliberately outside the digest)."""
    import hashlib

    idx = _atom_map(graph)
    gid_of_handle: dict[int, str] = {}
    pairs: list[tuple[str, int]] = []
    for key, hs in idx.bulk_items():
        gid = key.decode("utf-8")
        for h in hs.tolist():
            gid_of_handle[int(h)] = gid
            pairs.append((gid, int(h)))
    total = 0
    for gid, h in pairs:
        if not graph.contains(h):
            continue  # tombstoned twin: both sides skip it
        rec = graph.store.get_link(h)
        if rec is None:
            continue
        value_handle = rec[1]
        data = (graph.store.get_data(value_handle)
                if value_handle >= 0 else None)
        tgids = sorted(
            gid_of_handle.get(int(t), str(int(t))) for t in rec[3:]
        )
        hh = hashlib.sha256()
        hh.update(gid.encode("utf-8"))
        hh.update(b"\x00")
        hh.update(graph.typesystem.name_of(rec[0]).encode("utf-8"))
        hh.update(b"\x00")
        hh.update(data if data is not None else b"\xff")
        hh.update(b"\x00")
        hh.update("|".join(tgids).encode("utf-8"))
        total = (total + int.from_bytes(hh.digest()[:16], "big")) % (1 << 128)
    return f"{total:032x}"
