"""CACT — cluster activities: remote graph operations.

Re-expression of the reference's ``peer/cact/`` package: ``AddAtom``,
``GetAtom``, ``RemoveAtom``, ``ReplaceAtom``, ``GetIncidenceSet``,
``QueryCount``, ``RunRemoteQuery`` and the cursor-streaming
``RemoteQueryExecution`` (``peer/cact/RemoteQueryExecution.java:34``: the
server compiles+runs the query locally, holds the result open, and the
client pages through it over the wire).

Each op is a two-sided FSM activity over the performative protocol:
client sends REQUEST with op payload; server replies INFORM (result) or
FAILURE. RemoteQuery adds a paging loop (QUERY_REF → INFORM chunks →
CANCEL/complete)."""

from __future__ import annotations

from typing import Any, Optional

from hypergraphdb_tpu.peer import messages as M
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.activity import Activity, STARTED, from_state
from hypergraphdb_tpu.query import serialize as qser


# --------------------------------------------------------------- client side


class RemoteOpClient(Activity):
    """Generic request/response client activity. Traced: each op roots a
    ``peer.op`` trace whose context rides the REQUEST, so the server's
    ``op_serve`` span joins the same tree (remote-child parenting) — the
    ``RemoteGraphView`` window and every ``HyperGraphPeer.*_remote`` call
    get cross-process attribution for free."""

    TYPE = "cact"

    def __init__(self, peer, target: Optional[str] = None, op: Optional[dict] = None,
                 activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.target = target
        self.op = op or {}
        self._trace = None

    def initiate(self) -> None:
        tracer = self.peer.tracer
        ctx = None
        if tracer.enabled:
            self._trace = tr = tracer.start_trace(
                "peer.op", op=str(self.op.get("op")), target=self.target,
            )
            if tr is not None:
                tr.marks["root"] = tr.start_span(
                    "op", op=str(self.op.get("op")))
                ctx = tr.context()
        self.send(self.target, M.REQUEST, self.op, trace_ctx=ctx)

    @from_state(STARTED, M.INFORM)
    def on_result(self, sender: str, msg: dict) -> None:
        if self._trace is not None:
            self._trace.finish_terminal("resolve")
        self.complete(msg["content"])

    @from_state(STARTED, M.FAILURE)
    def on_failure(self, sender: str, msg: dict) -> None:
        if self._trace is not None:
            self._trace.finish_terminal("error", error="RemoteFailure")
        self.fail(RuntimeError(str(msg["content"])))


class RemoteOpServer(Activity):
    """Generic server: executes the op against the local graph."""

    TYPE = "cact"

    OPS = {}

    @from_state(STARTED, M.REQUEST)
    def on_request(self, sender: str, msg: dict) -> None:
        op = msg["content"] or {}
        tracer = self.peer.tracer
        tr = (tracer.start_remote_trace("peer.op.serve",
                                        M.trace_context(msg), peer=sender)
              if tracer.enabled else None)
        if tr is not None:
            tr.marks["root"] = tr.start_span("op_serve",
                                             op=str(op.get("op")))
        handler = self.OPS.get(op.get("op"))
        if handler is None:
            self.reply(sender, msg, M.FAILURE, f"unknown op {op.get('op')}")
            if tr is not None:
                tr.finish_terminal("error", error="UnknownOp")
            self.fail(f"unknown op {op.get('op')}")
            return
        try:
            result = handler(self, op)
        except Exception as e:
            self.reply(sender, msg, M.FAILURE, f"{type(e).__name__}: {e}")
            if tr is not None:
                tr.finish_error(e)
            self.fail(e)
            return
        self.reply(sender, msg, M.INFORM, result)
        if tr is not None:
            tr.finish_terminal("served")
        self.complete(result)

    # -- op handlers (the cact/ class-per-op set) -------------------------

    def _op_define_atom(self, op: dict) -> Any:
        """AddAtom/DefineAtom: store a transferred closure locally."""
        handles = transfer.store_closure(self.peer.graph, op["atoms"])
        return {"handles": handles}

    def _op_get_atom(self, op: dict) -> Any:
        g = self.peer.graph
        gid = op.get("gid")
        h = transfer.lookup_local(g, gid) if gid else op.get("handle")
        if h is None or not g.contains(int(h)):
            raise KeyError(f"atom not found: {gid or op.get('handle')}")
        return {"atoms": transfer.serialize_closure(g, int(h), self.peer.identity)}

    def _op_remove_atom(self, op: dict) -> Any:
        g = self.peer.graph
        gid = op.get("gid")
        h = transfer.lookup_local(g, gid) if gid else op.get("handle")
        ok = bool(h is not None and g.remove(int(h)))
        return {"removed": ok}

    def _op_get_incidence_set(self, op: dict) -> Any:
        g = self.peer.graph
        h = int(op["handle"])
        return {"incidence": g.get_incidence_set(h).array().tolist()}

    def _op_query_count(self, op: dict) -> Any:
        cond = qser.from_json(op["condition"])
        return {"count": self.peer.graph.count(cond)}

    def _op_run_query(self, op: dict) -> Any:
        """One-shot remote query: compile + run + return all handles.
        (Streaming variant: RemoteQueryServer below.)"""
        cond = qser.from_json(op["condition"])
        return {"handles": [int(h) for h in self.peer.graph.find_all(cond)]}

    def _op_replace_atom(self, op: dict) -> Any:
        """ReplaceAtom (ref ``peer/cact/ReplaceAtom.java``): replace the
        VALUE of the atom behind a global id, keeping identity/incidence."""
        import base64

        g = self.peer.graph
        h = transfer.lookup_local(g, op["gid"])
        if h is None or not g.contains(int(h)):
            return {"replaced": False}
        if op["type"] not in g.typesystem._by_name and op.get("type_schema"):
            transfer.install_type(g, op["type_schema"])
        atype = g.typesystem.get_type(op["type"])
        value = (
            atype.make(base64.b64decode(op["value_b64"]))
            if op.get("value_b64") is not None else None
        )
        # type passed EXPLICITLY: a class-less RecordType revives the value
        # as a dict, which inference would silently retype to 'dict',
        # unindexing the atom from its real type (review r5 finding 1)
        g.replace(int(h), value, type=op["type"])
        return {"replaced": True}

    def _op_get_atom_type(self, op: dict) -> Any:
        """GetAtomType (ref ``peer/cact/GetAtomType.java``): the type name
        + wire schema of a remote atom, keyed by global id."""
        g = self.peer.graph
        h = transfer.lookup_local(g, op["gid"])
        if h is None or not g.contains(int(h)):
            raise KeyError(f"atom not found: {op['gid']}")
        rec = g.store.get_link(int(h))
        name = g.typesystem.name_of(rec[0])
        return {"type": name, "schema": transfer.describe_type(g, name)}

    def _op_add_atom(self, op: dict) -> Any:
        """Create an atom on THIS peer from a wire value + target global
        ids (the ``PeerHyperNode.add`` server half): targets resolve
        through the atom map; returns the new atom's global id."""
        import base64

        g = self.peer.graph
        if op["type"] not in g.typesystem._by_name and op.get("type_schema"):
            transfer.install_type(g, op["type_schema"])
        atype = g.typesystem.get_type(op["type"])
        value = (
            atype.make(base64.b64decode(op["value_b64"]))
            if op.get("value_b64") is not None else None
        )
        tg = []
        for gid in op.get("targets", ()):
            h = transfer.lookup_local(g, gid)
            if h is None:
                raise KeyError(f"unmapped target {gid}")
            tg.append(int(h))
        if tg:
            h = g.add_link(tg, value=value, type=op["type"])
        else:
            h = g.add_node(value, type=op["type"])
        return {"gid": transfer.gid_of(g, int(h), self.peer.identity)}

    def _op_peek_atom(self, op: dict) -> Any:
        """One serialized atom, WITHOUT the closure — the read half of the
        remote view (the caller is a window, not a replica)."""
        g = self.peer.graph
        h = transfer.lookup_local(g, op["gid"])
        if h is None or not g.contains(int(h)):
            raise KeyError(f"atom not found: {op['gid']}")
        return {"atom": transfer.serialize_atom(g, int(h), self.peer.identity)}

    def _op_sync_types(self, op: dict) -> Any:
        """SyncTypes (ref ``peer/cact/SyncTypes.java``): install a batch of
        remote type schemas so subsequently pushed/pulled atoms of those
        types resolve locally instead of depending on name-keyed luck."""
        g = self.peer.graph
        installed = []
        for desc in op.get("types", ()):
            transfer.install_type(g, desc)
            installed.append(desc["name"])
        return {"installed": installed}


RemoteOpServer.OPS = {
    "define_atom": RemoteOpServer._op_define_atom,
    "get_atom": RemoteOpServer._op_get_atom,
    "add_atom": RemoteOpServer._op_add_atom,
    "peek_atom": RemoteOpServer._op_peek_atom,
    "remove_atom": RemoteOpServer._op_remove_atom,
    "replace_atom": RemoteOpServer._op_replace_atom,
    "get_atom_type": RemoteOpServer._op_get_atom_type,
    "sync_types": RemoteOpServer._op_sync_types,
    "get_incidence_set": RemoteOpServer._op_get_incidence_set,
    "query_count": RemoteOpServer._op_query_count,
    "run_query": RemoteOpServer._op_run_query,
}


# ------------------------------------------------------- streaming remote query


class RemoteQueryClient(Activity):
    """Cursor-paging remote query (RemoteQueryExecution): QUERY_REF opens a
    server-held result; INFORM chunks stream back; the final chunk (eof)
    completes with the full handle list."""

    TYPE = "cact-query"

    def __init__(self, peer, target: Optional[str] = None,
                 condition=None, page: int = 64,
                 activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.target = target
        self.condition = condition
        self.page = page
        self.rows: list[int] = []

    def initiate(self) -> None:
        self.send(self.target, M.QUERY_REF, {
            "condition": qser.to_json(self.condition),
            "page": self.page,
        })

    @from_state(STARTED, M.INFORM)
    def on_chunk(self, sender: str, msg: dict) -> None:
        c = msg["content"]
        self.rows.extend(c["rows"])
        if c["eof"]:
            self.complete(self.rows)
        else:
            self.reply(sender, msg, M.CONFIRM)  # pull next page

    @from_state(STARTED, M.FAILURE)
    def on_failure(self, sender: str, msg: dict) -> None:
        self.fail(RuntimeError(str(msg["content"])))


class RemoteQueryServer(Activity):
    """Server side: executes once, then streams pages on CONFIRM pulls —
    the server-held open-result-set state (``state=ResultSetOpen``)."""

    TYPE = "cact-query"

    def __init__(self, peer, activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.results: Optional[list[int]] = None
        self.pos = 0
        self.page = 64

    @from_state(STARTED, M.QUERY_REF)
    def on_open(self, sender: str, msg: dict) -> None:
        content = msg["content"]
        try:
            cond = qser.from_json(content["condition"])
            self.page = int(content.get("page", 64))
            self.results = [int(h) for h in self.peer.graph.find_all(cond)]
        except Exception as e:
            self.reply(sender, msg, M.FAILURE, f"{type(e).__name__}: {e}")
            self.fail(e)
            return
        self.state = "ResultSetOpen"
        self._send_page(sender, msg)

    @from_state("ResultSetOpen", M.CONFIRM)
    def on_pull(self, sender: str, msg: dict) -> None:
        self._send_page(sender, msg)

    @from_state("ResultSetOpen", M.CANCEL)
    def on_cancel(self, sender: str, msg: dict) -> None:
        self.complete(None)

    def _send_page(self, sender: str, msg: dict) -> None:
        rows = self.results[self.pos : self.pos + self.page]
        self.pos += len(rows)
        eof = self.pos >= len(self.results)
        self.reply(sender, msg, M.INFORM, {"rows": rows, "eof": eof})
        if eof:
            self.complete(len(self.results))


# ------------------------------------------------------- whole-graph bootstrap


class TransferGraphClient(Activity):
    """Whole-graph bootstrap (ref ``peer/cact/TransferGraph.java`` +
    ``SubgraphManager.java:57``): a joining peer pulls the ENTIRE remote
    graph in pages of serialized atoms — dependencies first, type atoms
    mapped onto local type atoms, record-type schemas installed on the fly.
    On completion the replication clock for the server jumps to the
    server's op-log head AT SNAPSHOT TIME, so a follow-up catch-up replays
    only what committed during/after the transfer — the convergence story
    for a peer whose incremental catch-up fell past the log floor.

    Self-healing (hgfault): pages are POSITION-addressed — every pull
    carries the client's next wanted position and every chunk echoes the
    position it starts at, so a dropped or duplicated chunk is detected
    and idempotently re-requested instead of corrupting the stream. The
    :meth:`tick` watchdog (driven by the ActivityManager's ticker) resumes
    a stalled transfer: re-pull the current page, or re-open the whole
    conversation when the opening exchange itself was eaten."""

    TYPE = "cact-transfer"

    def __init__(self, peer, target: Optional[str] = None, page: int = 256,
                 activity_id: Optional[str] = None,
                 retry_after_s: float = 1.0, max_resumes: int = 8):
        super().__init__(peer, activity_id)
        self.target = target
        self.page = page
        self.stored = 0
        self.log_head: Optional[int] = None
        self.expected = 0            # next page START we will apply
        self._snap: Optional[str] = None  # the server snapshot token
        self.retry_after_s = float(retry_after_s)
        self.max_resumes = int(max_resumes)
        self._resumes = 0
        self._last_rx = 0.0
        self._trace = None
        self._tctx: Optional[dict] = None

    def initiate(self) -> None:
        import time as _time

        self._last_rx = _time.monotonic()
        # the whole transfer is ONE cross-process trace: every client
        # send carries the context (resumes may reach a FRESH server
        # activity — it must still join the same tree)
        tracer = self.peer.tracer
        if tracer.enabled:
            self._trace = tr = tracer.start_trace(
                "peer.transfer", target=self.target, page=self.page,
            )
            if tr is not None:
                tr.marks["root"] = tr.start_span("transfer",
                                                 target=self.target)
                self._tctx = tr.context()
        self.send(self.target, M.QUERY_REF,
                  {"page": self.page, "pos": 0}, trace_ctx=self._tctx)

    @from_state(STARTED, M.INFORM)
    def on_chunk(self, sender: str, msg: dict) -> None:
        import time as _time

        self._last_rx = _time.monotonic()
        c = msg["content"]
        tok = c.get("snap")
        if self._snap is not None and tok != self._snap:
            # the server re-snapshotted (fresh activity after a lost eof
            # or a restart): positions from the old snapshot are NOT
            # comparable — removals shift every later index, so resuming
            # mid-stream could silently skip atoms. Restart from 0: the
            # gid-keyed write-through makes the re-apply idempotent, and
            # the new snapshot's log_head re-anchors catch-up.
            self._snap = tok
            self.log_head = int(c.get("log_head", 0))
            self.expected = 0
            if int(c.get("pos", -1)) != 0:
                self.reply(sender, msg, M.CONFIRM, {"pos": 0},
                           trace_ctx=self._tctx)
                return
        elif self._snap is None:
            self._snap = tok
        if self.log_head is None:
            self.log_head = int(c.get("log_head", 0))
        pos = int(c.get("pos", self.expected))
        if pos != self.expected:
            # duplicated/stale chunk (a redelivered page we already
            # applied, or one past a gap): applying would double-store or
            # skip — idempotently re-request OUR position instead
            self.reply(sender, msg, M.CONFIRM, {"pos": self.expected},
                       trace_ctx=self._tctx)
            return
        # the peer's apply mutex: replication pushes arriving WHILE the
        # transfer streams (a bootstrapping replica with its interest
        # already published) must not race a chunk's store of the same
        # gid — store_closure's check-then-act is idempotent only when
        # serialized
        with self.peer.apply_lock:
            n_applied = len(
                transfer.store_closure(self.peer.graph, c["atoms"])
            )
        self.stored += n_applied
        tr = self._trace
        if tr is not None:
            tr.start_span("apply_chunk", parent=tr.marks.get("root"),
                          pos=pos, atoms=n_applied).end()
        self.expected = int(c.get("next", self.expected))
        self._resumes = 0  # progress: the resume budget is PER STALL —
        # a long transfer over a mildly lossy link must not exhaust a
        # cumulative budget while every individual resume succeeds
        if c["eof"]:
            rep = getattr(self.peer, "replication", None)
            if rep is not None and self.log_head:
                # the transferred snapshot covers everything up to the
                # server's head at open; catch-up resumes from there
                if self.log_head > rep.last_seen.get(sender, 0):
                    rep.last_seen.set(sender, self.log_head)
                if self.log_head > rep.peer_heads.get(sender, 0):
                    rep.peer_heads[sender] = self.log_head
                rep.needs_full_sync.discard(sender)
            if tr is not None:
                tr.finish_terminal("resolve", stored=self.stored)
            self.complete(self.stored)
        else:
            self.reply(sender, msg, M.CONFIRM, {"pos": self.expected},
                       trace_ctx=self._tctx)

    @from_state(STARTED, M.FAILURE)
    def on_failure(self, sender: str, msg: dict) -> None:
        if self._trace is not None:
            self._trace.finish_terminal("error", error="RemoteFailure")
        self.fail(RuntimeError(str(msg["content"])))

    def tick(self, now: Optional[float] = None) -> bool:
        """Stall watchdog (ActivityManager ticker / tests call directly):
        when no chunk has arrived for ``retry_after_s``, re-request the
        current position — bounded by ``max_resumes`` consecutive
        no-progress resumes (the counter resets on every applied chunk),
        after which the transfer fails typed (``TransientFault``) instead
        of hanging the caller's future forever. Returns whether a resume
        was sent."""
        import time as _time

        from hypergraphdb_tpu.fault import TransientFault

        with self._handle_lock:
            if self.state != STARTED:
                return False
            if now is None:
                now = _time.monotonic()
            if now - self._last_rx < self.retry_after_s:
                return False
            self._resumes += 1
            if self._resumes > self.max_resumes:
                exc = TransientFault(
                    f"graph transfer from {self.target} stalled after "
                    f"{self.max_resumes} resume attempts"
                )
                if self._trace is not None:
                    self._trace.finish_error(exc)
                self.fail(exc)
                return False
            self._last_rx = now
            self.peer.graph.metrics.incr("peer.transfer_resumes")
            tr = self._trace
            if tr is not None:
                tr.start_span("resume", parent=tr.marks.get("root"),
                              pos=self.expected, attempt=self._resumes
                              ).end()
            if self.log_head is None and self.expected == 0:
                # nothing ever arrived: the opening exchange itself was
                # eaten — re-open (the server side re-opens idempotently)
                self.send(self.target, M.QUERY_REF,
                          {"page": self.page, "pos": 0},
                          trace_ctx=self._tctx)
            else:
                self.send(self.target, M.CONFIRM, {"pos": self.expected},
                          trace_ctx=self._tctx)
            return True


class TransferGraphServer(Activity):
    """Server side: snapshots the atom id list ONCE (ascending handle order
    IS dependencies-first — links are created after their targets), then
    streams serialized pages on position-addressed CONFIRM pulls (a pull
    may rewind ``pos`` — that is exactly what a client resuming past a
    dropped chunk does)."""

    TYPE = "cact-transfer"

    def __init__(self, peer, activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.handles: Optional[list[int]] = None
        self.pos = 0
        self.page = 256
        self.log_head = 0
        self.snap_token: Optional[str] = None
        self._trace = None

    def _adopt_trace(self, msg: dict) -> None:
        """Join the client's transfer trace (remote-child): the serve
        subtree hangs under the client's ``transfer`` span. A fresh
        server reached by a resume adopts the same context — one tree
        per logical transfer, however many server activities it took."""
        if self._trace is not None:
            return
        tracer = self.peer.tracer
        if not tracer.enabled:
            return
        tr = tracer.start_remote_trace("peer.transfer.serve",
                                       M.trace_context(msg))
        if tr is not None:
            tr.marks["root"] = tr.start_span("transfer_serve")
            self._trace = tr

    def _snapshot(self) -> None:
        import uuid

        rep = getattr(self.peer, "replication", None)
        # head BEFORE the snapshot: anything later re-ships via catch-up
        self.log_head = rep.log.head if rep is not None else 0
        self.handles = sorted(int(h) for h in self.peer.graph.atoms())
        # snapshot identity: positions are only comparable WITHIN one
        # handle-list snapshot — a re-snapshot (fresh server after a lost
        # eof / restart) may have shifted positions past removals, so
        # chunks carry the token and the client restarts on a change
        self.snap_token = uuid.uuid4().hex

    @from_state(STARTED, M.QUERY_REF)
    def on_open(self, sender: str, msg: dict) -> None:
        c = msg["content"] or {}
        self._adopt_trace(msg)
        try:
            self.page = max(1, int(c.get("page", 256)))
            self._snapshot()
        except Exception as e:
            self.reply(sender, msg, M.FAILURE, f"{type(e).__name__}: {e}")
            if self._trace is not None:
                self._trace.finish_error(e)
            self.fail(e)
            return
        self.state = "Streaming"
        self._send_page(sender, msg, pos=int(c.get("pos", 0)))

    @from_state("Streaming", M.QUERY_REF)
    def on_reopen(self, sender: str, msg: dict) -> None:
        # the client's opening chunk(s) were lost and it re-opened: serve
        # from its requested position over the SAME snapshot (idempotent)
        c = msg["content"] or {}
        self._send_page(sender, msg, pos=int(c.get("pos", 0)))

    @from_state(STARTED, M.CONFIRM)
    def on_resume_fresh(self, sender: str, msg: dict) -> None:
        """A pull for a conversation this side no longer holds (the
        server completed on an eof chunk the client never saw, or
        restarted mid-transfer): re-snapshot and serve from the requested
        position. The fresh ``snap`` token on every chunk tells the
        client positions changed meaning — it restarts from 0
        (idempotent) rather than trusting indices a removal may have
        shifted."""
        c = msg["content"] or {}
        self._adopt_trace(msg)
        try:
            self._snapshot()
        except Exception as e:
            self.reply(sender, msg, M.FAILURE, f"{type(e).__name__}: {e}")
            if self._trace is not None:
                self._trace.finish_error(e)
            self.fail(e)
            return
        self.state = "Streaming"
        self._send_page(sender, msg, pos=int(c.get("pos", 0)))

    @from_state("Streaming", M.CONFIRM)
    def on_pull(self, sender: str, msg: dict) -> None:
        self._send_page(sender, msg,
                        pos=(msg["content"] or {}).get("pos"))

    @from_state("Streaming", M.CANCEL)
    def on_cancel(self, sender: str, msg: dict) -> None:
        if self._trace is not None:
            self._trace.finish_terminal("cancelled")
        self.complete(None)

    def _send_page(self, sender: str, msg: dict, pos=None) -> None:
        g = self.peer.graph
        if pos is not None:
            self.pos = max(0, min(int(pos), len(self.handles)))
        start = self.pos
        atoms = []
        while self.pos < len(self.handles) and len(atoms) < self.page:
            h = self.handles[self.pos]
            self.pos += 1
            if not g.contains(h):
                continue  # removed mid-transfer; catch-up replays the remove
            try:
                atoms.append(transfer.serialize_atom(g, h, self.peer.identity))
            except KeyError:
                continue
        eof = self.pos >= len(self.handles)
        g.metrics.incr("peer.transfer_chunks")
        tr = self._trace
        if tr is not None:
            tr.start_span("chunk", parent=tr.marks.get("root"),
                          pos=start, atoms=len(atoms), eof=eof).end()
        self.reply(sender, msg, M.INFORM, {
            "atoms": atoms, "eof": eof, "log_head": self.log_head,
            "pos": start, "next": self.pos, "snap": self.snap_token,
        })
        if eof:
            if tr is not None:
                tr.finish_terminal("served", atoms=self.pos)
            self.complete(self.pos)
