"""CACT — cluster activities: remote graph operations.

Re-expression of the reference's ``peer/cact/`` package: ``AddAtom``,
``GetAtom``, ``RemoveAtom``, ``ReplaceAtom``, ``GetIncidenceSet``,
``QueryCount``, ``RunRemoteQuery`` and the cursor-streaming
``RemoteQueryExecution`` (``peer/cact/RemoteQueryExecution.java:34``: the
server compiles+runs the query locally, holds the result open, and the
client pages through it over the wire).

Each op is a two-sided FSM activity over the performative protocol:
client sends REQUEST with op payload; server replies INFORM (result) or
FAILURE. RemoteQuery adds a paging loop (QUERY_REF → INFORM chunks →
CANCEL/complete)."""

from __future__ import annotations

from typing import Any, Optional

from hypergraphdb_tpu.peer import messages as M
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.activity import Activity, STARTED, from_state
from hypergraphdb_tpu.query import serialize as qser


# --------------------------------------------------------------- client side


class RemoteOpClient(Activity):
    """Generic request/response client activity."""

    TYPE = "cact"

    def __init__(self, peer, target: Optional[str] = None, op: Optional[dict] = None,
                 activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.target = target
        self.op = op or {}

    def initiate(self) -> None:
        self.send(self.target, M.REQUEST, self.op)

    @from_state(STARTED, M.INFORM)
    def on_result(self, sender: str, msg: dict) -> None:
        self.complete(msg["content"])

    @from_state(STARTED, M.FAILURE)
    def on_failure(self, sender: str, msg: dict) -> None:
        self.fail(RuntimeError(str(msg["content"])))


class RemoteOpServer(Activity):
    """Generic server: executes the op against the local graph."""

    TYPE = "cact"

    OPS = {}

    @from_state(STARTED, M.REQUEST)
    def on_request(self, sender: str, msg: dict) -> None:
        op = msg["content"] or {}
        handler = self.OPS.get(op.get("op"))
        if handler is None:
            self.reply(sender, msg, M.FAILURE, f"unknown op {op.get('op')}")
            self.fail(f"unknown op {op.get('op')}")
            return
        try:
            result = handler(self, op)
        except Exception as e:
            self.reply(sender, msg, M.FAILURE, f"{type(e).__name__}: {e}")
            self.fail(e)
            return
        self.reply(sender, msg, M.INFORM, result)
        self.complete(result)

    # -- op handlers (the cact/ class-per-op set) -------------------------

    def _op_define_atom(self, op: dict) -> Any:
        """AddAtom/DefineAtom: store a transferred closure locally."""
        handles = transfer.store_closure(self.peer.graph, op["atoms"])
        return {"handles": handles}

    def _op_get_atom(self, op: dict) -> Any:
        g = self.peer.graph
        gid = op.get("gid")
        h = transfer.lookup_local(g, gid) if gid else op.get("handle")
        if h is None or not g.contains(int(h)):
            raise KeyError(f"atom not found: {gid or op.get('handle')}")
        return {"atoms": transfer.serialize_closure(g, int(h), self.peer.identity)}

    def _op_remove_atom(self, op: dict) -> Any:
        g = self.peer.graph
        gid = op.get("gid")
        h = transfer.lookup_local(g, gid) if gid else op.get("handle")
        ok = bool(h is not None and g.remove(int(h)))
        return {"removed": ok}

    def _op_get_incidence_set(self, op: dict) -> Any:
        g = self.peer.graph
        h = int(op["handle"])
        return {"incidence": g.get_incidence_set(h).array().tolist()}

    def _op_query_count(self, op: dict) -> Any:
        cond = qser.from_json(op["condition"])
        return {"count": self.peer.graph.count(cond)}

    def _op_run_query(self, op: dict) -> Any:
        """One-shot remote query: compile + run + return all handles.
        (Streaming variant: RemoteQueryServer below.)"""
        cond = qser.from_json(op["condition"])
        return {"handles": [int(h) for h in self.peer.graph.find_all(cond)]}


RemoteOpServer.OPS = {
    "define_atom": RemoteOpServer._op_define_atom,
    "get_atom": RemoteOpServer._op_get_atom,
    "remove_atom": RemoteOpServer._op_remove_atom,
    "get_incidence_set": RemoteOpServer._op_get_incidence_set,
    "query_count": RemoteOpServer._op_query_count,
    "run_query": RemoteOpServer._op_run_query,
}


# ------------------------------------------------------- streaming remote query


class RemoteQueryClient(Activity):
    """Cursor-paging remote query (RemoteQueryExecution): QUERY_REF opens a
    server-held result; INFORM chunks stream back; the final chunk (eof)
    completes with the full handle list."""

    TYPE = "cact-query"

    def __init__(self, peer, target: Optional[str] = None,
                 condition=None, page: int = 64,
                 activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.target = target
        self.condition = condition
        self.page = page
        self.rows: list[int] = []

    def initiate(self) -> None:
        self.send(self.target, M.QUERY_REF, {
            "condition": qser.to_json(self.condition),
            "page": self.page,
        })

    @from_state(STARTED, M.INFORM)
    def on_chunk(self, sender: str, msg: dict) -> None:
        c = msg["content"]
        self.rows.extend(c["rows"])
        if c["eof"]:
            self.complete(self.rows)
        else:
            self.reply(sender, msg, M.CONFIRM)  # pull next page

    @from_state(STARTED, M.FAILURE)
    def on_failure(self, sender: str, msg: dict) -> None:
        self.fail(RuntimeError(str(msg["content"])))


class RemoteQueryServer(Activity):
    """Server side: executes once, then streams pages on CONFIRM pulls —
    the server-held open-result-set state (``state=ResultSetOpen``)."""

    TYPE = "cact-query"

    def __init__(self, peer, activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.results: Optional[list[int]] = None
        self.pos = 0
        self.page = 64

    @from_state(STARTED, M.QUERY_REF)
    def on_open(self, sender: str, msg: dict) -> None:
        content = msg["content"]
        try:
            cond = qser.from_json(content["condition"])
            self.page = int(content.get("page", 64))
            self.results = [int(h) for h in self.peer.graph.find_all(cond)]
        except Exception as e:
            self.reply(sender, msg, M.FAILURE, f"{type(e).__name__}: {e}")
            self.fail(e)
            return
        self.state = "ResultSetOpen"
        self._send_page(sender, msg)

    @from_state("ResultSetOpen", M.CONFIRM)
    def on_pull(self, sender: str, msg: dict) -> None:
        self._send_page(sender, msg)

    @from_state("ResultSetOpen", M.CANCEL)
    def on_cancel(self, sender: str, msg: dict) -> None:
        self.complete(None)

    def _send_page(self, sender: str, msg: dict) -> None:
        rows = self.results[self.pos : self.pos + self.page]
        self.pos += len(rows)
        eof = self.pos >= len(self.results)
        self.reply(sender, msg, M.INFORM, {"rows": rows, "eof": eof})
        if eof:
            self.complete(len(self.results))
