"""Remote graph view — a HyperNode-over-the-wire façade.

Re-expression of the reference's ``PeerHyperNode``
(``p2p/src/java/org/hypergraphdb/peer/PeerHyperNode.java``): a local object
with graph-like CRUD + query methods whose every call executes on a REMOTE
peer through the CACT ops, addressing atoms by global id. Values travel in
the transfer wire format (type name + payload bytes + optional record
schema), so a dataclass record defined only on the remote side still
round-trips as a field dict locally.

The view deliberately does NOT write through the local graph (unlike
``HyperGraphPeer.get_remote``, which stores fetched closures): it is a
window onto the remote database, not a replica.

Observability: every call runs over ``cact.RemoteOpClient``, so with
tracing on (``obs.enable()``, or an injected ``peer.tracer``) each view
operation roots a ``peer.op`` trace whose context propagates to the
serving peer — the remote ``op_serve`` span joins the same tree
(remote-child parenting, joined on trace id). Nothing extra to wire
here; the window is traced because the transport it rides is.
"""

from __future__ import annotations

import base64
from typing import Any, Optional

from hypergraphdb_tpu.peer import transfer


class RemoteGraphView:
    """Graph-like façade over one remote peer (``PeerHyperNode``)."""

    def __init__(self, peer, target: str, timeout: float = 10.0):
        self.peer = peer
        self.target = target
        self.timeout = timeout

    # -- encoding helpers ------------------------------------------------------
    def _encode_value(self, value: Any) -> dict:
        ts = self.peer.graph.typesystem
        atype = ts.infer(value)
        if atype is None:
            raise TypeError(f"no type for value {value!r}")
        payload = atype.store(value) if value is not None else None
        out = {
            "type": atype.name,
            "value_b64": (
                base64.b64encode(payload).decode("ascii")
                if payload is not None else None
            ),
        }
        schema = transfer.describe_type(self.peer.graph, atype.name)
        if schema is not None and schema["schema"] != "builtin":
            out["type_schema"] = schema
        return out

    def _decode_atom(self, wire: dict) -> Any:
        g = self.peer.graph
        ts = g.typesystem
        if (
            wire["type"] not in ts._by_name
            and wire.get("type_schema") is not None
        ):
            transfer.install_type(g, wire["type_schema"])
        atype = ts.get_type(wire["type"])
        if wire.get("value_b64") is None:
            return None
        return atype.make(base64.b64decode(wire["value_b64"]))

    def _op(self, op: dict) -> Any:
        return self.peer._run_op(self.target, op, self.timeout)

    # -- CRUD ------------------------------------------------------------------
    def add(self, value: Any, targets: tuple = ()) -> str:
        """Create an atom (node or link) ON the remote peer; returns its
        global id."""
        op = {"op": "add_atom", "targets": [str(t) for t in targets]}
        op.update(self._encode_value(value))
        return self._op(op)["gid"]

    def get(self, gid: str) -> Any:
        """The remote atom's VALUE — a peek, nothing is stored locally."""
        wire = self._op({"op": "peek_atom", "gid": gid})["atom"]
        return self._decode_atom(wire)

    def get_targets(self, gid: str) -> list[str]:
        wire = self._op({"op": "peek_atom", "gid": gid})["atom"]
        return list(wire.get("targets", ()))

    def replace(self, gid: str, value: Any) -> bool:
        op = {"op": "replace_atom", "gid": gid}
        op.update(self._encode_value(value))
        return self._op(op)["replaced"]

    def remove(self, gid: str) -> bool:
        return self._op({"op": "remove_atom", "gid": gid})["removed"]

    def get_type_name(self, gid: str) -> str:
        return self.peer.get_remote_type(self.target, gid, self.timeout)["type"]

    # -- queries ---------------------------------------------------------------
    def find_all(self, condition, page: int = 64) -> list[int]:
        """Remote handles matching ``condition`` (streamed in pages)."""
        return self.peer.run_remote_query(
            self.target, condition, page=page, timeout=self.timeout
        )

    def count(self, condition) -> int:
        return self.peer.count_remote(self.target, condition, self.timeout)

    def incidence(self, handle: int) -> list[int]:
        return self.peer.remote_incidence_set(
            self.target, handle, self.timeout
        )


def remote_view(peer, target: str, timeout: float = 10.0) -> RemoteGraphView:
    """Open a :class:`RemoteGraphView` of ``target`` through ``peer``."""
    return RemoteGraphView(peer, target, timeout)
