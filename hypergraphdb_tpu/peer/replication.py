"""Interest-based replication with an op log and offline catch-up.

Re-expression of the reference's ``peer/replication/`` + ``peer/log/``:

- **Interest predicates** (``Replication.java:19``): each peer publishes a
  serialized query condition; others push atom changes matching it
  (``PublishInterestsTask``/``RememberTaskClient.java:54``).
- **Op log with vector timestamps** (``peer/log/Log.java:34``): every local
  mutation appends (seq, op, atom closure); peers track how far they've
  seen each other's logs.
- **Catch-up** (``CatchUpTaskClient.java:33``): a peer that was offline
  requests entries since its recorded timestamp and applies them in order.

Eventual consistency, no consensus — deliberately matching the reference's
stance (SURVEY §7 hard part 5)."""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Optional

from hypergraphdb_tpu.core import events as ev
from hypergraphdb_tpu.peer import messages as M
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.query import serialize as qser

#: redelivery-journal record format; pre-versioning journals parse as 1
JOURNAL_SCHEMA_VERSION = 1


class OpLog:
    """Append-only log of local mutations (one per peer).

    Entries: (seq, kind, payload). seq is this peer's own monotonically
    increasing timestamp — the vector-clock component it owns.

    Durable when constructed with a graph (the reference persists its
    versioned log, ``peer/log/Log.java:34``, so peers can serve CATCH-UP
    across restarts): each entry is a data record in the graph's store —
    WAL-protected on the native backend — addressed by an ordered system
    index keyed on the big-endian sequence number.

    The persisted index IS the log: opens read only the head/floor meta
    markers (a long-lived ingesting peer's log no longer bloats open time
    or RAM — VERDICT r4 missing #5), ``since`` serves by RANGE CURSOR from
    the index, and :meth:`truncate_below` reclaims entries every connected
    peer has acknowledged (the reference's log is likewise bounded by
    catch-up needs). Without a graph the log lives in a plain list (tests,
    ephemeral peers)."""

    IDX = "hg.sys.oplog"
    META = "hg.sys.oplog.meta"

    def __init__(self, graph=None) -> None:
        self._lock = threading.Lock()
        self._graph = graph
        self._mem: list[tuple[int, str, Any]] = []  # RAM mode only
        self._head = 0
        self._floor = 0  # entries with seq <= floor are truncated
        if graph is not None:
            self._head = self._meta_get(b"head")
            self._floor = self._meta_get(b"floor")
            if self._head == 0:
                # legacy log without meta markers: recover head from the
                # last index key (keys scan, no payload loads)
                idx = graph.store.get_index(self.IDX, create=False)
                if idx is not None:
                    for key in idx.scan_keys():
                        self._head = int.from_bytes(key, "big")

    # -- meta markers ---------------------------------------------------------
    def _meta_get(self, key: bytes) -> int:
        idx = self._graph.store.get_index(self.META, create=False)
        if idx is None:
            return 0
        vals = idx.find(key).array()
        return int(vals.max()) if len(vals) else 0

    @staticmethod
    def _meta_set(idx, key: bytes, prev: int, value: int) -> None:
        if prev:
            idx.remove_entry(key, prev)
        idx.add_entry(key, value)

    # -- appends ---------------------------------------------------------------
    def append(self, kind: str, payload: Any) -> int:
        seq = self.append_mem(kind, payload)
        self.persist_many([(seq, kind, payload)])
        return seq

    def append_mem(self, kind: str, payload: Any) -> int:
        """Assign a sequence number (and, in RAM mode, record the entry) —
        callers batching many appends persist once via
        :meth:`persist_many`."""
        with self._lock:
            self._head += 1
            if self._graph is None:
                self._mem.append((self._head, kind, payload))
            return self._head

    def rollback_mem(self, mark: int) -> None:
        """Un-assign every sequence number above ``mark`` (a batched
        prepare whose transaction conflicted retries with fresh seqs)."""
        with self._lock:
            self._head = mark
            if self._graph is None:
                while self._mem and self._mem[-1][0] > mark:
                    self._mem.pop()

    def persist_many(self, batch) -> None:
        """Durably record a batch of (seq, kind, payload) entries — plus
        the head marker — in ONE store transaction (the push worker drains
        dozens of mutations per cycle; a transaction per entry would
        serialize it against the ingest thread's commits)."""
        g = self._graph
        if g is None or not batch:
            return
        import json

        encoded = [
            (seq.to_bytes(8, "big"),
             json.dumps([kind, payload]).encode("utf-8"))
            for seq, kind, payload in batch
        ]
        new_head = max(seq for seq, _, _ in batch)

        def persist() -> None:
            idx = g.store.get_index(self.IDX)
            for key, raw in encoded:
                dh = g.handles.make()
                g.store.store_data(dh, raw)
                idx.add_entry(key, dh)
            meta = g.store.get_index(self.META)
            prev = self._meta_get(b"head")
            if new_head > prev:
                self._meta_set(meta, b"head", prev, new_head)

        g.txman.ensure_transaction(persist)

    # -- reads -----------------------------------------------------------------
    def since(self, seq: int,
              limit: Optional[int] = None) -> list[tuple[int, str, Any]]:
        """Entries with sequence > ``seq``, served by index range cursor
        (durable mode) — the in-RAM log is gone, so this is O(result), not
        O(log). Truncated entries (≤ floor) cannot be served; callers
        compare ``seq`` against :attr:`floor` to detect the gap."""
        g = self._graph
        if g is None:
            with self._lock:
                out = [e for e in self._mem if e[0] > seq]
            return out[:limit] if limit is not None else out
        import json

        idx = g.backend.get_index(self.IDX, create=False)
        if idx is None:
            return []
        lo = (max(seq, 0) + 1).to_bytes(8, "big")
        # key scan under the commit lock: memstore's bulk_items iterates the
        # LIVE sorted dict, so a concurrent persist_many would raise
        # RuntimeError mid-iteration (review r5 finding 3). The hold is
        # bounded by `limit`; payload loads happen outside the lock.
        pairs: list[tuple[int, int]] = []
        with g.txman._commit_lock:
            for key, hs in idx.bulk_items(lo=lo):
                s = int.from_bytes(key, "big")
                for dh in hs.tolist():
                    pairs.append((s, int(dh)))
                if limit is not None and len(pairs) >= limit:
                    break
        res: list[tuple[int, str, Any]] = []
        for s, dh in pairs:
            raw = g.store.get_data(dh)
            if raw is None:
                continue
            kind, payload = json.loads(raw.decode("utf-8"))
            res.append((s, kind, payload))
        return res[:limit] if limit is not None else res

    def truncate_below(self, seq: int) -> int:
        """Drop entries with sequence ≤ ``seq`` (their data records too)
        and advance the floor. Returns how many entries were dropped.
        Callers only pass positions every peer has acknowledged."""
        g = self._graph
        with self._lock:
            seq = min(seq, self._head)
            if seq <= self._floor:
                return 0
            old_floor = self._floor
        if g is None:
            with self._lock:
                self._floor = seq
                n0 = len(self._mem)
                self._mem = [e for e in self._mem if e[0] > seq]
                return n0 - len(self._mem)
        idx = g.backend.get_index(self.IDX, create=False)
        if idx is None:
            return 0
        victims: list[tuple[bytes, int]] = []
        with g.txman._commit_lock:  # live-iterator guard, same as since()
            for key, hs in idx.bulk_items():
                if int.from_bytes(key, "big") > seq:
                    break
                for dh in hs.tolist():
                    victims.append((key, int(dh)))

        def drop() -> None:
            sidx = g.store.get_index(self.IDX)
            for key, dh in victims:
                sidx.remove_entry(key, dh)
                g.store.remove_data(dh)
            meta = g.store.get_index(self.META)
            self._meta_set(meta, b"floor", old_floor, seq)

        # durable first: the in-memory floor only advances once the drop
        # committed, so a failed/conflicted truncation never makes since()
        # report a gap that pushes peers into needless full syncs
        g.txman.ensure_transaction(drop)
        with self._lock:
            self._floor = max(self._floor, seq)
        return len(victims)

    @property
    def head(self) -> int:
        with self._lock:
            return self._head

    @property
    def floor(self) -> int:
        with self._lock:
            return self._floor


class SeenMap:
    """Durable, GAP-AWARE vector clock: peer id → applied-seq intervals
    of THEIR log. Two views per peer:

    - the **contiguous ack** (:meth:`get`): the highest seq such that
      every entry up to it has been applied here — what we acknowledge
      to the sender and request catch-up ``since``. This is the value
      persisted through the store (one entry per peer, same index/schema
      as the pre-gap-aware map), so catch-up resumes correctly after
      BOTH sides restart (ref ``CatchUpTaskClient.java:33``);
    - the **applied intervals** (:meth:`intervals` / :meth:`gaps`): the
      full set of applied seq ranges, RAM-only. A push that skips ahead
      (its predecessors dropped past the redelivery budget) opens a
      HOLE between intervals — the divergence the old max-applied ack
      silently papered over. :class:`Replication` watches
      :meth:`has_gap` and repairs by targeted catch-up from the
      contiguous ack; the re-applied tail is idempotent, so losing the
      RAM intervals in a crash costs a re-fetch, never correctness.

    Seq 0 means "nothing" and is trivially applied, so interval 0 always
    starts at 0 and the contiguous ack is its high end. Anchors
    (:meth:`set` — a completed snapshot transfer, a legacy max-ack) cover
    the whole prefix ``[0, seq]``."""

    IDX = "hg.sys.oplog.seen"

    def __init__(self, graph=None) -> None:
        self._graph = graph
        self._lock = threading.Lock()
        self._map: dict[str, int] = {}  # contiguous ack (durable)
        #: pid → sorted disjoint [lo, hi] intervals of applied seqs
        self._ranges: dict[str, list[list[int]]] = {}
        if graph is not None:
            idx = graph.store.get_index(self.IDX, create=False)
            if idx is not None:
                for key, hs in idx.bulk_items():
                    vals = hs.tolist()
                    if vals:
                        self._map[key.decode("utf-8")] = max(vals)
        for pid, v in self._map.items():
            self._ranges[pid] = [[0, v]]
        #: pid → last value durably written (the remove key of the next
        #: persist); loaded state IS persisted state
        self._persisted: dict[str, int] = dict(self._map)

    def get(self, pid: str, default: int = 0) -> int:
        with self._lock:
            return self._map.get(pid, default)

    def set(self, pid: str, seq: int) -> None:
        """Anchor: everything up to ``seq`` is covered (snapshot
        bootstrap semantics — the transfer shipped the whole prefix)."""
        self._cover(pid, 0, int(seq))

    def record_applied(self, pid: str, seq: int,
                       prev: Optional[int] = None,
                       persist: bool = True) -> int:
        """One entry of ``pid``'s log applied here; returns the (possibly
        advanced) contiguous ack. ``prev`` — the seq the sender last
        PUSHED to us before this one — additionally covers the range
        ``(prev, seq)``: those positions hold entries the sender's
        interest predicate deliberately skipped, not losses (a real loss
        is a pushed-but-dropped seq, and ``prev`` points AT it, never
        past it — so the hole it leaves stays visible).
        ``persist=False`` defers the durable store write — a batch
        applier covers each position in RAM and calls :meth:`persist`
        ONCE per sender per cycle instead of paying one store
        transaction per in-order push."""
        if seq <= 0:
            return self.get(pid)
        seq = int(seq)
        lo = seq
        if prev is not None and 0 <= int(prev) < seq:
            lo = int(prev) + 1
        return self._cover(pid, lo, seq, persist=persist)

    def _cover(self, pid: str, lo: int, hi: int,
               persist: bool = True) -> int:
        with self._lock:
            ivs = self._ranges.setdefault(pid, [[0, 0]])
            ivs.append([lo, hi])
            # the sort-and-merge must stay atomic with the read (interval
            # invariant), and the list is bounded: it holds MERGED ranges,
            # so after every _cover it collapses back to a handful
            ivs.sort()  # hglint: disable=HG703
            merged = [ivs[0][:]]
            for a, b in ivs[1:]:
                if a <= merged[-1][1] + 1:  # overlapping or adjacent
                    merged[-1][1] = max(merged[-1][1], b)
                else:
                    merged.append([a, b])
            self._ranges[pid] = merged
            contiguous = merged[0][1]  # merged[0][0] == 0 by seeding
            prev = self._map.get(pid)
            advanced = prev is None or contiguous > prev
            if advanced:
                self._map[pid] = contiguous
        if persist and advanced:
            self.persist(pid)
        return contiguous

    def persist(self, pid: str) -> None:
        """Durably store ``pid``'s current contiguous ack if it advanced
        past the last persisted value (no-op otherwise). The store tx
        runs OUTSIDE the leaf lock; an exception propagates — callers
        must not ack past an unpersisted position (they retry on the
        next cycle; the sender re-serves from our last durable ack and
        apply is idempotent)."""
        g = self._graph
        if g is None:
            return
        with self._lock:
            cur = self._map.get(pid)
            prev = self._persisted.get(pid)
        if cur is None or (prev is not None and cur <= prev):
            return
        key = pid.encode("utf-8")

        def persist_tx() -> None:
            idx = g.store.get_index(self.IDX)
            if prev is not None:
                idx.remove_entry(key, prev)
            idx.add_entry(key, cur)

        g.txman.ensure_transaction(persist_tx)
        with self._lock:
            if self._persisted.get(pid, -1) < cur:
                self._persisted[pid] = cur

    # -- gap queries -----------------------------------------------------------
    def intervals(self, pid: str) -> list[tuple[int, int]]:
        with self._lock:
            return [tuple(iv) for iv in self._ranges.get(pid, [[0, 0]])]

    def max_applied(self, pid: str) -> int:
        with self._lock:
            ivs = self._ranges.get(pid)
            return ivs[-1][1] if ivs else 0

    def has_gap(self, pid: str) -> bool:
        with self._lock:
            return len(self._ranges.get(pid, ())) > 1

    def gaps(self, pid: str) -> list[tuple[int, int]]:
        """The missing seq ranges between applied intervals — what a
        targeted repair catch-up must re-fetch."""
        with self._lock:
            ivs = self._ranges.get(pid, [])
            return [
                (ivs[i][1] + 1, ivs[i + 1][0] - 1)
                for i in range(len(ivs) - 1)
            ]

    def items(self):
        with self._lock:
            return list(self._map.items())


class Replication:
    """Per-peer replication service: publishes interests, pushes matching
    changes, applies incoming pushes, serves/runs catch-up."""

    ACTIVITY_TYPE = "replication"

    def __init__(self, peer):
        self.peer = peer
        self.log = OpLog(peer.graph)
        #: my interest predicate (None = not interested in anything)
        self.interest = None
        #: peers whose logs truncated past our position — incremental
        #: catch-up cannot converge; bootstrap via cact.transfer_graph
        self.needs_full_sync: set[str] = set()
        #: peer id -> their deserialized interest condition
        self.peer_interests: dict[str, Any] = {}
        #: durable vector clock: peer id → last seq of THEIR log applied
        self.last_seen = SeenMap(peer.graph)
        self._listening = False
        # thread-local "applying a foreign push" flag: suppresses the local
        # event listeners so replicated writes don't echo back out, without
        # blinding OTHER threads' genuine local mutations
        self._tls = threading.local()
        # async push pipeline (VERDICT r2 item 10): the mutation path only
        # ENQUEUES a handle; serialization, logging and network push run on
        # a single worker thread (order-preserving, so log sequence numbers
        # follow commit order). The reference pushes via activities off the
        # event thread for the same reason (RememberTaskClient.java:54).
        # lock-free enqueue: deque.append is atomic under the GIL, so the
        # mutation path pays ONE C-level call — no lock, no notify (the
        # worker polls on short timeouts; flush() wakes it explicitly)
        self._pending: Any = deque()
        self._cv = threading.Condition()
        self._worker: Optional[threading.Thread] = None
        self._stopping = False
        self._draining = 0  # items popped but not yet fully processed
        self._flush_asap = False
        # incoming-apply pipeline (VERDICT r4 weak #7): pushes/catch-up
        # results are APPLIED off the transport dispatch thread — a large
        # closure store must not stall unrelated peer messages (the
        # reference applies via scheduled activities,
        # ActivityManager.java:63-103). One FIFO worker preserves per-peer
        # order; SeenMap writes batch per drained cycle (weak #8).
        self._apply_q: Any = deque()
        self._apply_cv = threading.Condition()
        self._apply_worker: Optional[threading.Thread] = None
        self._apply_busy = 0
        #: how far each peer has acknowledged MY log (their CONTIGUOUS
        #: applied seq — gap-aware); min over interested peers gates log
        #: truncation, so a peer stuck behind a gap pins the floor until
        #: its repair catch-up has what it needs
        self.peer_acks: dict[str, int] = {}
        #: last known HEAD of each peer's log (push/catch-up/digest
        #: metadata rides it along) — ``replication_lag`` reads this
        self.peer_heads: dict[str, int] = {}
        #: peers with a detected apply gap whose targeted repair
        #: catch-up is in flight (cleared when a catch-up page arrives,
        #: so a lost repair request re-triggers on the next apply cycle)
        self._gap_repairs: set[str] = set()
        #: contiguous position at each peer's LAST digest-result — the
        #: anti-entropy stall detector: behind-the-head is only repaired
        #: when we made no progress since the previous probe (or on the
        #: first probe), so steady in-flight ingest doesn't trigger a
        #: redundant catch-up every tick
        self._ae_seen_pos: dict[str, int] = {}
        #: auto-truncate the op log once every interested peer has
        #: acknowledged at least `truncate_batch` entries past the floor
        self.auto_truncate = True
        self.truncate_batch = 256
        #: catch-up responses are served in pages of this many entries (one
        #: rejoining peer must not make the dispatch thread materialize and
        #: wire-expand the whole surviving log); the client requests the
        #: next page after applying the previous one
        self.catchup_page = 1024
        #: debounce: wait for a quiet gap before draining so serialization
        #: does not steal cycles from a hot ingest loop (with the GIL, a
        #: busy worker halves writer throughput); backpressure cap bounds
        #: the deferred backlog
        self.debounce_s = 0.05
        self.max_backlog = 20_000
        # -- self-healing send plane (hgfault): pushes get bounded retry
        # with capped backoff ON THE WORKER THREAD (never the mutation
        # path), then land in a PER-PEER ORDERED redelivery queue. Order
        # is the invariant: once a peer has queued redeliveries (or is
        # down-marked), every later push to it queues BEHIND them and the
        # retry pass drains in order, stopping at the first failure — a
        # redelivered remove can never land after a newer re-add.
        # Receivers apply idempotently (store_closure is a write-through
        # upsert keyed by gid) and the SeenMap records only applied
        # progress, so a duplicated push is a no-op. A message dropped
        # past max_redeliveries is a real wire loss — but no longer a
        # SILENT one: the receiver's SeenMap tracks applied-seq
        # CONTIGUITY, so the hole shows the moment a later push lands
        # (targeted catch-up repairs it), and the periodic anti-entropy
        # digest catches the before-a-silence case; the journal below
        # additionally lets the queue itself survive a process death.
        self.send_attempts = 3
        self.send_backoff_s = 0.02
        self.send_backoff_max_s = 0.25
        self.max_redeliveries = 4
        #: spacing between redelivery passes when the drain queue is
        #: otherwise idle: back-to-back passes would burn the whole
        #: ladder in a fraction of a second, covering no realistic
        #: outage (flush() skips the spacing — "settle now" semantics)
        self.redelivery_interval_s = 0.25
        self.max_redelivery_backlog = 10_000
        #: pid -> deque[(message, attempt)] — worker-thread-owned;
        #: emptied entries are popped so dict truthiness == "work queued"
        self._redelivery: dict[str, Any] = {}
        self._redelivery_n = 0
        #: crash-surviving redelivery queue: path of a JSONL journal
        #: (None + a persistent graph → defaulted beside the store at
        #: attach()). Rewritten crash-atomically (fsync + os.replace,
        #: the ops/checkpoint discipline) by the worker whenever the
        #: queue changes; replayed on attach, so queued-but-undelivered
        #: pushes survive a process death instead of dying with it.
        #: Receivers apply idempotently, so replay is safe by
        #: construction.
        self.journal_path: Optional[str] = None
        self._journal_dirty = False
        #: minimum spacing of DIRTY-queue journal rewrites: each save is
        #: O(total backlog), so a hot ingest loop against one dead peer
        #: would otherwise pay a growing multi-MB rewrite EVERY worker
        #: cycle, throttling replication to the healthy peers through
        #: the shared worker. An EMPTY queue always saves immediately —
        #: the state flush() reports settled stays journal-exact; the
        #: widened crash window only risks re-losing messages the gap
        #: tracking / anti-entropy backstops already repair.
        self.journal_save_interval_s = 0.25
        self._journal_last_save = 0.0
        #: last seq actually pushed per peer (anchored at the log head
        #: when the interest registers): pushes carry it as ``prev`` so
        #: interest-filtered receivers can tell a predicate skip from a
        #: wire loss. RAM-only is safe: fanout only reaches peers in
        #: ``peer_interests``, and the interest handler re-anchors on
        #: every (re)registration
        self._sent_head: dict[str, int] = {}
        #: peers whose LAST ladder exhausted → fresh pushes skip straight
        #: to the redelivery queue until the grace expires, so one dead
        #: peer's backoff sleeps cannot head-of-line-block the worker's
        #: pushes to healthy peers (the redelivery pass probes ONE head
        #: message per down peer per pass and clears the mark on success)
        self.down_peer_grace_s = 0.5
        self._down_until: dict[str, float] = {}
        self._sleep = time.sleep  # injectable (tests)

    # -- wiring ---------------------------------------------------------------
    def attach(self) -> None:
        """Subscribe to local graph events (HGAtomAddedEvent push path)."""
        if self._listening:
            return
        g = self.peer.graph
        g.events.add_listener(ev.HGAtomAddedEvent, self._on_added)
        g.events.add_listener(ev.HGAtomRemovedEvent, self._on_removed)
        g.events.add_listener(ev.HGAtomReplacedEvent, self._on_replaced)
        self._listening = True
        self._stopping = False
        if self.journal_path is None:
            loc = getattr(getattr(g, "config", None), "location", None)
            if loc:
                import os

                self.journal_path = os.path.join(
                    loc, "replication.redelivery.jsonl"
                )
        self._journal_replay()
        self._worker = threading.Thread(
            target=self._drain, name="replication-push", daemon=True
        )
        self._worker.start()
        self._apply_worker = threading.Thread(
            target=self._apply_drain, name="replication-apply", daemon=True
        )
        self._apply_worker.start()

    def detach(self) -> None:
        """Flush the push queue and stop the worker + listeners."""
        if not self._listening:
            return
        g = self.peer.graph
        g.events.remove_listener(ev.HGAtomAddedEvent, self._on_added)
        g.events.remove_listener(ev.HGAtomRemovedEvent, self._on_removed)
        g.events.remove_listener(ev.HGAtomReplacedEvent, self._on_replaced)
        self._listening = False
        with self._cv:
            self._stopping = True
            self._cv.notify_all()
        with self._apply_cv:
            self._apply_cv.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=10)
            self._worker = None
        if self._apply_worker is not None:
            self._apply_worker.join(timeout=10)
            self._apply_worker = None

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every enqueued mutation has been logged and pushed
        (including the redelivery queue settling — delivered or dropped
        after ``max_redeliveries``), AND every received push/catch-up
        batch has been applied (both worker pipelines drained)."""
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cv:
            self._flush_asap = True
            self._cv.notify_all()
            while self._pending or self._draining or self._redelivery:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.notify_all()
                self._cv.wait(min(remaining, 0.05))
        with self._apply_cv:
            while self._apply_q or self._apply_busy:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._apply_cv.notify_all()
                self._apply_cv.wait(min(remaining, 0.05))
        return True

    # -- local mutation hooks (mutation path: enqueue ONLY) --------------------
    def _on_added(self, graph, event) -> None:
        self._enqueue("add", int(event.handle))

    def _on_replaced(self, graph, event) -> None:
        self._enqueue("add", int(event.handle))  # same write-through semantics

    def _on_removed(self, graph, event) -> None:
        self._enqueue("remove", int(event.handle))

    @property
    def _applying(self) -> bool:
        return getattr(self._tls, "applying", False)

    def _enqueue(self, kind: str, h: int) -> None:
        if self._applying:
            # this write IS a replicated one — re-pushing it would echo
            # forever between interested peers
            return
        self._pending.append((kind, h))  # atomic; no lock on this path

    def _drain(self) -> None:
        while True:
            with self._cv:
                while (not self._pending and not self._redelivery
                       and not self._stopping):
                    self._flush_asap = False
                    self._cv.wait(0.1)
                if not self._pending and self._stopping:
                    # redelivery is best-effort on shutdown: catch-up is
                    # the documented convergence path for whatever is left
                    return
                if (not self._pending and self._redelivery
                        and not self._stopping and not self._flush_asap):
                    # redelivery-only cycle: space the passes out so the
                    # bounded ladder spans a real outage window instead
                    # of burning out back-to-back (a submit/flush/stop
                    # notification still wakes us early)
                    self._cv.wait(self.redelivery_interval_s)
                batch = []
                if self._pending:
                    # debounce: while the writer is hot (queue growing)
                    # hold off, unless stopping/flushing or backlog-capped
                    last = len(self._pending)
                    while (not self._stopping and not self._flush_asap
                           and last < self.max_backlog):
                        self._cv.wait(self.debounce_s)
                        now = len(self._pending)
                        if now == last:
                            break  # quiet gap: the writer paused
                        last = now
                    while self._pending:
                        batch.append(self._pending.popleft())
                self._draining += len(batch)
            try:
                log_batch, pushes = (
                    self._prepare_batch(batch) if batch else ([], [])
                )
            except Exception:
                import logging

                logging.getLogger("hypergraphdb_tpu.peer").warning(
                    "replication batch prepare failed", exc_info=True
                )
                log_batch, pushes = [], []
            try:
                self.log.persist_many(log_batch)  # one tx for the batch
                for (seq, _, _), kind, h, entry in pushes:
                    self._fanout(kind, h, entry, seq)
                # truncation that lost a race against a hot ingest loop
                # retries here, when the writer has gone quiet
                self._maybe_truncate()
            except Exception:
                import logging

                logging.getLogger("hypergraphdb_tpu.peer").warning(
                    "replication batch persist/push failed", exc_info=True
                )
            try:
                if self._redelivery:
                    # busy-marked so flush() cannot observe "all queues
                    # empty" while a popped message is still in flight
                    with self._cv:
                        self._draining += 1
                    try:
                        self._retry_redeliveries()
                    finally:
                        with self._cv:
                            self._draining -= 1
            except Exception:
                import logging

                logging.getLogger("hypergraphdb_tpu.peer").warning(
                    "replication redelivery pass failed", exc_info=True
                )
            finally:
                if self._journal_dirty:
                    # persist queue changes BEFORE flush() can observe
                    # the cycle as settled — journal == queue state.
                    # Rate-limited while a backlog churns (each save is
                    # O(backlog)); the settled/EMPTY state always saves
                    now_m = time.monotonic()
                    if (not self._redelivery
                            or now_m - self._journal_last_save
                            >= self.journal_save_interval_s):
                        self._journal_dirty = False
                        self._journal_last_save = now_m
                        self._journal_save()
                with self._cv:
                    self._draining -= len(batch)
                    self._cv.notify_all()

    # -- worker-side log + push -------------------------------------------------
    def _prepare_batch(self, batch):
        """Prepare a drained batch inside ONE transaction (per-atom commits
        were half the worker's cost). The tx CAN conflict — serialization
        reads note cells a racing writer may move — so on conflict the
        memory-log appends are rolled back and the whole batch retried;
        the worker must never die (review r4 finding 1)."""
        from hypergraphdb_tpu.core.errors import TransactionConflict

        g = self.peer.graph
        for _ in range(8):
            log_batch: list[tuple] = []
            pushes: list[tuple] = []
            mark = self.log.head
            tx = g.txman.begin()
            try:
                for kind, h in batch:
                    try:
                        if kind == "remove":
                            item = self._prepare_remove(h)
                        else:
                            item = self._prepare_record(kind, h)
                        if item is not None:
                            log_batch.append(item[0])
                            pushes.append(item)
                    except Exception:
                        import logging

                        logging.getLogger("hypergraphdb_tpu.peer").warning(
                            "replication push failed for %s %s", kind, h,
                            exc_info=True,
                        )
            except BaseException:
                g.txman.abort(tx)
                self.log.rollback_mem(mark)
                raise
            try:
                g.txman.commit(tx)
                return log_batch, pushes
            except TransactionConflict:
                self.log.rollback_mem(mark)
                continue
        import logging

        logging.getLogger("hypergraphdb_tpu.peer").warning(
            "replication batch kept conflicting; re-enqueued for a later "
            "drain cycle"
        )
        # the log IS the catch-up source — dropping the batch would be
        # permanent silent replication loss. Put it back at the FRONT so
        # ordering is preserved and the next (debounced) cycle retries.
        self._pending.extendleft(reversed(batch))
        return [], []

    def _prepare_remove(self, h: int):
        gid = transfer.existing_gid(self.peer.graph, h)
        if gid is None:
            # the atom never crossed the wire: no peer can hold a copy, so
            # there is nothing to retract (and minting a gid for it would
            # pollute the atom map — ADVICE r2)
            return None
        entry = {"gid": gid}
        seq = self.log.append_mem("remove", entry)
        return (seq, "remove", entry), "remove", h, entry

    def _prepare_record(self, kind: str, h: int):
        g = self.peer.graph
        if not g.contains(h):
            return None  # removed before the worker got to it
        if self.peer_interests:
            # pushes are applied out of order at receivers → full closure
            atoms = transfer.serialize_closure(g, h, self.peer.identity)
        else:
            # log-only entry: catch-up replays IN ORDER, so an atom's
            # targets always have earlier entries — one record suffices
            # (serializing the whole closure per mutation tripled the
            # ingest-side overhead for nothing)
            atoms = [transfer.serialize_atom(g, h, self.peer.identity)]
        entry = {"atoms": atoms,
                 "root": transfer.gid_of(g, h, self.peer.identity)}
        seq = self.log.append_mem(kind, entry)
        return (seq, kind, entry), kind, h, entry

    def _expand_for_wire(self, kind: str, entry: dict):
        """Log entries hold the ROOT record only (ordered replay makes the
        closure redundant); a PARTIAL catch-up client may lack targets from
        before its `since`, so expand to the full closure at serve time —
        rare path, paid by the server, not the ingest hot loop."""
        atoms = entry.get("atoms")
        if kind == "remove" or not atoms or len(atoms) != 1:
            return entry
        if not atoms[0].get("targets"):
            return entry  # no dependencies to miss
        g = self.peer.graph
        h = transfer.lookup_local(g, entry["root"])
        if h is None or not g.contains(h):
            return entry  # atom gone; serve the recorded form
        return {
            "atoms": transfer.serialize_closure(g, int(h), self.peer.identity),
            "root": entry["root"],
        }

    def _fanout(self, kind: str, h: int, entry: dict, seq: int = 0) -> None:
        if kind == "remove":
            for pid in list(self.peer_interests):
                self._push(pid, "remove", entry, seq)
            return
        targets = [
            pid for pid, cond in list(self.peer_interests.items())
            if cond is None or self._matches(cond, h)
        ]
        if not targets:
            return
        # an interest may have arrived AFTER prepare chose the log-only
        # single-atom form; pushes are applied out of order at receivers,
        # so expand to the full closure (same rule as catch-up serving)
        entry = self._expand_for_wire(kind, entry)
        for pid in targets:
            self._push(pid, kind, entry, seq)

    def _matches(self, cond, h: int) -> bool:
        try:
            return bool(cond.satisfies(self.peer.graph, h))
        except Exception:
            return False

    def _push(self, pid: str, kind: str, entry: dict,
              seq: int = 0) -> None:
        # the push carries the ENTRY's own seq (gap-aware receivers
        # record exactly which log positions they applied — a batch-wide
        # head would make every entry of a drained batch look applied the
        # moment any one of them lands) plus the current head, so the
        # receiver's advertised lag is fresh on every push, plus ``prev``
        # — the last seq actually PUSHED to this peer: seqs in
        # (prev, seq) were skipped by the peer's own interest predicate,
        # so the receiver covers them as accounted-for instead of
        # reading every predicate skip as a wire loss and burning a
        # full-log repair catch-up per apply cycle (a REAL loss is a
        # pushed-but-dropped seq — prev points AT it, never past it)
        s = seq or self.log.head
        prev = self._sent_head.get(pid, 0)
        self._sent_head[pid] = s
        msg = M.make_message(
            M.INFORM, self.ACTIVITY_TYPE,
            {"what": "push", "kind": kind, "entry": entry,
             "seq": s, "head": self.log.head, "prev": prev},
        )
        # distributed tracing (worker thread, one enabled read): the push
        # roots a cross-process tree — the receiver's apply subtree joins
        # on the propagated trace id, even when delivery happens later
        # through the redelivery queue (the context rides the message)
        tracer = self.peer.tracer
        tr = None
        if tracer.enabled:
            tr = tracer.start_trace("peer.push", kind=kind, target=pid)
        if tr is not None:
            tr.marks["root"] = tr.start_span("push", target=pid, kind=kind)
            M.attach_trace(msg, tr.context())
        if (self._redelivery.get(pid)
                or time.monotonic() < self._down_until.get(pid, 0.0)):
            # ORDER: the peer already has queued redeliveries (or just
            # exhausted a ladder) — this push must line up behind them,
            # never overtake (and we skip paying 3 backoff sleeps per
            # message to a down peer)
            self._queue_redelivery(pid, msg, 1)
            if tr is not None:
                tr.finish_terminal("redelivery_queued")
            return
        if self._send_reliable(pid, msg):
            if tr is not None:
                tr.finish_terminal("sent")
        else:
            self._queue_redelivery(pid, msg, 1)
            if tr is not None:
                tr.finish_terminal("redelivery_queued")

    def _send_reliable(self, pid: str, message: dict) -> bool:
        """Send with bounded retry + capped backoff. Worker-thread only —
        the mutation path never sleeps here. Returns whether the
        transport accepted the message (delivery stays at-most-once;
        end-to-end convergence is redelivery + catch-up's job). Tracks
        per-peer down-marks: an exhausted ladder marks the peer down for
        ``down_peer_grace_s`` (fresh pushes skip the ladder), any success
        clears the mark."""
        m = self.peer.graph.metrics
        m.incr("peer.sends")
        for attempt in range(self.send_attempts):
            if attempt:
                m.incr("peer.send_retries")
                self._sleep(min(
                    self.send_backoff_s * (2.0 ** (attempt - 1)),
                    self.send_backoff_max_s,
                ))
            try:
                if self.peer.interface.send(pid, message):
                    self._down_until.pop(pid, None)
                    return True
            except Exception:  # hglint: disable=HG1005
                pass  # transport failure == unreachable now; the loop's
                # fall-through marks the peer down and counts send_failures
        self._down_until[pid] = time.monotonic() + self.down_peer_grace_s
        m.incr("peer.send_failures")
        return False

    def _queue_redelivery(self, pid: str, message: dict,
                          attempt: int) -> None:
        if self._redelivery_n >= self.max_redelivery_backlog:
            # a long-dead peer must not grow an unbounded queue; such a
            # peer re-joins via the TransferGraph bootstrap anyway
            self.peer.graph.metrics.incr("peer.redelivery_dropped")
            return
        q = self._redelivery.get(pid)
        if q is None:
            q = self._redelivery[pid] = deque()
        q.append((message, attempt))
        self._redelivery_n += 1
        self._journal_dirty = True
        with self._cv:
            self._cv.notify_all()

    def _retry_redeliveries(self) -> None:
        """One redelivery pass (worker thread, after the regular drain):
        per peer, drain the queue IN ORDER and stop at the first failure
        — one probe ladder per down peer per pass, so a dead peer with a
        deep backlog costs one bounded ladder, not sleeps-per-message.
        A head message failing past ``max_redeliveries`` drops with a
        counter (a real gap; see the class comment for the honest
        convergence story)."""
        m = self.peer.graph.metrics
        for pid in list(self._redelivery):
            q = self._redelivery.get(pid)
            while q:
                msg, attempt = q[0]
                m.incr("peer.redeliveries")
                if self._send_reliable(pid, msg):
                    q.popleft()
                    self._redelivery_n -= 1
                    self._journal_dirty = True
                    continue
                # ladder failed: leave the rest queued behind the head
                # (per-peer order is the invariant), probe again next
                # pass — unless the head is out of budget
                if attempt >= self.max_redeliveries:
                    q.popleft()
                    self._redelivery_n -= 1
                    self._journal_dirty = True
                    m.incr("peer.redelivery_dropped")
                else:
                    q[0] = (msg, attempt + 1)
                break
            if not q:
                self._redelivery.pop(pid, None)

    # -- redelivery journal (crash-surviving queue) -----------------------------
    def _journal_replay(self) -> None:
        """Load a surviving journal into the redelivery queue (peer
        open). Order within the file IS per-peer submission order — the
        save writes queues front-to-back — so the per-peer ordering
        invariant survives the restart too."""
        path = self.journal_path
        if path is None:
            return
        import json
        import os

        if not os.path.exists(path):
            return
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    # pre-versioning journals (no stamp) default to 1;
                    # a FUTURE stamp is skipped, not guessed at — losing
                    # a redelivery is recoverable (catch-up), a
                    # mis-parsed one is not
                    if rec.get("schema_version", 1) != JOURNAL_SCHEMA_VERSION:
                        continue
                    pid = rec["pid"]
                    q = self._redelivery.get(pid)
                    if q is None:
                        q = self._redelivery[pid] = deque()
                    q.append((rec["message"], int(rec.get("attempt", 1))))
                    self._redelivery_n += 1
        except Exception:
            import logging

            logging.getLogger("hypergraphdb_tpu.peer").warning(
                "redelivery journal %s unreadable; starting empty", path,
                exc_info=True,
            )

    def _journal_save(self) -> None:
        """Crash-atomic rewrite of the redelivery journal (worker thread
        only, after a cycle that changed the queue): same-directory tmp,
        fsync, ``os.replace`` — the ``ops/checkpoint._atomic_write``
        discipline, so a death at any instant leaves the previous
        complete journal, never a torn one. An unwritable path logs and
        degrades to the old dies-with-the-process behavior."""
        path = self.journal_path
        if path is None:
            return
        import json

        from hypergraphdb_tpu.ops.checkpoint import _atomic_write

        lines = []
        for pid, q in self._redelivery.items():
            for msg, attempt in q:
                lines.append(json.dumps(
                    {"schema_version": JOURNAL_SCHEMA_VERSION,
                     "pid": pid, "attempt": attempt, "message": msg},
                    sort_keys=True,
                ))
        data = "".join(line + "\n" for line in lines).encode("utf-8")
        try:
            # the ONE crash-atomic publish (tmp + fsync + os.replace +
            # the registered crash point), not a drifting inline copy:
            # ordinary failure cleans the tmp, an InjectedCrash at
            # peer.journal.save leaves it behind like a real kill
            _atomic_write(path, lambda f: f.write(data),
                          "peer.journal.save")
        except Exception:
            import logging

            logging.getLogger("hypergraphdb_tpu.peer").warning(
                "redelivery journal save failed (%s)", path, exc_info=True
            )

    # -- interest publication ---------------------------------------------------
    def publish_interest(self, condition) -> None:
        """Declare what I want replicated to me, to every known peer."""
        self.interest = condition
        payload = None if condition is None else qser.to_json(condition)
        for pid in self.peer.interface.peers():
            self.peer.interface.send(pid, M.make_message(
                M.SUBSCRIBE, self.ACTIVITY_TYPE,
                {"what": "interest", "condition": payload},
            ))

    # -- catch-up ---------------------------------------------------------------
    def catch_up(self, pid: str) -> bool:
        """Ask ``pid`` for its log entries after my recorded position
        (reliable-send: a dropped request retries with backoff — losing
        it would silently stall convergence until the next manual call).
        Returns whether the request was SENT (False when even the
        reliable-send budget couldn't reach the peer — the caller's cue
        that no catchup-result will ever arrive). Traced: each page
        roots one cross-process tree — request here, ``catchup_serve``
        on the server, ``apply`` back here — joined on the propagated
        trace id."""
        self.peer.graph.metrics.incr("peer.catchups")
        msg = M.make_message(
            M.REQUEST, self.ACTIVITY_TYPE,
            {"what": "catchup", "since": self.last_seen.get(pid, 0)},
        )
        tracer = self.peer.tracer
        tr = None
        if tracer.enabled:
            tr = tracer.start_trace("peer.catchup", target=pid)
        if tr is not None:
            tr.marks["root"] = tr.start_span("catchup_request", target=pid)
            M.attach_trace(msg, tr.context())
        ok = self._send_reliable(pid, msg)
        if tr is not None:
            tr.finish_terminal("sent" if ok else "error",
                               **({} if ok else {"error": "SendFailed"}))
        return ok

    def _check_gap(self, sender: str) -> None:
        """Receiver-side gap repair (apply worker): applied-seq intervals
        with a hole mean a push was lost past the redelivery budget —
        exactly the divergence the old max-applied ack could never see.
        Trigger ONE targeted catch-up from the contiguous ack (the pages
        re-cover the hole; re-applying the already-applied tail is
        idempotent); the in-flight mark clears when a catch-up page
        arrives, so a lost repair request re-triggers instead of wedging.
        NOTE for interest-FILTERED peers: a seq the sender's predicate
        skipped looks like a hole too — the repair catch-up then fetches
        it, which matches catch-up's existing unfiltered semantics."""
        if not self.last_seen.has_gap(sender):
            self._gap_repairs.discard(sender)
            return
        if sender in self._gap_repairs:
            return
        self._gap_repairs.add(sender)
        self.peer.graph.metrics.incr("peer.gaps_detected")
        try:
            if not self.catch_up(sender):
                # the request never left (reliable-send budget spent):
                # no catchup-result will ever clear the mark — drop it
                # so the next apply cycle re-triggers instead of wedging
                self._gap_repairs.discard(sender)
        except Exception:  # noqa: BLE001  # hglint: disable=HG1005
            # retried on the next cycle; dropping the mark re-arms it
            self._gap_repairs.discard(sender)

    def anti_entropy(self, pid: str) -> None:
        """Backstop convergence probe: ask ``pid`` for its log digest
        (head/floor) and catch up if our contiguous position is behind.
        Contiguity tracking only detects a loss once a LATER push lands;
        when the lost pushes were the last traffic before a silence,
        nothing ever exposes the hole — this periodic digest exchange
        does. Cheap on both sides (a few ints on the wire); safe from
        any thread (reliable-send may sleep its bounded backoff)."""
        self.peer.graph.metrics.incr("peer.anti_entropy_probes")
        self._send_reliable(pid, M.make_message(
            M.REQUEST, self.ACTIVITY_TYPE, {"what": "digest"},
        ))

    def replication_lag(self, pid: str) -> int:
        """Entries of ``pid``'s log not yet contiguously applied here —
        the replica staleness measure the serving gate and ``/healthz``
        advertise. Based on the freshest head ``pid`` told us (every
        push/catch-up/digest carries one), so between probes it can
        under-report; the anti-entropy cadence bounds that window."""
        return max(0, self.peer_heads.get(pid, 0)
                   - self.last_seen.get(pid, 0))

    # -- message handling (runs on the peer's dispatch path) --------------------
    def handle(self, sender: str, msg: dict) -> bool:
        if msg.get("activity_type") != self.ACTIVITY_TYPE:
            return False
        content = msg.get("content") or {}
        if not isinstance(content, dict):
            return False
        what = content.get("what")
        if what == "interest":
            cond = content.get("condition")
            # anchor the per-peer push chain at the CURRENT head: seqs
            # at or below it predate the interest — the peer's own
            # catch-up/bootstrap territory, never "skipped by predicate"
            self._sent_head.setdefault(sender, self.log.head)
            self.peer_interests[sender] = (
                None if cond is None else qser.from_json(cond)
            )
        elif what == "push":
            # apply OFF the dispatch thread — a slow closure store must not
            # stall unrelated peer traffic; the propagated trace context
            # rides along so the apply joins the sender's tree
            seq = int(content.get("seq", 0))
            head = int(content.get("head", seq))
            if head > self.peer_heads.get(sender, 0):
                self.peer_heads[sender] = head
            prev = content.get("prev")  # None: pre-prev wire format
            self._enqueue_apply(
                sender, [(content["kind"], content["entry"], seq,
                          M.trace_context(msg),
                          None if prev is None else int(prev))]
            )
        elif what == "catchup":
            # remote-child span: this serve hangs under the requester's
            # catchup_request span in the joined tree
            tracer = self.peer.tracer
            tr = None
            if tracer.enabled:
                tr = tracer.start_remote_trace(
                    "peer.catchup.serve", M.trace_context(msg),
                    peer=sender,
                )
            serve_span = (None if tr is None
                          else tr.start_span("catchup_serve"))
            if tr is not None:
                tr.marks["root"] = serve_span
            since = int(content.get("since", 0))
            floor = self.log.floor
            entries = []
            if since >= floor:
                # page-sized serve (review r5 finding 4): one request must
                # not materialize + wire-expand the whole remaining log on
                # the dispatch thread; the client re-requests after applying
                raw = self.log.since(since, limit=self.catchup_page)
                # re-read the floor AFTER the scan: a truncation that raced
                # the cursor may have dropped entries in (since, floor] —
                # serving the surviving tail would silently skip them
                # (review r5 finding 2); report the gap instead so the
                # client falls back to a full bootstrap
                floor = self.log.floor
                if since >= floor:
                    entries = [
                        {"seq": seq, "kind": kind,
                         "entry": self._expand_for_wire(kind, entry)}
                        for seq, kind, entry in raw
                    ]
            self.peer.graph.metrics.incr("peer.catchup_pages")
            result = M.make_message(
                M.INFORM, self.ACTIVITY_TYPE,
                {"what": "catchup-result", "entries": entries,
                 "head": self.log.head, "floor": floor},
            )
            if tr is not None:
                # chain the SAME trace onward: the client's apply spans
                # parent under this serve span
                M.attach_trace(result, tr.context(serve_span))
            self.peer.interface.send(sender, result)
            if tr is not None:
                tr.finish_terminal("served", entries=len(entries))
        elif what == "catchup-result":
            floor = int(content.get("floor", 0))
            entries = content.get("entries") or []
            head = int(content.get("head", 0))
            if head > self.peer_heads.get(sender, 0):
                self.peer_heads[sender] = head
            # a catch-up page arrived: a pending gap-repair request is no
            # longer in flight — if the gap survives this page, the next
            # apply cycle re-triggers the repair
            self._gap_repairs.discard(sender)
            if floor > self.last_seen.get(sender, 0) and not entries:
                # the server truncated past our position: incremental
                # catch-up cannot converge — a full bootstrap (TransferGraph)
                # is required
                self.needs_full_sync.add(sender)
                return True
            # a page-limited response may stop short of the server's head:
            # continue the catch-up after this page has been applied
            top = max((int(e["seq"]) for e in entries), default=0)
            tctx = M.trace_context(msg)
            self._enqueue_apply(
                sender,
                [(e["kind"], e["entry"], int(e["seq"]), tctx)
                 for e in entries],
                continue_catchup=bool(entries) and top < head,
            )
        elif what == "digest":
            # anti-entropy probe: answer with my log coordinates — cheap
            # dispatch-thread work (two lock reads, no payloads)
            self.peer.interface.send(sender, M.make_message(
                M.INFORM, self.ACTIVITY_TYPE,
                {"what": "digest-result", "head": self.log.head,
                 "floor": self.log.floor},
            ))
        elif what == "digest-result":
            head = int(content.get("head", 0))
            floor = int(content.get("floor", 0))
            if head > self.peer_heads.get(sender, 0):
                self.peer_heads[sender] = head
            mine = self.last_seen.get(sender, 0)
            prev = self._ae_seen_pos.get(sender)
            self._ae_seen_pos[sender] = mine
            if mine < floor:
                # truncated past us: incremental repair is impossible
                self.needs_full_sync.add(sender)
            elif head > mine and (prev is None or mine <= prev):
                # the backstop caught divergence no push ever revealed
                # (e.g. the LAST pushes before a silence were dropped
                # past the redelivery budget — nothing later arrives to
                # expose the hole via contiguity). Behind-the-head while
                # STILL ADVANCING is ordinary in-flight lag — repairing
                # it would shadow the push pipeline with a redundant
                # catch-up every probe; a stalled position (or the first
                # probe) is the loss signal
                self.peer.graph.metrics.incr("peer.anti_entropy_repairs")
                self.catch_up(sender)
        elif what == "ack":
            # receiver's applied position in MY log: feeds truncation
            seq = int(content.get("seq", 0))
            if seq > self.peer_acks.get(sender, 0):
                self.peer_acks[sender] = seq
            try:
                self._maybe_truncate()
            except Exception:  # hglint: disable=HG1005
                # e.g. the drop transaction kept conflicting with a hot
                # ingest loop — the push worker retries opportunistically
                pass
        else:
            return False
        return True

    def _enqueue_apply(self, sender: str, items: list,
                       continue_catchup: bool = False) -> None:
        if not items:
            return
        with self._apply_cv:
            self._apply_q.append((sender, items, continue_catchup))
            self._apply_cv.notify_all()

    def _apply_drain(self) -> None:
        while True:
            with self._apply_cv:
                while not self._apply_q and not self._stopping:
                    self._apply_cv.wait(0.1)
                if not self._apply_q:
                    return  # stopping and drained
                batch = []
                while self._apply_q:
                    batch.append(self._apply_q.popleft())
                self._apply_busy += 1
            try:
                # per-sender pre-batch contiguous positions: ONE ack per
                # sender per drained cycle (sent only when the contiguous
                # position advanced), not per push
                pre: dict[str, int] = {}
                failed: set[str] = set()
                noack: set[str] = set()
                conts: set[str] = set()
                tracer = self.peer.tracer
                for sender, items, cont in batch:
                    if cont:
                        conts.add(sender)
                    # push items carry a 5th element: the sender's prev
                    # pushed seq (predicate-skip accounting); catch-up
                    # pages apply exact positions only
                    for kind, entry, seq, tctx, *rest in items:
                        prev = rest[0] if rest else None
                        if sender in failed:
                            # a failed apply must not be acked past — stop
                            # advancing this sender; catch-up refetches
                            # from the last acknowledged position
                            continue
                        # remote-child trace: the apply subtree joins the
                        # sender's push/serve span tree on trace id (one
                        # enabled read; untraced messages carry no ctx)
                        tr = (tracer.start_remote_trace(
                                  "peer.apply", tctx, kind=kind,
                                  sender=sender)
                              if tracer.enabled else None)
                        if tr is not None:
                            tr.marks["root"] = tr.start_span(
                                "apply", kind=kind, seq=seq)
                        try:
                            self._apply(sender, kind, entry)
                            self.peer.graph.metrics.incr("peer.applies")
                        except Exception as apply_exc:
                            import logging

                            logging.getLogger(
                                "hypergraphdb_tpu.peer"
                            ).warning(
                                "replication apply failed (%s from %s)",
                                kind, sender, exc_info=True,
                            )
                            if tr is not None:
                                tr.finish_error(apply_exc)
                            failed.add(sender)
                            continue
                        if tr is not None:
                            tr.finish_terminal("applied")
                        if seq:
                            if sender not in pre:
                                pre[sender] = self.last_seen.get(sender)
                            try:
                                # gap-aware: record the exact position;
                                # the contiguous ack advances only over
                                # an unbroken applied prefix. RAM-only
                                # here — ONE durable persist per sender
                                # per cycle below, not one store tx per
                                # in-order push
                                self.last_seen.record_applied(
                                    sender, seq, prev, persist=False)
                            except Exception:
                                # e.g. TransactionConflict after retries
                                # under a hot ingest loop — the worker
                                # must NEVER die (review r5 finding 1).
                                # Not durably recorded → do not ack past
                                # it either; the sender re-serves from
                                # our last ack and _apply is idempotent.
                                import logging

                                logging.getLogger(
                                    "hypergraphdb_tpu.peer"
                                ).warning(
                                    "seen-map update failed for %s",
                                    sender, exc_info=True,
                                )
                                noack.add(sender)
                for sender, before in pre.items():
                    if sender in noack:
                        continue
                    cur = self.last_seen.get(sender)
                    try:
                        # the cycle's ONE durable write for this sender
                        # (no-op when nothing advanced); an unpersisted
                        # position must not be acked — skip, the sender
                        # re-serves from our last durable ack and the
                        # next cycle retries the persist
                        self.last_seen.persist(sender)
                    except Exception:
                        import logging

                        logging.getLogger(
                            "hypergraphdb_tpu.peer"
                        ).warning("seen-map persist failed for %s",
                                  sender, exc_info=True)
                        self._check_gap(sender)
                        continue
                    if cur > before:
                        try:
                            self.peer.graph.metrics.incr("peer.acks")
                            self.peer.interface.send(sender, M.make_message(
                                M.INFORM, self.ACTIVITY_TYPE,
                                {"what": "ack", "seq": cur},
                            ))
                        except Exception:  # noqa: BLE001 - peer gone
                            self.peer.graph.metrics.incr(
                                "peer.ack_send_failures")
                    self._check_gap(sender)
                # page-limited catch-up: pull the next page now that this
                # one is applied and acknowledged
                for sender in conts - failed:
                    try:
                        self.catch_up(sender)
                    except Exception:  # noqa: BLE001 - peer may be gone
                        self.peer.graph.metrics.incr(
                            "peer.catch_up_failures")
            except Exception:
                # belt-and-braces: anything unexpected is logged, the
                # worker loop survives
                import logging

                logging.getLogger("hypergraphdb_tpu.peer").warning(
                    "replication apply cycle failed", exc_info=True
                )
            finally:
                with self._apply_cv:
                    self._apply_busy -= 1
                    self._apply_cv.notify_all()

    def _maybe_truncate(self) -> None:
        """Reclaim log entries every interested peer has acknowledged. A
        peer with a declared interest but no ack yet pins the floor (its
        ack defaults to 0), so nothing a connected peer still needs is
        dropped; fully-detached peers re-join via catch-up or, past the
        floor, a full bootstrap."""
        if not self.auto_truncate or not self.peer_acks:
            return
        audience = set(self.peer_interests) | set(self.peer_acks)
        lo = min(self.peer_acks.get(pid, 0) for pid in audience)
        if lo - self.log.floor >= self.truncate_batch:
            self.log.truncate_below(lo)

    def _apply(self, sender: str, kind: str, entry: dict) -> None:
        g = self.peer.graph
        self._tls.applying = True
        try:
            # under the peer's apply mutex: a concurrently-streaming
            # snapshot transfer must not race this gid's check-then-act
            with self.peer.apply_lock:
                if kind == "remove":
                    local = transfer.lookup_local(g, entry["gid"])
                    if local is not None and g.contains(int(local)):
                        g.remove(int(local))
                    return
                transfer.store_closure(g, entry["atoms"])
        finally:
            self._tls.applying = False
