"""Interest-based replication with an op log and offline catch-up.

Re-expression of the reference's ``peer/replication/`` + ``peer/log/``:

- **Interest predicates** (``Replication.java:19``): each peer publishes a
  serialized query condition; others push atom changes matching it
  (``PublishInterestsTask``/``RememberTaskClient.java:54``).
- **Op log with vector timestamps** (``peer/log/Log.java:34``): every local
  mutation appends (seq, op, atom closure); peers track how far they've
  seen each other's logs.
- **Catch-up** (``CatchUpTaskClient.java:33``): a peer that was offline
  requests entries since its recorded timestamp and applies them in order.

Eventual consistency, no consensus — deliberately matching the reference's
stance (SURVEY §7 hard part 5)."""

from __future__ import annotations

import threading
from typing import Any, Optional

from hypergraphdb_tpu.core import events as ev
from hypergraphdb_tpu.peer import messages as M
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.query import serialize as qser


class OpLog:
    """Append-only log of local mutations (one per peer).

    Entries: (seq, kind, payload). seq is this peer's own monotonically
    increasing timestamp — the vector-clock component it owns.

    Durable when constructed with a graph (the reference persists its
    versioned log, ``peer/log/Log.java:34``, so peers can serve CATCH-UP
    across restarts): each entry is a data record in the graph's store —
    WAL-protected on the native backend — addressed by an ordered system
    index keyed on the big-endian sequence number. A RAM-only log would
    silently break offline catch-up the moment the serving peer restarts."""

    IDX = "hg.sys.oplog"

    def __init__(self, graph=None) -> None:
        self._lock = threading.Lock()
        self.entries: list[tuple[int, str, Any]] = []
        self._graph = graph
        if graph is not None:
            self._load()

    def _load(self) -> None:
        import json

        g = self._graph
        idx = g.store.get_index(self.IDX, create=False)
        if idx is None:
            return
        for key, hs in idx.bulk_items():  # ordered by big-endian seq key
            seq = int.from_bytes(key, "big")
            for dh in hs.tolist():
                raw = g.store.get_data(int(dh))
                if raw is None:
                    continue
                kind, payload = json.loads(raw.decode("utf-8"))
                self.entries.append((seq, kind, payload))

    def append(self, kind: str, payload: Any) -> int:
        with self._lock:
            seq = len(self.entries) + 1
            self.entries.append((seq, kind, payload))
            g = self._graph
            if g is not None:
                import json

                raw = json.dumps([kind, payload]).encode("utf-8")
                key = seq.to_bytes(8, "big")

                def persist() -> None:
                    dh = g.handles.make()
                    g.store.store_data(dh, raw)
                    g.store.get_index(self.IDX).add_entry(key, dh)

                g.txman.ensure_transaction(persist)
            return seq

    def since(self, seq: int) -> list[tuple[int, str, Any]]:
        with self._lock:
            return [e for e in self.entries if e[0] > seq]

    @property
    def head(self) -> int:
        with self._lock:
            return len(self.entries)


class SeenMap:
    """Durable vector clock: peer id → last seq of THEIR log applied here.
    Persisted through the store so catch-up resumes correctly after BOTH
    sides restart (ref ``CatchUpTaskClient.java:33``)."""

    IDX = "hg.sys.oplog.seen"

    def __init__(self, graph=None) -> None:
        self._graph = graph
        self._map: dict[str, int] = {}
        if graph is not None:
            idx = graph.store.get_index(self.IDX, create=False)
            if idx is not None:
                for key, hs in idx.bulk_items():
                    vals = hs.tolist()
                    if vals:
                        self._map[key.decode("utf-8")] = max(vals)

    def get(self, pid: str, default: int = 0) -> int:
        return self._map.get(pid, default)

    def set(self, pid: str, seq: int) -> None:
        prev = self._map.get(pid)
        if prev is not None and seq <= prev:
            return  # no durable rewrite for an unchanged/backward clock
        self._map[pid] = seq
        g = self._graph
        if g is not None:
            key = pid.encode("utf-8")

            def persist() -> None:
                idx = g.store.get_index(self.IDX)
                if prev is not None:
                    idx.remove_entry(key, prev)
                idx.add_entry(key, seq)

            g.txman.ensure_transaction(persist)

    def items(self):
        return self._map.items()


class Replication:
    """Per-peer replication service: publishes interests, pushes matching
    changes, applies incoming pushes, serves/runs catch-up."""

    ACTIVITY_TYPE = "replication"

    def __init__(self, peer):
        self.peer = peer
        self.log = OpLog(peer.graph)
        #: my interest predicate (None = not interested in anything)
        self.interest = None
        #: peer id -> their deserialized interest condition
        self.peer_interests: dict[str, Any] = {}
        #: durable vector clock: peer id → last seq of THEIR log applied
        self.last_seen = SeenMap(peer.graph)
        self._listening = False
        # thread-local "applying a foreign push" flag: suppresses the local
        # event listeners so replicated writes don't echo back out, without
        # blinding OTHER threads' genuine local mutations
        self._tls = threading.local()

    # -- wiring ---------------------------------------------------------------
    def attach(self) -> None:
        """Subscribe to local graph events (HGAtomAddedEvent push path)."""
        if self._listening:
            return
        g = self.peer.graph
        g.events.add_listener(ev.HGAtomAddedEvent, self._on_added)
        g.events.add_listener(ev.HGAtomRemovedEvent, self._on_removed)
        g.events.add_listener(ev.HGAtomReplacedEvent, self._on_replaced)
        self._listening = True

    # -- local mutation hooks → log + push ------------------------------------
    def _on_added(self, graph, event) -> None:
        self._record("add", int(event.handle))

    def _on_replaced(self, graph, event) -> None:
        self._record("add", int(event.handle))  # same write-through semantics

    @property
    def _applying(self) -> bool:
        return getattr(self._tls, "applying", False)

    def _on_removed(self, graph, event) -> None:
        if self._applying:
            return
        h = int(event.handle)
        gid = transfer.existing_gid(self.peer.graph, h)
        if gid is None:
            # the atom never crossed the wire: no peer can hold a copy, so
            # there is nothing to retract (and minting a gid for it would
            # pollute the atom map — ADVICE r2)
            return
        entry = {"gid": gid}
        self.log.append("remove", entry)
        for pid in list(self.peer_interests):
            self._push(pid, "remove", entry)

    def _record(self, kind: str, h: int) -> None:
        if self._applying:
            # this write IS a replicated one — re-pushing it would echo
            # forever between interested peers
            return
        g = self.peer.graph
        if not g.contains(h):
            return
        atoms = transfer.serialize_closure(g, h, self.peer.identity)
        entry = {"atoms": atoms,
                 "root": transfer.gid_of(g, h, self.peer.identity)}
        self.log.append(kind, entry)
        for pid, cond in list(self.peer_interests.items()):
            if cond is None or self._matches(cond, h):
                self._push(pid, kind, entry)

    def _matches(self, cond, h: int) -> bool:
        try:
            return bool(cond.satisfies(self.peer.graph, h))
        except Exception:
            return False

    def _push(self, pid: str, kind: str, entry: dict) -> None:
        self.peer.interface.send(pid, M.make_message(
            M.INFORM, self.ACTIVITY_TYPE,
            {"what": "push", "kind": kind, "entry": entry,
             "seq": self.log.head},
        ))

    # -- interest publication ---------------------------------------------------
    def publish_interest(self, condition) -> None:
        """Declare what I want replicated to me, to every known peer."""
        self.interest = condition
        payload = None if condition is None else qser.to_json(condition)
        for pid in self.peer.interface.peers():
            self.peer.interface.send(pid, M.make_message(
                M.SUBSCRIBE, self.ACTIVITY_TYPE,
                {"what": "interest", "condition": payload},
            ))

    # -- catch-up ---------------------------------------------------------------
    def catch_up(self, pid: str) -> None:
        """Ask ``pid`` for its log entries after my recorded position."""
        self.peer.interface.send(pid, M.make_message(
            M.REQUEST, self.ACTIVITY_TYPE,
            {"what": "catchup", "since": self.last_seen.get(pid, 0)},
        ))

    # -- message handling (runs on the peer's dispatch path) --------------------
    def handle(self, sender: str, msg: dict) -> bool:
        if msg.get("activity_type") != self.ACTIVITY_TYPE:
            return False
        content = msg.get("content") or {}
        if not isinstance(content, dict):
            return False
        what = content.get("what")
        if what == "interest":
            cond = content.get("condition")
            self.peer_interests[sender] = (
                None if cond is None else qser.from_json(cond)
            )
        elif what == "push":
            self._apply(sender, content["kind"], content["entry"])
            self.last_seen.set(sender, max(
                self.last_seen.get(sender, 0), int(content.get("seq", 0))
            ))
        elif what == "catchup":
            since = int(content.get("since", 0))
            entries = [
                {"seq": seq, "kind": kind, "entry": entry}
                for seq, kind, entry in self.log.since(since)
            ]
            self.peer.interface.send(sender, M.make_message(
                M.INFORM, self.ACTIVITY_TYPE,
                {"what": "catchup-result", "entries": entries,
                 "head": self.log.head},
            ))
        elif what == "catchup-result":
            hi = self.last_seen.get(sender, 0)
            for e in content.get("entries", ()):
                self._apply(sender, e["kind"], e["entry"])
                hi = max(hi, int(e["seq"]))
            # ONE durable clock write for the whole batch, after it applied
            self.last_seen.set(sender, hi)
        else:
            return False
        return True

    def _apply(self, sender: str, kind: str, entry: dict) -> None:
        g = self.peer.graph
        self._tls.applying = True
        try:
            if kind == "remove":
                local = transfer.lookup_local(g, entry["gid"])
                if local is not None and g.contains(int(local)):
                    g.remove(int(local))
                return
            transfer.store_closure(g, entry["atoms"])
        finally:
            self._tls.applying = False
