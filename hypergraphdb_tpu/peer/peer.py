"""HyperGraphPeer — the peer runtime.

Re-expression of ``peer/HyperGraphPeer.java:57``: owns a local graph, a
persisted identity, a pluggable transport, the activity scheduler, and the
bootstrap services (identity handshake, CACT responders, replication) —
``HyperGraphPeer.start()`` at :307-353.

Config is a plain dict (the reference uses a JSON file; ``from_config``
accepts the same shape)::

    peer = HyperGraphPeer(graph, interface=LoopbackNetwork().interface("p1"))
    peer.start()
    handles = peer.run_remote_query(other_id, q.type_("string"))
"""

from __future__ import annotations

import uuid
from typing import Any, Optional

from hypergraphdb_tpu.obs import global_tracer
from hypergraphdb_tpu.peer import cact
from hypergraphdb_tpu.peer.activity import ActivityManager
from hypergraphdb_tpu.peer.replication import Replication
from hypergraphdb_tpu.peer.transport import (
    LoopbackNetwork,
    PeerInterface,
    TCPPeerInterface,
)


class HyperGraphPeer:
    def __init__(
        self,
        graph,
        interface: PeerInterface,
        identity: Optional[str] = None,
    ):
        self.graph = graph
        self.interface = interface
        #: the hgobs tracer the peer plane reports into — the process
        #: tracer by default, injectable per peer (two-peer tests give
        #: each side its own so the joined span tree can be asserted
        #: from both halves); every peer-plane site gates on ONE
        #: ``tracer.enabled`` read
        self.tracer = global_tracer()
        #: persisted peer identity (HGPeerIdentity analogue)
        self.identity = identity or self._load_identity()
        #: serializes REPLICATED writes into the local graph across the
        #: peer's threads: the replication apply worker and a snapshot
        #: transfer both ``store_closure`` — unserialized, two threads
        #: racing the same gid's check-then-act would twin the atom
        #: (a bootstrapping replica receives pushes WHILE its transfer
        #: streams; both are idempotent only under this mutex)
        import threading

        self.apply_lock = threading.Lock()
        #: serializes start()/stop() (check-and-set on ``_started``)
        self._lifecycle_lock = threading.Lock()
        self.activities = ActivityManager(self)
        self.replication = Replication(self)
        #: peers whose identity handshake completed (AffirmIdentity
        #: bootstrap, ``peer/bootstrap/AffirmIdentityBootstrap``): id → info
        self.known_peers: dict[str, dict] = {}
        self._started = False

        # bootstrap: server-side activity factories (CACTBootstrap analogue)
        self.activities.register_type("cact", lambda peer, activity_id=None:
                                      cact.RemoteOpServer(peer, activity_id))
        self.activities.register_type("cact-query",
                                      lambda peer, activity_id=None:
                                      cact.RemoteQueryServer(peer, activity_id))
        self.activities.register_type("cact-transfer",
                                      lambda peer, activity_id=None:
                                      cact.TransferGraphServer(peer, activity_id))

    def _load_identity(self) -> str:
        """Stable identity persisted in the graph (one per database)."""
        idx = self.graph.store.get_index("hg.peer.identity")
        existing = idx.find_first(b"self")
        if existing is not None:
            data = self.graph.store.get_data(int(existing))
            if data:
                return data.decode("utf-8")
        ident = uuid.uuid4().hex

        def run():
            h = self.graph.handles.make()
            self.graph.store.store_data(h, ident.encode("utf-8"))
            idx.add_entry(b"self", h)

        self.graph.txman.ensure_transaction(run)
        return ident

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        with self._lifecycle_lock:
            if self._started:
                return
            self.interface.peer_id = self.identity
            if getattr(self.interface, "metrics", None) is None:
                # peer.* observability rides the graph's metrics registry —
                # one Prometheus scrape covers graph + tx + peer planes
                self.interface.metrics = self.graph.metrics
            self.interface.on_message(self._dispatch)
            self.interface.start()
            self.activities.start()
            self.replication.attach()
            self._started = True
        self.affirm_identity()

    def stop(self) -> None:
        # the WHOLE teardown runs under the lifecycle lock: flipping
        # _started first and tearing down outside it would let a racing
        # start() rebuild the components in the gap, only for this
        # in-flight stop to tear the fresh ones down (none of the joined
        # workers take this lock, so holding it across the joins is safe)
        with self._lifecycle_lock:
            if not self._started:
                return
            self.replication.detach()  # flush pushes, stop the worker
            self.activities.stop()
            self.interface.stop()
            self._started = False

    # -- identity handshake (AffirmIdentityBootstrap) --------------------------
    def affirm_identity(self) -> None:
        """Announce this peer's identity to every reachable peer; receivers
        record it and acknowledge with their own (the reference's
        AffirmIdentity bootstrap handshake that precedes other activity)."""
        for pid in self.interface.peers():
            if pid != self.identity:
                self.interface.send(pid, {
                    "activity_type": "identity",
                    "content": {"what": "affirm",
                                "identity": self.identity},
                })

    def _handle_identity(self, sender: str, msg: dict) -> bool:
        if msg.get("activity_type") != "identity":
            return False
        content = msg.get("content") or {}
        what = content.get("what")
        if what == "affirm":
            self.known_peers[sender] = {"identity": content.get("identity")}
            self.interface.send(sender, {
                "activity_type": "identity",
                "content": {"what": "affirm-ack",
                            "identity": self.identity},
            })
        elif what == "affirm-ack":
            self.known_peers[sender] = {"identity": content.get("identity")}
        else:
            return False
        return True

    def _dispatch(self, sender: str, msg: dict) -> None:
        # identity handshake first, then replication service traffic;
        # everything else is conversation-scoped and goes through the
        # activity scheduler
        if self._handle_identity(sender, msg):
            return
        if self.replication.handle(sender, msg):
            return
        self.activities.on_message(sender, msg)

    # -- remote op façade (the cact client calls) -----------------------------
    def _run_op(self, target: str, op: dict, timeout: float = 10.0) -> Any:
        act = self.activities.initiate(
            cact.RemoteOpClient(self, target=target, op=op)
        )
        return act.future.result(timeout=timeout)

    def define_remote(self, target: str, handle, timeout: float = 10.0) -> list[int]:
        """Push an atom closure to a remote peer (AddAtom/DefineAtom)."""
        from hypergraphdb_tpu.peer import transfer

        atoms = transfer.serialize_closure(self.graph, int(handle), self.identity)
        return self._run_op(target, {"op": "define_atom", "atoms": atoms},
                            timeout)["handles"]

    def get_remote(self, target: str, gid: str, timeout: float = 10.0) -> list[int]:
        """Fetch a remote atom closure and store it locally (GetAtom)."""
        from hypergraphdb_tpu.peer import transfer

        result = self._run_op(target, {"op": "get_atom", "gid": gid}, timeout)
        return transfer.store_closure(self.graph, result["atoms"])

    def remove_remote(self, target: str, gid: str, timeout: float = 10.0) -> bool:
        return self._run_op(target, {"op": "remove_atom", "gid": gid},
                            timeout)["removed"]

    def remote_incidence_set(self, target: str, handle: int,
                             timeout: float = 10.0) -> list[int]:
        return self._run_op(
            target, {"op": "get_incidence_set", "handle": int(handle)}, timeout
        )["incidence"]

    def count_remote(self, target: str, condition, timeout: float = 10.0) -> int:
        from hypergraphdb_tpu.query import serialize as qser

        return self._run_op(
            target, {"op": "query_count", "condition": qser.to_json(condition)},
            timeout,
        )["count"]

    def run_remote_query(self, target: str, condition, page: int = 64,
                         timeout: float = 10.0) -> list[int]:
        """Streaming remote query (RemoteQueryExecution): pages a server-held
        result cursor; returns all remote handles."""
        act = self.activities.initiate(
            cact.RemoteQueryClient(self, target=target, condition=condition,
                                   page=page)
        )
        return act.future.result(timeout=timeout)

    def replace_remote(self, target: str, gid: str, value,
                       timeout: float = 10.0) -> bool:
        """Replace a remote atom's value by global id (ReplaceAtom)."""
        import base64

        from hypergraphdb_tpu.peer import transfer

        atype = self.graph.typesystem.infer(value)
        if atype is None:
            raise TypeError(f"no type for value {value!r}")
        payload = atype.store(value) if value is not None else None
        op = {
            "op": "replace_atom",
            "gid": gid,
            "type": atype.name,
            "value_b64": (
                base64.b64encode(payload).decode("ascii")
                if payload is not None else None
            ),
        }
        schema = transfer.describe_type(self.graph, atype.name)
        if schema is not None and schema["schema"] != "builtin":
            op["type_schema"] = schema
        return self._run_op(target, op, timeout)["replaced"]

    def get_remote_type(self, target: str, gid: str,
                        timeout: float = 10.0) -> dict:
        """Type name + schema of a remote atom (GetAtomType)."""
        return self._run_op(target, {"op": "get_atom_type", "gid": gid},
                            timeout)

    def sync_types_to(self, target: str, names=None,
                      timeout: float = 10.0) -> list[str]:
        """Push local type schemas to a peer (SyncTypes): record types
        install there class-less, so atoms of those types resolve before
        any push/transfer arrives. ``names=None`` sends every local record
        type."""
        from hypergraphdb_tpu.peer import transfer
        from hypergraphdb_tpu.types.record import RecordType

        ts = self.graph.typesystem
        if names is None:
            names = [
                n for n, t in ts._by_name.items()
                if isinstance(t, RecordType)
            ]
        descs = [d for d in (
            transfer.describe_type(self.graph, n) for n in names
        ) if d is not None]
        return self._run_op(
            target, {"op": "sync_types", "types": descs}, timeout
        )["installed"]

    def transfer_graph_from(self, target: str, page: int = 256,
                            timeout: float = 60.0,
                            retry_after_s: float = 1.0,
                            max_resumes: int = 8) -> int:
        """Pull the ENTIRE remote graph (TransferGraph bootstrap): pages of
        dependency-ordered atoms; on completion the replication clock for
        ``target`` advances to the server's log head at snapshot time, so a
        follow-up ``replication.catch_up(target)`` converges the tail.
        Self-healing: a chunk lost on the wire is re-requested after
        ``retry_after_s`` of silence (the activity ticker drives the
        watchdog), up to ``max_resumes`` times before failing typed.
        Returns how many atoms were stored."""
        act = self.activities.initiate(
            cact.TransferGraphClient(self, target=target, page=page,
                                     retry_after_s=retry_after_s,
                                     max_resumes=max_resumes)
        )
        return act.future.result(timeout=timeout)

    # -- convenience constructors ---------------------------------------------
    @staticmethod
    def loopback(graph, network: LoopbackNetwork,
                 identity: Optional[str] = None) -> "HyperGraphPeer":
        peer = HyperGraphPeer(graph, network.interface("pending"), identity)
        peer.interface.peer_id = peer.identity
        return peer

    @staticmethod
    def tcp(graph, host: str = "127.0.0.1", port: int = 0,
            identity: Optional[str] = None) -> "HyperGraphPeer":
        peer = HyperGraphPeer(
            graph, TCPPeerInterface("pending", host, port), identity
        )
        peer.interface.peer_id = peer.identity
        return peer
