"""FIPA-ACL-style message envelopes.

Re-expression of the reference's performative vocabulary and Json envelopes
(``peer/Performative.java``, ``peer/Messages.java:22``): every message
carries a performative, an activity type + id (conversation correlation),
and content. ``reply_to`` builds the response envelope with the same
conversation id (the ``Messages.getReply`` analogue).

**Distributed tracing rides the envelope**: :func:`attach_trace` stamps a
message with the compact hgobs trace context
(``{"tid": trace id, "sid": parent span id, "s": sampled}`` under the
``"trace"`` key — three JSON scalars, transport-agnostic) and
:func:`trace_context` reads it back on the receiving side, tolerant of
messages from peers that predate tracing (absent key → None). The
context's semantics live in ``obs.trace`` (``Trace.context`` /
``Tracer.start_remote_trace``); this module only owns the wire placement.
"""

from __future__ import annotations

import uuid
from typing import Any, Optional

#: envelope key carrying the propagated hgobs trace context
TRACE_KEY = "trace"

# the performative constant pool (Performative.java)
REQUEST = "request"
INFORM = "inform"
QUERY_REF = "query-ref"
PROPOSE = "propose"
ACCEPT_PROPOSAL = "accept-proposal"
REJECT_PROPOSAL = "reject-proposal"
AGREE = "agree"
REFUSE = "refuse"
FAILURE = "failure"
CONFIRM = "confirm"
DISCONFIRM = "disconfirm"
CANCEL = "cancel"
SUBSCRIBE = "subscribe"
NOT_UNDERSTOOD = "not-understood"


def make_message(
    performative: str,
    activity_type: str,
    content: Any = None,
    activity_id: Optional[str] = None,
) -> dict:
    return {
        "performative": performative,
        "activity_type": activity_type,
        "activity_id": activity_id or str(uuid.uuid4()),
        "content": content,
    }


def reply_to(msg: dict, performative: str, content: Any = None) -> dict:
    """Response envelope correlated to the same activity/conversation."""
    return {
        "performative": performative,
        "activity_type": msg["activity_type"],
        "activity_id": msg["activity_id"],
        "content": content,
    }


def attach_trace(msg: dict, ctx: Optional[dict]) -> dict:
    """Stamp ``msg`` with a propagated trace context (no-op when ctx is
    falsy — untraced sends carry no extra bytes). Returns ``msg``."""
    if ctx:
        msg[TRACE_KEY] = ctx
    return msg


def trace_context(msg: dict) -> Optional[dict]:
    """The propagated trace context of a received message, or None."""
    ctx = msg.get(TRACE_KEY)
    return ctx if isinstance(ctx, dict) else None
