"""FIPA-ACL-style message envelopes.

Re-expression of the reference's performative vocabulary and Json envelopes
(``peer/Performative.java``, ``peer/Messages.java:22``): every message
carries a performative, an activity type + id (conversation correlation),
and content. ``reply_to`` builds the response envelope with the same
conversation id (the ``Messages.getReply`` analogue).
"""

from __future__ import annotations

import uuid
from typing import Any, Optional

# the performative constant pool (Performative.java)
REQUEST = "request"
INFORM = "inform"
QUERY_REF = "query-ref"
PROPOSE = "propose"
ACCEPT_PROPOSAL = "accept-proposal"
REJECT_PROPOSAL = "reject-proposal"
AGREE = "agree"
REFUSE = "refuse"
FAILURE = "failure"
CONFIRM = "confirm"
DISCONFIRM = "disconfirm"
CANCEL = "cancel"
SUBSCRIBE = "subscribe"
NOT_UNDERSTOOD = "not-understood"


def make_message(
    performative: str,
    activity_type: str,
    content: Any = None,
    activity_id: Optional[str] = None,
) -> dict:
    return {
        "performative": performative,
        "activity_type": activity_type,
        "activity_id": activity_id or str(uuid.uuid4()),
        "content": content,
    }


def reply_to(msg: dict, performative: str, content: Any = None) -> dict:
    """Response envelope correlated to the same activity/conversation."""
    return {
        "performative": performative,
        "activity_type": msg["activity_type"],
        "activity_id": msg["activity_id"],
        "content": content,
    }
