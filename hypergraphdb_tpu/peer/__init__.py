"""P2P distribution layer (SURVEY §2.3): peer runtime, pluggable transports
(loopback + TCP), FIPA-ACL messages, activity state machines, remote graph
ops (CACT), interest-based replication with op-log catch-up.

This is the host-side control plane over DCN; the on-device data plane
(collectives over ICI) lives in ``hypergraphdb_tpu.parallel`` (SURVEY §5
"Distributed communication backend": two planes)."""

from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import (
    LoopbackNetwork,
    PeerInterface,
    TCPPeerInterface,
)

__all__ = [
    "HyperGraphPeer",
    "LoopbackNetwork",
    "PeerInterface",
    "TCPPeerInterface",
]
