"""Activity framework: distributed async state machines.

Re-expression of the reference's workflow package (``peer/workflow/``):
``Activity``/``FSMActivity`` with ``@FromState``/``@OnMessage`` transition
methods, scheduled by an ``ActivityManager`` whose global queue ages
per-activity action queues by ``timestamp × queue-size`` for fairness
(``peer/workflow/ActivityManager.java:49,63-103``).

An activity is a small state machine keyed by (activity_type, activity_id).
Incoming messages are enqueued to the owning activity's action queue; a
worker pool drains the globally-fairest queue first. ``Activity.future``
resolves when the activity reaches a terminal state (Completed/Failed) —
the ``TaskActivity`` future-result analogue.
"""

from __future__ import annotations

import heapq
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Optional

from hypergraphdb_tpu.peer import messages as M

# terminal workflow states (WorkflowState analogue)
STARTED = "Started"
COMPLETED = "Completed"
FAILED = "Failed"
CANCELED = "Canceled"
TERMINAL = frozenset({COMPLETED, FAILED, CANCELED})


def from_state(state: str, performative: Optional[str] = None):
    """Decorator marking a transition method: runs when a message arrives
    while the activity is in ``state`` (optionally filtered by
    performative) — the ``@FromState``/``@OnMessage`` annotations."""

    def deco(fn):
        fn._from_state = state
        fn._performative = performative
        return fn

    return deco


class Activity:
    """Base distributed activity (one side of a conversation)."""

    TYPE = "activity"

    def __init__(self, peer, activity_id: Optional[str] = None):
        self.peer = peer
        self.id = activity_id or __import__("uuid").uuid4().hex
        self.state = STARTED
        self.future: Future = Future()
        self._transitions = self._collect_transitions()
        # transitions of ONE activity must serialize: the manager's worker
        # pool can otherwise run two messages of the same conversation
        # concurrently, racing FSM state (the reference serializes through
        # per-activity action queues; our heap pops can interleave).
        # RLock: complete()/fail() take it too, and transitions call them
        # from inside handle() with the lock already held.
        self._handle_lock = threading.RLock()

    @classmethod
    def _collect_transitions(cls) -> list:
        out = []
        for name in dir(cls):
            fn = getattr(cls, name, None)
            if callable(fn) and hasattr(fn, "_from_state"):
                out.append(fn)
        return out

    # -- lifecycle ----------------------------------------------------------
    def initiate(self) -> None:
        """Client-side kick-off: send the opening message."""

    def handle(self, sender: str, msg: dict) -> None:
        """Dispatch to the matching @from_state transition (serialized per
        activity — see ``_handle_lock``)."""
        with self._handle_lock:
            if self.state in TERMINAL:
                return  # late message after completion: drop, don't fail
            for fn in self._transitions:
                if fn._from_state == self.state and (
                    fn._performative is None
                    or fn._performative == msg.get("performative")
                ):
                    fn(self, sender, msg)
                    return
            self.fail(f"no transition from {self.state} "
                      f"for {msg.get('performative')}")

    def complete(self, result: Any = None) -> None:
        # state writes race handle()'s state reads when a caller (timeout
        # path, peer shutdown) terminates the activity from another thread
        # (hglint HG402) — reentrant from within a transition
        with self._handle_lock:
            self.state = COMPLETED
            if not self.future.done():
                self.future.set_result(result)

    def fail(self, reason: Any) -> None:
        with self._handle_lock:
            self.state = FAILED
            if not self.future.done():
                self.future.set_exception(
                    reason if isinstance(reason, Exception)
                    else RuntimeError(str(reason))
                )

    # -- conveniences --------------------------------------------------------
    def send(self, target: str, performative: str, content: Any = None,
             trace_ctx: Optional[dict] = None) -> None:
        """Send an activity message; ``trace_ctx`` (a ``Trace.context()``
        dict) stamps it for cross-process span-tree propagation."""
        self.peer.interface.send(
            target, M.attach_trace(
                M.make_message(performative, self.TYPE, content, self.id),
                trace_ctx,
            )
        )

    def reply(self, target: str, msg: dict, performative: str,
              content: Any = None,
              trace_ctx: Optional[dict] = None) -> None:
        self.peer.interface.send(target, M.attach_trace(
            M.reply_to(msg, performative, content), trace_ctx,
        ))


class ActivityManager:
    """Fair scheduler over per-activity action queues.

    Priority = enqueue-timestamp − backlog·age_weight: older and more
    backed-up activities run first (the ``ActivityManager.java:63-103``
    aging rule), drained by a small worker pool.
    """

    def __init__(self, peer, workers: int = 2, age_weight: float = 0.001,
                 tick_interval: float = 0.25):
        self.peer = peer
        self.age_weight = age_weight
        #: watchdog cadence: live activities exposing a ``tick(now)``
        #: method (e.g. TransferGraphClient's stall-resume) get called
        #: every interval — the timer infrastructure the message-driven
        #: FSMs otherwise lack; 0 disables the ticker
        self.tick_interval = tick_interval
        self._activities: dict[tuple[str, str], Activity] = {}
        self._factories: dict[str, Callable[..., Activity]] = {}
        self._queues: dict[tuple[str, str], list] = {}
        self._heap: list = []
        self._cv = threading.Condition()
        self._running = False
        self._workers = [
            threading.Thread(target=self._work, name=f"activity-worker-{i}",
                             daemon=True)
            for i in range(workers)
        ]
        self._stop_evt = threading.Event()
        self._ticker = threading.Thread(
            target=self._tick_loop, name="activity-ticker", daemon=True
        )
        self._seq = 0

    # -- registry -------------------------------------------------------------
    def register_type(self, activity_type: str,
                      factory: Callable[..., Activity]) -> None:
        """Server-side: how to instantiate the responding activity when a
        fresh conversation of this type arrives (bootstrap op analogue)."""
        self._factories[activity_type] = factory

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        for w in self._workers:
            w.start()
        if self.tick_interval:
            self._ticker.start()

    def stop(self) -> None:
        self._running = False
        self._stop_evt.set()
        with self._cv:
            self._cv.notify_all()
        for w in self._workers:
            w.join(timeout=5)
        if self._ticker.is_alive():
            self._ticker.join(timeout=5)

    def _tick_loop(self) -> None:
        import logging

        while not self._stop_evt.wait(self.tick_interval):
            with self._cv:
                acts = list(self._activities.values())
            now = time.monotonic()
            for act in acts:
                tick = getattr(act, "tick", None)
                if tick is not None:
                    try:
                        tick(now)
                    except Exception:  # a bug must not kill the timer
                        logging.getLogger(
                            "hypergraphdb_tpu.peer"
                        ).exception("activity tick failed")
                if act.state in TERMINAL:
                    # reap activities that reached a terminal state
                    # OUTSIDE a handle() transition (e.g. a watchdog
                    # fail(), or completion inside initiate()): _work
                    # only cleans up after messages, so these would
                    # otherwise sit in the registry forever
                    with self._cv:
                        key = (act.TYPE, act.id)
                        if self._activities.get(key) is act:
                            self._activities.pop(key, None)
                            self._queues.pop(key, None)

    # -- activity lifecycle ----------------------------------------------------
    def initiate(self, activity: Activity) -> Activity:
        key = (activity.TYPE, activity.id)
        with self._cv:
            self._activities[key] = activity
        activity.initiate()
        return activity

    def on_message(self, sender: str, msg: dict) -> None:
        """Transport handler: route to the owning activity's queue,
        instantiating a responder for fresh conversations."""
        atype = msg.get("activity_type")
        aid = msg.get("activity_id")
        if not atype or not aid:
            return
        key = (atype, aid)
        with self._cv:
            act = self._activities.get(key)
            if act is None:
                factory = self._factories.get(atype)
                if factory is None:
                    return
                act = factory(self.peer, activity_id=aid)
                self._activities[key] = act
            q = self._queues.setdefault(key, [])
            q.append((sender, msg))
            # fairness: older first, long backlogs boosted
            prio = time.monotonic() - len(q) * self.age_weight
            self._seq += 1
            heapq.heappush(self._heap, (prio, self._seq, key))
            self._cv.notify()

    def _work(self) -> None:
        while True:
            with self._cv:
                while self._running and not self._heap:
                    self._cv.wait(timeout=0.5)
                if not self._running:
                    return
                _, _, key = heapq.heappop(self._heap)
                q = self._queues.get(key)
                if not q:
                    continue
                sender, msg = q.pop(0)
                act = self._activities.get(key)
            if act is None or act.state in TERMINAL:
                continue
            try:
                act.handle(sender, msg)
            except Exception as e:  # a failing transition fails the activity
                act.fail(e)
            if act.state in TERMINAL:
                with self._cv:
                    self._activities.pop(key, None)
                    self._queues.pop(key, None)
