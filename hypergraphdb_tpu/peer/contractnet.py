"""Contract-net conversations — the ``ProposalConversation`` analogue.

The reference's workflow package ships a FIPA contract-net conversation
(``peer/workflow/ProposalConversation``, used with ``Conversation`` FSMs):
an initiator calls for proposals, participants bid (PROPOSE) or REFUSE,
the initiator accepts exactly one bid and rejects the rest, and the
accepted participant performs the task and reports the result. This module
re-expresses that protocol on the activity framework's ``@from_state``
FSM machinery (``peer/activity.py``) over any transport.

Usage::

    # participant side (each peer that can serve tasks):
    class Worker(TaskParticipant):
        def bid(self, task):      # None → REFUSE
            return {"cost": my_cost(task)}
        def perform(self, task):
            return do_work(task)
    peer.activities.register_type(
        ContractNet.TYPE, lambda peer, activity_id=None:
        Worker(peer, activity_id=activity_id))

    # initiator side:
    act = peer.activities.initiate(ContractNet(
        peer, task={"op": "count"}, participants=[p1, p2, p3]))
    winner, result = act.future.result(timeout=10)
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from hypergraphdb_tpu.peer import messages as M
from hypergraphdb_tpu.peer.activity import (
    STARTED,
    Activity,
    from_state,
)

WAITING_PROPOSALS = "WaitingProposals"
WAITING_RESULT = "WaitingResult"
PROPOSED = "Proposed"


def lowest_cost(bids: dict[str, Any]):
    """Default bid selector: minimal ``cost`` field (ties → peer id)."""
    return min(
        bids,
        key=lambda pid: (
            (bids[pid] or {}).get("cost", float("inf")), pid
        ),
    )


class ContractNet(Activity):
    """Initiator: call for proposals → collect bids → accept one →
    await the winner's result. ``future`` resolves to ``(winner_id,
    result)``; it fails if every participant refuses or the winner
    reports FAILURE."""

    TYPE = "contract-net"

    def __init__(self, peer, task: Any, participants: list[str],
                 select: Optional[Callable[[dict], str]] = None,
                 activity_id: Optional[str] = None):
        super().__init__(peer, activity_id)
        self.task = task
        self.participants = list(participants)
        self.select = select or lowest_cost
        self.bids: dict[str, Any] = {}
        self.refusals: set[str] = set()
        self.winner: Optional[str] = None

    def initiate(self) -> None:
        if not self.participants:
            self.fail("no participants to call for proposals")
            return
        self.state = WAITING_PROPOSALS
        for pid in self.participants:
            self.send(pid, M.REQUEST, {"what": "cfp", "task": self.task})

    def _maybe_decide(self) -> None:
        if len(self.bids) + len(self.refusals) < len(self.participants):
            return
        if not self.bids:
            self.fail("all participants refused the call for proposals")
            return
        self.winner = self.select(self.bids)
        for pid in self.bids:
            if pid == self.winner:
                self.send(pid, M.ACCEPT_PROPOSAL, {"task": self.task})
            else:
                self.send(pid, M.REJECT_PROPOSAL, None)
        self.state = WAITING_RESULT

    @from_state(WAITING_PROPOSALS, M.PROPOSE)
    def on_propose(self, sender: str, msg: dict) -> None:
        # only invited participants count, and a peer answers ONCE — a
        # stray or duplicate reply must not trip the decision threshold
        # early and strand a real bidder in PROPOSED forever
        if sender not in self.participants or sender in self.refusals:
            return
        self.bids[sender] = msg.get("content")
        self._maybe_decide()

    @from_state(WAITING_PROPOSALS, M.REFUSE)
    def on_refuse(self, sender: str, msg: dict) -> None:
        if sender not in self.participants or sender in self.bids:
            return
        self.refusals.add(sender)
        self._maybe_decide()

    @from_state(WAITING_RESULT, M.INFORM)
    def on_result(self, sender: str, msg: dict) -> None:
        if sender == self.winner:
            self.complete((sender, msg.get("content")))

    @from_state(WAITING_RESULT, M.FAILURE)
    def on_failure(self, sender: str, msg: dict) -> None:
        if sender == self.winner:
            self.fail(f"winner {sender} failed: {msg.get('content')}")

    # late bids/refusals after the decision are protocol noise, not errors
    @from_state(WAITING_RESULT, M.PROPOSE)
    def on_late_propose(self, sender: str, msg: dict) -> None:
        if sender in self.participants and sender not in self.bids:
            self.send(sender, M.REJECT_PROPOSAL, None)

    @from_state(WAITING_RESULT, M.REFUSE)
    def on_late_refuse(self, sender: str, msg: dict) -> None:
        pass


class TaskParticipant(Activity):
    """Participant FSM: bid on a CFP, then perform if accepted. Subclasses
    implement :meth:`bid` (return None to refuse) and :meth:`perform`."""

    TYPE = ContractNet.TYPE

    def bid(self, task: Any) -> Optional[dict]:  # pragma: no cover - abstract
        return None

    def perform(self, task: Any) -> Any:  # pragma: no cover - abstract
        raise NotImplementedError

    @from_state(STARTED, M.REQUEST)
    def on_cfp(self, sender: str, msg: dict) -> None:
        content = msg.get("content") or {}
        self.task = content.get("task")
        try:
            offer = self.bid(self.task)
        except Exception:
            import logging

            logging.getLogger("hypergraphdb_tpu.peer").warning(
                "bid() raised; refusing the call for proposals",
                exc_info=True,
            )
            offer = None
        if offer is None:
            self.reply(sender, msg, M.REFUSE)
            self.complete(None)
        else:
            self.reply(sender, msg, M.PROPOSE, offer)
            self.state = PROPOSED

    @from_state(PROPOSED, M.ACCEPT_PROPOSAL)
    def on_accept(self, sender: str, msg: dict) -> None:
        try:
            result = self.perform(self.task)
        except Exception as e:
            self.reply(sender, msg, M.FAILURE, str(e))
            self.fail(e)
            return
        self.reply(sender, msg, M.INFORM, result)
        self.complete(result)

    @from_state(PROPOSED, M.REJECT_PROPOSAL)
    def on_reject(self, sender: str, msg: dict) -> None:
        self.complete(None)
