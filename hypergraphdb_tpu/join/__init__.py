"""hgjoin: worst-case-optimal conjunctive pattern joins on TPU.

The subsystem that closes ROADMAP item 1: arbitrary conjunctive
patterns over incidence sets — triangles, paths, stars, anchored
multi-atom conjunctions — planned as left-deep generalized hypertree
decompositions (:mod:`~hypergraphdb_tpu.join.planner`) and executed as
batched per-variable multiway intersections on the CSR snapshot
(:mod:`~hypergraphdb_tpu.ops.join`), with ``graph.find_all``-based
exact host evaluation (:mod:`~hypergraphdb_tpu.join.host`) as both the
differential oracle and the serving fallback lane.

Entry points::

    from hypergraphdb_tpu import join
    p = join.extract_pattern(g, {
        "y": q.co_incident(join.var("z")) & ...,  # condition spec
        "z": ...,
    })
    sig, consts = join.split_constants(p)
    plan = join.plan_join(g.snapshot(), p)
    tuples = join.host_join(g, p)                 # exact ground truth

Serving rides ``ServeRuntime.submit_join`` / ``query.bridge.
to_join_request`` — see the README "Pattern joins" section.
"""

from hypergraphdb_tpu.join.host import (
    host_join,
    host_join_count,
    host_join_touching,
)
from hypergraphdb_tpu.join.ir import (
    ConjunctivePattern,
    JoinAtom,
    JoinUnsupported,
    PatternSignature,
    extract_pattern,
    pattern_to_conditions,
    split_constants,
)
from hypergraphdb_tpu.join.planner import (
    BagJoin,
    BushyJoinPlan,
    DeviceJoinPlan,
    JoinPlan,
    JoinStep,
    hub_lane_mask,
    plan_join,
)
from hypergraphdb_tpu.query.variables import Var, var

__all__ = [
    "BagJoin",
    "BushyJoinPlan",
    "ConjunctivePattern",
    "DeviceJoinPlan",
    "JoinAtom",
    "JoinPlan",
    "JoinStep",
    "JoinUnsupported",
    "PatternSignature",
    "Var",
    "extract_pattern",
    "host_join",
    "host_join_count",
    "host_join_touching",
    "hub_lane_mask",
    "pattern_to_conditions",
    "plan_join",
    "split_constants",
    "var",
]
