"""Join planning: variable elimination orders from cardinality estimates.

The EmptyHeaded recipe (PAPERS.md) specialized to this engine: a
conjunctive pattern becomes a **generalized hypertree decomposition** —
one bag per variable, processed in an elimination order chosen greedily
to minimize the expected binding-table growth at every step. Acyclic
patterns (paths, stars) get the classic width-1 GHD; cyclic ones
(triangles, loops) keep every extra atom as a membership filter on the
step that closes the cycle, which is exactly the worst-case-optimal
leapfrog discipline (TrieJax, PAPERS.md): never materialize a binary
join larger than the intersection the full conjunction allows.

Two plan shapes come out (join engine v2):

* **Left-deep** (:class:`JoinPlan`) — one chain binding every variable,
  the PR-10 executor's shape and still the default for single-component
  patterns.
* **Bushy** (:class:`BushyJoinPlan`) — when the pattern's variable-
  variable atom graph falls into ≥2 connected components (star-of-stars
  shapes: independently-anchored sub-patterns), each component plans as
  its own chain; the cheapest becomes the SPINE and the rest become
  materialized **bags** (EmptyHeaded's GHD bags) joined onto the spine
  by ``ops/join.join_bag_join`` with cross-component distinctness — a
  bag's multi-step chain runs once per batch instead of once per spine
  binding row.

The degree-split half of v2 also lives here as policy:
:func:`hub_lane_mask` decides which request lanes anchor on rows wider
than the hub threshold — those run the chunked dense-frontier chain
(``ops/join.join_hub_expand``) instead of the padded tail path, so hub
anchors stop falling off the device path.

Cardinalities come from the same places the host planner's
``estimate()`` chain reads — snapshot CSR offsets (exact row widths for
constant-anchored atoms, the device twin of
``compiler._capped_range_estimate``'s exact-count-first policy) and
whole-relation averages for variable-keyed expansions. Byte costs are
seeded from the committed hgverify budgets (``tools/hgverify/
costs.json`` — the statically verified bytes-per-probe of the executor
kernels), so the cost-based ``translate()`` comparison against
``IntersectPlan`` speaks the same unit the verification gate enforces.

The planner decides SEMANTICS only: the order, each step's expansion
source and membership filters. Shapes (expansion pads, row buckets) are
the executor's call at launch time, where the actual batch's anchor
widths are known (``ops/join.execute_join``).
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from hypergraphdb_tpu.join.ir import (
    ConjunctivePattern,
    JoinAtom,
    JoinUnsupported,
    PatternSignature,
    split_constants,
)

logger = logging.getLogger("hypergraphdb_tpu.join")


@dataclass(frozen=True)
class KeyRef:
    """Where a step's key comes from at run time: a bound binding-table
    column (``col``) or a per-request constant slot (``const``)."""

    kind: str   # "col" | "const"
    index: int


@dataclass(frozen=True)
class FilterSpec:
    """One membership filter on a step's candidates. ``rev=False``:
    candidate ∈ row(key) of ``rel``'s CSR; ``rev=True``: key ∈
    row(candidate) (the dual direction — used where the forward row is
    unsorted, e.g. target tuples)."""

    rel: str    # "co" | "inc"
    rev: bool
    key: KeyRef


@dataclass(frozen=True)
class JoinStep:
    """Bind one variable: gather candidate rows from ``source_rel`` keyed
    by ``source_key``, then intersect against every filter (the
    per-variable multiway intersection of the WCO loop)."""

    var: str
    source_rel: str          # "co" | "inc" | "tgt"
    source_key: KeyRef
    filters: tuple = ()
    type_handle: Optional[int] = None
    dedupe: bool = False     # tgt expansions may repeat values
    width_est: float = 1.0   # expected expansion row width (planning)


@dataclass(frozen=True)
class JoinPlan:
    """The compiled decomposition: elimination order + per-variable
    steps. ``order[i]`` binds to binding-table column ``i``."""

    sig: PatternSignature
    order: tuple[str, ...]
    steps: tuple[JoinStep, ...]
    distinct: bool
    n_consts: int
    est_rows: float          # expected bindings per request (planning)

    def describe(self) -> str:
        parts = []
        for s in self.steps:
            key = (f"${s.source_key.index}" if s.source_key.kind == "const"
                   else self.order[s.source_key.index])
            extra = f"+{len(s.filters)}f" if s.filters else ""
            parts.append(f"{s.var}←{s.source_rel}({key}){extra}")
        return "join[" + " ⋈ ".join(parts) + "]"


def _describe_chain(order: tuple, steps) -> str:
    parts = []
    for s in steps:
        key = (f"${s.source_key.index}" if s.source_key.kind == "const"
               else order[s.source_key.index])
        extra = f"+{len(s.filters)}f" if s.filters else ""
        parts.append(f"{s.var}←{s.source_rel}({key}){extra}")
    return " ⋈ ".join(parts)


@dataclass(frozen=True)
class BagJoin:
    """One materialized GHD bag of a bushy plan: a variable-connected
    component planned as its own chain. ``vars`` is the bag's local
    elimination order (its steps' ``col`` KeyRefs index the BAG's own
    binding table); the executor materializes the bag once per batch and
    joins its output onto the spine (``ops/join.join_bag_join``)."""

    vars: tuple[str, ...]
    steps: tuple[JoinStep, ...]
    est_rows: float


@dataclass(frozen=True)
class BushyJoinPlan:
    """A bushy decomposition: the SPINE chain (cheapest component) plus
    one materialized bag per remaining component, folded on in ``bags``
    order. ``order`` concatenates the spine's and each bag's local
    orders — binding-table column ``i`` holds ``order[i]`` after the
    last fold, so downstream consumers (finalize permutations, result
    assembly) read it exactly like a left-deep plan's."""

    sig: PatternSignature
    order: tuple[str, ...]
    spine: tuple[JoinStep, ...]
    bags: tuple[BagJoin, ...]
    distinct: bool
    n_consts: int
    est_rows: float

    @property
    def steps(self) -> tuple:
        """Every step across spine and bags — the flat view cost models
        and dispatch annotations read; executors MUST dispatch on
        ``bags`` instead (the chains have disjoint column spaces)."""
        return self.spine + tuple(
            s for b in self.bags for s in b.steps
        )

    def describe(self) -> str:
        spine = _describe_chain(self.order, self.spine)
        bags = " ⊗ ".join(
            "[" + _describe_chain(b.vars, b.steps) + "]"
            for b in self.bags
        )
        return f"bushy[{spine} ⊗ {bags}]"


# ---------------------------------------------------------------- statistics


class _Stats:
    """Planning cardinalities over one CSRSnapshot's host arrays."""

    def __init__(self, snap):
        self.snap = snap
        n = snap.num_atoms
        live = max(int((snap.type_of[:n] >= 0).sum()), 1)
        ar = snap.arity[:n].astype(np.int64)
        links = max(int((ar > 0).sum()), 1)
        self.avg = {
            # expected row widths per relation for variable-keyed
            # expansions (whole-relation averages)
            "co": float((ar * np.maximum(ar - 1, 0)).sum()) / live,
            "inc": float(snap.n_edges_inc) / live,
            "tgt": float(snap.n_edges_tgt) / links,
        }
        # skew guard: on zipf-shaped graphs the MEAN row width wildly
        # undersells what a variable-keyed expansion will actually
        # gather (one hub neighbour pays the hub's whole row), which
        # made the greedy prefer an "average-cheap" var expansion over
        # an exactly-bounded constant row and truncate on every hub.
        # Cost var-keyed candidates at a high quantile of the POSITIVE
        # widths instead — planning estimate only, shapes still come
        # from the executor.
        inc_w = np.diff(snap.inc_offsets[: n + 1].astype(np.int64))
        inc_p99 = self._q99(inc_w[inc_w > 0])
        avg_arity = float(snap.n_edges_tgt) / links
        self.p99 = {
            # a co row is roughly Σ (arity-1) over the atom's incident
            # links — approximated from the incidence tail × mean arity
            # (building the real neighbour CSR here would cost more
            # than the plan it prices)
            "co": inc_p99 * max(avg_arity - 1.0, 1.0),
            "inc": inc_p99,
            "tgt": self._q99(ar[ar > 0]),
        }

    @staticmethod
    def _q99(widths: np.ndarray) -> float:
        return float(np.percentile(widths, 99)) if len(widths) else 0.0

    def const_width(self, rel: str, handle: int) -> float:
        """EXACT expansion width of a constant-keyed atom (CSR offsets
        diff — the count-first half of the ``_capped_range_estimate``
        policy)."""
        s = self.snap
        if handle < 0 or handle >= s.num_atoms:
            return 0.0
        if rel == "inc":
            return float(s.inc_offsets[handle + 1] - s.inc_offsets[handle])
        if rel == "tgt":
            return float(s.arity[handle])
        # co: each incident link contributes (arity - 1) co-targets —
        # an upper bound (shared neighbours dedupe), cheap and exact
        # enough to order anchors
        row = s.inc_links[s.inc_offsets[handle]: s.inc_offsets[handle + 1]]
        return float(np.maximum(s.arity[row].astype(np.int64) - 1, 0).sum())

    def var_width(self, rel: str) -> float:
        return max(self.avg[rel], self.p99[rel])


# ------------------------------------------------------- direction resolution


def _expansion_of(atom: JoinAtom, new_var: str) -> str:
    """The CSR an expansion of ``new_var`` through ``atom`` gathers
    from. ``inc(x, y)`` (x is a link containing y) expands x from y's
    incidence row and y from x's target tuple; ``tgt`` is its mirror."""
    if atom.rel == "co":
        return "co"
    if atom.rel == "inc":
        return "inc" if atom.var == new_var else "tgt"
    # tgt(x, y): x ∈ targets(y) — expanding x reads y's target tuple,
    # expanding y (a link containing x) reads x's incidence row
    return "tgt" if atom.var == new_var else "inc"


def _filter_of(atom: JoinAtom, new_var: str, key: KeyRef) -> FilterSpec:
    """The membership test of ``atom`` when ``new_var`` is the candidate
    and the other side is bound. Target tuples are NOT sorted, so tests
    that would probe them run through the incidence dual instead
    (``cand ∈ targets(o)`` ≡ ``o ∈ incidence(cand)`` — rev inc)."""
    if atom.rel == "co":
        return FilterSpec("co", False, key)
    if atom.rel == "inc":
        if atom.var == new_var:        # cand is the link: cand ∈ inc(o)
            return FilterSpec("inc", False, key)
        return FilterSpec("inc", True, key)   # cand ∈ tgt(o) ≡ o ∈ inc(cand)
    # tgt(x, y)
    if atom.var == new_var:            # cand ∈ tgt(o) → dual
        return FilterSpec("inc", True, key)
    return FilterSpec("inc", False, key)      # cand is the link


# ---------------------------------------------------------------- planning


def _greedy_chain(stats: "_Stats", pattern: ConjunctivePattern,
                  slot_of: dict, chain_vars, chain_atoms,
                  seed_var: Optional[str] = None) -> tuple:
    """The greedy elimination core over ONE variable-connected subset:
    seed at the narrowest constant-anchored row, then repeatedly bind
    the connected variable whose cheapest expansion grows the binding
    table least; every other atom touching bound variables becomes a
    membership filter (the WCO intersection). ``col`` KeyRefs index the
    CHAIN's own binding table. Returns ``(order, steps, est_rows)``."""

    def key_ref(atom: JoinAtom, bound_idx: dict) -> KeyRef:
        if atom.key_is_var:
            return KeyRef("col", bound_idx[atom.key])
        return KeyRef("const", slot_of[id(atom)])

    bound: list[str] = []
    bound_idx: dict[str, int] = {}
    steps: list[JoinStep] = []
    remaining = list(chain_vars)
    used: set[int] = set()
    est_rows = 1.0
    if seed_var is not None:
        if seed_var not in remaining:
            raise JoinUnsupported(f"seed variable {seed_var!r} is not a "
                                  "pattern variable")
        # placeholder step: execute_join(seeds=...) replaces it with the
        # caller's candidate column and starts from steps[1:]
        steps.append(JoinStep(var=seed_var, source_rel="co",
                              source_key=KeyRef("const", 0)))
        bound_idx[seed_var] = 0
        bound.append(seed_var)
        remaining.remove(seed_var)
    while remaining:
        best = None  # (width, var, atom, source KeyRef)
        for v in remaining:
            for a in chain_atoms:
                if a.var == v and (not a.key_is_var or a.key in bound_idx):
                    ref = key_ref(a, bound_idx)
                    is_const = not a.key_is_var
                    other = a.key
                elif a.key == v and a.var in bound_idx:
                    ref = KeyRef("col", bound_idx[a.var])
                    is_const = False
                    other = a.var
                else:
                    continue
                if not bound and not is_const:
                    continue  # first variable must seed from a constant
                rel = _expansion_of(a, v)
                w = (stats.const_width(rel, int(other)) if is_const
                     else stats.var_width(rel))
                if best is None or w < best[0]:
                    best = (w, v, a, ref)
        if best is None:
            missing = ", ".join(remaining)
            raise JoinUnsupported(
                "pattern variables unreachable from any constant anchor: "
                f"{missing} (every pattern needs at least one constant-"
                "anchored variable, and every variable a path to one)"
            )
        w, v, src, src_ref = best
        used.add(id(src))
        filters = []
        for a in chain_atoms:
            if id(a) in used:
                continue
            if a.var == v and (not a.key_is_var or a.key in bound_idx):
                filters.append(_filter_of(a, v, key_ref(a, bound_idx)))
                used.add(id(a))
            elif a.key == v and a.var in bound_idx:
                # the atom's var side is bound; candidate is the key side
                filters.append(_filter_of(a, v, KeyRef(
                    "col", bound_idx[a.var]
                )))
                used.add(id(a))
        steps.append(JoinStep(
            var=v,
            source_rel=_expansion_of(src, v),
            source_key=src_ref,
            filters=tuple(filters),
            type_handle=pattern.type_of(v),
            dedupe=_expansion_of(src, v) == "tgt",
            width_est=max(w, 1.0),
        ))
        bound_idx[v] = len(bound)
        bound.append(v)
        remaining.remove(v)
        # filters are selective; the width bound alone keeps est_rows an
        # upper bound, which is what bucket sizing wants
        est_rows *= max(w, 1.0)
    unused = [a for a in chain_atoms if id(a) not in used]
    if unused:
        # only reachable in seed mode: an atom whose endpoints are the
        # seed variable and a constant has no step to ride (the caller's
        # seeds must already satisfy it) — refuse rather than drop it
        raise JoinUnsupported(
            f"atoms {[(a.rel, a.var, a.key) for a in unused]} bind only "
            "pre-seeded variables and constants; no executor step can "
            "apply them"
        )
    return tuple(bound), tuple(steps), est_rows


def _var_components(pattern: ConjunctivePattern) -> list:
    """Connected components of the variable-variable atom graph, in
    ``pattern.vars`` order (a variable with no var-var atoms is its own
    singleton) — the bushy decomposition's bag boundaries: components
    share no variables, only constants."""
    parent = {v: v for v in pattern.vars}

    def find(v):
        while parent[v] != v:
            parent[v] = parent[parent[v]]
            v = parent[v]
        return v

    for a in pattern.atoms:
        if a.key_is_var:
            parent[find(a.var)] = find(a.key)
    comps: dict = {}
    for v in pattern.vars:
        comps.setdefault(find(v), []).append(v)
    return list(comps.values())


def plan_join(snap, pattern: ConjunctivePattern,
              sig: Optional[PatternSignature] = None,
              consts: Optional[Sequence[int]] = None,
              seed_var: Optional[str] = None,
              bushy="auto"):
    """Plan ``pattern`` over ``snap``: a left-deep :class:`JoinPlan`
    (one greedy chain — see :func:`_greedy_chain`) or, for patterns
    whose variable-variable graph splits into ≥2 components, a
    :class:`BushyJoinPlan` with the cheapest component as spine and the
    rest as materialized bags. ``bushy="auto"`` (default) goes bushy
    exactly when a non-trivial bag exists (some component has ≥2
    variables — singleton-only splits like a plain star gain nothing
    over the left-deep chain); ``True``/``False`` force the shape.
    Raises :class:`JoinUnsupported` for patterns no step can seed (no
    constant anchor) or reach (disconnected variables).

    ``seed_var`` pre-binds one variable externally (the caller provides
    its candidates — ``ops/join.execute_join``'s ``seeds`` mode, how an
    UNANCHORED pattern like global triangle counting becomes runnable:
    chunk the id space into seeds, sum the counts). Its step is a
    placeholder the executor skips; seed mode is always left-deep."""
    if sig is None or consts is None:
        sig, consts = split_constants(pattern)
    stats = _Stats(snap)
    slot_of: dict[int, int] = {}
    # atom order == slot order (split_constants contract)
    slot = 0
    for a in pattern.atoms:
        if not a.key_is_var:
            slot_of[id(a)] = slot
            slot += 1
    comps = _var_components(pattern)
    use_bushy = (
        seed_var is None and len(comps) >= 2
        and (bushy is True
             or (bushy == "auto" and any(len(c) >= 2 for c in comps)))
    )
    if not use_bushy:
        order, steps, est_rows = _greedy_chain(
            stats, pattern, slot_of, list(pattern.vars),
            list(pattern.atoms), seed_var,
        )
        return JoinPlan(
            sig=sig, order=order, steps=steps,
            distinct=pattern.distinct, n_consts=sig.n_consts,
            est_rows=est_rows,
        )
    planned = []
    for comp in comps:
        comp_set = set(comp)
        atoms_c = [a for a in pattern.atoms
                   if a.var in comp_set
                   or (a.key_is_var and a.key in comp_set)]
        planned.append(_greedy_chain(stats, pattern, slot_of,
                                     list(comp), atoms_c))
    # fold the cheapest chains first: every bag join's output is the
    # running product, so ascending size keeps intermediates minimal
    planned.sort(key=lambda t: t[2])
    spine_order, spine_steps, spine_est = planned[0]
    bags = tuple(
        BagJoin(vars=o, steps=s, est_rows=e) for o, s, e in planned[1:]
    )
    order = spine_order + tuple(v for b in bags for v in b.vars)
    est_rows = spine_est
    for b in bags:
        est_rows *= max(b.est_rows, 1.0)
    return BushyJoinPlan(
        sig=sig, order=order, spine=spine_steps, bags=bags,
        distinct=pattern.distinct, n_consts=sig.n_consts,
        est_rows=est_rows,
    )


# ---------------------------------------------------------- degree split


def hub_lane_mask(snap, steps, consts: np.ndarray,
                  threshold: int) -> np.ndarray:
    """The degree-split policy (plan-level, applied to one batch's
    constant vectors): a lane is a HUB lane when any const-keyed step
    would expand a row wider than ``threshold`` — exactly the lanes the
    tail path's pads cannot hold, which PR 10 truncated onto the exact
    host lane. Hub lanes run the chunked dense-frontier chain instead
    (``ops/join.join_hub_expand``); dedupe (tgt) steps stay on the tail
    kernel and don't qualify a lane. O(steps × K) host arithmetic over
    CSR offsets already resident."""
    from hypergraphdb_tpu.ops.join import _rel_host_offsets

    consts = np.asarray(consts)
    mask = np.zeros(len(consts), dtype=bool)
    if not len(consts):
        return mask
    for s in steps:
        if s.source_key.kind != "const" or s.dedupe:
            continue
        off = np.asarray(_rel_host_offsets(snap, s.source_rel),
                         dtype=np.int64)
        keys = np.clip(consts[:, s.source_key.index].astype(np.int64),
                       0, snap.num_atoms)
        mask |= (off[keys + 1] - off[keys]) > threshold
    return mask


# ---------------------------------------------------------------- cost model


#: fallback bytes-per-candidate-probe when no committed budget exists yet
_DEFAULT_PROBE_BYTES = 24.0

_cost_cache: Optional[dict] = None


def _hgverify_costs() -> dict:
    """The committed hgverify budgets (``tools/hgverify/costs.json``) —
    the statically verified per-entry byte counts the planner's cost
    model is seeded from. Missing file / entries → empty (defaults
    apply)."""
    global _cost_cache
    if _cost_cache is None:
        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            "tools", "hgverify", "costs.json",
        )
        try:
            with open(path, encoding="utf-8") as f:
                _cost_cache = json.load(f).get("entries", {})
        except Exception:  # noqa: BLE001 - tools tree absent at runtime
            _cost_cache = {}
    return _cost_cache


def probe_bytes() -> float:
    """Bytes one candidate costs through one expand+filter round,
    normalized from the committed ``ops.join.join_expand_step`` budget's
    exemplar (R×pad candidate slots — see ``ops/join.EXEMPLAR_SLOTS``)."""
    entry = _hgverify_costs().get("ops.join.join_expand_step")
    if not entry:
        return _DEFAULT_PROBE_BYTES
    try:
        from hypergraphdb_tpu.ops.join import EXEMPLAR_SLOTS

        return max(float(entry["bytes_accessed"]) / EXEMPLAR_SLOTS, 1.0)
    except Exception:  # noqa: BLE001 - keep planning alive regardless
        return _DEFAULT_PROBE_BYTES


def device_cost_bytes(plan) -> float:
    """Expected device bytes for ONE request through ``plan`` — binding
    rows × expansion width × per-probe bytes × (1 + filters), summed
    over steps. Bushy plans charge each chain independently plus the
    product fold (one probe per joined row) — the bushy-vs-left-deep
    saving the shape choice banks on."""
    per_probe = probe_bytes()

    def chain(steps):
        rows = 1.0
        total = 0.0
        for s in steps:
            total += rows * s.width_est * per_probe * (1 + len(s.filters))
            rows *= s.width_est
        return total, rows

    bags = getattr(plan, "bags", None)
    if bags is None:
        return chain(plan.steps)[0]
    total, rows = chain(plan.spine)
    for b in bags:
        bag_total, bag_rows = chain(b.steps)
        total += bag_total + rows * bag_rows * per_probe
        rows *= bag_rows
    return total


#: host bytes one intersection element costs (sorted-merge over int64
#: arrays: read both sides + write; the IntersectPlan unit)
_HOST_BYTES_PER_ELEM = 24.0

#: host bytes one co-incidence PAIR costs to materialize (repeat +
#: lexsort + dedupe temps in ``ops/join.neighbor_csr``) — charged to
#: the device arm when the snapshot has no cached neighbour CSR yet,
#: so a one-shot query never pays a multi-GB build the host answer
#: would have skipped
_NBR_BUILD_BYTES_PER_PAIR = 32.0


def host_cost_bytes(graph, fallback_plan) -> float:
    """The classic host translation's byte estimate, from the same
    ``estimate()`` chain ``IntersectPlan.run`` orders children with."""
    try:
        est = float(fallback_plan.estimate(graph))
    except Exception:  # noqa: BLE001 - estimate must never kill planning
        return float("inf")
    if est == float("inf"):
        return est
    return max(est, 1.0) * _HOST_BYTES_PER_ELEM


# ------------------------------------------------------------- compiler hook


class DeviceJoinPlan:
    """``query/compiler.Plan`` for a single-variable conjunctive pattern
    (``And(CoIncident+, Incident*, [AtomType], [AtomValue{1,2}])``)
    answered by the multiway-intersection executor. Cost-based at run
    time, the ``DeviceValueConjPlan`` discipline: small inputs and
    device-hostile states (stale anchors, pending deletes) take the
    classic host ``fallback``; fresh link ingest is corrected host-side
    over the memtable, exact at any lag. ``value_conds`` push down as
    rank-window filters on the executor's intersection candidates
    (``ops/join.execute_join`` ``value_windows`` — the hgindex hook);
    variable-width value kinds decline to the host plan (rank ties)."""

    def __init__(self, pattern: ConjunctivePattern, fallback,
                 value_conds=()):
        self.pattern = pattern
        self.fallback = fallback
        self.value_conds = tuple(value_conds)
        sig, consts = split_constants(pattern)
        self.sig = sig
        self.consts = consts

    def _value_window(self, graph):
        """The executor window for ``value_conds`` —
        ``(kind, lo_rank, lo_op, hi_rank, hi_op)`` — or None for no
        conditions; raises ``JoinUnsupported`` for shapes the rank
        compare cannot serve exactly. The kind/rank/exactness rules are
        NOT re-implemented here: the conds fold into bounds and
        ``query/bridge.to_range_request`` (the one owner of those rules)
        derives the window — so the join pushdown and the range serve
        lane can never diverge on which predicates are device-exact."""
        if not self.value_conds:
            return None
        from hypergraphdb_tpu.query.bridge import to_range_request
        from hypergraphdb_tpu.serve.types import Unservable

        lo = hi = None
        lo_op, hi_op = "gte", "lte"
        for vc in self.value_conds:
            if vc.op == "eq":
                if lo is not None or hi is not None:
                    raise JoinUnsupported("eq beside another bound")
                lo = hi = vc.value
            elif vc.op in ("gt", "gte"):
                if lo is not None:
                    raise JoinUnsupported("two lower bounds")
                lo, lo_op = vc.value, vc.op
            elif vc.op in ("lt", "lte"):
                if hi is not None:
                    raise JoinUnsupported("two upper bounds")
                hi, hi_op = vc.value, vc.op
            else:
                raise JoinUnsupported(f"value op {vc.op!r}")
        try:
            req = to_range_request(graph, lo, hi, lo_op=lo_op, hi_op=hi_op)
        except Unservable as e:
            raise JoinUnsupported(str(e)) from e
        if not req.exact:
            raise JoinUnsupported(
                "variable-width value kind: rank windows tie"
            )
        return (
            req.dim,
            req.lo_rank,
            req.lo_op if lo is not None else None,
            req.hi_rank,
            req.hi_op if hi is not None else None,
        )

    def run(self, graph):
        import numpy as np

        from hypergraphdb_tpu.obs import global_tracer

        cfg = graph.config.query
        # planner duality in the cost model's own unit: if the host can
        # answer for less than one ad-hoc dispatch amortizes
        # (device_min_batch rows' worth of host bytes — CALIBRATION.md
        # §2), stay host. Gating on the raw ROW estimate here would
        # demand anchors so wide the executor's default pads could never
        # hold them — the arm would be unreachable by construction.
        host_cost = host_cost_bytes(graph, self.fallback)
        if host_cost < cfg.device_min_batch * _HOST_BYTES_PER_ELEM:
            return self.fallback.run(graph)
        mgr = graph.incremental
        if mgr is not None:
            snap, dead, new_atoms, revalued = mgr.read_view()
        else:
            snap = graph.snapshot()
            dead = revalued = frozenset()
            new_atoms = ()
        if any(a >= snap.num_atoms or a < 0 for a in self.consts):
            return self.fallback.run(graph)  # anchors beyond the base
        if dead or revalued:
            # a vanished link may have been a result's only witness; the
            # device result is not correctable without per-result
            # re-verification — the host plan is exact and fresh
            graph.metrics.incr("query.join.host")
            return self.fallback.run(graph)
        tracer = global_tracer()
        try:
            vwin = self._value_window(graph)
            with tracer.span("join.plan"):
                plan = plan_join(snap, self.pattern, self.sig, self.consts)
            from hypergraphdb_tpu.ops.join import (
                execute_join,
                nbr_pair_count,
            )

            dev_cost = device_cost_bytes(plan)
            if getattr(snap, "_nbr_csr", None) is None and any(
                a.rel == "co" for a in self.pattern.atoms
            ):
                # first co-query on this snapshot pays the relation
                # build — a real cost the probe-byte model cannot see
                dev_cost += nbr_pair_count(snap) * _NBR_BUILD_BYTES_PER_PAIR
            if dev_cost > host_cost:
                graph.metrics.incr("query.join.host")
                return self.fallback.run(graph)
            with tracer.span("join.execute", plan=plan.describe()):
                out = execute_join(
                    snap, plan,
                    np.asarray([self.consts], dtype=np.int32),
                    top_r=0, count_only=False, full=True,
                    # one-shot find_all wants the full set, not an
                    # honest prefix: exact pads and roomy caps (one
                    # lane — the slot budget still bounds peak memory)
                    var_pad_max=True, pad_cap=1 << 18, row_cap=1 << 20,
                    value_windows=(None if vwin is None
                                   else {plan.order[0]: vwin}),
                )
                if bool(np.asarray(out.trunc)[0]):
                    # a capped device run is a PREFIX; one-shot find_all
                    # promises the full set — the host plan delivers it
                    graph.metrics.incr("query.join.host")
                    return self.fallback.run(graph)
                rows = out.full_bindings(0)
        except JoinUnsupported:
            graph.metrics.incr("query.join.host")
            return self.fallback.run(graph)
        except Exception:  # noqa: BLE001 - device surprise → exact host
            logger.warning("device join failed; host fallback",
                           exc_info=True)
            graph.metrics.incr("query.join.host")
            return self.fallback.run(graph)
        graph.metrics.incr("query.join.device")
        arr = np.unique(rows[:, 0]).astype(np.int64) if len(rows) \
            else np.empty(0, dtype=np.int64)
        fresh = _memtable_candidates(graph, new_atoms, revalued, dead)
        if fresh:
            cond = _single_var_condition(self.pattern)
            extra = [
                h for h in fresh
                if cond.satisfies(graph, h)
                and all(vc.satisfies(graph, h) for vc in self.value_conds)
            ]
            if extra:
                arr = np.union1d(arr, np.asarray(extra, dtype=np.int64))
        return arr

    def estimate(self, graph):
        ests = []
        for a in self.pattern.atoms:
            if a.key_is_var:
                continue
            n = float(graph.store.incidence_count(int(a.key)))
            ests.append(2.0 * n if a.rel == "co" else n)
        return min(ests) if ests else float("inf")

    def describe(self):
        try:
            return f"device-join({self.sig.atoms})"
        except Exception:  # noqa: BLE001 - describe must never raise
            return "device-join"


def _memtable_candidates(graph, new_atoms, revalued, dead) -> list:
    """Atoms a memtable LINK could have pulled into a co-incidence
    result: the new links themselves plus every target of one. New
    nodes alone cannot create adjacency (nothing points at them from
    the base)."""
    out: set[int] = set()
    for h in set(new_atoms) - set(dead):
        try:
            ts = graph.get_targets(h)
        except Exception:
            continue
        if ts:
            out.add(int(h))
            out.update(int(t) for t in ts)
    return sorted(out)


def _single_var_condition(pattern: ConjunctivePattern):
    from hypergraphdb_tpu.join.ir import pattern_to_conditions

    (cond,) = pattern_to_conditions(pattern).values()
    return cond


def try_single_var_join(graph, clauses, fallback, value_conds=()):
    """Build the single-variable pattern for ``translate()``'s
    ``And(CoIncident+, ...)`` hook — None when extraction declines.
    ``value_conds`` (AtomValue clauses the caller split off) ride the
    plan as executor rank-window filters; shapes the window cannot
    serve exactly decline to the fallback at run time."""
    from hypergraphdb_tpu.join.ir import extract_pattern
    from hypergraphdb_tpu.query import conditions as c

    try:
        # distinct=False: with one variable there are no var-var pairs,
        # and var-vs-const exclusion is already inherent where it is
        # semantically true (CoIncident is irreflexive by construction;
        # Incident(a) legitimately admits a self-targeting a)
        pattern = extract_pattern(
            graph, {"x": c.And(*clauses)}, distinct=False
        )
    except JoinUnsupported:
        return None
    if not any(not a.key_is_var for a in pattern.atoms):
        return None  # no constant anchor: nothing to seed from
    return DeviceJoinPlan(pattern, fallback, value_conds=value_conds)
