"""Conjunctive-pattern IR: variables + incidence/type/link predicates.

The richest capability of the reference's query compiler — arbitrary
conjunctive patterns over incidence sets (``cond2qry/AndToQuery.java``
composing per-variable cursor trees) — expressed as a flat relational IR
the TPU executor can lower (EmptyHeaded's "query language → GHD →
set-intersection plan" pipeline, PAPERS.md).

A pattern is a set of named VARIABLES plus binary atoms over three
relations, every one of which is a sorted-CSR row-membership predicate on
the snapshot (which is what makes the whole pattern servable by the
``ops/setops`` intersection kernels):

=========  =====================================  ======================
relation   meaning                                device rows
=========  =====================================  ======================
``co``     var and key share at least one link    ``ops/join.neighbor_csr``
``inc``    var is a link whose targets include    incidence CSR
           key
``tgt``    var is a target of link key            target CSR (dual of
           (≡ ``key ∈ incidence(var)``)           ``inc``)
=========  =====================================  ======================

plus unary type constraints and an all-distinct flag (vars bind pairwise
distinct atoms, and never a pattern constant — the "simple path/triangle"
convention every counting benchmark assumes).

Extraction (:func:`extract_pattern`) starts from ordinary query
conditions — one condition per variable, cross-references spelled with
``query.variables.Var`` — and reuses the compiler's own normalization
(``expand`` → ``to_dnf`` → ``simplify``) before mapping ``And`` clauses
onto atoms, so every piece of sugar the single-variable pipeline accepts
(``Link``, ``TypedIncident``, ``TypePlus``…) works in a pattern spec too.

:func:`split_constants` factors a pattern into a hashable
:class:`PatternSignature` (the structure — what gets a compiled device
program) plus the constant vector (what varies per request), which is
exactly the serve tier's batch-key/payload split.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping, Optional

from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.variables import Var
from hypergraphdb_tpu.serve.types import Unservable

#: binary relations a pattern atom may use
RELATIONS = ("co", "inc", "tgt")


class JoinUnsupported(Unservable):
    """The condition spec is outside the conjunctive-pattern vocabulary —
    run it through ``graph.find_all`` per variable instead."""


@dataclass(frozen=True)
class JoinAtom:
    """One binary predicate: ``var`` related to ``key`` under ``rel``.
    ``key`` is another variable's name (str) or a constant atom handle
    (int)."""

    rel: str
    var: str
    key: Any

    def __post_init__(self):
        if self.rel not in RELATIONS:
            raise JoinUnsupported(f"unknown join relation {self.rel!r}")

    @property
    def key_is_var(self) -> bool:
        return isinstance(self.key, str)


@dataclass(frozen=True)
class ConjunctivePattern:
    """A normalized conjunctive pattern: ordered variables, binary atoms,
    per-variable type constraints, all-distinct convention."""

    vars: tuple[str, ...]
    atoms: tuple[JoinAtom, ...]
    types: tuple[tuple[str, int], ...] = ()
    distinct: bool = True

    def __post_init__(self):
        names = set(self.vars)
        if len(names) != len(self.vars):
            raise JoinUnsupported("duplicate pattern variable names")
        for a in self.atoms:
            if a.var not in names:
                raise JoinUnsupported(f"atom over unknown variable {a.var!r}")
            if a.key_is_var and a.key not in names:
                raise JoinUnsupported(f"atom references unknown {a.key!r}")
            if a.key_is_var and a.key == a.var:
                raise JoinUnsupported(f"self-referential atom on {a.var!r}")
        for v, _ in self.types:
            if v not in names:
                raise JoinUnsupported(f"type over unknown variable {v!r}")

    def atoms_of(self, var: str) -> tuple[JoinAtom, ...]:
        """Atoms touching ``var`` on either side."""
        return tuple(a for a in self.atoms
                     if a.var == var or a.key == var)

    def type_of(self, var: str) -> Optional[int]:
        for v, th in self.types:
            if v == var:
                return th
        return None


# ---------------------------------------------------------------- signature


@dataclass(frozen=True)
class PatternSignature:
    """The structural half of a pattern: constants replaced by slot
    indices (``("$", i)``), so requests sharing one signature batch into
    one compiled device program regardless of which atoms they anchor on.
    ``n_consts`` is the length of the per-request constant vector."""

    vars: tuple[str, ...]
    atoms: tuple[tuple[str, str, Any], ...]   # (rel, var, key|("$", slot))
    types: tuple[tuple[str, int], ...]
    distinct: bool
    n_consts: int

    def bind(self, consts) -> ConjunctivePattern:
        """Re-inflate the concrete pattern for one constant vector — the
        host-fallback / ground-truth side of the signature split."""
        consts = tuple(int(x) for x in consts)
        if len(consts) != self.n_consts:
            raise JoinUnsupported(
                f"signature expects {self.n_consts} constants, "
                f"got {len(consts)}"
            )

        def key_of(k):
            return consts[k[1]] if isinstance(k, tuple) else k

        return ConjunctivePattern(
            vars=self.vars,
            atoms=tuple(JoinAtom(r, v, key_of(k)) for r, v, k in self.atoms),
            types=self.types,
            distinct=self.distinct,
        )

    def to_conditions(self, consts) -> dict:
        """The pattern as a per-variable condition spec (``Var`` cross
        references) — what ``graph.find_all``-based evaluation consumes."""
        return pattern_to_conditions(self.bind(consts))


def split_constants(p: ConjunctivePattern
                    ) -> tuple[PatternSignature, tuple[int, ...]]:
    """Factor ``p`` into (signature, constant vector). Constants are
    slotted in atom order — two patterns with the same shape but
    different anchors share a signature and differ only in the vector."""
    consts: list[int] = []
    atoms = []
    for a in p.atoms:
        if a.key_is_var:
            atoms.append((a.rel, a.var, a.key))
        else:
            atoms.append((a.rel, a.var, ("$", len(consts))))
            consts.append(int(a.key))
    return PatternSignature(
        vars=p.vars, atoms=tuple(atoms), types=p.types,
        distinct=p.distinct, n_consts=len(consts),
    ), tuple(consts)


# ---------------------------------------------------------------- extraction


def _clauses_of(cond: c.HGQueryCondition) -> tuple:
    if isinstance(cond, c.And):
        return cond.clauses
    return (cond,)


def _key_of(ref, var: str):
    """Var → its name; anything int-coercible → constant handle."""
    if isinstance(ref, Var):
        return ref.name
    try:
        return int(ref)
    except (TypeError, ValueError):
        raise JoinUnsupported(
            f"pattern reference on {var!r} must be a handle or Var, "
            f"got {type(ref).__name__}"
        ) from None


def extract_pattern(graph, spec: Mapping[str, c.HGQueryCondition],
                    distinct: bool = True) -> ConjunctivePattern:
    """Extract the conjunctive-pattern IR from a per-variable condition
    spec. Each variable's condition runs through the compiler's own
    ``expand → to_dnf → simplify`` normalization; the surviving ``And``
    clauses must all be pattern vocabulary (CoIncident / Incident /
    Target / AtomType, constants or ``Var`` references) — anything else
    raises :class:`JoinUnsupported` naming the offending clause, the
    same honest-scoping contract as ``query/bridge.to_request``."""
    from hypergraphdb_tpu.query.compiler import expand, simplify, to_dnf

    vars_ = tuple(spec.keys())
    atoms: list[JoinAtom] = []
    types: list[tuple[str, int]] = []
    for v, cond in spec.items():
        norm = simplify(graph, to_dnf(expand(graph, cond)))
        if isinstance(norm, c.Or):
            raise JoinUnsupported(
                f"variable {v!r} normalizes to a disjunction; pattern "
                "variables must be conjunctive"
            )
        if isinstance(norm, c.Nothing):
            raise JoinUnsupported(
                f"variable {v!r} normalizes to a contradiction; the "
                "host path answers it (exactly empty) for free"
            )
        for cl in _clauses_of(norm):
            if isinstance(cl, c.AnyAtom):
                continue
            if isinstance(cl, c.CoIncident):
                atoms.append(JoinAtom("co", v, _key_of(cl.other, v)))
            elif isinstance(cl, c.Incident):
                atoms.append(JoinAtom("inc", v, _key_of(cl.target, v)))
            elif isinstance(cl, c.Target):
                atoms.append(JoinAtom("tgt", v, _key_of(cl.link, v)))
            elif isinstance(cl, c.AtomType):
                types.append((v, int(cl.type_handle(graph))))
            else:
                raise JoinUnsupported(
                    f"{type(cl).__name__} on variable {v!r} is outside "
                    "the pattern vocabulary (CoIncident/Incident/Target/"
                    "AtomType)"
                )
    # dedupe mirrored var-var atoms: co(x, y) and co(y, x) are the same
    # constraint (the relation is symmetric); inc(x, y) and tgt(y, x) are
    # each other's duals
    seen: set = set()
    uniq: list[JoinAtom] = []
    for a in atoms:
        if a.key_is_var:
            if a.rel == "co":
                k = ("co",) + tuple(sorted((a.var, a.key)))
            elif a.rel == "inc":
                k = ("inc", a.var, a.key)
            else:  # tgt(x, y) ≡ inc(y, x)
                k = ("inc", a.key, a.var)
        else:
            k = (a.rel, a.var, a.key)
        if k in seen:
            continue
        seen.add(k)
        uniq.append(a)
    return ConjunctivePattern(
        vars=vars_, atoms=tuple(uniq), types=tuple(dict(types).items()),
        distinct=distinct,
    )


def pattern_to_conditions(p: ConjunctivePattern) -> dict:
    """The inverse of :func:`extract_pattern`: one condition per
    variable, ``Var`` cross references — what the find_all-based ground
    truth (``join/host.py``) and the serve host fallback evaluate."""
    out: dict[str, list] = {v: [] for v in p.vars}

    def ref(k):
        return Var(k) if isinstance(k, str) else int(k)

    for a in p.atoms:
        if a.rel == "co":
            out[a.var].append(c.CoIncident(ref(a.key)))
        elif a.rel == "inc":
            out[a.var].append(c.Incident(ref(a.key)))
        else:
            out[a.var].append(c.Target(ref(a.key)))
    for v, th in p.types:
        out[v].append(c.AtomType(int(th)))
    return {
        v: (cls[0] if len(cls) == 1 else c.And(*cls)) if cls
        else c.AnyAtom()
        for v, cls in out.items()
    }
