"""Exact host evaluation of conjunctive patterns — the ground truth.

Recursive enumeration through the ordinary single-variable query engine:
binding variables in order, each variable's candidates come from
``graph.find_all`` over the clauses whose references are already bound
(the compiler's own cost-based planning answers each step), and every
deferred cross-reference is checked via the conditions' ``satisfies``
contract the moment its last variable binds. This is the differential
oracle ``tests/test_join.py`` holds the device executor to, and the
serving tier's exact fallback lane — deliberately a SEPARATE
implementation path from ``ops/join.py`` (find_all + satisfies vs CSR
kernels), so agreement is evidence.
"""

from __future__ import annotations

from hypergraphdb_tpu.join.ir import (
    ConjunctivePattern,
    JoinUnsupported,
    pattern_to_conditions,
)
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.variables import substitute, variables_of


def _clauses(cond) -> tuple:
    return cond.clauses if isinstance(cond, c.And) else (cond,)


def host_join(graph, pattern: ConjunctivePattern) -> list[tuple]:
    """Enumerate every binding tuple of ``pattern`` (variables in
    ``pattern.vars`` order), sorted lexicographically. Always complete:
    a capped enumeration would be a DFS-order sample, not the
    lexicographic prefix a truncation differential needs — callers
    slice the sorted result instead."""
    spec = pattern_to_conditions(pattern)
    # owner clauses, tagged with their free variables
    items = []
    for v, cond in spec.items():
        for cl in _clauses(cond):
            items.append((v, cl, frozenset(variables_of(cl))))
    # binding order must be FEASIBLE, not the spec's declaration order:
    # each variable needs a generating clause whose references are
    # already bound when its turn comes (the device planner reorders
    # freely — e.g. {'y': co(var('z')), 'z': co(a)} binds z first).
    # Greedy: repeatedly take any unbound variable with a ready
    # generator; emitted tuples stay in pattern.vars order.
    order: list[str] = []
    bound_set: set[str] = set()
    remaining = list(pattern.vars)
    while remaining:
        ready = next(
            (v for v in remaining if any(
                owner == v and free <= bound_set
                for owner, _, free in items
            )),
            None,
        )
        if ready is None:
            raise JoinUnsupported(
                f"variables {remaining} have no constant-anchored path "
                "into the pattern (disconnected or unanchored)"
            )
        order.append(ready)
        bound_set.add(ready)
        remaining.remove(ready)
    consts = {int(a.key) for a in pattern.atoms if not a.key_is_var}
    out: list[tuple] = []

    def bind(depth: int, bound: dict) -> bool:
        if depth == len(order):
            out.append(tuple(bound[v] for v in pattern.vars))
            return False
        v = order[depth]
        gen: list = []
        checks: list = []
        for owner, cl, free in items:
            if owner == v and free <= bound.keys():
                gen.append(substitute(cl, bound) if free else cl)
            elif (owner != v and owner in bound and v in free
                  and free <= bound.keys() | {v}):
                checks.append((owner, cl))
        cond_v = gen[0] if len(gen) == 1 else c.And(*gen)
        for h in sorted(int(x) for x in graph.find_all(cond_v)):
            if pattern.distinct and (
                h in consts or any(h == b for b in bound.values())
            ):
                continue
            ok = True
            for owner, cl in checks:
                inst = substitute(cl, {**bound, v: h})
                if not inst.satisfies(graph, bound[owner]):
                    ok = False
                    break
            if not ok:
                continue
            bound[v] = h
            stop = bind(depth + 1, bound)
            del bound[v]
            if stop:
                return True
        return False

    bind(0, {})
    return sorted(out)


def host_join_count(graph, pattern: ConjunctivePattern) -> int:
    return len(host_join(graph, pattern))
