"""Exact host evaluation of conjunctive patterns — the ground truth.

Recursive enumeration through the ordinary single-variable query engine:
binding variables in order, each variable's candidates come from
``graph.find_all`` over the clauses whose references are already bound
(the compiler's own cost-based planning answers each step), and every
deferred cross-reference is checked via the conditions' ``satisfies``
contract the moment its last variable binds. This is the differential
oracle ``tests/test_join.py`` holds the device executor to, and the
serving tier's exact fallback lane — deliberately a SEPARATE
implementation path from ``ops/join.py`` (find_all + satisfies vs CSR
kernels), so agreement is evidence.
"""

from __future__ import annotations

from hypergraphdb_tpu.join.ir import (
    ConjunctivePattern,
    JoinAtom,
    JoinUnsupported,
    pattern_to_conditions,
)
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.variables import substitute, variables_of


def _clauses(cond) -> tuple:
    return cond.clauses if isinstance(cond, c.And) else (cond,)


def host_join(graph, pattern: ConjunctivePattern) -> list[tuple]:
    """Enumerate every binding tuple of ``pattern`` (variables in
    ``pattern.vars`` order), sorted lexicographically. Always complete:
    a capped enumeration would be a DFS-order sample, not the
    lexicographic prefix a truncation differential needs — callers
    slice the sorted result instead."""
    spec = pattern_to_conditions(pattern)
    # owner clauses, tagged with their free variables
    items = []
    for v, cond in spec.items():
        for cl in _clauses(cond):
            items.append((v, cl, frozenset(variables_of(cl))))
    # binding order must be FEASIBLE, not the spec's declaration order:
    # each variable needs a generating clause whose references are
    # already bound when its turn comes (the device planner reorders
    # freely — e.g. {'y': co(var('z')), 'z': co(a)} binds z first).
    # Greedy: repeatedly take any unbound variable with a ready
    # generator; emitted tuples stay in pattern.vars order.
    order: list[str] = []
    bound_set: set[str] = set()
    remaining = list(pattern.vars)
    while remaining:
        ready = next(
            (v for v in remaining if any(
                owner == v and free <= bound_set
                for owner, _, free in items
            )),
            None,
        )
        if ready is None:
            raise JoinUnsupported(
                f"variables {remaining} have no constant-anchored path "
                "into the pattern (disconnected or unanchored)"
            )
        order.append(ready)
        bound_set.add(ready)
        remaining.remove(ready)
    consts = {int(a.key) for a in pattern.atoms if not a.key_is_var}
    out: list[tuple] = []

    def bind(depth: int, bound: dict) -> bool:
        if depth == len(order):
            out.append(tuple(bound[v] for v in pattern.vars))
            return False
        v = order[depth]
        gen: list = []
        checks: list = []
        for owner, cl, free in items:
            if owner == v and free <= bound.keys():
                gen.append(substitute(cl, bound) if free else cl)
            elif (owner != v and owner in bound and v in free
                  and free <= bound.keys() | {v}):
                checks.append((owner, cl))
        cond_v = gen[0] if len(gen) == 1 else c.And(*gen)
        for h in sorted(int(x) for x in graph.find_all(cond_v)):
            if pattern.distinct and (
                h in consts or any(h == b for b in bound.values())
            ):
                continue
            ok = True
            for owner, cl in checks:
                inst = substitute(cl, {**bound, v: h})
                if not inst.satisfies(graph, bound[owner]):
                    ok = False
                    break
            if not ok:
                continue
            bound[v] = h
            stop = bind(depth + 1, bound)
            del bound[v]
            if stop:
                return True
        return False

    bind(0, {})
    return sorted(out)


def host_join_count(graph, pattern: ConjunctivePattern) -> int:
    return len(host_join(graph, pattern))


def _substitute_var(graph, pattern: ConjunctivePattern, v: str, d: int):
    """The reduced pattern with variable ``v`` bound to atom ``d``:
    every atom touching ``v`` becomes either a constant-keyed atom on
    its OTHER variable (relation direction rewritten — ``inc(v, w)``
    with ``v`` a link becomes ``tgt(w, d)``, etc.) or, when the other
    side is already a constant, a direct ``satisfies`` check on ``d``.
    Returns ``(ok, atoms)`` — ``ok`` False when a direct check failed
    (no tuple through this substitution exists)."""
    atoms: list[JoinAtom] = []
    for a in pattern.atoms:
        if a.var == v:
            if a.key_is_var:
                w = a.key
                if a.rel == "co":
                    atoms.append(JoinAtom("co", w, d))
                elif a.rel == "inc":
                    # d is a link whose targets include w
                    atoms.append(JoinAtom("tgt", w, d))
                else:  # tgt(v, w): d ∈ targets(w) → w is a link over d
                    atoms.append(JoinAtom("inc", w, d))
            else:
                cond = {"co": c.CoIncident, "inc": c.Incident,
                        "tgt": c.Target}[a.rel](int(a.key))
                if not cond.satisfies(graph, d):
                    return False, ()
        elif a.key == v:
            # the var side stays a variable; v becomes its constant key
            atoms.append(JoinAtom(a.rel, a.var, d))
        else:
            atoms.append(a)
    return True, tuple(atoms)


def host_join_touching(graph, pattern: ConjunctivePattern,
                       touched) -> list[tuple]:
    """Every binding tuple of ``pattern`` that contains at least one
    atom from ``touched`` — the per-lane memtable correction's work set
    (ROADMAP 2d). Soundness rests on link immutability: a tuple that is
    a result NOW but not over the pre-ingest base must witness some
    newly added link, and every endpoint a new link makes newly
    co-incident/incident/target-related is the link itself or one of
    its targets — all members of the dirty set. So enumerating tuples
    through each ``(variable, touched atom)`` substitution
    (:func:`_substitute_var` + :func:`host_join` on the reduced
    pattern) covers exactly the results a device answer over the base
    can be missing, at cost proportional to the dirty set instead of
    the whole batch's host re-serve."""
    out: set = set()
    consts_in = {int(a.key) for a in pattern.atoms if not a.key_is_var}
    touched = sorted({int(x) for x in touched})
    for vi, v in enumerate(pattern.vars):
        rest = tuple(x for x in pattern.vars if x != v)
        th = pattern.type_of(v)
        types_rest = tuple(
            (w, t) for w, t in pattern.types if w != v
        )
        for d in touched:
            if pattern.distinct and d in consts_in:
                continue
            if th is not None and not c.AtomType(int(th)).satisfies(
                graph, d
            ):
                continue
            ok, atoms = _substitute_var(graph, pattern, v, d)
            if not ok:
                continue
            if not rest:
                out.add((d,))
                continue
            sub = ConjunctivePattern(
                vars=rest, atoms=atoms, types=types_rest,
                distinct=pattern.distinct,
            )
            for t in host_join(graph, sub):
                # the ORIGINAL pattern's all-distinct convention: no
                # binding repeats d or any original constant (atoms the
                # substitution folded into direct checks dropped their
                # constant from the reduced pattern's exclusion set)
                if pattern.distinct and (
                    d in t or any(x in consts_in for x in t)
                ):
                    continue
                out.add(t[:vi] + (d,) + t[vi:])
    return sorted(out)
