"""Cross-process trace propagation over the peer plane.

The acceptance contract: a replication push, a catch-up page, and a
snapshot transfer each render as ONE connected span tree spanning the
sending and the receiving peer — the compact context (trace id, parent
span id, sampling decision) rides the wire message, the receiver opens
remote-child spans against the propagated parent, and joining the two
peers' drained tracers on ``trace_id`` reconstructs the tree.

Each peer gets its OWN injected tracer (``peer.tracer``) so both halves
of every tree are independently observable — exactly what two real
processes would drain."""

from __future__ import annotations

import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.obs.trace import Tracer
from hypergraphdb_tpu.peer import messages as M
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.query import dsl as q


def make_pair():
    net = LoopbackNetwork()
    ga, gb = hg.HyperGraph(), hg.HyperGraph()
    pa = HyperGraphPeer.loopback(ga, net, identity="trace-a")
    pb = HyperGraphPeer.loopback(gb, net, identity="trace-b")
    for p in (pa, pb):
        p.replication.debounce_s = 0.005
        p.tracer = Tracer(max_finished=256).enable()
    pa.start()
    pb.start()
    return pa, pb


def stop_pair(pa, pb):
    pa.stop()
    pb.stop()


def wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def by_name(traces, name):
    return [t for t in traces if t.name == name]


def span(trace, name):
    sp = trace.find(name)
    assert sp is not None, (trace.name, name,
                            [s.name for s in trace.spans()])
    return sp


# --------------------------------------------------------- wire format


def test_context_attach_and_extract_roundtrip():
    tr = Tracer().enable().start_trace("peer.push")
    root = tr.start_span("push")
    tr.marks["root"] = root
    msg = M.make_message(M.INFORM, "replication", {"what": "push"})
    M.attach_trace(msg, tr.context())
    # survives the loopback/TCP wire constraint (JSON round trip)
    import json

    wired = json.loads(json.dumps(msg))
    ctx = M.trace_context(wired)
    assert ctx == {"tid": tr.trace_id, "sid": root.span_id, "s": 1}
    assert M.trace_context(M.make_message(M.INFORM, "replication", {})) \
        is None  # pre-tracing peers carry no context


def test_remote_trace_joins_on_id_and_parent():
    ta, tb = Tracer().enable(), Tracer().enable()
    tr = ta.start_trace("peer.push")
    root = tr.start_span("push")
    tr.marks["root"] = root
    remote = tb.start_remote_trace("peer.apply", tr.context())
    assert remote.trace_id == tr.trace_id
    child = remote.start_span("apply")   # parentless → remote parent
    assert child.parent_id == root.span_id
    grand = remote.start_span("inner", parent=child)
    assert grand.parent_id == child.span_id


# ------------------------------------------------- replication push


def test_replication_push_one_connected_tree():
    pa, pb = make_pair()
    try:
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "trace-b" in pa.replication.peer_interests)
        pa.graph.add("traced-push")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("traced-push")) != [])
        assert pb.replication.flush()

        pushes = by_name(pa.tracer.drain(), "peer.push")
        applies = by_name(pb.tracer.drain(), "peer.apply")
        assert pushes and applies
        # join on trace id: at least one push tree has its apply subtree
        joined = 0
        apply_by_tid = {t.trace_id: t for t in applies}
        for push in pushes:
            recv = apply_by_tid.get(push.trace_id)
            if recv is None:
                continue
            joined += 1
            # remote-child parenting: the receiver's apply root hangs
            # under the sender's push span
            assert span(recv, "apply").parent_id == \
                span(push, "push").span_id
            assert span(push, "sent") is not None  # sender terminal
            assert span(recv, "applied") is not None
        assert joined >= 1
    finally:
        stop_pair(pa, pb)


def test_push_sampling_decision_propagates():
    """Head decision is the SENDER's: an unsampled push drops BOTH
    halves of the tree (receiver honors ctx, no local draw)."""
    pa, pb = make_pair()
    try:
        pa.tracer.set_sample_rate("peer.push", 0.0)
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "trace-b" in pa.replication.peer_interests)
        pa.graph.add("unsampled-push")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("unsampled-push")) != [])
        assert pb.replication.flush()
        assert by_name(pa.tracer.drain(), "peer.push") == []
        assert by_name(pb.tracer.drain(), "peer.apply") == []
        assert pa.tracer.traces_dropped >= 1
        assert pb.tracer.traces_dropped >= 1
    finally:
        stop_pair(pa, pb)


# ------------------------------------------------------- catch-up


def test_catchup_page_one_connected_tree():
    pa, pb = make_pair()
    try:
        # no interest: mutations land in A's log only
        pa.graph.add("cu-1")
        pa.graph.add("cu-2")
        assert pa.replication.flush()
        pb.replication.catch_up("trace-a")
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("cu-2")) != [])
        assert pb.replication.flush()

        b_traces = pb.tracer.drain()
        (req,) = by_name(b_traces, "peer.catchup")
        (serve,) = by_name(pa.tracer.drain(), "peer.catchup.serve")
        applies = by_name(b_traces, "peer.apply")
        # one tree: request (B) → serve (A) → applies (B)
        assert serve.trace_id == req.trace_id
        assert span(serve, "catchup_serve").parent_id == \
            span(req, "catchup_request").span_id
        assert serve.find("served").attrs["entries"] >= 2
        assert applies and all(t.trace_id == req.trace_id for t in applies)
        for ap in applies:
            assert span(ap, "apply").parent_id == \
                span(serve, "catchup_serve").span_id
    finally:
        stop_pair(pa, pb)


# ------------------------------------------------- snapshot transfer


def test_snapshot_transfer_one_connected_tree():
    pa, pb = make_pair()
    try:
        handles = [pa.graph.add(f"tr-{i}") for i in range(20)]
        pa.graph.add_link(handles[:2], value="tr-link")
        n = pb.transfer_graph_from("trace-a", page=8, timeout=30.0)
        assert n >= 21

        (client,) = by_name(pb.tracer.drain(), "peer.transfer")
        (server,) = by_name(pa.tracer.drain(), "peer.transfer.serve")
        assert server.trace_id == client.trace_id
        # remote-child parenting across the wire
        assert span(server, "transfer_serve").parent_id == \
            span(client, "transfer").span_id
        # the client applied every streamed page, the server chunked them
        client_chunks = [s for s in client.spans()
                         if s.name == "apply_chunk"]
        server_chunks = [s for s in server.spans() if s.name == "chunk"]
        assert len(server_chunks) >= 3          # 21 atoms / page 8
        assert len(client_chunks) == len(server_chunks)
        assert client.find("resolve").attrs["stored"] == n
        assert server.find("served") is not None
    finally:
        stop_pair(pa, pb)


# ------------------------------------------------- remote ops (views)


def test_remote_op_one_connected_tree():
    pa, pb = make_pair()
    try:
        h = pa.graph.add("op-me")
        gid = None
        from hypergraphdb_tpu.peer import transfer

        gid = transfer.gid_of(pa.graph, int(h), pa.identity)
        view = __import__(
            "hypergraphdb_tpu.peer.remote_view", fromlist=["remote_view"]
        ).remote_view(pb, "trace-a")
        assert view.get(gid) == "op-me"
        (client,) = by_name(pb.tracer.drain(), "peer.op")
        (server,) = by_name(pa.tracer.drain(), "peer.op.serve")
        assert server.trace_id == client.trace_id
        assert span(server, "op_serve").parent_id == \
            span(client, "op").span_id
        assert client.attrs["op"] == "peek_atom"
        assert server.find("served") is not None
    finally:
        stop_pair(pa, pb)


def test_tracing_off_peer_plane_untouched():
    """Off-gate: with both tracers disabled (the default), peer traffic
    carries no context key and nothing is buffered."""
    net = LoopbackNetwork()
    ga, gb = hg.HyperGraph(), hg.HyperGraph()
    pa = HyperGraphPeer.loopback(ga, net, identity="off-a")
    pb = HyperGraphPeer.loopback(gb, net, identity="off-b")
    seen = []
    orig = pb.interface.__class__._deliver

    def spy(self, sender, message):
        seen.append(message)
        orig(self, sender, message)

    pb.interface._deliver = spy.__get__(pb.interface)
    pa.start()
    pb.start()
    try:
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "off-b" in pa.replication.peer_interests)
        pa.graph.add("untraced")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("untraced")) != [])
        assert all(M.TRACE_KEY not in m for m in seen)
        assert pa.tracer.finished_count() == 0
        assert pb.tracer.finished_count() == 0
    finally:
        stop_pair(pa, pb)
