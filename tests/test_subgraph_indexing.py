"""Subgraph + user-indexing tests, incl. review-finding regressions."""

import dataclasses

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.atom.subgraph import HGSubgraph
from hypergraphdb_tpu.indexing import manager as im
from hypergraphdb_tpu.query import dsl as hg


@dataclasses.dataclass
class Person:
    name: str
    age: int


@dataclasses.dataclass
class Robot:
    name: str


def test_subgraph_membership(graph: HyperGraph):
    sg = HGSubgraph.create(graph, "mine")
    a = sg.add("a")
    b = graph.add("b")
    sg.add_member(b)
    assert sg.is_member(a) and sg.is_member(b)
    assert sorted(sg) == sorted([a, b])
    assert set(graph.find_all(hg.member_of(sg.handle))) == {a, b}
    sg.remove_member(b)
    assert not sg.is_member(b)


def test_subgraph_find_by_name(graph: HyperGraph):
    HGSubgraph.create(graph, "one")
    sg2 = HGSubgraph.create(graph, "two")
    found = HGSubgraph.find_by_name(graph, "two")
    assert found is not None and found.handle == sg2.handle


def test_subgraph_contains_query(graph: HyperGraph):
    sg = HGSubgraph.create(graph, "s")
    a = sg.add("a")
    res = graph.find_all(hg.contains(a))
    assert res == [sg.handle]


def test_removed_atom_leaves_subgraph(graph: HyperGraph):
    """Regression: graph.remove() must purge membership index entries."""
    sg = HGSubgraph.create(graph, "s")
    a = sg.add("x")
    graph.remove(a)
    assert not sg.is_member(a)
    assert graph.find_all(hg.member_of(sg.handle)) == []


def test_removed_subgraph_drops_member_list(graph: HyperGraph):
    sg = HGSubgraph.create(graph, "s")
    a = sg.add("x")
    graph.remove(sg.handle, keep_incident_links=True)
    sg2 = HGSubgraph.of(graph, sg.handle)
    assert len(sg2) == 0


# ---------------------------------------------------------------- indexing


def test_by_part_indexer_used_when_type_pinned(graph: HyperGraph):
    people = [graph.add(Person(f"p{i}", i)) for i in range(20)]
    th = graph.get_type_handle_of(people[0])
    im.register(graph, im.ByPartIndexer("person.name", th, "name"))
    tname = graph.typesystem.name_of(th)
    res = graph.find_all(hg.and_(hg.type_(tname), hg.part("name", "p7")))
    assert res == [people[7]]
    # plan shows the index lookup
    from hypergraphdb_tpu.query.compiler import compile_query

    d = compile_query(
        graph, hg.and_(hg.type_(tname), hg.part("name", "p7"))
    ).analyze()
    assert "index(person.name)" in d


def test_part_index_does_not_change_untyped_answers(graph: HyperGraph):
    """Regression: registering an index must not exclude other types from
    an unconstrained AtomPart query."""
    p = graph.add(Person("ada", 1))
    r = graph.add(Robot("ada"))
    before = sorted(graph.find_all(hg.part("name", "ada")))
    th = graph.get_type_handle_of(p)
    im.register(graph, im.ByPartIndexer("pname", th, "name"))
    after = sorted(graph.find_all(hg.part("name", "ada")))
    assert before == after == sorted([p, r])


def test_by_target_indexer(graph: HyperGraph):
    a, b, c = graph.add("a"), graph.add("b"), graph.add("c")
    l1 = graph.add_link((a, b), value=1)
    l2 = graph.add_link((a, c), value=2)
    th = graph.typesystem.handle_of("int")
    im.register(graph, im.ByTargetIndexer("bytarget0", th, 0))
    from hypergraphdb_tpu.utils.ordered_bytes import encode_int

    idx = im.get_index(graph, "bytarget0")
    assert sorted(idx.find(encode_int(a))) == sorted([l1, l2])
    graph.remove(l1)
    assert sorted(idx.find(encode_int(a))) == [l2]


def test_target_to_target_indexer(graph: HyperGraph):
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b), value=1)
    th = graph.typesystem.handle_of("int")
    im.register(graph, im.TargetToTargetIndexer("t2t", th, 0, 1))
    from hypergraphdb_tpu.utils.ordered_bytes import encode_int

    idx = im.get_index(graph, "t2t")
    assert idx.find(encode_int(a)).array().tolist() == [b]


def test_indexer_rebuild_covers_existing_atoms(graph: HyperGraph):
    people = [graph.add(Person(f"p{i}", i)) for i in range(5)]
    th = graph.get_type_handle_of(people[0])
    im.register(graph, im.ByPartIndexer("names", th, "name"), populate=True)
    st = graph.typesystem.get_type("string")
    idx = im.get_index(graph, "names")
    assert idx.find(st.to_key("p3")).array().tolist() == [people[3]]


def test_unregister_removes_index(graph: HyperGraph):
    p = graph.add(Person("x", 1))
    th = graph.get_type_handle_of(p)
    im.register(graph, im.ByPartIndexer("tmp", th, "name"))
    im.unregister(graph, "tmp")
    assert "hg.user.tmp" not in graph.store.index_names()


# ---------------------------------------------------------------- setops pad


def test_pattern_kernel_asymmetric_incidence(graph: HyperGraph):
    """Regression: pad_len must cover the longest anchor row, not anchor 0's."""
    a = graph.add("rare")
    b = graph.add("hub")
    others = list(graph.add_nodes_bulk([f"o{i}" for i in range(300)]))
    # 300 links on b so the shared link sorts late in b's row
    for o in others:
        graph.add_link((o, b))
    shared = graph.add_link((a, b))
    snap = graph.snapshot()
    from hypergraphdb_tpu.ops.setops import and_incident_pattern

    got = and_incident_pattern(snap, [(a, b)])[0]
    assert got.tolist() == [shared]