"""hgindex differential tests: the device value-index lanes == host truth.

The range serve lane's contract is the serving contract everywhere else:
coalescing, padding, and the sorted-column machinery are INVISIBLE — a
batched range/ordered/top-k request returns exactly what an exact host
scan of the by-value index returns, across pad-adjacent lanes, duplicate
bounds, empty windows, mid-ingest delta/tombstone visibility, and
truncation prefixes. Runs the REAL DeviceExecutor under
``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query import dsl
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from hypergraphdb_tpu.serve.types import RangeRequest, Unservable


def _runtime(g, bucket=64, **kw):
    kw.setdefault("top_r", 256)
    cfg = ServeConfig(buckets=(bucket,), manual=True, max_linger_s=0.0,
                      **kw)
    return ServeRuntime(g, cfg)


def _drain(rt):
    while rt.step(drain=True):
        pass


def _int_graph(g, n=40, dup_every=0):
    """Nodes with int values 0..n-1 (``dup_every`` > 0 repeats every
    k-th value — duplicate ranks) plus typed links carrying int values
    100..; returns (node_handles, link_handles, link_type_handle)."""
    nodes = []
    for i in range(n):
        v = i - (i % dup_every) if dup_every else i
        nodes.append(int(g.add(v)))
    links = [int(g.add_link([nodes[i], nodes[(i + 1) % n]], value=100 + i))
             for i in range(n // 2)]
    return nodes, links, int(g.get_type_handle_of(links[0]))


def _host_truth(g, lo=None, hi=None, lo_op="gte", hi_op="lte",
                type_handle=None, anchor=None, desc=False):
    """The oracle: every live atom satisfying the predicate, in value
    order (ascending key; ``desc`` flips the key order, gid-ascending
    within ties either way — the kernel's complemented-rank order)."""
    from hypergraphdb_tpu.storage.value_index import value_key_of

    clauses = []
    if lo is not None:
        clauses.append(c.AtomValue(lo, lo_op))
    if hi is not None:
        clauses.append(c.AtomValue(hi, hi_op))
    if type_handle is not None:
        clauses.append(c.AtomType(int(type_handle)))
    if anchor is not None:
        clauses.append(c.Incident(int(anchor)))
    cond = clauses[0] if len(clauses) == 1 else c.And(*clauses)
    hs = [int(h) for h in g.find_all(cond)]
    keyed = sorted(
        ((value_key_of(g, h)[1:], h) for h in hs),
        key=lambda kv: (kv[0], kv[1]),
    )
    if desc:
        keyed.sort(key=lambda kv: kv[1])
        keyed.sort(key=lambda kv: kv[0], reverse=True)
    return [h for _, h in keyed]


def test_range_batched_equals_host_scan_pad_adjacent(graph):
    """A bucket-minus-one batch (the last lane sits against padding):
    every lane == the exact host scan, including duplicate requests,
    duplicate BOUNDS (eq windows over repeated values), and empty
    windows."""
    nodes, links, lt = _int_graph(graph, n=40, dup_every=4)
    probes = [
        dict(lo=5, hi=17),                      # plain window
        dict(lo=8, hi=8),                       # eq over DUPLICATED value
        dict(lo=0, hi=39),                      # whole dimension
        dict(lo=500, hi=900),                   # provably empty
        dict(lo=12, hi=12, lo_op="gt", hi_op="lt"),  # empty by ops
        dict(lo=10, hi=None),                   # open upper
        dict(lo=None, hi=6, hi_op="lt"),        # open lower
        dict(lo=5, hi=17),                      # duplicate request
    ]
    bucket = 64
    reqs = [probes[i % len(probes)] for i in range(bucket - 1)]
    rt = _runtime(graph, bucket)
    futs = [rt.submit_range(**p) for p in reqs]
    _drain(rt)
    assert rt.stats.batches == 1          # ONE coalesced dispatch
    assert rt.stats.range_dispatches == 1
    rt.close()
    for p, f in zip(reqs, futs):
        res = f.result(timeout=0)
        truth = _host_truth(graph, **p)
        assert res.count == len(truth)
        assert res.matches.tolist() == truth[: len(res.matches)]
        assert res.truncated == (res.count > len(res.matches))
        assert res.served_by == "device"


def test_ordered_and_topk_shapes(graph):
    nodes, links, lt = _int_graph(graph, n=30)
    rt = _runtime(graph, 64)
    fa = rt.submit_range(lo=3, hi=25)                      # ascending
    fd = rt.submit_range(lo=3, hi=25, desc=True)           # descending
    fk = rt.submit_range(lo=3, hi=25, limit=4)             # top-4 smallest
    fkd = rt.submit_range(lo=3, hi=25, desc=True, limit=4)  # top-4 largest
    _drain(rt)
    rt.close()
    truth = _host_truth(graph, lo=3, hi=25)
    truth_d = _host_truth(graph, lo=3, hi=25, desc=True)
    assert fa.result(timeout=0).matches.tolist() == truth
    assert fd.result(timeout=0).matches.tolist() == truth_d
    rk = fk.result(timeout=0)
    assert rk.matches.tolist() == truth[:4]
    assert rk.count == len(truth) and rk.truncated is True
    assert fkd.result(timeout=0).matches.tolist() == truth_d[:4]


def test_truncation_prefix_is_honest(graph):
    """count stays exact past the compact window; matches is the
    value-ordered prefix — and a truncated window under a dirty
    memtable re-serves exactly on host (prefixes cannot absorb
    corrections)."""
    nodes, links, lt = _int_graph(graph, n=40)
    rt = _runtime(graph, 64, top_r=5)
    fut = rt.submit_range(lo=0, hi=39)
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    truth = _host_truth(graph, lo=0, hi=39)
    assert res.truncated is True
    assert res.count == len(truth) > 5
    assert res.matches.tolist() == truth[:5]
    assert res.served_by == "device"

    graph.enable_incremental(background=False, compact_ratio=100.0)
    graph.remove(nodes[2])  # memtable tombstone → prefix not correctable
    rt = _runtime(graph, 64, top_r=5)
    fut = rt.submit_range(lo=0, hi=39)
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    truth = _host_truth(graph, lo=0, hi=39)
    assert res.served_by == "host"
    assert res.count == len(truth)
    assert res.matches.tolist() == truth[:5]


def test_mid_ingest_delta_tombstone_revalue_visibility(graph):
    """Post-pack mutations stay exact: fresh atoms arrive through the
    delta column, tombstones drop, revalues move atoms to their new
    window — all against one pinned view."""
    nodes, links, lt = _int_graph(graph, n=30)
    mgr = graph.enable_incremental(background=False, compact_ratio=100.0)
    fresh = [int(graph.add(1000 + i)) for i in range(4)]
    graph.remove(nodes[12])
    graph.replace(nodes[13], 9999)
    assert mgr.correction()[1]  # really still memtable
    rt = _runtime(graph, 64)
    f_win = rt.submit_range(lo=10, hi=20)       # straddles both mutations
    f_new = rt.submit_range(lo=999, hi=1002)    # delta-column only
    f_rev = rt.submit_range(lo=9000, hi=10000)  # revalued's new home
    _drain(rt)
    rt.close()
    for fut, kw in ((f_win, dict(lo=10, hi=20)),
                    (f_new, dict(lo=999, hi=1002)),
                    (f_rev, dict(lo=9000, hi=10000))):
        res = fut.result(timeout=0)
        truth = _host_truth(graph, **kw)
        assert res.matches.tolist() == truth
        assert res.count == len(truth)
    assert fresh[0] in f_new.result(timeout=0).matches.tolist()
    assert nodes[12] not in f_win.result(timeout=0).matches.tolist()
    assert nodes[13] in f_rev.result(timeout=0).matches.tolist()


def test_value_delta_column_reuse_under_lag(graph):
    """The delta column refreshes under the max_lag_edges drift
    discipline: within the bound the cached column is reused and the
    residual is host-corrected — results stay exact either way."""
    nodes, links, lt = _int_graph(graph, n=20)
    graph.enable_incremental(background=False, compact_ratio=100.0)
    int(graph.add(500))
    rt = _runtime(graph, 64, max_lag_edges=1_000_000)
    f1 = rt.submit_range(lo=400, hi=600)
    _drain(rt)
    # a second fresh atom INSIDE the lag bound: the cached column may
    # skip it — the host residual correction must not
    h2 = int(graph.add(501))
    f2 = rt.submit_range(lo=400, hi=600)
    _drain(rt)
    rt.close()
    assert f1.result(timeout=0).count == 1
    r2 = f2.result(timeout=0)
    assert h2 in r2.matches.tolist() and r2.count == 2


def test_type_filter_and_anchor_filter(graph):
    nodes, links, lt = _int_graph(graph, n=30)
    rt = _runtime(graph, 64)
    f_typed = rt.submit_range(lo=100, hi=110, type_handle=lt)
    anchor = nodes[3]
    f_anch = rt.submit_range(lo=100, hi=130, anchor=anchor)
    _drain(rt)
    rt.close()
    rt_res = f_typed.result(timeout=0)
    truth = _host_truth(graph, lo=100, hi=110, type_handle=lt)
    assert rt_res.matches.tolist() == truth
    ra = f_anch.result(timeout=0)
    truth_a = _host_truth(graph, lo=100, hi=130, anchor=anchor)
    assert ra.matches.tolist() == truth_a
    assert ra.served_by == "device"


def test_typed_lane_sees_fresh_memtable_atoms(graph):
    """A type-filtered range must not lose covered memtable atoms: the
    kernel's type filter reads the BASE type_of column (a delta gid is
    -1 there — masked out on device), so the collect merge re-offers
    the FULL memtable candidate set for typed lanes."""
    nodes, links, lt = _int_graph(graph, n=20)
    graph.enable_incremental(background=False, compact_ratio=100.0)
    a, b = nodes[2], nodes[5]
    fresh = int(graph.add_link([a, b], value=777))  # type lt, memtable
    rt = _runtime(graph, 64)
    f_typed = rt.submit_range(lo=100, hi=800, type_handle=lt)
    f_plain = rt.submit_range(lo=100, hi=800)
    _drain(rt)
    rt.close()
    res = f_typed.result(timeout=0)
    truth = _host_truth(graph, lo=100, hi=800, type_handle=lt)
    assert fresh in truth
    assert res.matches.tolist() == truth
    assert res.count == len(truth)
    assert f_plain.result(timeout=0).count == len(
        _host_truth(graph, lo=100, hi=800))


def test_anchored_lane_under_fresh_ingest_stays_on_device(graph):
    """A memtable link incident to the anchor is invisible to the BASE
    incidence rows the device filter probes — but the probe only masks
    candidates OUT, so the lane stays on device and the collect's
    delta-incidence re-offer (the live-graph ``get_targets`` check)
    merges the fresh link back in exactly."""
    nodes, links, lt = _int_graph(graph, n=20)
    graph.enable_incremental(background=False, compact_ratio=100.0)
    anchor = nodes[3]
    fresh = int(graph.add_link([anchor, nodes[7]], value=777))
    rt = _runtime(graph, 64)
    fut = rt.submit_range(lo=100, hi=800, anchor=anchor)
    _drain(rt)
    res = fut.result(timeout=0)
    truth = _host_truth(graph, lo=100, hi=800, anchor=anchor)
    assert fresh in truth
    assert res.served_by == "device"
    assert rt.stats.range_dispatches == 1
    assert res.matches.tolist() == truth
    rt.close()


def test_anchored_lane_under_churn_equals_host_oracle(graph):
    """Anchored lanes ride the device through the full memtable menu —
    a fresh incident link in-window, a fresh incident link out-of-window,
    a fresh NON-incident link in-window, a removed incident link, and a
    revalued one — and still equal the exact host oracle."""
    nodes, links, lt = _int_graph(graph, n=20)
    graph.enable_incremental(background=False, compact_ratio=100.0)
    anchor = nodes[3]
    inwin = int(graph.add_link([anchor, nodes[9]], value=350))
    outwin = int(graph.add_link([anchor, nodes[11]], value=9000))
    other = int(graph.add_link([nodes[5], nodes[6]], value=360))
    graph.remove(links[2])          # base link incident to anchor dies
    graph.replace(links[3], 370)    # base link revalued into the window
    rt = _runtime(graph, 64)
    fut = rt.submit_range(lo=100, hi=800, anchor=anchor)
    f_free = rt.submit_range(lo=100, hi=800)  # anchor-free control lane
    _drain(rt)
    res = fut.result(timeout=0)
    truth = _host_truth(graph, lo=100, hi=800, anchor=anchor)
    assert inwin in truth and outwin not in truth and other not in truth
    assert links[2] not in truth
    assert res.served_by == "device"
    assert res.matches.tolist() == truth
    assert res.count == len(truth)
    free = f_free.result(timeout=0)
    assert free.matches.tolist() == _host_truth(graph, lo=100, hi=800)
    rt.close()


def test_clean_variable_width_windows_serve_on_device(graph):
    """str values with CLEAN keys (≤16 payload bytes, NUL-free) ride the
    device lane through the 128-bit rank pair — including rank ties in
    the first word ('alphabetical' vs 'alphabetic': identical first 8
    payload bytes) — and return exactly the host scan."""
    words = ("apple", "alphabetic", "alphabetical", "banana", "blueberry",
             "cherry", "cherrystone", "date")
    for s in words:
        graph.add(s)
    rt = _runtime(graph, 64)
    fut = rt.submit_range(lo="alphabetical", hi="cherry")
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    truth = _host_truth(graph, lo="alphabetical", hi="cherry")
    assert "alphabetic" not in [graph.get(h) for h in res.matches.tolist()]
    assert res.served_by == "device"
    assert res.matches.tolist() == truth
    assert rt.stats.range_dispatches == 1


def test_ambiguous_variable_width_kinds_serve_host_exactly(graph):
    """Ambiguity past the rank pair falls back to the exact host lane:
    an AMBIGUOUS BOUND (>16 payload bytes) makes the request inexact,
    and an ambiguous COLUMN ENTRY clears device_exact so even clean
    bounds host-serve. Both answered exactly, never approximated."""
    for s in ("apple", "banana", "cherry", "date"):
        graph.add(s)
    rt = _runtime(graph, 64)
    fut = rt.submit_range(lo="b", hi="an unambiguously long upper bound")
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    truth = _host_truth(graph, lo="b", hi="an unambiguously long upper bound")
    assert res.served_by == "host"
    assert res.matches.tolist() == truth
    assert rt.stats.range_dispatches == 0  # nothing device-dispatched

    g2 = type(graph)()
    g2.add("a long string past the sixteen-byte rank pair")
    g2.add("brief")
    rt2 = _runtime(g2, 64)
    fut2 = rt2.submit_range(lo="a", hi="z")  # clean bounds, dirty column
    _drain(rt2)
    rt2.close()
    res2 = fut2.result(timeout=0)
    assert res2.served_by == "host"
    assert res2.matches.tolist() == _host_truth(g2, lo="a", hi="z")
    assert rt2.stats.range_dispatches == 0


def test_batch_key_separates_dimensions(graph):
    """int and float requests probe different sorted columns — they must
    never share a batch (the statics key is ("range", dim))."""
    graph.add(5)
    graph.add(5.0)
    rt = _runtime(graph, 64)
    fi = rt.submit_range(lo=0, hi=10)
    ff = rt.submit_range(lo=0.0, hi=10.0)
    _drain(rt)
    rt.close()
    assert rt.stats.batches == 2
    assert fi.result(timeout=0).count == 1
    assert ff.result(timeout=0).count == 1


def test_bridge_value_conditions(graph):
    """The condition front door: AtomValue / TypedValue / range-And
    conjunctions ride the range lane through submit_query."""
    nodes, links, lt = _int_graph(graph, n=20)
    rt = _runtime(graph, 64)
    f1 = rt.submit_query(dsl.value(7, "lte"))
    f2 = rt.submit_query(c.And(c.AtomValue(3, "gte"), c.AtomValue(9, "lt")))
    f3 = rt.submit_query(c.And(c.AtomValue(100, "gte"),
                               c.AtomValue(130, "lte"), c.AtomType(lt)))
    f4 = rt.submit_query(c.And(c.AtomValue(100, "gte"),
                               c.AtomValue(130, "lte"),
                               c.Incident(nodes[3])))
    with pytest.raises(Unservable):
        rt.submit_query(c.And(c.AtomValue(3, "gte"), c.AtomValue("z", "lt")))
    _drain(rt)
    rt.close()
    assert f1.result(timeout=0).matches.tolist() == _host_truth(
        graph, hi=7, hi_op="lte")
    assert f2.result(timeout=0).matches.tolist() == _host_truth(
        graph, lo=3, hi=9, hi_op="lt")
    assert f3.result(timeout=0).matches.tolist() == _host_truth(
        graph, lo=100, hi=130, type_handle=lt)
    assert f4.result(timeout=0).matches.tolist() == _host_truth(
        graph, lo=100, hi=130, anchor=nodes[3])


def test_range_prewarm_hits_aot_cache(graph, tmp_path):
    """``prewarm_range_dims``: a fresh runtime over a populated AOT
    cache reaches its first range dispatch without compiling (and the
    sorted column is built at startup, off the dispatch thread)."""
    _int_graph(graph, n=30)
    cfg = dict(buckets=(4,), max_linger_s=0.001, top_r=8,
               aot_cache_dir=str(tmp_path), use_pallas_bfs=False,
               prewarm_range_dims=(ord("i"),))
    rt1 = ServeRuntime(graph, ServeConfig(**cfg))
    r1 = rt1.submit_range(lo=3, hi=9).result(timeout=60)
    cold = rt1.stats_snapshot()["aot"]
    rt1.close()
    assert cold["puts"] >= 1, cold

    rt2 = ServeRuntime(graph, ServeConfig(**cfg))
    assert getattr(graph.incremental.base, "_value_index_cols", None)
    r2 = rt2.submit_range(lo=3, hi=9).result(timeout=60)
    warm = rt2.stats_snapshot()["aot"]
    rt2.close()
    assert warm["misses"] == 0, warm
    assert warm["disk_hits"] >= 1 or warm["hits"] >= 1, warm
    assert r1.count == r2.count
    np.testing.assert_array_equal(r1.matches, r2.matches)


def test_range_request_validation():
    with pytest.raises(Unservable):
        RangeRequest(dim=ord("i"), lo_rank=0, hi_rank=1, lo_op="lt")
    with pytest.raises(Unservable):
        RangeRequest(dim=ord("i"), lo_rank=0, hi_rank=1, limit=0)


def test_range_probe_batch_matches_numpy_searchsorted():
    """Kernel-level differential: the 4-word branchless binary search ==
    np.searchsorted over the recombined 128-bit rank pairs, both sides,
    at duplicate values, first-word ties, and both column ends."""
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.value_index import range_probe_batch

    r = np.random.default_rng(9)
    ranks = np.sort(r.integers(0, 1 << 40, size=100).astype(np.uint64))
    ranks[10:15] = ranks[10]  # duplicates
    ranks2 = r.integers(0, 1 << 40, size=100).astype(np.uint64)
    ranks2[10:15] = np.sort(ranks2[10:15])  # tie band stays sorted
    ranks2[12] = ranks2[11]  # a full 128-bit duplicate inside the band
    order = np.lexsort((ranks2, ranks))
    ranks, ranks2 = ranks[order], ranks2[order]
    hi = (ranks >> np.uint64(32)).astype(np.uint32)
    lo = (ranks & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi2 = (ranks2 >> np.uint64(32)).astype(np.uint32)
    lo2 = (ranks2 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    pad = np.full(28, 0xFFFFFFFF, dtype=np.uint32)
    col_hi = np.concatenate([hi, pad])
    col_lo = np.concatenate([lo, pad])
    col_hi2 = np.concatenate([hi2, pad])
    col_lo2 = np.concatenate([lo2, pad])
    qi = [0, 10, 12, 50, 99]
    q = np.concatenate([ranks[qi], np.asarray([0, 1 << 63], np.uint64)])
    q2 = np.concatenate([ranks2[qi], np.asarray([0, 0], np.uint64)])
    # the reference search runs over the pair as python ints (numpy has
    # no native 128-bit ordering)
    pairs = [(int(a), int(b)) for a, b in zip(ranks, ranks2)]
    for right in (False, True):
        lo_idx, hi_idx = range_probe_batch(
            jnp.asarray(col_hi), jnp.asarray(col_lo),
            jnp.asarray(col_hi2), jnp.asarray(col_lo2), jnp.int32(100),
            jnp.asarray((q >> np.uint64(32)).astype(np.uint32)),
            jnp.asarray((q & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
            jnp.asarray((q2 >> np.uint64(32)).astype(np.uint32)),
            jnp.asarray((q2 & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
            jnp.asarray(np.full(len(q), right)),
            jnp.asarray((q >> np.uint64(32)).astype(np.uint32)),
            jnp.asarray((q & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
            jnp.asarray((q2 >> np.uint64(32)).astype(np.uint32)),
            jnp.asarray((q2 & np.uint64(0xFFFFFFFF)).astype(np.uint32)),
            jnp.asarray(np.full(len(q), right)),
        )
        import bisect

        probe = list(zip((int(v) for v in q), (int(v) for v in q2)))
        fn = bisect.bisect_right if right else bisect.bisect_left
        want = np.asarray([fn(pairs, p) for p in probe], dtype=np.int32)
        np.testing.assert_array_equal(np.asarray(lo_idx), want)
        np.testing.assert_array_equal(np.asarray(hi_idx), want)


def test_join_value_window_filters_candidates(graph):
    """The executor hook: a rank window passed through
    ``execute_join(value_windows=...)`` filters the intersection
    candidates ON DEVICE — counts and bindings match the host plan's
    answer for the same conjunction."""
    from hypergraphdb_tpu.join.ir import split_constants
    from hypergraphdb_tpu.join.planner import plan_join, try_single_var_join
    from hypergraphdb_tpu.ops.join import execute_join
    from hypergraphdb_tpu.utils.ordered_bytes import encode_int, rank64

    vn = [int(graph.add(100 + i)) for i in range(12)]
    anchor = vn[0]
    for i in range(1, 12):
        graph.add_link([anchor, vn[i]], value=f"l{i}")
    cond = c.And(c.CoIncident(anchor), c.AtomValue(103, "gte"),
                 c.AtomValue(108, "lt"))
    truth = sorted(int(h) for h in graph.find_all(cond))
    assert len(truth) == 5

    plan_obj = try_single_var_join(
        graph, [c.CoIncident(anchor)], fallback=None,
        value_conds=[c.AtomValue(103, "gte"), c.AtomValue(108, "lt")],
    )
    snap = graph.snapshot()
    jp = plan_join(snap, plan_obj.pattern, plan_obj.sig, plan_obj.consts)
    win = {jp.order[0]: (ord("i"), rank64(encode_int(103)), "gte",
                         rank64(encode_int(108)), "lt")}
    consts = np.asarray([plan_obj.consts], dtype=np.int32)
    out = execute_join(snap, jp, consts, top_r=16, value_windows=win)
    assert not bool(np.asarray(out.trunc)[0])
    assert int(np.asarray(out.counts)[0]) == len(truth)
    rows = np.asarray(out.tuples)[0]
    got = sorted(int(x) for x in rows[rows[:, 0] >= 0][:, 0])
    assert got == truth
    # and WITHOUT the window the same plan binds the unfiltered set —
    # the filter really ran inside the step, not in this test
    out_nf = execute_join(snap, jp, consts, top_r=16)
    assert int(np.asarray(out_nf.counts)[0]) == 11


def test_join_pushdown_plan_carries_value_conds(graph):
    """Through find_all: the value-constrained co-incidence conjunction
    translates to a DeviceJoinPlan carrying the value conds (cost-based
    at run time, exact on either arm), and memtable candidates respect
    the window."""
    from hypergraphdb_tpu.join.planner import DeviceJoinPlan
    from hypergraphdb_tpu.query.compiler import compile_query

    vn = [int(graph.add(100 + i)) for i in range(12)]
    anchor = vn[0]
    for i in range(1, 12):
        graph.add_link([anchor, vn[i]], value=f"l{i}")
    cond = c.And(c.CoIncident(anchor), c.AtomValue(103, "gte"),
                 c.AtomValue(108, "lt"))
    cq = compile_query(graph, cond)
    assert isinstance(cq.plan, DeviceJoinPlan)
    assert len(cq.plan.value_conds) == 2
    truth = sorted(int(h) for h in graph.find_all(cond))
    assert len(truth) == 5
    # memtable candidates respect the value window too
    graph.enable_incremental(background=False, compact_ratio=100.0)
    inwin = int(graph.add(105))
    outwin = int(graph.add(150))
    graph.add_link([anchor, inwin], value="f1")
    graph.add_link([anchor, outwin], value="f2")
    got2 = sorted(int(h) for h in graph.find_all(cond))
    assert inwin in got2 and outwin not in got2
