"""Query engine tests: DSL coverage, plan shape, correctness.

Covers the intent of ``testcore/test/java/hgtest/query/`` (``Queries.java``
DSL coverage, ``QueryCompilation.java`` plan shape, ``Inters1``
intersection correctness — SURVEY §4), plus a differential check that the
planner's index-based answers match brute-force predicate evaluation.
"""

import dataclasses

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query import dsl as hg
from hypergraphdb_tpu.query.compiler import compile_query

from conftest import make_random_hypergraph


@dataclasses.dataclass
class Person:
    name: str
    age: int


@pytest.fixture
def populated(graph: HyperGraph):
    g = graph
    strings = [g.add(s) for s in ("apple", "banana", "cherry")]
    ints = [g.add(i) for i in (1, 2, 3, 42)]
    people = [g.add(Person("ada", 36)), g.add(Person("bob", 25))]
    l1 = g.add_link((strings[0], ints[0]), value="l1")
    l2 = g.add_link((strings[0], ints[1]), value="l2")
    l3 = g.add_link((strings[1], ints[0], ints[1]), value="l3")
    return g, strings, ints, people, (l1, l2, l3)


def test_find_by_type(populated):
    g, strings, ints, people, links = populated
    res = g.find_all(hg.type_("string"))
    assert set(strings) | {links[0], links[1], links[2]} >= set(res)
    assert set(strings) <= set(res)


def test_find_by_value(populated):
    g, strings, ints, *_ = populated
    assert g.find_all(hg.eq("banana")) == [strings[1]]
    assert g.find_all(hg.eq(42)) == [ints[3]]
    assert g.find_all(hg.eq("nope")) == []


def test_value_type_strict(populated):
    """int 1 must not match float 1.0 or bool True (reference Java equals)."""
    g, strings, ints, *_ = populated
    fh = g.add(1.0)
    bh = g.add(True)
    res = g.find_all(hg.eq(1))
    assert ints[0] in res
    assert fh not in res and bh not in res


def test_value_ranges(populated):
    g, strings, ints, *_ = populated
    assert set(g.find_all(hg.lt(3))) == {ints[0], ints[1]}
    assert set(g.find_all(hg.gte(3))) == {ints[2], ints[3]}
    assert set(g.find_all(hg.and_(hg.gt(1), hg.lt(42)))) == {ints[1], ints[2]}


def test_typed_value(populated):
    g, strings, *_ = populated
    assert g.find_all(hg.typed_value("string", "apple")) == [strings[0]]
    assert g.find_all(hg.typed_value("int", "apple")) == []


def test_incident(populated):
    g, strings, ints, people, (l1, l2, l3) = populated
    assert set(g.find_all(hg.incident(strings[0]))) == {l1, l2}
    assert set(g.find_all(hg.incident(ints[0]))) == {l1, l3}
    # conjunctive pattern: And(incident, incident) — the headline query shape
    assert g.find_all(hg.and_(hg.incident(strings[0]), hg.incident(ints[0]))) == [l1]


def test_incident_at_position(populated):
    g, strings, ints, people, (l1, l2, l3) = populated
    assert set(g.find_all(hg.incident_at(ints[0], 1))) == {l1, l3}
    assert g.find_all(hg.incident_at(ints[0], 0)) == []


def test_link_condition(populated):
    g, strings, ints, people, (l1, l2, l3) = populated
    assert set(g.find_all(hg.link(strings[0]))) == {l1, l2}
    assert g.find_all(hg.link(ints[0], ints[1])) == [l3]


def test_ordered_link(populated):
    g, strings, ints, people, (l1, l2, l3) = populated
    assert g.find_all(hg.ordered_link(strings[1], ints[0])) == [l3]
    assert g.find_all(hg.ordered_link(ints[0], strings[1])) == []


def test_target(populated):
    g, strings, ints, people, (l1, l2, l3) = populated
    assert set(g.find_all(hg.target(l3))) == {strings[1], ints[0], ints[1]}


def test_arity_and_islink(populated):
    g, strings, ints, people, (l1, l2, l3) = populated
    res = g.find_all(hg.and_(hg.is_link(), hg.arity(3)))
    assert res == [l3]
    nodes = g.find_all(hg.and_(hg.type_("int"), hg.is_node()))
    assert set(nodes) == set(ints)


def test_or_and_not(populated):
    g, strings, ints, *_ = populated
    res = set(g.find_all(hg.or_(hg.eq("apple"), hg.eq("banana"))))
    assert res == {strings[0], strings[1]}
    res = set(
        g.find_all(hg.and_(hg.type_("string"), hg.not_(hg.eq("apple")), hg.is_node()))
    )
    assert res == {strings[1], strings[2]}


def test_nothing_and_any(populated):
    g, *_ = populated
    assert g.find_all(hg.nothing()) == []
    assert g.count(hg.all_atoms()) == g.atom_count()
    # contradiction folds to Nothing at compile time
    q = compile_query(g, hg.and_(hg.type_("int"), hg.type_("string")))
    assert isinstance(q.simplified, c.Nothing)


def test_is_identity(populated):
    g, strings, *_ = populated
    assert g.find_all(hg.is_(strings[0])) == [strings[0]]
    assert g.find_all(hg.and_(hg.is_(strings[0]), hg.type_("int"))) == []


def test_part_condition(populated):
    g, strings, ints, people, links = populated
    assert g.find_all(hg.part("name", "ada")) == [people[0]]
    assert set(g.find_all(hg.part("age", 26, "lt"))) == {people[1]}


def test_type_plus(populated):
    g, *_ = populated

    @dataclasses.dataclass
    class Base:
        x: int

    @dataclasses.dataclass
    class Derived(Base):
        y: int = 0

    b = g.add(Base(1))
    d = g.add(Derived(2, 3))
    base_t = g.typesystem.infer(Base(0)).name
    assert set(g.find_all(hg.type_plus(base_t))) == {b, d}
    assert g.find_all(hg.type_(base_t)) == [b]


def test_predicate_condition(populated):
    g, strings, ints, *_ = populated
    odd = g.find_all(
        hg.and_(hg.type_("int"), hg.predicate(lambda gr, h: gr.get(h) % 2 == 1))
    )
    assert set(odd) == {ints[0], ints[2]}


def test_plan_shapes(populated):
    """QueryCompilation analogue: check the planner picks indices."""
    g, strings, ints, people, (l1, l2, l3) = populated
    q = compile_query(g, hg.and_(hg.type_("string"), hg.incident(ints[0])))
    d = q.analyze()
    # type+incident now FUSES into the typed-incidence plan (the
    # bdb-native annotation analogue) instead of a two-set intersection
    assert "typed-incident" in d and "type" in d
    q2 = compile_query(g, hg.eq("apple"))
    assert "value" in q2.analyze()
    q3 = compile_query(g, hg.predicate(lambda gr, h: True))
    assert "scan" in q3.analyze()


def test_query_count(populated):
    g, strings, *_ = populated
    assert g.count(hg.type_("int")) == 4


def test_parallel_or(populated):
    g, strings, ints, *_ = populated
    g.config.query.parallel_or = True
    res = set(g.find_all(hg.or_(hg.eq("apple"), hg.eq(42), hg.eq(1))))
    assert res == {strings[0], ints[3], ints[0]}
    g.config.query.parallel_or = False


def test_differential_random_graph(graph: HyperGraph):
    """Planner answers == brute-force predicate answers on a random graph."""
    g = graph
    nodes, links = make_random_hypergraph(g, n_nodes=60, n_links=120, seed=7)
    conds = [
        hg.type_("string"),
        hg.type_("int"),
        hg.incident(nodes[0]),
        hg.incident(nodes[1]),
        hg.and_(hg.type_("int"), hg.incident(nodes[0])),
        hg.and_(hg.incident(nodes[0]), hg.incident(nodes[1])),
        hg.or_(hg.incident(nodes[2]), hg.incident(nodes[3])),
        hg.and_(hg.is_link(), hg.arity(2)),
        hg.and_(hg.type_("int"), hg.not_(hg.incident(nodes[0]))),
        hg.lt(50),
        hg.and_(hg.gte(10), hg.lt(20)),
    ]
    all_atoms = list(g.atoms())
    for cond in conds:
        expected = sorted(h for h in all_atoms if cond.satisfies(g, h))
        got = sorted(g.find_all(cond))
        assert got == expected, f"mismatch for {cond}"
