"""hgobs × serving integration: the overhead contract, the span chain,
admission priorities, and the cross-layer wiring (query / compaction / tx).

The acceptance-critical pair:

- **tracing off** (the default): a serving loop executes the IDENTICAL
  dispatch sequence as before hgobs existed (event-order differential
  against the fake executor) and allocates nothing per request beyond the
  one gate read — asserted by poisoning ``Tracer.start_trace``;
- **tracing on**: a served request's trace carries the full
  ``submit → queue_wait → batch_form → launch → collect → resolve``
  chain (+ ``device`` with timing opt-in, ``host_fallback`` on the exact
  path, ``shed`` on deadline expiry) with non-negative, properly nested
  durations.

Deterministic throughout: manual-mode runtimes, one FakeClock shared by
the runtime and the tracer, fake executors everywhere the device does not
matter.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu.obs.trace import Tracer
from hypergraphdb_tpu.serve import (
    DeadlineExceeded,
    ServeConfig,
    ServeResult,
    ServeRuntime,
)
from tests.test_serve_runtime import FakeClock, FakeExecutor


def make_runtime(tracer=None, clock=None, buckets=(4, 16), linger=0.010,
                 **kw):
    clock = clock or FakeClock()
    cfg = ServeConfig(buckets=buckets, max_linger_s=linger, clock=clock,
                      manual=True, tracer=tracer, **kw)
    ex = FakeExecutor()
    return ServeRuntime(graph=None, config=cfg, executor=ex), ex, clock


def traced_runtime(**kw):
    clock = FakeClock()
    tracer = Tracer(clock=clock)
    tracer.enable()
    rt, ex, _ = make_runtime(tracer=tracer, clock=clock, **kw)
    return rt, ex, clock, tracer


def run_workload(rt, clock):
    """A fixed mixed workload; returns the executor's event log."""
    rt.submit_bfs(1)
    rt.submit_bfs(2)
    rt.pump(drain=True)          # launch B0
    rt.submit_pattern([1, 2])
    rt.submit_bfs(3, max_hops=5)
    clock.advance(0.02)          # linger both remaining groups
    while rt.pump(drain=True):
        pass
    rt.close(drain=True)


# ------------------------------------------------------------- off-gate


def test_tracing_off_identical_dispatch_sequence():
    """Differential: the event order with obs wired in (disabled) matches
    the machinery's committed pipelining contract exactly."""
    rt, ex, clock = make_runtime()
    assert rt.tracer.enabled is False
    run_workload(rt, clock)
    assert ex.events == [
        ("launch", 0), ("launch", 1), ("collect", 0),
        ("launch", 2), ("collect", 1), ("collect", 2),
    ]


def test_tracing_off_allocates_no_trace_objects(monkeypatch):
    """The disabled path must never reach trace construction: poison
    start_trace and run the full serving workload."""
    def boom(self, name, **attrs):  # pragma: no cover - must not run
        raise AssertionError("start_trace called with tracing off")

    monkeypatch.setattr(Tracer, "start_trace", boom)
    rt, ex, clock = make_runtime()
    run_workload(rt, clock)
    assert len(ex.batches) == 3


def test_tracing_off_tickets_carry_no_trace():
    rt, ex, clock = make_runtime()
    rt.submit_bfs(1)
    (t,) = rt.queue._dq
    assert t.trace is None
    rt.close(drain=True)


# ------------------------------------------------------------ span chain


def test_served_request_full_span_chain():
    rt, ex, clock, tracer = traced_runtime(linger=0.0)
    fut = rt.submit_bfs(7, max_hops=2)
    clock.advance(0.003)
    assert rt.step(drain=True)
    assert fut.result(timeout=0).kind == "bfs"
    (tr,) = tracer.drain()

    assert tr.name == "serve.request"
    assert tr.attrs == {"kind": "bfs", "priority": 0}
    names = [s.name for s in tr.spans()]
    assert names == ["request", "submit", "queue_wait", "batch_form",
                     "launch", "collect", "resolve"]
    root = tr.find("request")
    by = {s.name: s for s in tr.spans()}
    # every stage is a child of the root request span
    for n in names[1:]:
        assert by[n].parent_id == root.span_id, n
    # chain is ordered, durations non-negative, all nested in the root
    for a, b in zip(names[1:], names[2:]):
        assert by[a].t0 <= by[b].t0, (a, b)
    for s in tr.spans():
        assert s.t1 is not None and s.t1 >= s.t0
        assert root.t0 <= s.t0 and s.t1 <= root.t1
    assert by["queue_wait"].duration == pytest.approx(0.003)
    assert by["batch_form"].attrs == {"bucket": 4, "n_real": 1, "n_pad": 3}
    assert by["resolve"].attrs == {"delivered": True}
    assert tr.dropped == 0


def test_shed_request_trace_ends_with_shed():
    rt, ex, clock, tracer = traced_runtime()
    fut = rt.submit_bfs(1, deadline_s=0.5)
    clock.advance(1.0)
    assert rt.step(drain=True) is False
    with pytest.raises(DeadlineExceeded):
        fut.result(timeout=0)
    (tr,) = tracer.drain()
    names = [s.name for s in tr.spans()]
    assert names == ["request", "submit", "queue_wait", "shed"]
    assert tr.find("shed").attrs["waited_s"] == pytest.approx(1.0)
    assert ex.batches == []  # still no dispatch for a dead request


def test_launch_error_trace_ends_with_error():
    from tests.test_serve_runtime import ExplodingExecutor

    clock = FakeClock()
    tracer = Tracer(clock=clock)
    tracer.enable()
    cfg = ServeConfig(buckets=(4,), clock=clock, manual=True,
                      max_linger_s=0.0, tracer=tracer)
    rt = ServeRuntime(graph=None, config=cfg, executor=ExplodingExecutor())
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    with pytest.raises(RuntimeError):
        fut.result(timeout=0)
    (tr,) = tracer.drain()
    assert [s.name for s in tr.spans()][-1] == "error"
    assert tr.find("error").attrs == {"error": "RuntimeError"}


def test_host_fallback_span_recorded():
    class HostExecutor(FakeExecutor):
        def collect(self, token):
            idx, batch = token
            self.events.append(("collect", idx))
            return [
                (t, ServeResult(t.request.kind, 0,
                                np.empty(0, dtype=np.int64), False, 0,
                                served_by="host"))
                for t in batch.tickets
            ]

    clock = FakeClock()
    tracer = Tracer(clock=clock)
    tracer.enable()
    cfg = ServeConfig(buckets=(4,), clock=clock, manual=True,
                      max_linger_s=0.0, tracer=tracer)
    rt = ServeRuntime(graph=None, config=cfg, executor=HostExecutor())
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    assert fut.result(timeout=0).served_by == "host"
    (tr,) = tracer.drain()
    names = [s.name for s in tr.spans()]
    assert "host_fallback" in names
    assert names[-1] == "resolve"


def test_span_budget_bounds_a_request_trace():
    rt, ex, clock, tracer = traced_runtime(linger=0.0)
    tracer.max_spans = 3
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    fut.result(timeout=0)
    (tr,) = tracer.drain()
    assert len(tr.spans()) == 3
    assert tr.dropped > 0


# ------------------------------------------------------------- priorities


def test_higher_priority_class_pops_first():
    rt, ex, clock = make_runtime(linger=1e9)
    lo = rt.submit_bfs(1, max_hops=2, priority=0)
    lo2 = rt.submit_bfs(2, max_hops=2, priority=0)
    hi = rt.submit_pattern([1, 2], priority=5)
    # batch formation follows the HIGHEST class present, not arrival order
    assert rt.step(drain=True)
    assert ex.batches[0].key == ("pattern", 2)
    assert rt.step(drain=True)
    assert ex.batches[1].key == ("bfs", 2)
    assert [t.request.seed for t in ex.batches[1].tickets] == [1, 2]
    for f in (lo, lo2, hi):
        assert f.result(timeout=0) is not None


def test_priority_fifo_within_class_and_lane_order():
    rt, ex, clock = make_runtime(linger=1e9)
    rt.submit_bfs(1, priority=0)
    rt.submit_bfs(2, priority=9)
    rt.submit_bfs(3, priority=9)
    rt.submit_bfs(4, priority=0)
    assert rt.step(drain=True)
    (batch,) = ex.batches
    # one batch (same key); lanes ordered class-desc, FIFO within class
    assert [t.request.seed for t in batch.tickets] == [2, 3, 1, 4]
    assert [t.priority for t in batch.tickets] == [9, 9, 0, 0]


def test_lingered_low_priority_not_starved_by_hi_trickle():
    """A lingered low-priority group must keep flushing the queue until
    it reaches the front — a trickle of fresh high-priority arrivals
    (each younger than the linger) cannot park it forever."""
    rt, ex, clock = make_runtime(linger=0.10)
    lo = rt.submit_bfs(1, max_hops=2, priority=0)      # key A at t=0
    clock.advance(0.08)
    rt.submit_pattern([1, 2], priority=5)              # key B, fresh
    clock.advance(0.03)                                # t=0.11: lo lingered
    # lo's linger forces a flush even though the hi-pri head is young;
    # priority still decides WHICH key goes first
    assert rt.step() is True
    assert ex.batches[0].key == ("pattern", 2)
    # the very next cycle reaches the lingered low-priority group
    assert rt.step() is True
    assert ex.batches[1].key == ("bfs", 2)
    assert lo.result(timeout=0).kind == "bfs"
    # and the dispatch thread's sleep is keyed to the oldest ticket too
    rt.submit_bfs(9, priority=0)
    clock.advance(0.05)
    rt.submit_pattern([3, 4], priority=5)
    assert rt.batcher.time_to_flush(clock()) == pytest.approx(0.05)


def test_priority_deadline_shedding_unchanged():
    rt, ex, clock = make_runtime()
    hi_dead = rt.submit_bfs(1, deadline_s=0.5, priority=9)
    lo_live = rt.submit_bfs(2, deadline_s=10.0, priority=0)
    clock.advance(1.0)
    assert rt.step(drain=True)
    with pytest.raises(DeadlineExceeded):
        hi_dead.result(timeout=0)  # priority does not outrank a deadline
    assert lo_live.result(timeout=0).kind == "bfs"
    assert rt.stats.shed_deadline == 1


def test_priority_backpressure_unchanged():
    from hypergraphdb_tpu.serve import QueueFull

    rt, ex, clock = make_runtime(policy="fail", max_queue=2)
    rt.submit_bfs(1, priority=0)
    rt.submit_bfs(2, priority=0)
    with pytest.raises(QueueFull):
        rt.submit_bfs(3, priority=9)  # a full queue is priority-blind
    assert rt.stats.rejected_queue_full == 1


def test_priority_rides_into_trace_attrs():
    rt, ex, clock, tracer = traced_runtime(linger=0.0)
    fut = rt.submit_bfs(1, priority=3)
    rt.step(drain=True)
    fut.result(timeout=0)
    (tr,) = tracer.drain()
    assert tr.attrs["priority"] == 3


# ------------------------------------------------- cross-layer wiring


@pytest.fixture
def global_tracing():
    """Enable the PROCESS tracer for one test, restore after."""
    from hypergraphdb_tpu import obs

    tracer = obs.tracer()
    tracer.enable()
    tracer.drain()
    try:
        yield tracer
    finally:
        tracer.disable()
        tracer.drain()


def test_query_trace_compile_plan_execute(graph, global_tracing):
    from hypergraphdb_tpu.query import dsl
    from hypergraphdb_tpu.query.compiler import compile_query

    h = graph.add("obs-q")
    cq = compile_query(graph, dsl.value("obs-q"))
    assert list(cq.execute()) == [int(h)]
    traces = [t for t in global_tracing.drain() if t.name == "query"]
    assert traces, "no query trace recorded"
    tr = traces[-1]
    names = [s.name for s in tr.spans()]
    assert names == ["query", "compile", "plan", "execute"]
    root = tr.find("query")
    for s in tr.spans()[1:]:
        assert s.parent_id == root.span_id
        assert s.t1 is not None and s.t1 >= s.t0
    assert tr.find("execute").attrs["results"] == 1
    assert "plan" in tr.find("plan").attrs
    # a second execute() must not grow the finished trace
    list(cq.execute())
    assert [t.name for t in global_tracing.drain()].count("query") == 0


def test_query_trace_finishes_via_results_and_count(graph, global_tracing):
    from hypergraphdb_tpu.query import dsl
    from hypergraphdb_tpu.query.compiler import compile_query

    graph.add("obs-r")
    assert len(compile_query(graph, dsl.value("obs-r")).results()) == 1
    assert compile_query(graph, dsl.value("obs-r")).count() == 1
    finished = [t for t in global_tracing.drain() if t.name == "query"]
    assert len(finished) == 2  # both read paths export their trace
    for tr in finished:
        assert tr.find("execute") is not None


def test_query_trace_exported_when_execute_raises(graph, global_tracing):
    from hypergraphdb_tpu.query import dsl
    from hypergraphdb_tpu.query.compiler import compile_query

    cq = compile_query(graph, dsl.value("whatever"))

    class BrokenPlan:
        def run(self, g):
            raise RuntimeError("plan fell over")

    cq.plan = BrokenPlan()
    with pytest.raises(RuntimeError, match="plan fell over"):
        list(cq.execute())
    (tr,) = [t for t in global_tracing.drain() if t.name == "query"]
    # the failing query is the one worth keeping: closed execute span
    # plus the shared error terminal
    assert tr.find("execute").t1 is not None
    assert tr.find("error").attrs == {"error": "RuntimeError"}
    assert tr.finished


def test_compact_trace_exported_when_swap_raises(graph, global_tracing,
                                                 monkeypatch):
    mgr = graph.enable_incremental()
    global_tracing.drain()
    monkeypatch.setattr(
        mgr, "_assemble_and_swap",
        lambda ext: (_ for _ in ()).throw(RuntimeError("swap OOM")),
    )
    with pytest.raises(RuntimeError, match="swap OOM"):
        mgr._compact_sync()
    (tr,) = [t for t in global_tracing.drain() if t.name == "compact"]
    assert tr.find("error").attrs == {"error": "RuntimeError"}
    assert tr.find("buffer_drain") is not None
    snap = graph.metrics.snapshot()
    assert snap["counters"]["compact.failures"] == 1


def test_compaction_trace_and_metrics(graph, global_tracing):
    for i in range(4):
        graph.add(f"c{i}")
    mgr = graph.enable_incremental()
    global_tracing.drain()  # drop the init-pack trace
    a, b = graph.add("x"), graph.add("y")
    graph.add_link([a, b], value="e")
    mgr._compact_sync()
    traces = [t for t in global_tracing.drain() if t.name == "compact"]
    assert traces
    tr = traces[-1]
    names = [s.name for s in tr.spans()]
    assert names == ["compact", "buffer_drain", "device_swap"]
    root = tr.find("compact")
    for s in tr.spans()[1:]:
        assert s.parent_id == root.span_id
        assert root.t0 <= s.t0 <= s.t1 <= root.t1
    snap = graph.metrics.snapshot()
    assert snap["counters"]["compact.passes"] >= 1
    assert snap["timings"]["compact.extract_seconds"]["count"] >= 1


def test_tx_counters_mirrored_into_registry(graph):
    before = graph.metrics.snapshot()["counters"].get("tx.commits", 0)
    graph.add("tx-obs")
    after = graph.metrics.snapshot()["counters"]["tx.commits"]
    assert after > before
    # the mirror attaches before the typesystem bootstrap: the registry
    # counter and the legacy attribute agree EXACTLY, from atom zero
    assert graph.txman.committed == after


def test_query_trace_exported_when_compile_raises(graph, global_tracing):
    from hypergraphdb_tpu.core.errors import QueryError
    from hypergraphdb_tpu.query.compiler import compile_query

    with pytest.raises(QueryError):
        compile_query(graph, "not a condition at all")
    # pre-trace validation (no trace started) — now force a mid-compile
    # failure so the trace exists and must still export
    from hypergraphdb_tpu.query import dsl
    import hypergraphdb_tpu.query.compiler as qc

    orig = qc.translate

    def boom(*a, **k):
        raise QueryError("translate fell over")

    qc.translate = boom
    try:
        with pytest.raises(QueryError, match="translate fell over"):
            compile_query(graph, dsl.value("x"))
    finally:
        qc.translate = orig
    traces = [t for t in global_tracing.drain() if t.name == "query"]
    (tr,) = traces
    assert tr.find("error").attrs == {"error": "QueryError"}
    assert tr.finished


def test_device_timing_span_on_real_executor(graph):
    """Opt-in device attribution: a real DeviceExecutor batch carries a
    ``device`` span whose window sits between launch and collect."""
    import time

    for i in range(8):
        graph.add(f"d{i}")
    a, b = graph.add("da"), graph.add("db")
    graph.add_link([a, b], value="de")
    tracer = Tracer(clock=time.perf_counter)
    tracer.enable()
    cfg = ServeConfig(buckets=(4,), manual=True, max_linger_s=0.0,
                      tracer=tracer, device_timing=True, top_r=16)
    rt = ServeRuntime(graph, cfg)
    fut = rt.submit_bfs(int(a), max_hops=1)
    rt.step(drain=True)
    res = fut.result(timeout=30)
    assert res.served_by == "device"
    rt.close(drain=True)
    (tr,) = [t for t in tracer.drain() if t.name == "serve.request"]
    by = {s.name: s for s in tr.spans()}
    assert "device" in by, [s.name for s in tr.spans()]
    dev, launch, collect = by["device"], by["launch"], by["collect"]
    assert dev.duration >= 0.0
    assert launch.t0 <= dev.t0          # dispatched after launch began
    assert dev.t1 <= collect.t1         # ready before collect finished


def test_queue_depth_gauge_live_without_snapshot():
    """A direct registry scrape must see the real queue depth — the gauge
    is pushed on every admission mutation, not set as a snapshot() side
    effect."""
    rt, ex, clock = make_runtime(linger=1e9)
    gauge = rt.stats.registry.get("serve.queue_depth")
    rt.submit_bfs(1)
    rt.submit_bfs(2)
    assert gauge.value == 2.0
    rt.step(drain=True)
    assert gauge.value == 0.0
    import hypergraphdb_tpu.obs as obs

    assert "serve_queue_depth 2.0" not in obs.prometheus_text(
        rt.stats.registry
    )
    rt.close(drain=True)


def test_stats_snapshot_namespaced_through_runtime():
    rt, ex, clock = make_runtime(linger=0.0)
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    fut.result(timeout=0)
    legacy = rt.stats_snapshot()
    ns = rt.stats.snapshot_namespaced(queue_depth=legacy["queue_depth"])
    assert ns["serve.submitted"] == legacy["submitted"] == 1
    assert ns["serve.completed"] == legacy["completed"] == 1
    # the dotted key carries SECONDS (the unit its histogram commits to)
    assert ns["serve.latency_seconds"]["p50"] == pytest.approx(
        legacy["latency_ms"]["p50"] / 1e3
    )


# ----------------------------------------- sampling × serving (PR 7)


def test_sampled_on_dispatch_sequence_identical():
    """The sampled-on extension of the off-gate differential: the event
    order with tracing ON is identical to tracing off — at 100% AND at
    1% sampling. Sampling decides retention, never dispatch."""
    rt_off, ex_off, clock_off = make_runtime()
    run_workload(rt_off, clock_off)

    for rate in (1.0, 0.01):
        clock = FakeClock()
        tracer = Tracer(clock=clock, seed=7)
        tracer.enable()
        tracer.set_sample_rate("serve.request", rate)
        rt, ex, _ = make_runtime(tracer=tracer, clock=clock)
        run_workload(rt, clock)
        assert ex.events == ex_off.events, rate


def test_tracing_overhead_under_committed_bound():
    """The committed overhead bound (README "Distributed tracing &
    operations"): with tracing on, the full submit→dispatch→resolve path
    averages < 5 ms/request on the fake-executor differential — a ~50×
    cushion over the measured cost, tight enough to catch a pathological
    regression (unbounded retention, per-span lock convoys), loose
    enough to never flake on a busy CI box."""
    import time as _time

    N = 300

    def run(tracer, rate):
        clock = FakeClock()
        if tracer is not None:
            tracer.clock = clock
            tracer.set_sample_rate("serve.request", rate)
        rt, ex, _ = make_runtime(tracer=tracer, clock=clock,
                                 buckets=(64,), linger=0.0)
        t0 = _time.perf_counter()
        for i in range(N):
            rt.submit_bfs(i)
            if i % 64 == 63:
                rt.step(drain=True)
        while rt.step(drain=True):
            pass
        rt.close(drain=True)
        return (_time.perf_counter() - t0) / N

    for rate in (1.0, 0.01):
        tracer = Tracer(seed=3)
        tracer.enable()
        per_request = run(tracer, rate)
        assert per_request < 0.005, (rate, per_request)


def test_one_percent_sampling_bounded_buffer_full_incident_capture():
    """The production posture: 1% head sampling against a SMALL finished
    buffer under a c6-style request storm — the buffer never overflows
    (zero evictions) while shed/error traces are still captured at 100%
    (always-sample overrides)."""
    clock = FakeClock()
    tracer = Tracer(clock=clock, max_finished=64, seed=11)
    tracer.enable()
    tracer.set_sample_rate("serve.request", 0.01)
    rt, ex, _ = make_runtime(tracer=tracer, clock=clock, buckets=(64,),
                             linger=0.0)
    retained = []
    # 960 healthy requests in waves, scraping (drain) like an exporter
    for wave in range(15):
        for i in range(64):
            rt.submit_bfs(i)
        rt.step(drain=True)
        retained.extend(tracer.drain())
    # 20 doomed requests: deadline expires before dispatch → shed
    doomed = [rt.submit_bfs(i, deadline_s=0.5) for i in range(20)]
    clock.advance(1.0)
    rt.step(drain=True)
    for f in doomed:
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=0)
    rt.close(drain=True)
    retained.extend(tracer.drain())

    assert tracer.traces_evicted == 0          # never overflowed
    shed = [t for t in retained
            if any(s.name == "shed" for s in t.spans())]
    assert len(shed) == 20                     # incidents at 100%
    healthy = len(retained) - len(shed)
    # ~1% of 960 — bounded well below the buffer, but the stream is real
    assert 0 < healthy < 64
    assert tracer.traces_dropped == 960 - healthy
