"""Graph kernel tests: atom CRUD, links, incidence, types.

Covers the intent of the reference's ``testcore`` CRUD/link/type suites
(``hgtest.TestCreateDB``, ``hgtest/links/``, ``hgtest/types/`` — SURVEY §4).
"""

import dataclasses

import pytest

from hypergraphdb_tpu import HGLink, HyperGraph, NotFoundError


def test_add_get_node(graph: HyperGraph):
    h = graph.add("hello")
    assert graph.get(h) == "hello"
    assert graph.contains(h)
    assert not graph.is_link(h)
    assert graph.arity(h) == 0


def test_add_primitives(graph: HyperGraph):
    vals = [42, -7, 3.14, True, False, "s", b"raw", [1, "two"], {"k": 1}, None]
    hs = [graph.add(v) for v in vals]
    for h, v in zip(hs, vals):
        assert graph.get(h) == v


def test_add_link(graph: HyperGraph):
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b), value="edge")
    got = graph.get(l)
    assert isinstance(got, HGLink)
    assert got.targets == (a, b)
    assert got.value == "edge"
    assert graph.is_link(l)
    assert graph.arity(l) == 2
    assert graph.get_targets(l) == (a, b)


def test_links_to_links(graph: HyperGraph):
    """The hypergraph property: links can target links
    (reference doc ``HyperGraph.java:64-75``)."""
    a, b = graph.add("a"), graph.add("b")
    l1 = graph.add_link((a, b))
    l2 = graph.add_link((l1, a), value="meta")
    assert graph.get(l2).targets == (l1, a)
    assert l2 in graph.get_incidence_set(l1)


def test_zero_arity_link(graph: HyperGraph):
    l = graph.add_link((), value="unit")
    assert graph.is_link(l)
    assert graph.arity(l) == 0


def test_incidence_maintained(graph: HyperGraph):
    a, b, c = (graph.add(x) for x in "abc")
    l1 = graph.add_link((a, b))
    l2 = graph.add_link((a, c))
    assert graph.get_incidence_set(a).array().tolist() == sorted([l1, l2])
    assert graph.get_incidence_set(b).array().tolist() == [l1]
    assert graph.get_incidence_set(c).array().tolist() == [l2]


def test_duplicate_target_incidence(graph: HyperGraph):
    a = graph.add("a")
    l = graph.add_link((a, a))
    assert graph.get_incidence_set(a).array().tolist() == [l]
    assert graph.get(l).targets == (a, a)


def test_get_missing_raises(graph: HyperGraph):
    with pytest.raises(NotFoundError):
        graph.get(99999)


def test_replace_value(graph: HyperGraph):
    h = graph.add("old")
    graph.replace(h, "new")
    assert graph.get(h) == "new"


def test_replace_changes_type(graph: HyperGraph):
    h = graph.add("str")
    graph.replace(h, 42)
    assert graph.get(h) == 42
    th = graph.get_type_handle_of(h)
    assert graph.typesystem.name_of(th) == "int"


def test_replace_keeps_incidence(graph: HyperGraph):
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b), value=1)
    graph.replace(l, 2)
    got = graph.get(l)
    assert got.value == 2
    assert got.targets == (a, b)
    assert l in graph.get_incidence_set(a)


def test_remove_node(graph: HyperGraph):
    h = graph.add("x")
    assert graph.remove(h)
    assert not graph.contains(h)
    assert not graph.remove(h)  # idempotent


def test_remove_cascades_to_incident_links(graph: HyperGraph):
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b))
    meta = graph.add_link((l,))
    graph.remove(a)
    assert not graph.contains(l)
    assert not graph.contains(meta)  # cascade through link-to-link
    assert graph.contains(b)
    assert len(graph.get_incidence_set(b)) == 0


def test_remove_keep_incident_links(graph: HyperGraph):
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b))
    graph.remove(a, keep_incident_links=True)
    assert graph.contains(l)
    assert graph.get(l).targets == (b,)


def test_remove_link_cleans_target_incidence(graph: HyperGraph):
    a, b = graph.add("a"), graph.add("b")
    l = graph.add_link((a, b))
    graph.remove(l)
    assert len(graph.get_incidence_set(a)) == 0
    assert graph.contains(a)


def test_atoms_scan_and_count(graph: HyperGraph):
    base = graph.atom_count()  # type atoms exist already
    hs = [graph.add(i) for i in range(5)]
    assert graph.atom_count() == base + 5
    assert set(hs) <= set(graph.atoms())


def test_bulk_nodes(graph: HyperGraph):
    r = graph.add_nodes_bulk(["a", "b", "c"])
    assert len(r) == 3
    assert [graph.get(h) for h in r] == ["a", "b", "c"]


def test_bulk_links(graph: HyperGraph):
    ns = list(graph.add_nodes_bulk([1, 2, 3]))
    r = graph.add_links_bulk([(ns[0], ns[1]), (ns[1], ns[2])], values=["x", "y"])
    got = graph.get(r[0])
    assert got.targets == (ns[0], ns[1])
    assert got.value == "x"
    assert r[1] in graph.get_incidence_set(ns[1])


# ---------------------------------------------------------------- types


@dataclasses.dataclass
class Person:
    name: str
    age: int


@dataclasses.dataclass
class Employee(Person):
    company: str = ""


def test_dataclass_roundtrip(graph: HyperGraph):
    p = Person("ada", 36)
    h = graph.add(p)
    assert graph.get(h) == p


def test_dataclass_type_registered(graph: HyperGraph):
    h = graph.add(Person("bob", 1))
    th = graph.get_type_handle_of(h)
    assert "Person" in graph.typesystem.name_of(th)


def test_record_projection(graph: HyperGraph):
    p = Person("ada", 36)
    t = graph.typesystem.infer(p)
    assert t.dimensions() == ["name", "age"]
    assert t.project(p, "name") == "ada"


def test_subtype_closure(graph: HyperGraph):
    graph.add(Person("a", 1))
    graph.add(Employee("b", 2, "acme"))
    ts = graph.typesystem
    pname = next(n for n in ts._by_name if n.endswith("Person"))
    closure = ts.subtypes_closure(pname)
    assert any(n.endswith("Employee") for n in closure)


def test_type_atoms_are_atoms(graph: HyperGraph):
    th = graph.typesystem.handle_of("int")
    assert graph.get(th) == "int"  # value of a type atom is its name
    assert graph.typesystem.is_type_handle(th)


def test_value_key_ordering(graph: HyperGraph):
    """Order-preserving key contract (HGPrimitiveType comparator analogue)."""
    it = graph.typesystem.get_type("int")
    assert it.to_key(-5) < it.to_key(0) < it.to_key(5) < it.to_key(1000)
    ft = graph.typesystem.get_type("float")
    assert ft.to_key(-2.5) < ft.to_key(-1.0) < ft.to_key(0.0) < ft.to_key(3.7)
    st = graph.typesystem.get_type("string")
    assert st.to_key("abc") < st.to_key("abd") < st.to_key("b")


# ---------------------------------------------------------------- bulk loader


def test_bulk_import_equals_buffered_path(graph):
    import numpy as np
    import hypergraphdb_tpu as hg
    from hypergraphdb_tpu.query import dsl as q

    nodes = graph.bulk_import(values=[f"b{i}" for i in range(50)])
    links = graph.bulk_import(
        values=list(range(20)),
        target_lists=[[int(nodes[i]), int(nodes[i + 1])] for i in range(20)],
    )
    assert graph.get(links[3]).targets == (int(nodes[3]), int(nodes[4]))
    assert q.find_all(graph, q.value("b7")) == [int(nodes[7])]
    assert int(links[0]) in graph.get_incidence_set(nodes[0]).array().tolist()
    # reference graph through the buffered path must produce the same CSR
    g2 = hg.HyperGraph()
    n2 = g2.add_nodes_bulk([f"b{i}" for i in range(50)])
    g2.add_links_bulk(
        [[int(n2[i]), int(n2[i + 1])] for i in range(20)],
        values=list(range(20)),
    )
    s1, s2 = graph.snapshot(), g2.snapshot()
    np.testing.assert_array_equal(s1.inc_offsets, s2.inc_offsets)
    np.testing.assert_array_equal(s1.tgt_flat, s2.tgt_flat)
    g2.close()


def test_bulk_import_inside_tx_uses_buffered_path(graph):
    def run():
        r = graph.bulk_import(values=["tx1", "tx2"])
        return r

    r = graph.txman.transact(run)
    assert graph.get(r[0]) == "tx1"


def test_multihost_helpers():
    from hypergraphdb_tpu.parallel import multihost

    info = multihost.local_process_info()
    assert info["process_count"] >= 1
    mesh = multihost.global_mesh()
    assert mesh.devices.size == info["global_devices"]
    assert not multihost.is_multihost()


# ---------------------------------------------------------------- caching


def test_incidence_cache_hit_and_invalidation(graph):
    """The incidence LRU (HGConfiguration.maxCachedIncidenceSetSize
    analogue) must serve repeated reads and invalidate on mutation."""
    a = graph.add("hub")
    l1 = graph.add_link((a,), value=1)
    cache = graph.store._inc_cache
    assert cache is not None
    r1 = graph.get_incidence_set(a).array()
    assert r1.tolist() == [int(l1)]
    assert int(a) in cache  # populated
    # a cached array is shared readonly — callers cannot corrupt it
    import numpy as np
    import pytest as _pytest
    hit = graph.get_incidence_set(a).array()
    if hit.base is not None or not hit.flags.writeable:
        with _pytest.raises(ValueError):
            hit[0] = 999
    # mutation bumps the cell version: next read re-fetches
    l2 = graph.add_link((a,), value=2)
    r2 = graph.get_incidence_set(a).array()
    assert r2.tolist() == sorted([int(l1), int(l2)])


def test_oversized_incidence_sets_not_cached():
    from hypergraphdb_tpu import HGConfiguration, HyperGraph

    cfg = HGConfiguration()
    cfg.cache.max_cached_incidence_set_size = 2
    g = HyperGraph(cfg)
    a = g.add("hub")
    for i in range(5):
        g.add_link((a,), value=i)
    assert len(g.get_incidence_set(a)) == 5
    assert int(a) not in g.store._inc_cache  # over the cap: not cached
    g.close()


def test_memory_warning_evicts_caches():
    from hypergraphdb_tpu import HGConfiguration, HyperGraph

    cfg = HGConfiguration()
    cfg.cache.memory_warning_bytes = 1  # any RSS trips it
    cfg.cache.memory_warning_interval_s = 3600  # no background noise
    g = HyperGraph(cfg)
    a = g.add("x")
    g.add_link((a,), value=1)
    g.get_incidence_set(a)
    assert len(g.store._inc_cache) > 0
    assert g._memwatch.check_now()  # over threshold → listeners fired
    assert len(g.store._inc_cache) == 0
    g.close()
