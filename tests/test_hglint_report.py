"""Machine-readable hglint report (``--output json``) + CLI exit-code
contract: 0 clean, 1 findings, 3 analyzer crash (tools/lint.sh treats
>= 2 as an infrastructure failure, not a finding)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.hglint import RULES, build_report, doc_anchor, run_lint  # noqa: E402
from tools.hglint import __main__ as hglint_main  # noqa: E402
from tools.hglint import engine  # noqa: E402

FIXTURES = Path(__file__).parent / "hglint_fixtures"


def _cli(*args):
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", str(REPO))
    return subprocess.run(
        [sys.executable, "-m", "tools.hglint", *args],
        cwd=REPO, capture_output=True, text=True, env=env,
    )


# ---------------------------------------------------------------- report


def test_output_json_report_shape():
    out = _cli(str(FIXTURES / "bad_pkg"), "--output", "json")
    assert out.returncode == 1
    rep = json.loads(out.stdout)
    assert rep["tool"] == "hglint"
    assert rep["report_version"] >= 2
    assert rep["baseline"] == {
        "path": None, "applied": False, "suppressed": 0,
    }
    counts = rep["counts"]
    assert counts["total"] == len(rep["findings"])
    assert sum(counts["by_rule"].values()) == counts["total"]
    assert sum(counts["by_severity"].values()) == counts["total"]
    for f in rep["findings"]:
        assert {"rule", "severity", "path", "line", "scope", "message",
                "doc"} <= set(f)
        assert f["rule"] in RULES
        assert f["doc"].startswith("README.md#")
        assert f["doc"] == doc_anchor(f["rule"])
    # the report must cover every family the bad fixtures seed
    fams = {r[:3] for r in counts["by_rule"]}
    assert {"HG1", "HG2", "HG3", "HG4", "HG5", "HG6"} <= fams


def test_output_json_clean_report():
    out = _cli(str(FIXTURES / "clean_pkg"), "--output", "json")
    assert out.returncode == 0
    rep = json.loads(out.stdout)
    assert rep["counts"]["total"] == 0
    assert rep["findings"] == []


def test_report_builder_records_baseline_suppression():
    findings = run_lint([str(FIXTURES / "bad_pkg")])
    rep = build_report(
        findings, ["bad_pkg"], baseline_path="b.json", suppressed=3,
        only="HG5", vmem_budget=8 << 20,
    )
    assert rep["baseline"] == {
        "path": "b.json", "applied": True, "suppressed": 3,
    }
    assert rep["only"] == ["HG5"]
    assert rep["vmem_budget_bytes"] == 8 << 20


# ---------------------------------------------------------------- filters


def test_cli_only_filter_runs_one_family():
    out = _cli(str(FIXTURES / "bad_pkg"), "--only", "HG5",
               "--output", "json")
    assert out.returncode == 1
    rep = json.loads(out.stdout)
    assert rep["only"] == ["HG5"]
    assert rep["counts"]["by_rule"]
    assert all(r.startswith("HG5") for r in rep["counts"]["by_rule"])


def test_cli_vmem_budget_flag():
    out = _cli(str(FIXTURES / "bad_pkg" / "vmem_bad.py"),
               "--only", "HG501", "--vmem-budget", str(64 << 20))
    assert out.returncode == 0, out.stdout
    out = _cli(str(FIXTURES / "bad_pkg" / "vmem_bad.py"),
               "--only", "HG501", "--vmem-budget", str(1 << 20))
    assert out.returncode == 1
    assert "HG501" in out.stdout


# ------------------------------------------------------------- exit codes


def test_analyzer_crash_exits_3_not_1(monkeypatch, capsys):
    def boom(*a, **k):
        raise RuntimeError("synthetic analyzer crash")

    monkeypatch.setattr(engine, "run_lint", boom)
    rc = hglint_main.main([str(FIXTURES / "clean_pkg")])
    assert rc == 3
    err = capsys.readouterr().err
    assert "synthetic analyzer crash" in err
    assert "not a finding" in err


def test_lint_sh_reports_crash_distinctly(tmp_path):
    """tools/lint.sh must surface analyzer crashes (exit >= 2) as
    infrastructure failures rather than findings. A baseline whose
    version the engine refuses exercises the real crash path end-to-end
    (extra args override the gate's default --baseline)."""
    if os.name == "nt":  # pragma: no cover
        pytest.skip("bash gate")
    bad = tmp_path / "baseline.json"
    bad.write_text(json.dumps({"version": 999, "counts": {}}))
    out = subprocess.run(
        ["bash", str(REPO / "tools" / "lint.sh"), "--baseline", str(bad)],
        capture_output=True, text=True, cwd=REPO,
    )
    assert out.returncode == 3
    assert "crashed" in out.stderr
    assert "not a finding" in out.stderr


def test_cli_only_typo_is_usage_error():
    out = _cli(str(FIXTURES / "clean_pkg"), "--only", "HG0")
    assert out.returncode == 2          # argparse usage error, not clean
    assert "matches no known rule" in out.stderr


def test_text_output_carries_doc_anchor():
    out = _cli(str(FIXTURES / "bad_pkg" / "vmem_bad.py"), "--only", "HG5")
    assert out.returncode == 1
    assert "[README.md#hg5xx-vmem-budgets]" in out.stdout
