"""ReplicaNode: bootstrap → follow → serve, the lag-bounded staleness
contract, the /healthz payload, and rejoin-by-resume."""

from __future__ import annotations

import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.replica import ReplicaConfig, ReplicaNode
from hypergraphdb_tpu.serve import AdmissionGated, ServeConfig


def serve_cfg(**kw):
    kw.setdefault("max_linger_s", 0.001)
    kw.setdefault("prewarm_aot", False)
    return ServeConfig(**kw)


def wait_digest_equal(ga, gb, timeout=30.0):
    """Poll for content convergence. ``wait_converged`` alone is the
    replica's ADVERTISED lag — a push still in flight (sent, not yet
    dispatched) is invisible to it, so equality tests poll the digest."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if transfer.content_digest(ga) == transfer.content_digest(gb):
            return True
        time.sleep(0.02)
    return False


def make_primary(net, n_nodes=16):
    gp = hg.HyperGraph()
    pp = HyperGraphPeer.loopback(gp, net, identity="primary")
    pp.replication.debounce_s = 0.005
    pp.start()
    nodes = [int(gp.add(f"n{i}")) for i in range(n_nodes)]
    for i in range(n_nodes - 1):
        gp.add_link([nodes[i], nodes[i + 1]], value=f"e{i}")
    return gp, pp, nodes


def make_replica(net, ident="replica-1", **cfg_kw):
    gr = hg.HyperGraph()
    pr = HyperGraphPeer.loopback(gr, net, identity=ident)
    pr.replication.debounce_s = 0.005
    cfg_kw.setdefault("anti_entropy_interval_s", 0.1)
    cfg_kw.setdefault("serve", serve_cfg())
    node = ReplicaNode(gr, pr, ReplicaConfig(primary="primary", **cfg_kw))
    return node


def test_bootstrap_follow_serve():
    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net)
    node = make_replica(net)
    try:
        node.start()
        assert node.bootstrap_mode == "transfer"
        assert pp.replication.flush()
        assert node.wait_converged(timeout=30)
        # content converged exactly
        assert wait_digest_equal(gp, node.graph)
        # serve a read LOCALLY (the replica's own runtime + graph)
        local_seed = int(transfer.lookup_local(
            node.graph, transfer.gid_of(gp, nodes[0], "primary")))
        res = node.runtime.submit_bfs(local_seed, max_hops=1) \
                  .result(timeout=30)
        assert res.count >= 2              # seed + its neighbor
        # live follow: a new primary atom shows up on the replica
        gp.add("fresh")
        assert pp.replication.flush()
        assert wait_digest_equal(gp, node.graph)
        assert node.wait_converged(timeout=30)
    finally:
        node.stop()
        pp.stop()
        gp.close()
        node.graph.close()


def test_lag_gate_refuses_reads_and_unhealths():
    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net, n_nodes=6)
    node = make_replica(net, max_replication_lag=4,
                        anti_entropy_interval_s=0)  # manual control
    try:
        node.start()
        pp.replication.flush()
        assert node.wait_converged(timeout=30)
        ok, payload = node.health_probe()()
        assert ok and payload["replication_lag"] == 0
        assert payload["role"] == "replica"
        assert payload["lag_bound"] == 4
        assert payload["bootstrapped"] is True
        assert "breakers" in payload       # runtime_health merged in
        # simulate trailing far behind: the primary's advertised head
        # races ahead of our applied clock
        node.peer.replication.peer_heads["primary"] = (
            node.peer.replication.last_seen.get("primary") + 100)
        assert node.replication_lag == 100
        with pytest.raises(AdmissionGated):
            node.runtime.submit_bfs(0, max_hops=1)
        assert node.runtime.stats.gated == 1
        ok, payload = node.health_probe()()
        assert not ok and "read_gate" in payload
        # catch-up heals the advertised lag → reads re-admit
        node.peer.replication.peer_heads["primary"] = (
            node.peer.replication.last_seen.get("primary"))
        assert node._read_gate() is None
        ok, _ = node.health_probe()()
        assert ok
    finally:
        node.stop()
        pp.stop()
        gp.close()
        node.graph.close()


def test_rejoin_resumes_without_full_transfer():
    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net, n_nodes=8)
    node = make_replica(net, ident="replica-r")
    try:
        node.start()
        pp.replication.flush()
        assert node.wait_converged(timeout=30)
        transfers_before = gp.metrics.counters.get("peer.transfer_chunks",
                                                   0)
        node.stop()                        # clean shutdown (clock persisted
        # in RAM graph object we keep — the graph IS the surviving state)
        gp.add("while-down-1")
        gp.add("while-down-2")
        pp.replication.flush()
        # rejoin: same graph, fresh peer with the same identity
        gr = node.graph
        pr2 = HyperGraphPeer.loopback(gr, net, identity="replica-r")
        pr2.replication.debounce_s = 0.005
        node2 = ReplicaNode(gr, pr2, ReplicaConfig(
            primary="primary", anti_entropy_interval_s=0.1,
            serve=serve_cfg()))
        node2.start()
        assert node2.bootstrap_mode == "resume"   # no re-transfer
        assert gp.metrics.counters.get("peer.transfer_chunks", 0) \
            == transfers_before
        assert node2.wait_converged(timeout=30)
        assert wait_digest_equal(gp, gr)
        node2.stop()
    finally:
        pp.stop()
        gp.close()
        node.graph.close()


def test_failed_bootstrap_does_not_leak_started_peer():
    """start() must tear the peer back down when the bootstrap fails —
    otherwise its worker/transport threads keep running (and the primary
    keeps pushing to a zombie interest) while stop() is a no-op because
    ``_started`` never flipped."""
    net = LoopbackNetwork()              # NO primary on the wire
    gr = hg.HyperGraph()
    pr = HyperGraphPeer.loopback(gr, net, identity="orphan")
    node = ReplicaNode(gr, pr, ReplicaConfig(
        primary="primary", bootstrap_timeout_s=10.0,
        bootstrap_retry_after_s=0.02, bootstrap_max_resumes=2,
        serve=serve_cfg()))
    try:
        with pytest.raises(Exception):
            node.start()
        assert not pr._started           # peer fully stopped again
        assert node.runtime is None
        node.stop()                      # and stop() stays a safe no-op
    finally:
        gr.close()


def test_runtime_truncation_forces_in_place_rebootstrap():
    """A RUNNING replica whose primary truncated past it
    (``needs_full_sync`` raised by a digest/catch-up response) must
    re-bootstrap in place from the follow phase — not wedge permanently
    gated until an operator restart."""
    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net, n_nodes=6)
    node = make_replica(net, anti_entropy_interval_s=0.05)
    try:
        node.start()
        pp.replication.flush()
        assert node.wait_converged(timeout=30)
        chunks_before = gp.metrics.counters.get("peer.transfer_chunks", 0)
        # the divergence a digest would report: primary's log no longer
        # covers us — incremental repair cannot converge
        node.peer.replication.needs_full_sync.add("primary")
        assert wait_for_rebootstrap(node, gp, chunks_before)
        assert node.bootstrapped
        # and the re-bootstrapped replica still follows live pushes
        gp.add("post-rebootstrap")
        assert pp.replication.flush()
        assert wait_digest_equal(gp, node.graph)
    finally:
        node.stop()
        pp.stop()
        gp.close()
        node.graph.close()


def wait_for_rebootstrap(node, gp, chunks_before, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if ("primary" not in node.peer.replication.needs_full_sync
                and gp.metrics.counters.get("peer.transfer_chunks", 0)
                > chunks_before):
            return True
        time.sleep(0.02)
    return False


def test_anti_entropy_loop_drives_convergence_during_push_outage():
    """With pushes entirely suppressed (no interest published — the
    primary logs but never pushes), the replica's periodic digest probe
    alone must still converge it."""
    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net, n_nodes=4)
    node = make_replica(net, anti_entropy_interval_s=0.05)
    try:
        node.start()
        pp.replication.flush()
        assert node.wait_converged(timeout=30)
        # sever the push path: primary forgets the replica's interest
        pp.replication.peer_interests.clear()
        gp.add("push-less")
        assert pp.replication.flush()
        assert wait_digest_equal(gp, node.graph)
        assert node.graph.metrics.counters.get(
            "peer.anti_entropy_probes", 0) >= 1
    finally:
        node.stop()
        pp.stop()
        gp.close()
        node.graph.close()


def test_truncation_lazy_rebootstrap_with_ae_loop_disabled():
    """With the AE loop OFF (anti_entropy_interval_s=0) a
    ``needs_full_sync`` mark must still be actionable: the read gate
    kicks the re-bootstrap lazily, so a gated read — not an operator
    restart — is what repairs a truncated-past replica."""
    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net, n_nodes=6)
    node = make_replica(net, anti_entropy_interval_s=0)
    try:
        node.start()
        assert node._ae_thread is None          # the loop really is off
        pp.replication.flush()
        assert node.wait_converged(timeout=30)
        chunks_before = gp.metrics.counters.get("peer.transfer_chunks", 0)
        node.peer.replication.needs_full_sync.add("primary")
        # the kick happens on the gate path, and the refusal is typed
        # as "diverged", not a permanent "bootstrapping" wedge
        reason = node._read_gate()
        assert reason is not None and "re-bootstrapping" in reason
        assert wait_for_rebootstrap(node, gp, chunks_before)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not node.bootstrapped:
            time.sleep(0.02)
        assert node.bootstrapped
        assert node._read_gate() is None
        # and the repaired replica still follows live pushes
        gp.add("post-lazy-rebootstrap")
        assert pp.replication.flush()
        assert wait_digest_equal(gp, node.graph)
    finally:
        node.stop()
        pp.stop()
        gp.close()
        node.graph.close()


def test_resume_gate_until_primary_head_known():
    """A RESUMED replica reads replication_lag 0 until the primary's
    head arrives this incarnation (peer_heads is per-process) — the gate
    must refuse until then, or hour-old data serves at advertised lag 0."""
    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net, n_nodes=4)
    node = make_replica(net, anti_entropy_interval_s=0)
    try:
        node.start()
        pp.replication.flush()
        assert node.wait_converged(timeout=30)
        # the resumed-and-silent state: no head heard since restart
        node.bootstrap_mode = "resume"
        node.peer.replication.peer_heads.pop("primary", None)
        reason = node._read_gate()
        assert reason is not None and "head unknown" in reason
        ok, payload = node.health_probe()()
        assert not ok and "read_gate" in payload
        # the first head-carrying message (push/catch-up/digest) heals it
        node.peer.replication.peer_heads["primary"] = (
            node.peer.replication.last_seen.get("primary"))
        assert node._read_gate() is None
    finally:
        node.stop()
        pp.stop()
        gp.close()
        node.graph.close()


def test_resume_catch_up_send_failure_fails_bootstrap_typed():
    """Resume mode's catch-up request is its ONLY wake-up signal: if the
    reliable send cannot reach the primary, start() must fail typed
    (TransientFault) instead of parking the node gated at 'head unknown'
    until unrelated traffic happens by."""
    from hypergraphdb_tpu.fault import TransientFault

    net = LoopbackNetwork()
    gp, pp, nodes = make_primary(net, n_nodes=4)
    node = make_replica(net, ident="replica-rf")
    try:
        node.start()
        pp.replication.flush()
        assert node.wait_converged(timeout=30)
        node.stop()
        gr = node.graph
        pr2 = HyperGraphPeer.loopback(gr, net, identity="replica-rf")
        pr2.replication.debounce_s = 0.005
        pr2.replication.catch_up = lambda pid: False   # unreachable
        node2 = ReplicaNode(gr, pr2, ReplicaConfig(
            primary="primary", anti_entropy_interval_s=0,
            serve=serve_cfg()))
        with pytest.raises(TransientFault):
            node2.start()
        assert not node2._started                      # nothing leaked
    finally:
        pp.stop()
        gp.close()
        node.graph.close()
