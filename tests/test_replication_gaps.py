"""Gap-aware replication convergence: SeenMap contiguity tracking,
targeted catch-up repair of detected holes, the anti-entropy digest
backstop, and the crash-surviving redelivery journal.

The scenario these close (PR-6 follow-up / ROADMAP fault item): a push
dropped past the redelivery budget used to leave a hole the max-applied
ack could never see — the receiver acked PAST the loss and incremental
catch-up never refetched it. Silent divergence. Now the hole is visible
(applied-seq intervals), the ack is gap-aware (max CONTIGUOUS seq), a
later push exposes the loss immediately (targeted catch-up repairs it),
and the periodic digest probe catches the loss-then-silence case.
"""

from __future__ import annotations

import json
import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.fault import global_faults
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.replication import SeenMap
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.query import dsl as q


@pytest.fixture
def faults():
    f = global_faults()
    f.reset()
    yield f
    f.reset()
    f.disable()


def make_pair(a="peer-a", b="peer-b"):
    net = LoopbackNetwork()
    ga, gb = hg.HyperGraph(), hg.HyperGraph()
    pa = HyperGraphPeer.loopback(ga, net, identity=a)
    pb = HyperGraphPeer.loopback(gb, net, identity=b)
    for p in (pa, pb):
        p.replication.send_backoff_s = 0.001
        p.replication.send_backoff_max_s = 0.005
        p.replication.debounce_s = 0.005
        p.replication.redelivery_interval_s = 0.01
        p.replication.down_peer_grace_s = 0.05
    pa.start()
    pb.start()
    return net, pa, pb


def wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return False


def to_b_only(ctx):
    """Fault filter: eat replication traffic TOWARD peer-b only (B's
    acks and catch-up requests still flow)."""
    return (ctx.get("activity") == "replication"
            and ctx.get("target") == "peer-b")


# ------------------------------------------------------------ SeenMap unit


def test_seenmap_contiguity_and_gaps():
    sm = SeenMap()
    assert sm.get("p") == 0 and not sm.has_gap("p")
    sm.record_applied("p", 1)
    sm.record_applied("p", 2)
    assert sm.get("p") == 2 and not sm.has_gap("p")
    sm.record_applied("p", 5)           # 3, 4 missing
    assert sm.get("p") == 2             # ack NEVER crosses the hole
    assert sm.max_applied("p") == 5
    assert sm.has_gap("p")
    assert sm.gaps("p") == [(3, 4)]
    sm.record_applied("p", 4)
    assert sm.gaps("p") == [(3, 3)]
    sm.record_applied("p", 3)           # hole closed → ack jumps
    assert sm.get("p") == 5 and not sm.has_gap("p")
    # duplicates are no-ops (idempotent apply)
    sm.record_applied("p", 4)
    assert sm.get("p") == 5 and sm.intervals("p") == [(0, 5)]


def test_seenmap_anchor_covers_prefix():
    sm = SeenMap()
    sm.record_applied("p", 9)
    assert sm.get("p") == 0 and sm.has_gap("p")
    sm.set("p", 8)                      # snapshot transfer anchored at 8:
    # [0,8] is adjacent to the applied [9,9] — everything contiguous
    assert sm.get("p") == 9 and not sm.has_gap("p")


def test_seenmap_anchor_gap_stays_open():
    sm = SeenMap()
    sm.record_applied("p", 10)
    sm.set("p", 7)
    assert sm.get("p") == 7
    assert sm.gaps("p") == [(8, 9)]


def test_seenmap_durable_contiguous_ack():
    g = hg.HyperGraph()
    try:
        sm = SeenMap(g)
        sm.record_applied("p", 1)
        sm.record_applied("p", 3)       # gap at 2: durable ack stays 1
        sm2 = SeenMap(g)                # reopen
        assert sm2.get("p") == 1
        assert not sm2.has_gap("p")     # RAM intervals do not persist —
        # a restart re-fetches from the contiguous ack (idempotent)
    finally:
        g.close()


# ------------------------------------------- gap detection + targeted repair


def test_lost_push_detected_and_repaired_by_later_push(faults):
    net, pa, pb = make_pair()
    try:
        # tight budgets: the drop exhausts in milliseconds
        pa.replication.send_attempts = 1
        pa.replication.max_redeliveries = 1
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        pa.graph.add("before-outage")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("before-outage")) != [])
        # total outage toward B: this push drops past the budget
        faults.enable(seed=0)
        faults.arm("peer.transport.send", prob=1.0, when=to_b_only)
        pa.graph.add("lost-in-outage")
        assert pa.replication.flush(timeout=30)
        assert pa.graph.metrics.counters.get(
            "peer.redelivery_dropped", 0) >= 1
        # B is oblivious — max-applied semantics would have stayed so
        assert q.find_all(pb.graph, q.value("lost-in-outage")) == []
        # wire heals; the NEXT push's seq skips past the hole
        faults.disarm("peer.transport.send")
        pa.graph.add("after-outage")
        assert pa.replication.flush(timeout=30)
        # contiguity sees the hole → targeted catch-up repairs it
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("lost-in-outage")) != [])
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("after-outage")) != [])
        assert pb.graph.metrics.counters.get("peer.gaps_detected", 0) >= 1
        assert wait_for(
            lambda: not pb.replication.last_seen.has_gap("peer-a"))
        assert pb.replication.flush()
        assert (transfer.content_digest(pa.graph)
                == transfer.content_digest(pb.graph))
        # the repaired ack reaches the sender's full head
        assert wait_for(lambda: pb.replication.last_seen.get("peer-a")
                        == pa.replication.log.head)
    finally:
        pa.stop()
        pb.stop()


def test_gap_pins_sender_truncation(faults):
    """A receiver stuck behind a hole acks only the contiguous prefix,
    so the sender's auto-truncation cannot reclaim the entries the
    repair catch-up still needs."""
    net, pa, pb = make_pair()
    try:
        pa.replication.send_attempts = 1
        pa.replication.max_redeliveries = 1
        pa.replication.truncate_batch = 1   # eager truncation
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        pa.graph.add("t-base")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("t-base")) != [])
        base_ack = pb.replication.last_seen.get("peer-a")
        faults.enable(seed=0)
        faults.arm("peer.transport.send", prob=1.0, when=to_b_only)
        pa.graph.add("t-lost")
        assert pa.replication.flush(timeout=30)
        faults.disarm("peer.transport.send")
        pa.graph.add("t-after")
        assert pa.replication.flush(timeout=30)
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("t-lost")) != [])
        # the log floor never crossed the gap while it was open: the
        # repair could always be served (floor <= base_ack at drop time)
        assert pa.replication.log.floor <= pa.replication.log.head
        assert pb.replication.last_seen.get("peer-a") > base_ack
    finally:
        pa.stop()
        pb.stop()


# ---------------------------------------------------- anti-entropy backstop


def test_anti_entropy_digest_repairs_silent_loss(faults):
    """The nastiest loss: the LAST pushes before a silence drop past the
    budget — no later push ever exposes the hole, contiguity alone
    cannot help. The periodic digest probe does."""
    net, pa, pb = make_pair()
    try:
        pa.replication.send_attempts = 1
        pa.replication.max_redeliveries = 1
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        pa.graph.add("ae-base")
        assert pa.replication.flush()
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("ae-base")) != [])
        faults.enable(seed=0)
        faults.arm("peer.transport.send", prob=1.0, when=to_b_only)
        pa.graph.add("ae-lost-1")
        pa.graph.add("ae-lost-2")
        assert pa.replication.flush(timeout=30)
        faults.disarm("peer.transport.send")
        # silence: NO further mutations. B probes the digest instead.
        assert q.find_all(pb.graph, q.value("ae-lost-2")) == []
        pb.replication.anti_entropy("peer-a")
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("ae-lost-1")) != [])
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("ae-lost-2")) != [])
        assert pb.graph.metrics.counters.get(
            "peer.anti_entropy_probes", 0) >= 1
        assert pb.replication.flush()
        assert (transfer.content_digest(pa.graph)
                == transfer.content_digest(pb.graph))
    finally:
        pa.stop()
        pb.stop()


# ------------------------------------------------------- redelivery journal


def test_redelivery_journal_roundtrip_and_replay(faults, tmp_path):
    """The queue survives a process death: while the wire is down the
    journal mirrors the in-memory queue exactly (crash-atomic rewrite);
    a restarted peer replays it and delivers once the wire heals — no
    catch-up needed, per-peer order preserved."""
    journal = str(tmp_path / "redelivery.jsonl")
    net, pa, pb = make_pair()
    try:
        pa.replication.journal_path = journal
        pa.replication.send_attempts = 1
        pa.replication.max_redeliveries = 10**6  # keep them QUEUED
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        faults.enable(seed=0)
        faults.arm("peer.transport.send", prob=1.0, when=to_b_only)
        pa.graph.add("journal-1")
        pa.graph.add("journal-2")
        # both pushes end up queued for redelivery (budget is deep)
        assert wait_for(lambda: pa.replication._redelivery_n >= 2)

        def journal_lines():
            with open(journal, encoding="utf-8") as f:
                return [json.loads(line) for line in f if line.strip()]

        # "kill" A: stop freezes the queue; the journal mirrors it
        # (attempt counters may trail by the in-flight probe — the
        # (pid, seq) content and ORDER are the replay contract)
        pa.stop()
        q_mem = [
            (pid, msg["content"]["seq"])
            for pid, dq in pa.replication._redelivery.items()
            for msg, _attempt in dq
        ]
        q_disk = [
            (r["pid"], r["message"]["content"]["seq"])
            for r in journal_lines()
        ]
        assert q_disk == q_mem and len(q_disk) == 2
        seqs = [s for _, s in q_disk]
        assert seqs == sorted(seqs)              # per-peer order on disk
        # restart on the same graph: the journal replays into the queue
        ga = pa.graph
        pa2 = HyperGraphPeer.loopback(ga, net, identity="peer-a")
        pa2.replication.journal_path = journal
        pa2.replication.send_backoff_s = 0.001
        pa2.replication.redelivery_interval_s = 0.01
        faults.disarm("peer.transport.send")     # wire healed
        pa2.start()
        assert pa2.replication._redelivery_n == 2    # replayed
        assert pa2.replication.flush(timeout=30)
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("journal-1")) != [])
        assert wait_for(
            lambda: q.find_all(pb.graph, q.value("journal-2")) != [])
        # delivered queue → journal rewritten empty
        assert wait_for(lambda: journal_lines() == [])
        pa2.stop()
    finally:
        pb.stop()


def test_replication_lag_tracks_peer_head():
    net, pa, pb = make_pair()
    try:
        pb.replication.publish_interest(None)
        assert wait_for(lambda: "peer-b" in pa.replication.peer_interests)
        pa.graph.add("lag-1")
        pa.graph.add("lag-2")
        assert pa.replication.flush()
        # wait on the advertised head, not on lag == 0: lag reads 0
        # vacuously while peer_heads has no entry yet (the push carrying
        # the head may still be in flight on the apply thread)
        assert wait_for(lambda: pb.replication.peer_heads.get("peer-a")
                        == pa.replication.log.head)
        assert wait_for(lambda: pb.replication.replication_lag("peer-a")
                        == 0)
    finally:
        pa.stop()
        pb.stop()


def test_gap_repair_mark_clears_when_request_cannot_send():
    """A repair catch-up that never left the process (reliable-send
    budget spent) must drop the in-flight mark — otherwise no
    catchup-result can ever clear it and the hole wedges unrepaired."""
    net, pa, pb = make_pair()
    try:
        rep = pb.replication
        rep.last_seen.record_applied("peer-a", 1)
        rep.last_seen.record_applied("peer-a", 3)   # 2 lost
        assert rep.last_seen.has_gap("peer-a")
        calls = []

        def unsendable(pid):
            calls.append(pid)
            return False

        orig, rep.catch_up = rep.catch_up, unsendable
        try:
            rep._check_gap("peer-a")
            assert calls == ["peer-a"]
            # mark dropped: the NEXT apply cycle re-triggers
            assert "peer-a" not in rep._gap_repairs

            def sendable(pid):
                calls.append(pid)
                return True

            rep.catch_up = sendable
            rep._check_gap("peer-a")
            assert "peer-a" in rep._gap_repairs     # awaiting the page
            rep._check_gap("peer-a")                # no double-fire
            assert calls == ["peer-a", "peer-a"]
        finally:
            rep.catch_up = orig
    finally:
        pa.stop()
        pb.stop()


def test_anti_entropy_skips_repair_while_position_advances():
    """The digest backstop repairs a STALLED position (and the first
    sight of one), not ordinary in-flight lag: behind-the-head while
    still advancing means pushes are flowing and a catch-up would just
    shadow them with duplicate traffic."""
    from hypergraphdb_tpu.peer import messages as M

    net, pa, pb = make_pair()
    try:
        rep = pb.replication
        calls = []
        orig, rep.catch_up = rep.catch_up, lambda pid: (
            calls.append(pid), True)[1]
        try:
            def digest(head):
                rep.handle("peer-a", M.make_message(
                    M.INFORM, rep.ACTIVITY_TYPE,
                    {"what": "digest-result", "head": head, "floor": 0},
                ))

            digest(10)                    # first sight, mine=0 → repair
            assert calls == ["peer-a"]
            for s in range(1, 6):         # progress: mine advances to 5
                rep.last_seen.record_applied("peer-a", s)
            digest(10)                    # advancing → in-flight lag, skip
            assert calls == ["peer-a"]
            digest(12)                    # stalled at 5 since last probe
            assert calls == ["peer-a", "peer-a"]
            assert pb.graph.metrics.counters.get(
                "peer.anti_entropy_repairs", 0) == 2
        finally:
            rep.catch_up = orig
    finally:
        pa.stop()
        pb.stop()


def test_seenmap_deferred_persist_batches_store_writes():
    """``persist=False`` covers positions in RAM only; ONE explicit
    :meth:`SeenMap.persist` per sender makes the batch durable — the
    apply worker's cost model (one store tx per drained cycle, not one
    per in-order push)."""
    g = hg.HyperGraph()
    try:
        sm = SeenMap(g)
        for s in range(1, 6):
            sm.record_applied("p", s, persist=False)
        assert sm.get("p") == 5                 # RAM view advanced
        assert SeenMap(g).get("p") == 0         # nothing durable yet
        sm.persist("p")
        assert SeenMap(g).get("p") == 5         # one write, all covered
        sm.persist("p")                         # no-op when unadvanced
        assert SeenMap(g).get("p") == 5
    finally:
        g.close()
