"""Pallas kernel tests (interpreter mode on the CPU mesh; the same kernel
compiles for TPU and is differential-identical by construction)."""

import numpy as np
import pytest

from hypergraphdb_tpu.ops.pallas_kernels import (
    SENTINEL,
    fits_vmem,
    intersect_sorted_pallas,
    membership_mask_pallas,
)


def _rand_sorted(rng, n, hi):
    return np.unique(rng.integers(0, hi, size=n)).astype(np.int64)


def test_intersection_matches_numpy():
    rng = np.random.default_rng(0)
    for trial in range(5):
        arrays = [_rand_sorted(rng, n, 5_000) for n in (700, 350, 900)]
        got = intersect_sorted_pallas(arrays, interpret=True)
        want = sorted(
            set(arrays[0].tolist())
            & set(arrays[1].tolist())
            & set(arrays[2].tolist())
        )
        assert got.tolist() == want, f"trial {trial}"


def test_empty_and_disjoint():
    a = np.array([1, 2, 3], dtype=np.int64)
    b = np.array([10, 20], dtype=np.int64)
    assert intersect_sorted_pallas([a, b], interpret=True).tolist() == []
    assert intersect_sorted_pallas([a], interpret=True).tolist() == [1, 2, 3]


def test_membership_sentinel_excluded():
    import jax.numpy as jnp

    base = jnp.asarray(
        np.array([5, 7, SENTINEL, SENTINEL], dtype=np.int32)
    )
    others = jnp.asarray(
        np.array([[5, SENTINEL, SENTINEL, SENTINEL]], dtype=np.int32)
    )
    mask = membership_mask_pallas(base, others, interpret=True)
    got = np.asarray(mask)
    # 5 ∈ other; 7 ∉; SENTINEL padding never matches even though the other
    # row contains SENTINEL padding values
    assert got.tolist() == [True, False, False, False]


def test_fits_vmem_guard():
    assert fits_vmem(4096, 4, 4096)
    assert not fits_vmem(4096, 1024, 16384)
