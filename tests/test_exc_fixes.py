"""Behavior pins for the hgexc (HG10xx) real-tree runtime fixes.

Every broad swallow the analyzer flagged was either narrowed, given
evidence (a log line or a counter), or pragma-audited. These tests pin
the EVIDENCE, not the analyzer: each fix must observably change runtime
behavior, so a revert fails here before it ever reaches hglint.
"""

from __future__ import annotations

import logging
import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.algorithms.traversals import HyperTraversal
from hypergraphdb_tpu.core.errors import NotFoundError
from hypergraphdb_tpu.obs.http import runtime_health
from hypergraphdb_tpu.peer import HyperGraphPeer, LoopbackNetwork
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from hypergraphdb_tpu.serve.stats import ServeStats
from tests.test_serve_runtime import FakeClock, FakeExecutor


def _wait(cond, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(0.01)
    return False


def _counter(registry, name):
    c = registry.get(name)
    return 0 if c is None else c.value


# ------------------------------------------ traversals: narrowed swallow


def test_hypertraversal_skips_plain_atoms():
    """``get_targets`` on a plain atom raises NotFoundError — the
    flattened traversal treats that as "no targets" and keeps walking."""
    g = hg.HyperGraph()
    try:
        a, b = int(g.add("a")), int(g.add("b"))
        link = int(g.add_link([a, b]))
        seen = {nbr for _, nbr in HyperTraversal(g, a)}
        assert link in seen and b in seen
    finally:
        g.close()


def test_hypertraversal_propagates_unexpected_errors():
    """The old broad swallow ate storage faults and evaluation bugs
    alongside the benign miss; only NotFoundError is absorbed now."""

    class TornGraph:
        def get_incidence_set(self, node):
            return []

        def get_targets(self, node):
            raise RuntimeError("storage fault")

    with pytest.raises(RuntimeError, match="storage fault"):
        list(HyperTraversal(TornGraph(), 0))

    class EmptyGraph(TornGraph):
        def get_targets(self, node):
            raise NotFoundError("plain atom")

    assert list(HyperTraversal(EmptyGraph(), 0)) == []


# ------------------------------------- /healthz: named torn enrichments


class _Breaker:
    def states(self):
        return {("bfs", 4): "closed"}

    def worst_code(self):
        return 0


class _Queue:
    closed = False

    def depth(self):
        return 0


def _fake_rt(executor, perf):
    class RT:
        pass

    rt = RT()
    rt.breaker = _Breaker()
    rt.queue = _Queue()
    rt.executor = executor
    rt.perf = perf
    return rt


def test_health_probe_names_torn_enrichments():
    """A raising mesh/perf enrichment must not 500 the probe OR vanish
    silently — the payload names the degraded field."""

    class TornExecutor:
        def mesh_report(self):
            raise RuntimeError("mesh probe torn")

    class TornPerf:
        def health_summary(self):
            raise RuntimeError("sentinel bug")

    healthy, payload = runtime_health(
        _fake_rt(TornExecutor(), TornPerf()))()
    assert healthy                        # enrichment never flips health
    assert payload["degraded"] == ["mesh", "perf"]
    assert "mesh" not in payload and "perf" not in payload


def test_health_probe_clean_enrichments_carry_no_degraded_marker():
    class Executor:
        def mesh_report(self):
            return {"mesh_shape": [1]}

    class Perf:
        def health_summary(self):
            return {"status": "ok"}

    healthy, payload = runtime_health(_fake_rt(Executor(), Perf()))()
    assert healthy
    assert "degraded" not in payload
    assert payload["mesh"] == {"mesh_shape": [1]}
    assert payload["perf"] == {"status": "ok"}


# ------------------------- serve: dropped perf observations are counted


def test_record_perf_error_counts_and_resets():
    stats = ServeStats()
    assert _counter(stats.registry, "serve.perf_observe_errors") == 0
    stats.record_perf_error()
    stats.record_perf_error()
    assert _counter(stats.registry, "serve.perf_observe_errors") == 2
    stats.reset()
    assert _counter(stats.registry, "serve.perf_observe_errors") == 0


def test_broken_sentinel_is_counted_not_silent():
    """The dispatch loop swallows a raising perf sentinel (a perf bug
    must never fail the request) — but the swallow now leaves evidence:
    ``serve.perf_observe_errors`` counts every dropped observation."""

    class ExplodingSentinel:
        def observe(self, *a, **k):
            raise RuntimeError("boom")

        def observe_batch(self, *a, **k):
            raise RuntimeError("boom")

        def maybe_tick(self):
            raise RuntimeError("boom")

    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=clock,
                      manual=True, perf=ExplodingSentinel())
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    try:
        fut = rt.submit_bfs(1)
        rt.step(drain=True)
        assert fut.result(timeout=0).kind == "bfs"   # request unharmed
        assert _counter(rt.stats.registry,
                        "serve.perf_observe_errors") >= 1
    finally:
        rt.close()


# --------------------------- peer replication: failure-path counters


@pytest.fixture
def two_peers():
    net = LoopbackNetwork()
    g1, g2 = hg.HyperGraph(), hg.HyperGraph()
    p1 = HyperGraphPeer.loopback(g1, net, identity="peer-1")
    p2 = HyperGraphPeer.loopback(g2, net, identity="peer-2")
    p1.start()
    p2.start()
    yield p1, p2
    p1.stop()
    p2.stop()
    g1.close()
    g2.close()


def test_ack_send_failure_is_counted(two_peers):
    """A torn ack pipe used to vanish into ``except Exception: pass`` —
    now ``peer.ack_send_failures`` counts it (the sender just re-serves
    from the last durable ack, so counting IS the whole remedy)."""
    p1, p2 = two_peers
    p2.replication.publish_interest(None)
    assert _wait(lambda: "peer-2" in p1.replication.peer_interests)

    orig_send = p2.interface.send

    def flaky_send(to, msg):
        if "ack" in str(msg):
            raise ConnectionError("ack pipe torn")
        return orig_send(to, msg)

    p2.interface.send = flaky_send
    p1.graph.add("hello")
    reg2 = p2.graph.metrics.registry
    assert _wait(
        lambda: _counter(reg2, "peer.ack_send_failures") >= 1
    ), "ack-send failure left no counter evidence"


def test_catch_up_failure_is_counted(two_peers):
    """A raising catch-up continuation (peer gone mid-page) increments
    ``peer.catch_up_failures`` instead of disappearing."""
    _, p2 = two_peers
    p2.replication._apply = lambda sender, kind, entry: None

    def gone(pid):
        raise ConnectionError("peer gone")

    p2.replication.catch_up = gone
    # a continuation page: applied items + continue_catchup=True drives
    # the drain loop into the catch-up pull that now fails
    p2.replication._enqueue_apply(
        "peer-1", [("record", {}, 999, None)], True)
    reg2 = p2.graph.metrics.registry
    assert _wait(
        lambda: _counter(reg2, "peer.catch_up_failures") >= 1
    ), "catch-up failure left no counter evidence"


# ----------------------------- serve: prewarm failures log, never block


def test_failed_prewarm_logs_and_startup_still_serves(tmp_path, caplog,
                                                      monkeypatch):
    """Join/range prewarm failures must not block startup (first
    dispatch builds cold) — and must not be silent: each names what went
    cold on the ``hypergraphdb_tpu.serve`` logger."""
    graph = hg.HyperGraph()
    try:
        nodes = [int(graph.add(i)) for i in range(12)]
        for i in range(6):
            graph.add_link([nodes[i], nodes[i + 1]], value=100 + i)

        from hypergraphdb_tpu.ops import join as join_ops
        from hypergraphdb_tpu.storage import value_index

        def torn(*a, **k):
            raise RuntimeError("prewarm torn")

        cfg = ServeConfig(buckets=(4,), max_linger_s=0.001,
                          use_pallas_bfs=False,
                          aot_cache_dir=str(tmp_path),
                          prewarm_join_nbr=True,
                          prewarm_range_dims=(ord("i"),))
        with monkeypatch.context() as mp:
            mp.setattr(join_ops, "neighbor_csr_device", torn)
            mp.setattr(value_index, "value_index_column", torn)
            with caplog.at_level(logging.WARNING, "hypergraphdb_tpu.serve"):
                rt = ServeRuntime(graph, cfg)
        messages = [r.getMessage() for r in caplog.records]
        assert any("join prewarm failed" in m for m in messages), messages
        assert any("range-column prewarm failed" in m for m in messages), \
            messages
        # the patches are gone: first dispatch builds cold and serves
        res = rt.submit_range(lo=3, hi=9).result(timeout=60)
        assert res.matches.tolist()       # nonempty window over 0..11
        rt.close()
    finally:
        graph.close()
