"""Vectorized residual predicates (VERDICT r2 item 7): Arity / IsLink /
IsNode / AtomType / PositionedIncident must evaluate against snapshot
columns, not one Python ``satisfies`` call per handle."""

import time

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.query.compiler import filter_predicates
from hypergraphdb_tpu.query import dsl as hg


@pytest.fixture()
def filled():
    g = HyperGraph()
    nodes = [g.add(f"n{i}") for i in range(40)]
    links = []
    rng = np.random.default_rng(3)
    for i in range(300):
        k = int(rng.integers(2, 5))
        ts = rng.choice(40, size=k, replace=False)
        links.append(g.add_link(tuple(nodes[t] for t in ts), value=i))
    g.snapshot()  # fresh column cache
    yield g, nodes, links
    g.close()


def _loop(g, arr, preds):
    return np.asarray(
        [h for h in arr.tolist() if all(p.satisfies(g, h) for p in preds)],
        dtype=np.int64,
    )


@pytest.mark.parametrize("pred", [
    c.Arity(2, "eq"),
    c.Arity(3, "gte"),
    c.IsLink(),
    c.IsNode(),
    c.AtomType("int"),
])
def test_vector_matches_loop(filled, pred):
    g, nodes, links = filled
    arr = np.asarray(sorted(int(x) for x in nodes + links), dtype=np.int64)
    got = filter_predicates(g, arr, [pred])
    want = _loop(g, arr, [pred])
    assert got.tolist() == want.tolist()


def test_positioned_incident_vectorized(filled):
    g, nodes, links = filled
    arr = np.asarray(sorted(int(x) for x in links), dtype=np.int64)
    for pos in (0, 1, 3):
        pred = c.PositionedIncident(int(nodes[5]), pos)
        got = filter_predicates(g, arr, [pred])
        want = _loop(g, arr, [pred])
        assert got.tolist() == want.tolist(), pos


def test_vector_filter_exact_under_incremental(filled):
    """Handles touched after the base pack must be evaluated exactly."""
    g, nodes, links = filled
    g.enable_incremental(headroom=5.0, background=False)
    l_new = g.add_link((nodes[0], nodes[1], nodes[2]), value=777)
    g.remove(links[0])
    arr = np.asarray(
        sorted(int(x) for x in links[1:] + [l_new]), dtype=np.int64
    )
    pred = c.Arity(3, "eq")
    got = filter_predicates(g, arr, [pred])
    want = _loop(g, arr, [pred])
    assert got.tolist() == want.tolist()
    assert int(l_new) in got.tolist()


def test_vector_filter_speedup():
    """The VERDICT bar: a large predicate filter must beat the per-handle
    Python loop by >= 50x (typically far more)."""
    g = HyperGraph()
    n = 200_000
    g.bulk_import(values=list(range(n)))
    nodes = np.arange(n, dtype=np.int64) + 0  # handles not exact; re-derive
    arr = np.fromiter(g.atoms(), dtype=np.int64)
    g.snapshot()
    preds = [c.IsNode(), c.Arity(0, "eq")]

    t0 = time.perf_counter()
    fast = filter_predicates(g, arr, preds)
    t_fast = time.perf_counter() - t0

    sub = arr[:20_000]  # loop timed on a slice, extrapolated
    t0 = time.perf_counter()
    slow = _loop(g, sub, preds)
    t_slow = (time.perf_counter() - t0) * (len(arr) / len(sub))

    assert set(sub.tolist()) <= set(fast.tolist())
    assert len(fast) >= n
    ratio = t_slow / max(t_fast, 1e-9)
    assert ratio >= 50, f"vectorized filter only {ratio:.1f}x faster"
    g.close()
