"""Differential serving tests: batched == unbatched, per shape bucket.

The serving contract is that coalescing + padding is INVISIBLE: a padded
micro-batch of mixed requests must return results identical to unbatched
per-request execution (and to the host query engine's ground truth) —
including seeds adjacent to padding lanes, duplicate seeds, and empty
result sets. Runs the REAL DeviceExecutor over small graphs under
``JAX_PLATFORMS=cpu``; the concurrent-ingest soak is marked ``slow``.
"""

from __future__ import annotations

import numpy as np
import pytest

from hypergraphdb_tpu.query import dsl
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from tests.conftest import make_random_hypergraph

BUCKETS = (64, 256, 1024)


def _build(g, seed=3):
    nodes, links = make_random_hypergraph(
        g, n_nodes=100, n_links=200, max_arity=4, seed=seed
    )
    iso = [int(g.add(f"iso{i}")) for i in range(3)]
    return [int(n) for n in nodes], [int(x) for x in links], iso


def _runtime(g, bucket, **kw):
    kw.setdefault("top_r", 512)
    cfg = ServeConfig(buckets=(bucket,), manual=True, max_linger_s=0.0,
                      **kw)
    return ServeRuntime(g, cfg)


def _drain(rt):
    while rt.step(drain=True):
        pass


def _bfs_truth(g, seed, hops):
    return sorted(int(h) for h in g.find_all(
        dsl.bfs(seed, max_distance=hops)
    ))


@pytest.mark.parametrize("bucket", BUCKETS)
def test_bfs_batched_equals_unbatched(graph, bucket):
    nodes, links, iso = _build(graph)
    # unique probes: first/last packed atoms, isolated (empty result),
    # a link as seed — then CYCLED to fill the bucket minus one (so the
    # final lane sits right against the padding lanes)
    probes = [nodes[0], nodes[1], nodes[-1], iso[0], iso[1], links[0],
              nodes[7], nodes[7]]  # duplicate seed in the same batch
    n_req = bucket - 1
    reqs = [probes[i % len(probes)] for i in range(n_req)]

    rt = _runtime(graph, bucket)
    futs = [rt.submit_bfs(s, max_hops=2, include_seed=False) for s in reqs]
    _drain(rt)
    batched = [f.result(timeout=0) for f in futs]
    assert rt.stats.batches == 1  # everything coalesced into ONE dispatch
    rt.close()

    # unbatched: the same requests one per dispatch (K=1 bucket)
    rt1 = _runtime(graph, 1)
    singles = {}
    for s in set(reqs):
        fut = rt1.submit_bfs(s, max_hops=2, include_seed=False)
        _drain(rt1)
        singles[s] = fut.result(timeout=0)
    rt1.close()

    for s, res in zip(reqs, batched):
        one = singles[s]
        assert res.count == one.count
        assert res.truncated == one.truncated is False
        np.testing.assert_array_equal(res.matches, one.matches)
        assert res.matches.tolist() == _bfs_truth(graph, s, 2)


@pytest.mark.parametrize("bucket", BUCKETS)
def test_pattern_batched_equals_unbatched(graph, bucket):
    nodes, links, iso = _build(graph)
    pairs = []
    for lk in links[:6]:
        ts = [int(t) for t in graph.get_targets(lk)]
        if len(ts) >= 2 and ts[0] != ts[1]:
            pairs.append((ts[0], ts[1]))
    pairs.append((iso[0], iso[1]))       # provably empty result
    pairs.append((nodes[3], nodes[3]))   # duplicate anchor
    pairs.append(pairs[0])               # duplicate request
    n_req = min(bucket, 2 * len(pairs))
    reqs = [pairs[i % len(pairs)] for i in range(n_req)]

    rt = _runtime(graph, bucket)
    futs = [rt.submit_pattern(p) for p in reqs]
    _drain(rt)
    batched = [f.result(timeout=0) for f in futs]
    rt.close()

    rt1 = _runtime(graph, 1)
    singles = {}
    for p in set(reqs):
        fut = rt1.submit_pattern(p)
        _drain(rt1)
        singles[p] = fut.result(timeout=0)
    rt1.close()

    for p, res in zip(reqs, batched):
        one = singles[p]
        assert res.count == one.count
        np.testing.assert_array_equal(res.matches, one.matches)
        truth = sorted(int(h) for h in graph.find_all(
            dsl.and_(dsl.incident(p[0]), dsl.incident(p[1]))
        ))
        assert res.matches.tolist() == truth


def test_mixed_kind_batches_match_ground_truth(graph):
    nodes, links, iso = _build(graph)
    th = int(graph.get_type_handle_of(links[0]))  # links carry int values
    rt = _runtime(graph, 64)
    fb = rt.submit_bfs(nodes[0], max_hops=2, include_seed=False)
    ts = [int(t) for t in graph.get_targets(links[0])][:2]
    fp = rt.submit_pattern(ts)
    ftp = rt.submit_pattern(ts, type_handle=th)
    fq = rt.submit_query(dsl.bfs(nodes[5], max_distance=2))
    f1 = rt.submit_query(dsl.incident(nodes[2]))
    _drain(rt)
    rt.close()
    assert fb.result(timeout=0).matches.tolist() == _bfs_truth(
        graph, nodes[0], 2
    )
    truth_p = sorted(int(h) for h in graph.find_all(
        dsl.and_(*[dsl.incident(t) for t in ts])
    ))
    assert fp.result(timeout=0).matches.tolist() == truth_p
    truth_tp = sorted(int(h) for h in graph.find_all(dsl.and_(
        dsl.type_(th), *[dsl.incident(t) for t in ts]
    )))
    assert ftp.result(timeout=0).matches.tolist() == truth_tp
    assert fq.result(timeout=0).matches.tolist() == _bfs_truth(
        graph, nodes[5], 2
    )
    assert f1.result(timeout=0).matches.tolist() == sorted(
        int(h) for h in graph.find_all(dsl.incident(nodes[2]))
    )


def test_include_seed_variants(graph):
    nodes, links, iso = _build(graph)
    rt = _runtime(graph, 64)
    fin = rt.submit_bfs(nodes[0], max_hops=2, include_seed=True)
    fout = rt.submit_bfs(nodes[0], max_hops=2, include_seed=False)
    fiso = rt.submit_bfs(iso[0], max_hops=2, include_seed=False)
    _drain(rt)
    rt.close()
    rin, rout, riso = (f.result(timeout=0) for f in (fin, fout, fiso))
    assert rin.count == rout.count + 1
    assert sorted(set(rout.matches.tolist()) | {nodes[0]}) \
        == rin.matches.tolist()
    assert riso.count == 0 and len(riso.matches) == 0  # empty result set


def test_serve_sees_delta_and_tombstones(graph):
    """Requests under pending (uncompacted) ingest stay EXACT: BFS flows
    through the device delta overlay, patterns through the host memtable
    merge, removals through tombstones — all pinned to one view."""
    nodes, links, iso = _build(graph)
    mgr = graph.enable_incremental(background=False, compact_ratio=100.0)
    # post-pack mutations living purely in the delta/memtable
    a, b = nodes[2], nodes[9]
    fresh_link = int(graph.add_link([a, b], value="fresh"))
    removed = links[0]
    rm_ts = [int(t) for t in graph.get_targets(removed)][:2]
    graph.remove(removed)
    assert mgr.delta_edges > 0  # the new edges are really still delta

    rt = _runtime(graph, 64)
    f_bfs = rt.submit_bfs(a, max_hops=1, include_seed=False)
    f_pat = rt.submit_pattern((a, b))
    f_rm = rt.submit_pattern(tuple(rm_ts)) if rm_ts[0] != rm_ts[1] else None
    _drain(rt)
    rt.close()

    r = f_bfs.result(timeout=0)
    assert b in r.matches.tolist()  # reached THROUGH the delta edge
    assert r.matches.tolist() == _bfs_truth(graph, a, 1)
    p = f_pat.result(timeout=0)
    assert fresh_link in p.matches.tolist()  # memtable merge found it
    assert p.matches.tolist() == sorted(int(h) for h in graph.find_all(
        dsl.and_(dsl.incident(a), dsl.incident(b))
    ))
    if f_rm is not None:
        assert removed not in f_rm.result(timeout=0).matches.tolist()


def test_truncation_flag_and_prefix(graph):
    nodes, links, iso = _build(graph)
    rt = _runtime(graph, 64, top_r=2)
    fut = rt.submit_bfs(nodes[0], max_hops=2, include_seed=False)
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    truth = _bfs_truth(graph, nodes[0], 2)
    assert len(truth) > 2
    assert res.truncated is True
    assert res.count == len(truth)          # count stays exact
    assert res.matches.tolist() == truth[:2]  # ascending prefix


def test_host_fallback_is_exact(graph):
    """Anchors whose base incidence row exceeds pattern_pad leave the
    batched path but stay exact (served_by='host')."""
    nodes, links, iso = _build(graph)
    hub = int(graph.add("hub"))
    for i in range(9):
        graph.add_link([hub, nodes[i]], value=f"h{i}")
    rt = _runtime(graph, 64, pattern_pad=4)
    fut = rt.submit_pattern((hub, nodes[0]))
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    assert res.served_by == "host"
    assert rt.stats.host_fallbacks == 1
    assert res.matches.tolist() == sorted(int(h) for h in graph.find_all(
        dsl.and_(dsl.incident(hub), dsl.incident(nodes[0]))
    ))


def test_unservable_conditions_raise(graph):
    from hypergraphdb_tpu.serve.types import Unservable

    rt = _runtime(graph, 64)
    with pytest.raises(Unservable):
        rt.submit_query(dsl.bfs(1))  # unbounded hops
    with pytest.raises(Unservable):
        rt.submit_query(dsl.value_regex("x.*"))  # predicates stay host
    with pytest.raises(Unservable):
        rt.submit_query(dsl.or_(dsl.incident(1), dsl.incident(2)))
    # value predicates are SERVABLE since hgindex (the range lane) —
    # the old "value predicates raise Unservable" scoping is retired
    fut = rt.submit_query(dsl.value(3, op="lte"))
    _drain(rt)
    rt.close()
    assert fut.result(timeout=0).count >= 0


@pytest.mark.slow
def test_soak_threaded_under_concurrent_ingest(graph):
    """The real thing: threaded runtime, background-compacting manager,
    concurrent writer — every future resolves (result or a typed
    deadline), the drain completes, stats add up."""
    import threading

    from hypergraphdb_tpu.serve import DeadlineExceeded

    nodes, links, iso = _build(graph)
    graph.enable_incremental(background=True, compact_ratio=0.05)
    cfg = ServeConfig(buckets=(16, 64), max_linger_s=0.002,
                      max_queue=512, top_r=512)
    rt = ServeRuntime(graph, cfg)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            graph.bulk_import(
                values=[f"w{i}_{j}" for j in range(20)],
                target_lists=[
                    [nodes[(i + j) % len(nodes)],
                     nodes[(i * 7 + j) % len(nodes)]]
                    for j in range(20)
                ],
            )
            i += 1

    wt = threading.Thread(target=writer, daemon=True)
    wt.start()
    futs = []
    r = np.random.default_rng(5)
    for i in range(400):
        if i % 3 == 0:
            ts = [int(t) for t in graph.get_targets(
                links[int(r.integers(0, len(links)))]
            )][:2]
            if len(ts) == 2 and ts[0] != ts[1]:
                futs.append(rt.submit_pattern(ts, deadline_s=5.0))
                continue
        futs.append(rt.submit_bfs(
            nodes[int(r.integers(0, len(nodes)))], max_hops=2,
            deadline_s=5.0,
        ))
    stop.set()
    wt.join(30)
    rt.close(drain=True, timeout=60)
    resolved = 0
    for f in futs:
        try:
            res = f.result(timeout=10)
            assert res.count >= 0
            resolved += 1
        except DeadlineExceeded:
            pass
    assert resolved > 0
    s = rt.stats_snapshot()
    assert s["submitted"] == len(futs)
    assert s["completed"] + s["shed_deadline"] == len(futs)
    mgr = graph.incremental
    assert mgr.wait_compacted(30.0)


def test_truncated_pattern_under_memtable_serves_exactly(graph):
    """A truncated device window cannot absorb memtable corrections (a
    tombstone beyond the prefix would overcount; a fresh link would punch
    a hole) — such requests must come back exact via the host path."""
    nodes, links, iso = _build(graph)
    a, b = nodes[2], nodes[9]
    base_links = [int(graph.add_link([a, b], value=f"m{i}"))
                  for i in range(8)]
    graph.enable_incremental(background=False, compact_ratio=100.0)
    # post-pack memtable activity touching the SAME pattern
    graph.remove(base_links[-1])                      # beyond any 3-prefix
    fresh = int(graph.add_link([a, b], value="fresh"))
    rt = _runtime(graph, 64, top_r=3)
    fut = rt.submit_pattern((a, b))
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    truth = sorted(int(h) for h in graph.find_all(
        dsl.and_(dsl.incident(a), dsl.incident(b))
    ))
    assert fresh in truth and base_links[-1] not in truth
    assert res.served_by == "host"
    assert res.count == len(truth)            # no tombstone overcount
    assert res.matches.tolist() == truth[:3]  # gap-free ascending prefix


def test_pattern_correction_uses_pinned_state_not_live_graph(graph):
    """Memtable corrections evaluate records captured at launch: a
    mutation landing while the device executes must not leak into a batch
    pinned before it."""
    nodes, links, iso = _build(graph)
    a, b = nodes[2], nodes[9]
    graph.enable_incremental(background=False, compact_ratio=100.0)
    fresh = int(graph.add_link([a, b], value="fresh"))
    rt = _runtime(graph, 64)
    fut = rt.submit_pattern((a, b))
    assert rt.pump(drain=True) is True   # launched, NOT yet collected
    graph.remove(fresh)                  # post-launch mutation
    rt.close(drain=True)                 # collects the pending batch
    res = fut.result(timeout=0)
    assert res.served_by == "device"
    assert fresh in res.matches.tolist()  # the pinned view still had it


def test_memtable_merge_past_top_r_truncates(graph):
    """A non-truncated device window whose memtable merge overflows top_r
    must come back truncated with a top_r-wide prefix and an exact
    count — one shape contract for every path."""
    nodes, links, iso = _build(graph)
    a, b = nodes[2], nodes[9]
    base = [int(graph.add_link([a, b], value=f"m{i}")) for i in range(2)]
    graph.enable_incremental(background=False, compact_ratio=100.0)
    fresh = [int(graph.add_link([a, b], value=f"f{i}")) for i in range(2)]
    rt = _runtime(graph, 64, top_r=3)
    fut = rt.submit_pattern((a, b))
    _drain(rt)
    rt.close()
    res = fut.result(timeout=0)
    truth = sorted(base + fresh)
    assert res.count == 4 and res.truncated is True
    assert res.matches.tolist() == truth[:3]


def test_all_host_batch_counts_no_device_dispatch(graph):
    nodes, links, iso = _build(graph)
    hub = int(graph.add("hub"))
    for i in range(9):
        graph.add_link([hub, nodes[i]], value=f"h{i}")
    rt = _runtime(graph, 64, pattern_pad=2)  # every pair over budget
    f1 = rt.submit_pattern((hub, nodes[0]))
    f2 = rt.submit_pattern((hub, nodes[1]))
    _drain(rt)
    rt.close()
    assert f1.result(timeout=0).served_by == "host"
    assert f2.result(timeout=0).served_by == "host"
    s = rt.stats_snapshot()
    assert s["batches"] == 1              # the micro-batch formed and served
    assert s["device_dispatches"] == 0    # but no kernel ever launched


def test_pattern_launch_skips_device_delta_upload(graph):
    """Pattern batches consume base + HOST corrections only — pinning one
    must not pay a device-delta upload (that transfer is the BFS path's
    freshness cost, not the pattern path's)."""
    nodes, links, iso = _build(graph)
    a, b = nodes[2], nodes[9]
    mgr = graph.enable_incremental(background=False, compact_ratio=100.0)
    fresh = int(graph.add_link([a, b], value="fresh"))  # dirty memtable
    up0 = (mgr.full_uploads, mgr.tail_uploads)
    rt = _runtime(graph, 64)
    fut = rt.submit_pattern((a, b))
    _drain(rt)
    rt.close()
    assert (mgr.full_uploads, mgr.tail_uploads) == up0  # no upload paid
    assert fresh in fut.result(timeout=0).matches.tolist()  # still exact
