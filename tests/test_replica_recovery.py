"""Recovery drill: a replica InjectedCrash-killed at each snapshot-
transfer chunk boundary, restarted, and required to rejoin (resume or
clean re-bootstrap) and converge to the primary's canonical content —
differential-equal vs an uninterrupted replica.

The kill: ``InjectedCrash`` armed on the replica's position-addressed
CONFIRM pull at chunk index ``k``. The client applies chunk ``k`` FIRST
and pulls next second, so the crash lands exactly on the k-th chunk
boundary — with the chunk's atoms partially durable in the replica's
graph, the worst possible restart state. ``InjectedCrash`` is a
``BaseException``, so no ``except Exception`` healing layer can swallow
it — but in-process the unwound stack is ONE worker thread, while a
real kill takes the whole process (and the peer plane is deliberately
robust to single-thread deaths: the stall watchdog would quietly
re-pull and heal). The ``process_kill`` fixture completes the
simulation: the instant the crash unwinds its thread, the victim's
transport is severed — nothing received from then on, exactly a killed
process's silence. The stalled transfer then fails typed
(``TransientFault`` after the resume budget) and the restarted node
must make the partially-applied graph converge anyway (gid
write-through makes the re-transfer idempotent)."""

from __future__ import annotations

import threading

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.fault import InjectedCrash, TransientFault, \
    global_faults
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.replica import ReplicaConfig, ReplicaNode
from hypergraphdb_tpu.serve import ServeConfig


@pytest.fixture
def faults():
    f = global_faults()
    f.reset()
    yield f
    f.reset()
    f.disable()


@pytest.fixture
def process_kill(monkeypatch):
    """InjectedCrash unwinding ANY thread == the PROCESS died. The hook
    counts the kill, keeps the intended traceback out of the test log,
    and — when the test registered ``state["transport"]`` — severs that
    transport on the spot, so the in-process victim goes as silent as a
    real corpse (single-thread deaths alone the peer plane survives by
    design)."""
    state = {"transport": None, "crashes": []}
    orig = threading.excepthook

    def hook(args):
        if args.exc_type is InjectedCrash:
            state["crashes"].append(args)
            t = state["transport"]
            if t is not None:
                t.stop()
            return
        orig(args)

    monkeypatch.setattr(threading, "excepthook", hook)
    return state


def serve_cfg():
    return ServeConfig(max_linger_s=0.001, prewarm_aot=False)


def replica_cfg():
    return ReplicaConfig(primary="primary",
                         anti_entropy_interval_s=0.1,
                         bootstrap_page=8,         # ~5 chunks
                         bootstrap_timeout_s=30.0,
                         serve=serve_cfg())


def wait_digest_equal(ga, gb, timeout=30.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if transfer.content_digest(ga) == transfer.content_digest(gb):
            return True
        time.sleep(0.02)
    return False


@pytest.mark.parametrize("crash_chunk", [1, 2, 3, 4])
def test_crash_at_each_chunk_boundary_rejoins_and_converges(
        faults, process_kill, crash_chunk):
    net = LoopbackNetwork()
    gp = hg.HyperGraph()
    pp = HyperGraphPeer.loopback(gp, net, identity="primary")
    pp.replication.debounce_s = 0.005
    pp.start()
    nodes = [int(gp.add(f"d{i}")) for i in range(30)]
    for i in range(0, 28, 2):
        gp.add_link([nodes[i], nodes[i + 1]], value=f"dl{i}")

    # the uninterrupted CONTROL replica — the differential baseline
    gc_ = hg.HyperGraph()
    control = ReplicaNode(
        gc_, HyperGraphPeer.loopback(gc_, net, identity="control"),
        replica_cfg())
    control.start()
    assert pp.replication.flush()
    assert wait_digest_equal(gp, gc_)

    # the VICTIM: InjectedCrash at the crash_chunk-th CONFIRM pull
    gr = hg.HyperGraph()
    faults.enable(seed=crash_chunk)
    faults.arm(
        "peer.transport.send", at={crash_chunk}, error=InjectedCrash,
        when=lambda ctx: (ctx.get("activity") == "cact-transfer"
                          and ctx.get("performative") == "confirm"),
    )
    pr = HyperGraphPeer.loopback(gr, net, identity="victim")
    process_kill["transport"] = pr.interface    # what the kill severs
    victim = ReplicaNode(gr, pr, ReplicaConfig(
        primary="primary", anti_entropy_interval_s=0.1,
        bootstrap_page=8, bootstrap_timeout_s=30.0,
        bootstrap_retry_after_s=0.05, bootstrap_max_resumes=3,
        serve=serve_cfg()))
    # the dead node hears nothing more: the transfer stalls and fails
    # typed after the resume budget; the node never reaches serving
    with pytest.raises((TransientFault, TimeoutError)):
        victim.start()
    assert len(process_kill["crashes"]) == 1    # the kill really fired
    assert faults.fired("peer.transport.send") == 1
    assert not victim.bootstrapped
    # the partially-applied graph holds SOME but not all atoms
    n_applied = sum(1 for _ in gr.atoms())
    assert n_applied > 0
    pr.stop()                                   # bury the dead process

    # RESTART over the same (partially bootstrapped) graph
    faults.disarm("peer.transport.send")
    pr2 = HyperGraphPeer.loopback(gr, net, identity="victim")
    node2 = ReplicaNode(gr, pr2, replica_cfg())
    node2.start()
    try:
        # a crash mid-transfer never anchored the clock → the rejoin is
        # a CLEAN RE-BOOTSTRAP (idempotent over the partial apply)
        assert node2.bootstrap_mode == "transfer"
        assert node2.wait_converged(timeout=30)
        # canonical content: rejoined == primary == uninterrupted
        assert wait_digest_equal(gp, gr)
        assert (transfer.content_digest(gr)
                == transfer.content_digest(gc_))
        # and it SERVES: reads flow on the rejoined node
        gid0 = transfer.gid_of(gp, nodes[0], "primary")
        local = int(transfer.lookup_local(gr, gid0))
        res = node2.runtime.submit_bfs(local, max_hops=1) \
                   .result(timeout=30)
        assert res.count >= 2
    finally:
        node2.stop()
        control.stop()
        pp.stop()
        gp.close()
        gr.close()
        gc_.close()


def test_crash_after_transfer_rejoins_by_resume(faults, process_kill):
    """The other boundary: the crash lands AFTER the transfer anchored
    the clock (mid-follow) — the rejoin must take the cheap resume path
    and converge by catch-up alone. (No transport registered with the
    kill hook: this drill stops the node explicitly, modelling an
    operator restart rather than a mid-transfer corpse.)"""
    net = LoopbackNetwork()
    gp = hg.HyperGraph()
    pp = HyperGraphPeer.loopback(gp, net, identity="primary")
    pp.replication.debounce_s = 0.005
    pp.start()
    for i in range(12):
        gp.add(f"s{i}")
    gr = hg.HyperGraph()
    node = ReplicaNode(
        gr, HyperGraphPeer.loopback(gr, net, identity="victim"),
        replica_cfg())
    node.start()
    assert pp.replication.flush()
    assert wait_digest_equal(gp, gr)
    # kill the follower's receive loop with a push-delivery crash
    faults.enable(seed=0)
    faults.arm(
        "peer.transport.send", at={1}, error=InjectedCrash,
        when=lambda ctx: (ctx.get("activity") == "replication"
                          and ctx.get("target") == "primary"),
    )
    gp.add("during-crash")          # the ack send kills the apply side
    pp.replication.flush()
    # give the victim's doomed ack a moment to fire, then bury it
    import time

    deadline = time.monotonic() + 10
    while not process_kill["crashes"] and time.monotonic() < deadline:
        time.sleep(0.02)
    faults.disarm("peer.transport.send")
    node.stop()
    gp.add("after-crash")
    pp.replication.flush()
    # restart: clock is anchored → RESUME, catch-up converges the tail
    node2 = ReplicaNode(
        gr, HyperGraphPeer.loopback(gr, net, identity="victim"),
        replica_cfg())
    node2.start()
    try:
        assert node2.bootstrap_mode == "resume"
        assert wait_digest_equal(gp, gr)
    finally:
        node2.stop()
        pp.stop()
        gp.close()
        gr.close()
