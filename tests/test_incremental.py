"""Incremental CSR re-pack (delta overlays): BASELINE config 5 semantics.

Differential tests: BFS over (base ∪ delta) must equal BFS over a full
re-pack at every point in a streaming ingest/remove workload."""

import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu.ops.frontier import bfs_levels
from hypergraphdb_tpu.ops.incremental import SnapshotManager, bfs_levels_delta
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

from conftest import make_random_hypergraph


def _bfs_sets(dev, delta, snap_full, seeds, hops):
    """(delta-path visited, full-repack visited) as numpy bool arrays,
    trimmed of padding differences."""
    lv_d, vis_d = bfs_levels_delta(dev, delta, jnp.asarray(seeds), hops)
    lv_f, vis_f = bfs_levels(snap_full.device, jnp.asarray(seeds), hops)
    vd = np.asarray(vis_d)
    vf = np.asarray(vis_f)
    out_d, out_f = [], []
    for i in range(len(seeds)):
        out_d.append(set(np.nonzero(vd[i])[0].tolist()) - {dev.num_atoms})
        out_f.append(set(np.nonzero(vf[i])[0].tolist()) - {snap_full.num_atoms})
    return out_d, out_f


def test_delta_matches_full_repack_on_ingest(graph):
    nodes, links = make_random_hypergraph(graph, n_nodes=80, n_links=120, seed=9)
    mgr = SnapshotManager(graph, headroom=3.0)
    base_version = mgr.base.version

    # stream in new structure AFTER the base pack
    new_nodes = list(graph.add_nodes_bulk([f"x{i}" for i in range(30)]))
    r = np.random.default_rng(1)
    for i in range(60):
        a = int(r.choice(nodes))
        b = int(r.choice(new_nodes))
        graph.add_link([a, b], value=1000 + i)

    dev, delta = mgr.device()
    assert mgr.base.version == base_version, "ingest must NOT force a repack"
    assert mgr.delta_edges > 0

    seeds = np.asarray([int(nodes[0]), int(new_nodes[0])], dtype=np.int32)
    snap_full = CSRSnapshot.pack(graph, capacity=dev.num_atoms)
    got, want = _bfs_sets(dev, delta, snap_full, seeds, hops=3)
    assert got == want


def test_delta_handles_removals(graph):
    a = graph.add("a")
    b = graph.add("b")
    c = graph.add("c")
    l1 = graph.add_link((a, b))
    l2 = graph.add_link((b, c))
    mgr = SnapshotManager(graph, headroom=3.0)

    graph.remove(int(l2))  # now a--b only
    dev, delta = mgr.device()
    seeds = np.asarray([int(a)], dtype=np.int32)
    snap_full = CSRSnapshot.pack(graph, capacity=dev.num_atoms)
    got, want = _bfs_sets(dev, delta, snap_full, seeds, hops=4)
    assert got == want
    assert int(c) not in got[0]


def test_cascade_removal_tombstones_links(graph):
    """Removing an atom cascade-removes incident links; the delta must
    tombstone those links too (they get their own removed events)."""
    a = graph.add("a")
    b = graph.add("b")
    c = graph.add("c")
    graph.add_link((a, b))
    lbc = graph.add_link((b, c))
    mgr = SnapshotManager(graph, headroom=3.0)

    graph.remove(int(b))  # cascades to both links
    dev, delta = mgr.device()
    assert bool(np.asarray(delta.dead)[int(lbc)])
    seeds = np.asarray([int(a)], dtype=np.int32)
    snap_full = CSRSnapshot.pack(graph, capacity=dev.num_atoms)
    got, want = _bfs_sets(dev, delta, snap_full, seeds, hops=4)
    assert got == want
    assert got[0] == {int(a)}  # nothing reachable anymore


def test_compaction_on_headroom_exhaustion(graph):
    graph.add("seed")
    mgr = SnapshotManager(graph, headroom=1.05)
    before = mgr.compactions
    # overflow the tiny headroom
    graph.add_nodes_bulk([f"n{i}" for i in range(5000)])
    dev, delta = mgr.device()
    assert mgr.compactions > before
    # post-compaction the delta is empty and the base covers everything
    assert mgr.delta_edges == 0
    assert dev.num_atoms >= 5000


def test_compaction_on_delta_ratio(graph):
    nodes, _ = make_random_hypergraph(graph, n_nodes=50, n_links=20, seed=2)
    mgr = SnapshotManager(graph, headroom=50.0, compact_ratio=0.0)
    mgr._maybe_compact()
    before = mgr.compactions
    r = np.random.default_rng(3)
    for i in range(5000):
        ts = r.choice(nodes, size=2, replace=False)
        graph.add_link([int(t) for t in ts], value=i)
    mgr.device()
    assert mgr.compactions > before


# ---------------------------------------------------------------- model families


def test_model_generators(graph):
    from hypergraphdb_tpu.models import Synset, wordnet_like, zipf_hypergraph
    from hypergraphdb_tpu.query import dsl as q

    nodes, links = zipf_hypergraph(graph, n_nodes=200, n_links=100, seed=1)
    assert len(nodes) == 200 and len(links) == 100
    assert graph.arity(int(links[0])) >= 2

    syn, rels = wordnet_like(graph, n_synsets=100, n_relations=150, seed=2)
    st = graph.typesystem.infer(Synset()).name
    assert len(q.find_all(graph, q.type_(st))) == 100
    # relations are value-typed links: typed-value queries work
    hyper = q.find_all(graph, q.value("hypernym"))
    assert all(graph.is_link(h) for h in hyper)
