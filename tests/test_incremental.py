"""Incremental CSR re-pack (delta overlays): BASELINE config 5 semantics.

Differential tests: BFS over (base ∪ delta) must equal BFS over a full
re-pack at every point in a streaming ingest/remove workload."""

import jax.numpy as jnp
import numpy as np

from hypergraphdb_tpu.ops.frontier import bfs_levels
from hypergraphdb_tpu.ops.incremental import SnapshotManager, bfs_levels_delta
from hypergraphdb_tpu.ops.snapshot import CSRSnapshot

from conftest import make_random_hypergraph


def _bfs_sets(dev, delta, snap_full, seeds, hops):
    """(delta-path visited, full-repack visited) as numpy bool arrays,
    trimmed of padding differences."""
    lv_d, vis_d = bfs_levels_delta(dev, delta, jnp.asarray(seeds), hops)
    lv_f, vis_f = bfs_levels(snap_full.device, jnp.asarray(seeds), hops)
    vd = np.asarray(vis_d)
    vf = np.asarray(vis_f)
    out_d, out_f = [], []
    for i in range(len(seeds)):
        out_d.append(set(np.nonzero(vd[i])[0].tolist()) - {dev.num_atoms})
        out_f.append(set(np.nonzero(vf[i])[0].tolist()) - {snap_full.num_atoms})
    return out_d, out_f


def test_delta_matches_full_repack_on_ingest(graph):
    nodes, links = make_random_hypergraph(graph, n_nodes=80, n_links=120, seed=9)
    mgr = SnapshotManager(graph, headroom=3.0)
    base_version = mgr.base.version

    # stream in new structure AFTER the base pack
    new_nodes = list(graph.add_nodes_bulk([f"x{i}" for i in range(30)]))
    r = np.random.default_rng(1)
    for i in range(60):
        a = int(r.choice(nodes))
        b = int(r.choice(new_nodes))
        graph.add_link([a, b], value=1000 + i)

    dev, delta = mgr.device()
    assert mgr.base.version == base_version, "ingest must NOT force a repack"
    assert mgr.delta_edges > 0

    seeds = np.asarray([int(nodes[0]), int(new_nodes[0])], dtype=np.int32)
    snap_full = CSRSnapshot.pack(graph, capacity=dev.num_atoms)
    got, want = _bfs_sets(dev, delta, snap_full, seeds, hops=3)
    assert got == want


def test_delta_handles_removals(graph):
    a = graph.add("a")
    b = graph.add("b")
    c = graph.add("c")
    l1 = graph.add_link((a, b))
    l2 = graph.add_link((b, c))
    mgr = SnapshotManager(graph, headroom=3.0)

    graph.remove(int(l2))  # now a--b only
    dev, delta = mgr.device()
    seeds = np.asarray([int(a)], dtype=np.int32)
    snap_full = CSRSnapshot.pack(graph, capacity=dev.num_atoms)
    got, want = _bfs_sets(dev, delta, snap_full, seeds, hops=4)
    assert got == want
    assert int(c) not in got[0]


def test_cascade_removal_tombstones_links(graph):
    """Removing an atom cascade-removes incident links; the delta must
    tombstone those links too (they get their own removed events)."""
    a = graph.add("a")
    b = graph.add("b")
    c = graph.add("c")
    graph.add_link((a, b))
    lbc = graph.add_link((b, c))
    mgr = SnapshotManager(graph, headroom=3.0)

    graph.remove(int(b))  # cascades to both links
    dev, delta = mgr.device()
    assert bool(np.asarray(delta.dead)[int(lbc)])
    seeds = np.asarray([int(a)], dtype=np.int32)
    snap_full = CSRSnapshot.pack(graph, capacity=dev.num_atoms)
    got, want = _bfs_sets(dev, delta, snap_full, seeds, hops=4)
    assert got == want
    assert got[0] == {int(a)}  # nothing reachable anymore


def test_compaction_on_headroom_exhaustion(graph):
    graph.add("seed")
    mgr = SnapshotManager(graph, headroom=1.05)
    before = mgr.compactions
    # overflow the tiny headroom
    graph.add_nodes_bulk([f"n{i}" for i in range(5000)])
    dev, delta = mgr.device()
    assert mgr.compactions > before
    # post-compaction the delta is empty and the base covers everything
    assert mgr.delta_edges == 0
    assert dev.num_atoms >= 5000


def test_compaction_on_delta_ratio(graph):
    nodes, _ = make_random_hypergraph(graph, n_nodes=50, n_links=20, seed=2)
    mgr = SnapshotManager(graph, headroom=50.0, compact_ratio=0.0)
    mgr._maybe_compact()
    before = mgr.compactions
    r = np.random.default_rng(3)
    for i in range(5000):
        ts = r.choice(nodes, size=2, replace=False)
        graph.add_link([int(t) for t in ts], value=i)
    mgr.device()
    assert mgr.compactions > before


# ---------------------------------------------------------------- model families


def test_model_generators(graph):
    from hypergraphdb_tpu.models import Synset, wordnet_like, zipf_hypergraph
    from hypergraphdb_tpu.query import dsl as q

    nodes, links = zipf_hypergraph(graph, n_nodes=200, n_links=100, seed=1)
    assert len(nodes) == 200 and len(links) == 100
    assert graph.arity(int(links[0])) >= 2

    syn, rels = wordnet_like(graph, n_synsets=100, n_relations=150, seed=2)
    st = graph.typesystem.infer(Synset()).name
    assert len(q.find_all(graph, q.type_(st))) == 100
    # relations are value-typed links: typed-value queries work
    hyper = q.find_all(graph, q.value("hypernym"))
    assert all(graph.is_link(h) for h in hyper)


# ---------------------------------------------------------------- LSM read mode


def test_enable_incremental_no_repack_on_mutation(graph):
    """snapshot() under mutation returns the SAME base object (no full
    repack — VERDICT r2 item 2) while find_all answers stay exact."""
    nodes, _ = make_random_hypergraph(graph, n_nodes=60, n_links=40, seed=4)
    mgr = graph.enable_incremental(headroom=10.0, background=False)
    base0 = graph.snapshot()
    packs_before = mgr.compactions
    l_new = graph.add_link((nodes[0], nodes[1]), value=12345)
    assert graph.snapshot() is base0  # no repack happened
    assert mgr.compactions == packs_before


def test_incremental_value_query_sees_delta(graph):
    """Device value-pushdown plans must merge the memtable: adds, removes,
    and replaces after the base pack all reflect in query answers."""
    from hypergraphdb_tpu.query import dsl as hg

    graph.config.query.device_min_batch = 0
    nodes = [graph.add(f"n{i}") for i in range(10)]
    rels = [
        graph.add_link((nodes[0], nodes[i % 9 + 1]), value=i * 10)
        for i in range(12)
    ]
    graph.enable_incremental(headroom=10.0, background=False)
    base = graph.snapshot()

    cond = hg.and_(hg.value(35, "gte"), hg.incident(nodes[0]))

    def answer():
        return sorted(graph.find_all(cond))

    want = sorted(int(l) for i, l in enumerate(rels) if i * 10 >= 35)
    assert answer() == want

    # add after pack → appears without repack
    l_add = graph.add_link((nodes[0], nodes[2]), value=999)
    assert graph.snapshot() is base
    assert int(l_add) in answer()

    # remove after pack → disappears
    graph.remove(rels[11])
    assert int(rels[11]) not in answer()

    # replace value in place → reflects the new value
    graph.replace(rels[10], 5)  # 100 → 5, no longer >= 35
    assert int(rels[10]) not in answer()
    graph.replace(rels[9], 77)  # 90 → 77, still matches
    assert int(rels[9]) in answer()
    assert graph.snapshot() is base  # still zero repacks


def test_incremental_background_compaction(graph):
    """Background compaction swaps the base without breaking answers."""
    from hypergraphdb_tpu.query import dsl as hg

    graph.config.query.device_min_batch = 0
    nodes = [graph.add(f"n{i}") for i in range(8)]
    mgr = graph.enable_incremental(
        headroom=50.0, compact_ratio=0.0, background=True
    )
    base0 = mgr.base
    import numpy as np

    r = np.random.default_rng(9)
    rels = []
    for i in range(2000):
        a, b = r.choice(8, size=2, replace=False)
        rels.append(graph.add_link((nodes[a], nodes[b]), value=int(i)))
    mgr._maybe_compact()
    t = mgr._compact_thread
    if t is not None:
        t.join(timeout=30)
    assert mgr.compactions > 1
    assert mgr.base is not base0
    # adds racing the background extraction stay in the delta (epoch
    # handoff); a final sync compaction drains it fully
    mgr._compact_sync()
    assert mgr.delta_edges == 0
    cond = hg.and_(hg.value(1995, "gte"), hg.incident(nodes[0]))
    want = sorted(
        int(l) for i, l in enumerate(rels)
        if i >= 1995 and int(nodes[0]) in [
            int(x) for x in graph.get(l).targets
        ]
    )
    assert sorted(graph.find_all(cond)) == want


def test_overflow_add_defers_compaction_to_read(graph):
    """Adds beyond the base capacity must not compact inside the event
    handler (lock order: commit → mgr); the next read heals by compacting
    and the new link's edges are traversable (review r4 finding 1)."""
    import jax
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.incremental import bfs_levels_delta

    nodes = [graph.add(f"n{i}") for i in range(6)]
    mgr = graph.enable_incremental(headroom=1.01, background=False)
    packs = mgr.compactions
    # capacity floor is 1024 ids — push past it to overflow the bitmap
    extra = list(graph.add_nodes_bulk([f"x{i}" for i in range(2000)]))
    l = graph.add_link((extra[-1], extra[0]), value="late")
    assert mgr._needs_recompact  # flagged, not compacted, inside the event
    assert mgr.compactions == packs
    dev, delta = mgr.device()  # the read triggers the compaction
    assert mgr.compactions > packs
    seeds = jnp.asarray(np.asarray([int(extra[-1])], dtype=np.int32))
    _, visited = bfs_levels_delta(dev, delta, seeds, 1)
    assert bool(np.asarray(visited)[0, int(extra[0])])


def test_concurrent_writers_and_readers_no_deadlock(graph):
    """Sync-mode compaction from the read path while writers commit —
    regression for the commit/mgr lock-order inversion (review r4 #3)."""
    import threading

    nodes = [graph.add(f"n{i}") for i in range(8)]
    mgr = graph.enable_incremental(
        headroom=1.05, compact_ratio=0.0, background=False
    )
    errors = []

    def writer():
        try:
            for i in range(300):
                graph.add_link(
                    (nodes[i % 8], nodes[(i + 1) % 8]), value=int(i)
                )
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(30):
                mgr.device()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=writer), threading.Thread(target=reader)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in ts), "deadlock: threads still alive"
    assert not errors


def test_shape_stable_packing_and_compaction_stats(graph):
    """pack_pad_multiple keeps base device shapes IDENTICAL across
    compactions (cached executables survive base swaps) and every
    compaction records wall timing."""
    nodes = [graph.add(f"n{i}") for i in range(10)]
    mgr = graph.enable_incremental(
        headroom=1.5, compact_ratio=50.0, background=False,
        pack_pad_multiple=4096,
    )
    assert len(mgr.compaction_stats) == 1  # the init pack
    n0 = mgr.base.num_atoms
    e0 = len(mgr.base.inc_links)
    assert n0 % 4096 == 0 and e0 % 4096 == 0

    for i in range(50):  # modest growth, well inside one pad bucket
        graph.add_link((nodes[i % 10], nodes[(i + 3) % 10]), value=i)
    mgr._compact_sync()
    assert mgr.base.num_atoms == n0, "capacity must stay in the same bucket"
    assert len(mgr.base.inc_links) == e0, "edge pad must stay in the bucket"
    stats = mgr.compaction_stats[-1]
    assert stats["total_s"] >= 0 and "extract_s" in stats
    mgr.close()


def test_incremental_delta_upload_appends_tail(graph):
    """Delta refreshes between compactions ship only the appended tail
    (and packed tombstones) — bit-for-bit equal to a full re-upload."""
    import jax.numpy as jnp

    from hypergraphdb_tpu.ops.incremental import bfs_levels_delta

    nodes = [graph.add(f"n{i}") for i in range(20)]
    mgr = graph.enable_incremental(
        headroom=3.0, compact_ratio=50.0, background=False,
        delta_bucket_min=1 << 12,
    )
    for i in range(30):
        graph.add_link((nodes[i % 20], nodes[(i + 1) % 20]), value=i)
    dev, d1 = mgr.device()
    assert mgr.full_uploads == 1 and mgr.tail_uploads == 0

    extra = graph.add_link((nodes[0], nodes[7]), value="tail-link")
    dev, d2 = mgr.device()
    assert mgr.tail_uploads == 1, (mgr.full_uploads, mgr.tail_uploads)

    # the spliced delta answers exactly like a freshly-uploaded one
    seeds = jnp.asarray([int(nodes[0])], dtype=jnp.int32)
    lv_a, vis_a = bfs_levels_delta(dev, d2, seeds, 3)
    mgr._device_delta = None  # force a clean full upload
    mgr._uploaded_marker = (-1, -1, -1)
    dev, d3 = mgr.device()
    lv_b, vis_b = bfs_levels_delta(dev, d3, seeds, 3)
    np.testing.assert_array_equal(np.asarray(vis_a), np.asarray(vis_b))
    np.testing.assert_array_equal(np.asarray(lv_a), np.asarray(lv_b))


def test_incremental_dead_only_refresh_reuses_edge_buffers(graph):
    """A removal with no new edges refreshes only the (packed) tombstone
    mask; the resident edge buffers are reused as-is."""
    a = graph.add("a")
    b = graph.add("b")
    c = graph.add("c")
    l1 = graph.add_link((a, b), value=1)
    mgr = graph.enable_incremental(
        headroom=3.0, compact_ratio=50.0, background=False,
        delta_bucket_min=1 << 12,
    )
    l2 = graph.add_link((b, c), value=2)
    dev, d1 = mgr.device()
    graph.remove(int(l2))
    dev, d2 = mgr.device()
    assert d2.inc_links is d1.inc_links  # no edge re-upload
    assert bool(np.asarray(d2.dead)[int(l2)])
    from hypergraphdb_tpu.ops.incremental import bfs_levels_delta
    import jax.numpy as jnp

    _, vis = bfs_levels_delta(
        dev, d2, jnp.asarray([int(a)], dtype=jnp.int32), 4
    )
    row = np.asarray(vis)[0]
    assert row[int(b)] and not row[int(c)]


def test_wait_compacted_bounds_inflight_compaction(graph):
    """wait_compacted blocks until the background pass settles (including
    its coalesced catch-up) instead of callers polling delta_edges."""
    nodes = [graph.add(f"n{i}") for i in range(8)]
    mgr = graph.enable_incremental(
        headroom=50.0, compact_ratio=0.0, background=True
    )
    assert mgr.wait_compacted(1.0)  # idle manager: returns at once
    # enough atoms to overflow the initial 1024-id capacity → the next
    # read requests a background pass
    for i in range(1500):
        graph.add_link((nodes[i % 8], nodes[(i + 1) % 8]), value=i)
    mgr._maybe_compact()  # kicks a background pass
    assert mgr.wait_compacted(30.0)
    assert not mgr._compacting
    assert mgr.compactions > 1
    # after quiescing, the device pair reflects the new epoch immediately
    dev, delta = mgr.device()
    assert dev.num_atoms == mgr.base.num_atoms


def test_pinned_view_is_one_epoch(graph):
    """pinned_view captures base + device pair + memtable under one lock:
    the correction sets always compensate for exactly that base."""
    nodes = [graph.add(f"n{i}") for i in range(6)]
    mgr = graph.enable_incremental(background=False, compact_ratio=100.0)
    lk = graph.add_link((nodes[0], nodes[1]), value="after-pack")
    graph.remove(int(nodes[5]))
    pv = mgr.pinned_view()
    assert pv.epoch == mgr.compactions
    assert pv.base.device is pv.device
    assert int(lk) in pv.new_atoms
    assert int(nodes[5]) in pv.dead
    # the delta in the view is the one uploaded for THIS marker
    assert pv.delta is mgr._device_delta


def test_sharded_base_reshard_retries_on_mid_shard_compaction(graph,
                                                              monkeypatch):
    """The sharded-base epoch re-shard swap loop: a compaction landing
    WHILE the (lock-free) base repartition runs must discard the stale
    shard and retry against the new epoch — the epoch re-check in
    ``_ensure_sharded_base`` plus ``pinned_view``'s re-shard loop. The
    retry branch converges: the returned view's sharded base belongs to
    the epoch the view is pinned at."""
    from hypergraphdb_tpu.parallel import sharded as psh

    nodes = [graph.add(f"n{i}") for i in range(8)]
    for i in range(16):
        graph.add_link((nodes[i % 8], nodes[(i + 1) % 8]), value=i)
    mgr = graph.enable_incremental(background=False, compact_ratio=100.0)
    mgr.attach_mesh(psh.make_mesh(), edge_chunk=64, delta_edge_chunk=32)

    real_from_host = psh.ShardedSnapshot.from_host
    calls = {"n": 0}

    def racing_from_host(base, mesh, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            # a compaction lands mid-shard: the epoch this shard was
            # captured against is stale by the time it would swap in
            graph.add_link((nodes[0], nodes[3]), value="mid-shard")
            mgr._compact_sync()
        return real_from_host(base, mesh, **kw)

    monkeypatch.setattr(psh.ShardedSnapshot, "from_host",
                        staticmethod(racing_from_host))
    epoch_before = mgr.compactions
    view = mgr.pinned_view(sharded=True)
    # the first shard was discarded (epoch moved), the retry converged
    assert calls["n"] >= 2
    assert mgr.compactions == epoch_before + 1
    assert view.epoch == mgr.compactions
    assert mgr._sharded_epoch == view.epoch
    assert view.sharded_base is mgr._sharded_base
    # and the swapped-in shard really is the NEW base's partition (the
    # mid-shard edge is in it)
    assert view.sharded_base.num_atoms == mgr.base.num_atoms

    # a second pin with a quiet epoch re-shards nothing
    monkeypatch.setattr(psh.ShardedSnapshot, "from_host", real_from_host)
    n_after = calls["n"]
    view2 = mgr.pinned_view(sharded=True)
    assert calls["n"] == n_after
    assert view2.sharded_base is view.sharded_base
