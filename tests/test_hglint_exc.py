"""Precision pins for the hgexc rule family (HG10xx exception flow &
failure discipline).

Three jobs, mirroring tests/test_hglint_conc.py:

1. pin the seeded exception fixtures exactly — rule AND line — so a
   precision regression in either direction (missed swallow, new false
   positive) fails loudly;
2. pin the diagnostics' CONTENT: the interprocedural witness chain, the
   fault-point origin, and the inferred raise-set each name the evidence
   a reviewer needs to judge the finding;
3. act as the zero-baseline gate: ``hypergraphdb_tpu`` must carry NO
   HG10xx findings — swallows get fixed (or pragma-audited), never
   baselined.
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.hglint import run_lint  # noqa: E402
from tools.hglint.model import rule_matches  # noqa: E402

FIXTURES = Path(__file__).parent / "hglint_fixtures"
BAD = FIXTURES / "bad_pkg" / "exceptions_bad.py"
OK = FIXTURES / "clean_pkg" / "exceptions_ok.py"


def _pins(findings):
    return sorted((f.rule, f.line) for f in findings)


# ------------------------------------------------------------- exact pins


def test_exceptions_bad_exact_rule_and_line():
    findings = run_lint([str(BAD)])
    assert _pins(findings) == [
        ("HG1001", 26),   # except BaseException eats the drill's kill
        ("HG1002", 43),   # typed fault handler over a ValueError-only body
        ("HG1003", 54),   # explicit: except PermanentFault -> continue
        ("HG1003", 71),   # inferred: broad retry over a permanent raise
        ("HG1004", 79),   # unguarded thread target lets ValueError escape
        ("HG1005", 96),   # pass-only swallow with no evidence
    ], "\n".join(f.render() for f in findings)


def test_exceptions_clean_shapes_are_silent():
    # EVERY family must stay silent: the disciplined twins re-raise
    # kills, catch live types, gate retries on transience, guard thread
    # bodies, and leave evidence when they swallow
    findings = run_lint([str(OK)])
    assert findings == [], "\n".join(f.render() for f in findings)


# ----------------------------------------------------- diagnostic content


def test_swallowed_kill_names_the_interprocedural_witness():
    findings = run_lint([str(BAD)])
    (hit,) = [f for f in findings if f.rule == "HG1001"]
    # the chain walks caller -> callee and lands on the fault point
    assert "pump_once -> _arm_fault_point" in hit.message
    assert "fault point 'ingest.pump'" in hit.message
    assert "InjectedCrash" in hit.message


def test_dead_handler_reports_the_inferred_raise_set():
    findings = run_lint([str(BAD)])
    (hit,) = [f for f in findings if f.rule == "HG1002"]
    assert "except TransientFault" in hit.message
    assert "raise-set" in hit.message


def test_retry_findings_distinguish_explicit_and_inferred():
    findings = run_lint([str(BAD)])
    explicit, inferred = sorted(
        (f for f in findings if f.rule == "HG1003"), key=lambda f: f.line
    )
    assert "retry loop catches non-transient" in explicit.message
    assert "broad retry handler" in inferred.message
    assert "is_transient" in inferred.message
    assert "PermanentFault" in explicit.message
    assert "PermanentFault" in inferred.message


def test_thread_entry_names_the_escaping_type():
    findings = run_lint([str(BAD)])
    (hit,) = [f for f in findings if f.rule == "HG1004"]
    assert hit.scope == "crashy_worker"
    assert "ValueError" in hit.message
    assert "kills the thread" in hit.message


def test_injected_crash_passthrough_is_exempt():
    # clean_pkg drill_worker lets ONLY InjectedCrash escape its guard —
    # by design a simulated kill must take the thread down, so HG1004
    # exempts BaseException-only escapes
    findings = run_lint([str(OK)], only="HG1004")
    assert findings == [], "\n".join(f.render() for f in findings)


# --------------------------------------------------------- family scoping


def test_only_hg10_selects_the_family_not_hg1xx():
    # "HG10" must mean the HG10xx family — HG101/HG102... are HG1xx and
    # live in a different analyzer generation
    findings = run_lint([str(FIXTURES / "bad_pkg")], only="HG10")
    assert findings and all(f.rule.startswith("HG10") for f in findings)
    assert all(len(f.rule) == 6 for f in findings), _pins(findings)
    hostsync = run_lint([str(FIXTURES / "bad_pkg")], only="HG1")
    assert any(len(f.rule) == 5 for f in hostsync)  # HG1xx still reachable


def test_rule_matches_is_family_aware():
    assert rule_matches("HG1001", "HG10")
    assert not rule_matches("HG101", "HG10")
    assert rule_matches("HG101", "HG1")
    assert not rule_matches("HG1001", "HG1")    # HG1 is exactly the HG1xx
    # family — a four-digit family never aliases into a three-digit one
    assert rule_matches("HG1003", "HG1003")
    assert not rule_matches("HG1003", "HG1001")


def test_single_rule_scoping():
    findings = run_lint([str(BAD)], only="HG1005")
    assert _pins(findings) == [("HG1005", 96)]


# ------------------------------------------------------ zero-baseline gate


def test_repo_carries_zero_exception_findings(monkeypatch):
    """The hgexc acceptance bar: HG10xx holds a ZERO baseline on the real
    tree — every broad swallow either resolves its ticket with evidence
    or carries an audited pragma that HG901 keeps honest."""
    monkeypatch.chdir(REPO)
    findings = run_lint(["hypergraphdb_tpu"], only="HG10")
    assert findings == [], (
        "exception-discipline findings must be FIXED, not baselined:\n"
        + "\n".join(f.render() for f in findings)
    )


def test_repo_carries_zero_lock_contract_findings(monkeypatch):
    monkeypatch.chdir(REPO)
    findings = run_lint(["hypergraphdb_tpu"], only="HG403")
    assert findings == [], "\n".join(f.render() for f in findings)
