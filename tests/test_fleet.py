"""hgfleet: the fleet collector — per-node-labelled metric merges,
cross-process trace assembly, worst-of health, incident visibility
through the door, and per-request EXPLAIN cost attribution.

The acceptance contracts:

- a single fleet trace contains spans from ≥ 2 distinct processes
  (sender + receiver halves joined on one 128-bit trace id);
- an incident on a replica-side flight recorder is visible through the
  door's fleet view (the collector pulls the remote window on incident);
- an ``explain=True`` response's lane/occupancy/device_seconds agree
  EXACTLY with the ticket's drained span tree.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from hypergraphdb_tpu import obs
from hypergraphdb_tpu.obs.fleet import (
    FleetCollector,
    HTTPNodeSource,
    LocalNodeSource,
    explain_record,
)
from hypergraphdb_tpu.obs.flight import FlightRecorder
from hypergraphdb_tpu.obs.http import TelemetryServer
from hypergraphdb_tpu.obs.registry import Registry
from hypergraphdb_tpu.obs.trace import Tracer
from hypergraphdb_tpu.replica.httpd import SubmitServer
from hypergraphdb_tpu.replica.router import submit_payload
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime, Unservable
from tests.test_serve_runtime import FakeClock, FakeExecutor


def get(url):
    """(status, body) — urllib raises on >=400, we want both."""
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def make_node(node_id, healthy=True, role="node"):
    """One fake fleet node: registry + tracer + flight + health."""
    reg = Registry(node_id)
    tracer = Tracer(clock=FakeClock()).enable()
    flight = FlightRecorder(clock=FakeClock())
    payload = {"role": role, "queue_depth": 0}

    def health():
        return healthy, dict(payload)

    return LocalNodeSource(node_id, registries=[reg], tracer=tracer,
                           flight=flight, health=health, role=role), \
        reg, tracer, flight


# ---------------------------------------------------------------- metrics


def test_fleet_metrics_keeps_per_node_series_distinct():
    src_a, reg_a, _, _ = make_node("a")
    src_b, reg_b, _, _ = make_node("b")
    reg_a.counter("serve.submitted").inc(3)
    reg_b.counter("serve.submitted").inc(7)
    col = FleetCollector([src_a, src_b], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    col.poll()
    text = col.fleet_metrics()
    assert 'serve_submitted_total{node="a"} 3' in text
    assert 'serve_submitted_total{node="b"} 7' in text
    # one TYPE line per metric, however many nodes export it
    assert text.count("# TYPE serve_submitted_total counter") == 1
    # the collector's own counters ride the same page
    assert 'fleet_polls_total{node="fleet"} 1' in text
    # and the fleet-wide total is readable back off the merged page
    assert col.metric_total("serve_submitted_total") == 10.0


def test_fleet_healthz_worst_of_with_per_node_detail():
    src_a, *_ = make_node("a", healthy=True)
    src_b, *_ = make_node("b", healthy=False, role="replica")
    col = FleetCollector([src_a, src_b], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    col.poll()
    ok, payload = col.fleet_healthz()
    assert ok is False                       # worst-of: b is unhealthy
    assert payload["healthy_nodes"] == 1 and payload["nodes_total"] == 2
    assert payload["nodes"]["a"]["healthy"] is True
    assert payload["nodes"]["b"]["healthy"] is False
    assert payload["nodes"]["b"]["role"] == "replica"
    assert payload["nodes"]["b"]["detail"]["role"] == "replica"


def test_unreachable_node_counts_unhealthy_not_fatal():
    src_a, *_ = make_node("a")
    dead = HTTPNodeSource("dead", "http://127.0.0.1:1", timeout_s=0.2)
    col = FleetCollector([src_a, dead], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    verdicts = col.poll()
    assert verdicts == {"a": True, "dead": False}
    ok, payload = col.fleet_healthz()
    assert ok is False
    assert payload["nodes"]["dead"]["scraped"] is False
    assert "error" in payload["nodes"]["dead"]
    assert col.registry.get("fleet.scrape_errors").value == 1


# ---------------------------------------------------- trace assembly


def joined_pair():
    """A sender trace on tracer A and its remote half on tracer B —
    the peer-plane propagation shape, two 'processes'."""
    src_a, reg_a, ta, _ = make_node("a")
    src_b, reg_b, tb, _ = make_node("b", role="replica")
    reg_a.counter("serve.submitted").inc(1)
    reg_b.counter("serve.submitted").inc(1)
    tr = ta.start_trace("peer.push")
    root = tr.start_span("push")
    tr.marks["root"] = root
    remote = tb.start_remote_trace("peer.apply", tr.context())
    rs = remote.start_span("apply")
    rs.end()
    remote.finish()
    root.end()
    tr.finish()
    return src_a, src_b, tr, root


def test_fleet_trace_joins_spans_from_two_processes():
    src_a, src_b, tr, root = joined_pair()
    col = FleetCollector([src_a, src_b], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    col.poll()
    joined = col.fleet_trace(tr.trace_id)
    assert joined is not None
    assert joined["n_processes"] == 2
    assert joined["processes"] == ["a", "b"]
    assert {s["node"] for s in joined["spans"]} == {"a", "b"}
    # the receiver's span hangs under the sender's propagated span id:
    # ONE tree, no heuristics
    apply_span = next(s for s in joined["spans"] if s["name"] == "apply")
    assert apply_span["parent_id"] == root.span_id
    push = next(n for n in joined["tree"] if n["name"] == "push")
    assert any(c["name"] == "apply" and c["node"] == "b"
               for c in push.get("children", ()))
    # summaries agree
    summary = next(s for s in col.fleet_traces()
                   if s["trace_id"] == tr.trace_id)
    assert summary["n_processes"] == 2


def test_fleet_trace_dedupes_repeated_polls():
    src_a, src_b, tr, _ = joined_pair()
    col = FleetCollector([src_a, src_b], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    col.poll()
    n1 = col.fleet_trace(tr.trace_id)["n_spans"]
    col.poll()   # /debug/traces is a peek: same records arrive again
    assert col.fleet_trace(tr.trace_id)["n_spans"] == n1


def test_fleet_trace_store_is_bounded():
    src_a, _, ta, _ = make_node("a")
    col = FleetCollector([src_a], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0,
                         max_traces=4, traces_limit=64)
    for _ in range(10):
        t = ta.start_trace("serve.request")
        t.start_span("request").end()
        t.finish()
    col.poll()
    assert len(col.fleet_traces()) == 4
    assert col.registry.get("fleet.traces_assembled").value == 4


def test_failed_scrape_keeps_last_good_metrics_totals():
    """A down node must not make the fleet's cumulative counter totals
    regress: the SLO sources read totals off the latest pages, and a
    drop would clamp the burn windows empty exactly mid-incident."""
    src, reg, _, _ = make_node("a")
    reg.counter("serve.completed").inc(40)
    reg.counter("serve.shed_deadline").inc(10)
    col = FleetCollector([src], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    col.poll()
    assert col.metric_total("serve_shed_deadline_total") == 10.0

    def boom(traces_limit=64):
        raise OSError("telemetry port died")

    src.scrape = boom
    col.poll()
    ok, payload = col.fleet_healthz()
    assert ok is False                            # health stays honest
    assert payload["nodes"]["a"]["scraped"] is False
    # ...but the totals hold at the last-good page
    assert col.metric_total("serve_shed_deadline_total") == 10.0
    assert col.metric_total("serve_completed_total") == 40.0


def test_http_source_rejects_non_200_telemetry_bodies():
    """A node whose /metrics errors must fail the scrape — its error
    body kept as metrics_text would corrupt the merged exposition page
    and silently zero the node's SLO contributions."""
    # a SubmitServer answers /metrics with a 404 JSON error body
    srv = SubmitServer(_NullDoor()).start()
    try:
        scrape = HTTPNodeSource("bad", srv.url).scrape()
    finally:
        srv.stop()
    assert scrape.ok is False
    assert scrape.metrics_text == ""
    assert "404" in scrape.error


def test_http_source_scrapes_a_real_telemetry_server():
    _, reg, tracer, flight = make_node("n")
    reg.counter("serve.submitted").inc(5)
    t = tracer.start_trace("serve.request")
    t.start_span("request").end()
    t.finish()
    flight.record("serve.retry", attempt=1)
    srv = TelemetryServer(registries=[reg], tracer=tracer, flight=flight,
                          health=lambda: (True, {"role": "replica"})).start()
    try:
        scrape = HTTPNodeSource("n", srv.url, role="replica").scrape()
    finally:
        srv.stop()
    assert scrape.ok and scrape.healthy
    assert "serve_submitted_total 5" in scrape.metrics_text
    assert len(scrape.traces) == 1
    assert scrape.flight[-1]["kind"] == "serve.retry"
    assert scrape.health["role"] == "replica"


# ------------------------------------------- incidents through the door


def test_replica_incident_visible_through_fleet_view():
    src_a, *_ = make_node("a")
    src_b, _, _, flight_b = make_node("b", role="replica")
    col = FleetCollector([src_a, src_b], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    col.poll()
    assert col.incidents() == {}
    # an incident fires ON THE REPLICA (breaker trip / typed error / SLO
    # burn all land here) — the collector pulls the remote window
    flight_b.record("serve.retry", key="bfs_2", attempt=1)
    flight_b.incident("serve_error", error="InjectedFault", tickets=3)
    col.poll()
    snap = col.incidents()
    assert "b" in snap and snap["b"]["reason"] == "serve_error"
    # the PULLED window holds the remote history leading into it
    kinds = [r["kind"] for r in snap["b"]["window"]]
    assert "serve.retry" in kinds and "incident" in kinds
    ok, payload = col.fleet_healthz()
    assert payload["incidents"]["b"]["reason"] == "serve_error"
    assert "window" not in payload["incidents"]["b"]  # summary, not bulk
    assert col.registry.get("fleet.incidents_seen").value == 1
    # re-polling the same window does not recount
    col.poll()
    assert col.registry.get("fleet.incidents_seen").value == 1


# ----------------------------------------------------- door HTTP wiring


class _NullDoor:
    """A minimal submit_fn stand-in: the fleet routes don't need it."""

    def __call__(self, payload):  # pragma: no cover - not exercised
        raise Unservable("no backends in this test")


@pytest.fixture
def door():
    src_a, src_b, tr, _ = joined_pair()
    col = FleetCollector([src_a, src_b], clock=FakeClock(),
                         flight=FlightRecorder(), poll_interval_s=0)
    col.slo = obs.SLOMonitor(clock=col.clock, flight=col.flight)
    col.slo.add(obs.Objective("availability", 0.999))
    col.poll()
    srv = SubmitServer(_NullDoor(), fleet=col).start()
    try:
        yield srv, col, tr
    finally:
        srv.stop()


def test_door_serves_fleet_metrics_and_healthz(door):
    srv, col, tr = door
    status, body = get(srv.url + "/fleet/metrics")
    assert status == 200
    assert 'node="a"' in body and 'node="b"' in body
    status, body = get(srv.url + "/fleet/healthz")
    assert status == 200
    payload = json.loads(body)
    assert payload["nodes_total"] == 2 and payload["role"] == "fleet"


def test_door_serves_one_joined_fleet_trace(door):
    srv, col, tr = door
    status, body = get(srv.url + f"/fleet/traces/{tr.trace_id}")
    assert status == 200
    joined = json.loads(body)
    assert joined["trace_id"] == tr.trace_id
    assert joined["n_processes"] == 2          # the acceptance bar
    assert {s["node"] for s in joined["spans"]} == {"a", "b"}
    status, body = get(srv.url + "/fleet/traces")
    assert status == 200
    assert any(s["trace_id"] == tr.trace_id
               for s in json.loads(body)["traces"])
    status, _ = get(srv.url + "/fleet/traces/12345")
    assert status == 404
    status, _ = get(srv.url + "/fleet/traces/not-an-id")
    assert status == 400


def test_door_serves_slo_snapshot(door):
    srv, col, tr = door
    status, body = get(srv.url + "/fleet/slo")
    assert status == 200
    snap = json.loads(body)
    assert "availability" in snap
    assert snap["availability"]["target"] == 0.999


def test_door_without_fleet_404s_fleet_routes():
    srv = SubmitServer(_NullDoor()).start()
    try:
        status, _ = get(srv.url + "/fleet/metrics")
        assert status == 404
    finally:
        srv.stop()


# ------------------------------------------------------------- EXPLAIN


def make_traced_runtime():
    tracer = Tracer(clock=FakeClock()).enable()
    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=clock,
                      manual=True, tracer=tracer)
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    return rt, tracer, clock


def test_explain_requires_tracing():
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=FakeClock(),
                      manual=True, tracer=Tracer())  # NOT enabled
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    with pytest.raises(Unservable):
        rt.submit_bfs(1, explain=True)
    rt.close()


def test_explain_agrees_exactly_with_drained_span_tree():
    rt, tracer, clock = make_traced_runtime()
    fut = rt.submit_bfs(1, explain=True)
    rt.step(drain=True)
    res = fut.result(timeout=0)
    rec = fut.explain
    assert rec is not None
    rt.close()
    # the independently drained trace is the record's source of truth
    drained = [t for t in tracer.drain() if t.name == "serve.request"
               and t.trace_id == rec["trace_id"]]
    assert len(drained) == 1
    again = explain_record(drained[0], result=res, lane_path="device",
                           breaker_state=rec["breaker"])
    for k in ("lane", "occupancy", "bucket", "lanes_real", "device_s",
              "queue_wait_s", "retries", "total_s", "count",
              "trace_id"):
        assert again[k] == rec[k], k
    assert rec["lane"] == "bfs/device"
    assert rec["occupancy"] == pytest.approx(0.25)   # 1 real / bucket 4
    assert rec["retries"] == 0
    assert rec["breaker"] == "closed"


def test_explain_record_is_attached_before_result_delivery():
    rt, tracer, clock = make_traced_runtime()
    futs = [rt.submit_bfs(i, explain=True) for i in range(3)]
    rt.step(drain=True)
    for fut in futs:
        fut.result(timeout=0)
        # no settling window: the record must already be there
        assert fut.explain["kind"] == "bfs"
    rt.close()


def test_explain_survives_any_sampling_rate():
    rt, tracer, clock = make_traced_runtime()
    tracer.set_sample_rate("serve.request", 0.0)   # drop everything...
    fut = rt.submit_bfs(1, explain=True)
    rt.step(drain=True)
    fut.result(timeout=0)
    assert fut.explain is not None                 # ...except explained
    assert any(t.trace_id == fut.explain["trace_id"]
               for t in tracer.drain())            # retained for the fleet
    rt.close()


def test_explain_rides_the_submit_payload_schema():
    tracer = Tracer(clock=FakeClock()).enable()
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, tracer=tracer)
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    try:
        out = submit_payload(
            rt, {"kind": "bfs", "seed": 1, "explain": True}, 10.0,
            node_id="replica-1",
        )
        assert out["explain"]["lane"] == "bfs/device"
        assert out["explain"]["node"] == "replica-1"
        assert out["explain"]["trace_id"] > 0
        # without the flag the response carries no explain key
        out2 = submit_payload(rt, {"kind": "bfs", "seed": 1}, 10.0)
        assert "explain" not in out2
    finally:
        rt.close()


def test_explain_over_http_submit():
    tracer = Tracer(clock=FakeClock()).enable()
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, tracer=tracer)
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    srv = SubmitServer(
        lambda p: submit_payload(rt, p, 10.0, node_id="n1")
    ).start()
    try:
        body = json.dumps({"kind": "bfs", "seed": 2, "explain": True})
        req = urllib.request.Request(
            srv.url + "/submit", data=body.encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            out = json.loads(r.read().decode())
        assert out["explain"]["node"] == "n1"
        assert out["explain"]["lane"] == "bfs/device"
        assert out["explain"]["occupancy"] is not None
    finally:
        srv.stop()
        rt.close()


# ------------------------------------- the replicated tier, end to end


def wait_for(cond, timeout=15.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_replicated_tier_fleet_view_end_to_end():
    """The tier PRs 9–12 built, observed as ONE system: a primary and a
    replica with their own tracers, a front door with the fleet
    collector, and — through the door's HTTP port — a merged metrics
    page, a cross-PROCESS trace joined from both peers' halves, and a
    replica-side flight incident surfaced in the fleet health view."""
    import hypergraphdb_tpu as hg
    from hypergraphdb_tpu.peer.peer import HyperGraphPeer
    from hypergraphdb_tpu.peer.transport import LoopbackNetwork
    from hypergraphdb_tpu.replica import (
        FrontDoor,
        LocalBackend,
        ReplicaConfig,
        ReplicaNode,
        RouterConfig,
        frontdoor_server,
    )
    from hypergraphdb_tpu.obs.http import runtime_health

    net = LoopbackNetwork()
    gp = hg.HyperGraph()
    pp = HyperGraphPeer.loopback(gp, net, identity="primary")
    pp.replication.debounce_s = 0.005
    pp.tracer = Tracer(max_finished=256).enable()
    pp.start()
    hs = [int(gp.add(f"n{i}")) for i in range(4)]
    gr = hg.HyperGraph()
    pr = HyperGraphPeer.loopback(gr, net, identity="replica-1")
    pr.replication.debounce_s = 0.005
    pr.tracer = Tracer(max_finished=256).enable()
    node = ReplicaNode(gr, pr, ReplicaConfig(
        primary="primary",
        serve=ServeConfig(max_linger_s=0.001, prewarm_aot=False,
                          tracer=pr.tracer),
    ))
    prt = fd = fsrv = col = None
    try:
        node.start()
        assert node.wait_converged(timeout=30)
        gp.add("traced")                  # a push both tracers record
        assert pp.replication.flush()
        prt = ServeRuntime(gp, ServeConfig(max_linger_s=0.001,
                                           prewarm_aot=False))
        fd = FrontDoor(
            LocalBackend("primary", prt, runtime_health(prt),
                         role="primary"),
            [LocalBackend("replica-1", node.runtime,
                          node.health_probe())],
            RouterConfig(poll_interval_s=0),
        )
        replica_flight = FlightRecorder()
        replica_src = node.fleet_source()
        replica_src.flight = replica_flight   # per-node recorder
        col = FleetCollector(
            [LocalNodeSource("primary", registries=[prt.stats.registry],
                             tracer=pp.tracer,
                             health=runtime_health(prt), role="primary"),
             replica_src, fd.fleet_source()],
            poll_interval_s=0, flight=FlightRecorder(),
        )
        fsrv = frontdoor_server(fd, fleet=col).start()

        def joined():
            col.poll()
            return [s for s in col.fleet_traces()
                    if s["n_processes"] >= 2]
        assert wait_for(lambda: bool(joined())), col.fleet_traces()
        tid = joined()[0]["trace_id"]
        status, body = get(fsrv.url + f"/fleet/traces/{tid}")
        assert status == 200
        trace = json.loads(body)
        assert trace["n_processes"] >= 2           # the acceptance bar
        assert {"primary", "replica-1"} <= set(trace["processes"])
        # one request through the door mints the router's counters
        res = fd.submit({"kind": "bfs", "seed": hs[0], "max_hops": 1,
                         "deadline_s": 10.0})
        assert res["routed_to"] in ("primary", "replica-1")
        col.poll()
        status, body = get(fsrv.url + "/fleet/metrics")
        assert status == 200
        assert 'node="primary"' in body
        assert 'node="replica-1"' in body
        assert 'router_submitted_total{node="router"} 1' in body
        # a replica-side incident reaches the door's fleet health view
        replica_flight.incident("breaker_trip", key="bfs_2")
        col.poll()
        status, body = get(fsrv.url + "/fleet/healthz")
        payload = json.loads(body)
        assert payload["incidents"]["replica-1"]["reason"] == \
            "breaker_trip"
    finally:
        if fsrv is not None:
            fsrv.stop()
        if col is not None:
            col.stop()
        if prt is not None:
            prt.close()
        node.stop()
        pp.stop()
        gp.close()
        gr.close()


# ------------------------------------------------------- lane counters


def test_lane_counters_follow_served_path():
    rt, tracer, clock = make_traced_runtime()
    rt.submit_bfs(1)
    rt.submit_pattern([2])
    rt.step(drain=True)
    rt.step(drain=True)
    counts = rt.stats.lane_counts()
    assert counts[("bfs", "device")] == 1
    assert counts[("pattern", "device")] == 1
    assert counts[("bfs", "host")] == 0
    rt.close()


# --------------------------------------------------- join EXPLAIN (hgperf)


def test_join_explain_plan_shape_derivation():
    """The EXPLAIN join attribution (PR-13 records predate join engine
    v2): plan shape flat/bushy/hub/host + the batch's hub/correction
    counts, derived from the launched token."""
    from types import SimpleNamespace as NS

    derive = ServeRuntime._join_explain
    res = NS(kind="join")
    flat_plan = NS()
    # non-join results carry no join section
    assert derive(NS(kind="bfs"), "device", NS(join_plan=flat_plan)) is None
    # host path (or no device plan): "host", no hub lanes
    rec = derive(res, "host", NS(join_plan=None, join_hub_lanes=0,
                                 join_partials=0))
    assert rec == {"plan": "host", "hub_dispatches": 0,
                   "partial_corrections": 0}
    # flat vs hub distinguished by the batch's hub lanes
    rec = derive(res, "device", NS(join_plan=flat_plan, join_hub_lanes=0,
                                   join_partials=1))
    assert rec["plan"] == "flat" and rec["partial_corrections"] == 1
    rec = derive(res, "device", NS(join_plan=flat_plan, join_hub_lanes=3,
                                   join_partials=0))
    assert rec["plan"] == "hub" and rec["hub_dispatches"] == 3
    # a bushy decomposition is named by its plan class
    from hypergraphdb_tpu.join.planner import BushyJoinPlan

    bushy = BushyJoinPlan.__new__(BushyJoinPlan)
    rec = derive(res, "device", NS(join_plan=bushy, join_hub_lanes=2,
                                   join_partials=0))
    assert rec["plan"] == "bushy"


def test_join_explain_record_rides_the_span_tree():
    rec = explain_record(
        _finished_trace(), join={"plan": "hub", "hub_dispatches": 2,
                                 "partial_corrections": 1},
    )
    assert rec["join"] == {"plan": "hub", "hub_dispatches": 2,
                           "partial_corrections": 1}
    assert "join" not in explain_record(_finished_trace())


def _finished_trace():
    tracer = Tracer(clock=FakeClock()).enable()
    tr = tracer.start_trace("serve.request", kind="join")
    tr.finish_terminal("resolve")
    return tr


def test_join_explain_end_to_end_device_and_host():
    """A real device-served join carries its plan shape + batch counts;
    a tombstoned memtable routes the next join to the exact host path
    and the record says so."""
    jax = pytest.importorskip("jax")  # noqa: F841 - device lane needed
    from hypergraphdb_tpu import HyperGraph
    from hypergraphdb_tpu.query import conditions as c
    from hypergraphdb_tpu.query.variables import var
    from tests.conftest import make_random_hypergraph

    g = HyperGraph()
    try:
        nodes, links = make_random_hypergraph(g, n_nodes=60, n_links=120,
                                              max_arity=3, seed=7)
        tracer = Tracer().enable()
        rt = ServeRuntime(g, ServeConfig(buckets=(4,), max_linger_s=0.001,
                                         tracer=tracer, top_r=128))
        try:
            spec = {"y": c.And(c.CoIncident(int(nodes[3])),
                               c.CoIncident(var("z"))),
                    "z": c.CoIncident(int(nodes[3]))}
            fut = rt.submit_join(spec, explain=True)
            fut.result(timeout=120)
            rec = fut.explain
            assert rec is not None and rec["kind"] == "join"
            assert rec["join"]["plan"] in ("flat", "bushy", "hub")
            assert rec["join"]["hub_dispatches"] >= 0
            assert rec["join"]["partial_corrections"] >= 0
            # a tombstone dirties the memtable past correction: the
            # whole next batch serves exactly on host, attributed so
            g.remove(int(links[0]))
            fut2 = rt.submit_join(spec, explain=True)
            fut2.result(timeout=120)
            assert fut2.explain["join"]["plan"] == "host"
            assert fut2.explain["lane"] == "join/host"
        finally:
            rt.close()
    finally:
        g.close()
