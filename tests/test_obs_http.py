"""HTTP telemetry endpoint: /metrics, /healthz (per-key breaker states
within one scrape), /debug/traces, /debug/flight — end to end over real
HTTP against a live (manual-mode) runtime."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from hypergraphdb_tpu import obs
from hypergraphdb_tpu.obs.http import (
    TelemetryServer,
    breaker_key_label,
    runtime_health,
)
from hypergraphdb_tpu.obs.trace import Tracer
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from tests.test_serve_runtime import FakeClock, FakeExecutor


def make_runtime(tracer=None):
    clock = FakeClock()
    cfg = ServeConfig(buckets=(4,), max_linger_s=0.0, clock=clock,
                      manual=True, tracer=tracer, breaker_threshold=3)
    rt = ServeRuntime(graph=None, config=cfg, executor=FakeExecutor())
    return rt, clock


def get(url):
    """(status, body) — urllib raises on >=400, we want both."""
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


@pytest.fixture
def served():
    tracer = Tracer(clock=FakeClock())
    tracer.enable()
    rt, clock = make_runtime(tracer=tracer)
    srv = TelemetryServer(registries=[rt.stats.registry], tracer=tracer,
                          health=runtime_health(rt)).start()
    try:
        yield rt, clock, srv, tracer
    finally:
        srv.stop()
        rt.close(drain=True)


def test_metrics_endpoint_serves_prometheus_text(served):
    rt, clock, srv, tracer = served
    fut = rt.submit_bfs(1)
    rt.step(drain=True)
    fut.result(timeout=0)
    status, body = get(srv.url + "/metrics")
    assert status == 200
    assert "serve_submitted_total 1" in body
    assert "serve_completed_total 1" in body
    assert "serve_latency_seconds_count 1" in body


def test_healthz_reflects_per_key_breaker_within_one_scrape(served):
    rt, clock, srv, tracer = served
    status, body = get(srv.url + "/healthz")
    assert status == 200
    h = json.loads(body)
    assert h["breakers"] == {} and h["queue_depth"] == 0
    assert h["accepting"] is True

    key = ("bfs", 2)
    for _ in range(3):                      # threshold=3 → OPEN
        rt.breaker.record_failure(key)
    status, body = get(srv.url + "/healthz")   # the very next scrape
    h = json.loads(body)
    assert status == 503
    assert h["breakers"] == {"bfs_2": "open"}
    assert h["breaker_worst"] == 2
    # the labelled instrument family agrees with the healthz view
    _, metrics = get(srv.url + "/metrics")
    assert "serve_breaker_state_bfs_2 2.0" in metrics
    assert "serve_breaker_trips_bfs_2_total 1" in metrics

    rt.breaker.record_success(key)             # recovery
    status, body = get(srv.url + "/healthz")
    assert status == 200
    assert json.loads(body)["breakers"] == {"bfs_2": "closed"}
    _, metrics = get(srv.url + "/metrics")
    assert "serve_breaker_state_bfs_2 0.0" in metrics


def test_debug_traces_peeks_without_draining(served):
    rt, clock, srv, tracer = served
    fut = rt.submit_bfs(7)
    rt.step(drain=True)
    fut.result(timeout=0)
    status, body = get(srv.url + "/debug/traces")
    assert status == 200
    recs = obs.parse_traces_jsonl(body)
    assert [r["name"] for r in recs] == ["serve.request"]
    # a peek, not a drain: the exporter still gets the trace
    assert tracer.finished_count() == 1


def test_debug_flight_and_404(served):
    rt, clock, srv, tracer = served
    obs.global_flight().record("http.test", marker=1)
    status, body = get(srv.url + "/debug/flight")
    assert status == 200
    assert any(json.loads(line)["kind"] == "http.test"
               for line in body.splitlines() if line.strip())
    status, _ = get(srv.url + "/nope")
    assert status == 404


def test_broken_health_probe_returns_500_not_crash():
    def bad_probe():
        raise RuntimeError("probe fell over")

    srv = TelemetryServer(health=bad_probe).start()
    try:
        status, body = get(srv.url + "/healthz")
        assert status == 500
        # the server survives: the next route still answers
        status, _ = get(srv.url + "/metrics")
        assert status == 200
    finally:
        srv.stop()


def test_key_label_shapes():
    assert breaker_key_label(("bfs", 2)) == "bfs_2"
    assert breaker_key_label(("pattern", 3)) == "pattern_3"
    assert breaker_key_label("k") == "k"


def test_server_start_stop_idempotent():
    srv = TelemetryServer()
    srv.start()
    srv.start()                 # second start is a no-op, not a 2nd loop
    assert get(srv.url + "/metrics")[0] == 200
    srv.stop()
    srv.stop()                  # double stop tolerated
    # a stopped server's port is gone: restarting must fail LOUDLY, not
    # hand back a dead endpoint
    with pytest.raises(RuntimeError, match="stopped"):
        srv.start()


def test_stop_without_start_releases_the_port():
    """The listener binds in __init__ — stop() must release it even when
    serve_forever never ran (and must not hang in shutdown())."""
    import socket

    srv = TelemetryServer()
    host, port = srv.host, srv.port
    srv.stop()
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))        # rebinding proves the port was released
    s.close()
