"""Behavior pins for the HG1103 (persisted-artifact versioning) runtime
fixes that took the hgwire family to a zero baseline on the real tree.

Three artifacts gained a ``schema_version`` stamp; each fix has the same
contract, pinned here per artifact:

- a stamped write round-trips through its own reader;
- a LEGACY (pre-versioning, unstamped) record still parses — it
  defaults to version 1, so upgrading never strands existing data;
- a FUTURE stamp is rejected, not guessed at: the redelivery journal
  skips the record (losing a redelivery is recoverable via catch-up),
  the partition marker hard-fails (mis-routing every record is not).
"""

from __future__ import annotations

import json
import types
from collections import deque

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.core.errors import HGException
from hypergraphdb_tpu.obs.perf import (
    MANIFEST_SCHEMA_VERSION,
    PerfSentinel,
    _ProfileSession,
)
from hypergraphdb_tpu.peer.replication import (
    JOURNAL_SCHEMA_VERSION,
    Replication,
)


# ---------------------------------------------- redelivery journal (peer)


def make_replication(journal_path):
    r = Replication(types.SimpleNamespace(graph=hg.HyperGraph()))
    r.journal_path = str(journal_path)
    return r


def journal_records(path):
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def test_journal_save_stamps_and_replay_restores(tmp_path):
    path = tmp_path / "redelivery.jsonl"
    r = make_replication(path)
    r._redelivery["peer-x"] = deque(
        [({"op": "push", "seq": 1}, 1), ({"op": "push", "seq": 2}, 3)]
    )
    r._journal_save()
    recs = journal_records(path)
    assert [rec["schema_version"] for rec in recs] == [1, 1]
    assert recs[0]["schema_version"] == JOURNAL_SCHEMA_VERSION

    r2 = make_replication(path)
    r2._journal_replay()
    assert dict(r2._redelivery) == {
        "peer-x": deque([({"op": "push", "seq": 1}, 1),
                         ({"op": "push", "seq": 2}, 3)]),
    }
    assert r2._redelivery_n == 2


def test_journal_legacy_unstamped_record_still_replays(tmp_path):
    # a journal written by a pre-versioning build has no stamp at all:
    # it must parse as version 1, not be dropped by the upgrade
    path = tmp_path / "redelivery.jsonl"
    path.write_text(json.dumps(
        {"pid": "peer-y", "attempt": 2, "message": {"op": "push"}}) + "\n")
    r = make_replication(path)
    r._journal_replay()
    assert dict(r._redelivery) == {"peer-y": deque([({"op": "push"}, 2)])}


def test_journal_future_version_is_skipped_not_guessed(tmp_path):
    # a future stamp means a newer build wrote fields this one cannot
    # interpret — skip the record (catch-up repairs the loss), but keep
    # replaying the records this build DOES understand
    path = tmp_path / "redelivery.jsonl"
    path.write_text(
        json.dumps({"schema_version": 99, "pid": "peer-z", "attempt": 1,
                    "message": {"op": "push", "seq": 1}}) + "\n"
        + json.dumps({"schema_version": 1, "pid": "peer-z", "attempt": 1,
                      "message": {"op": "push", "seq": 2}}) + "\n")
    r = make_replication(path)
    r._journal_replay()
    assert dict(r._redelivery) == {
        "peer-z": deque([({"op": "push", "seq": 2}, 1)]),
    }
    assert r._redelivery_n == 1


# ------------------------------------------ PROFILE.json manifest (hgperf)


def test_profile_manifest_carries_schema_version(tmp_path):
    sen = PerfSentinel(eval_interval_s=0.0)
    session = _ProfileSession(None, str(tmp_path), "bfs", 0.0, False)
    sen._write_manifest(session, t0=1.0)
    rec = json.loads((tmp_path / "PROFILE.json").read_text())
    assert rec["schema_version"] == MANIFEST_SCHEMA_VERSION == 1
    assert rec["lane"] == "bfs" and rec["t0"] == 1.0


def test_profile_manifest_merge_cannot_strip_the_stamp(tmp_path):
    # the close path merges the on-disk record back in; a PRE-VERSIONING
    # manifest on disk (no stamp) must not dilute the rewrite — the
    # stamp is applied after the merge, and the disk t0 survives
    (tmp_path / "PROFILE.json").write_text(
        json.dumps({"lane": "bfs", "t0": 1.0, "profiler_active": True,
                    "bound_s": 2.0}))
    sen = PerfSentinel(eval_interval_s=0.0)
    session = _ProfileSession(None, str(tmp_path), "bfs", 0.0, False)
    sen._write_manifest(session, t1=3.0)
    rec = json.loads((tmp_path / "PROFILE.json").read_text())
    assert rec["schema_version"] == MANIFEST_SCHEMA_VERSION
    assert rec["t0"] == 1.0 and rec["t1"] == 3.0


# --------------------------------------- partitions.json marker (storage)


def partitioned_cfg(loc, n):
    return hg.HGConfiguration(store_backend="partitioned",
                              location=str(loc), n_partitions=n)


def test_partition_marker_is_stamped_on_first_open(tmp_path):
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    loc = tmp_path / "grid"
    g = hg.HyperGraph(partitioned_cfg(loc, 3))
    g.close()
    rec = json.loads((loc / "partitions.json").read_text())
    assert rec == {"schema_version": 1, "n_partitions": 3}


def test_partition_marker_legacy_unstamped_is_accepted(tmp_path):
    # a pre-versioning marker parses as version 1 — and its recorded
    # count still wins over the config (the whole point of the marker)
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    loc = tmp_path / "grid"
    loc.mkdir()
    (loc / "partitions.json").write_text(json.dumps({"n_partitions": 3}))
    g = hg.HyperGraph(partitioned_cfg(loc, 5))
    assert len(g.backend._parts) == 3
    g.close()


def test_partition_marker_future_version_hard_fails(tmp_path):
    # handle routing is h % n: guessing n under an unknown layout would
    # silently mis-route every record, so this one REFUSES to open
    pytest.importorskip("hypergraphdb_tpu.storage.native")
    loc = tmp_path / "grid"
    loc.mkdir()
    (loc / "partitions.json").write_text(
        json.dumps({"schema_version": 99, "n_partitions": 3}))
    with pytest.raises(HGException, match="partition-marker schema"):
        hg.HyperGraph(partitioned_cfg(loc, 3))
