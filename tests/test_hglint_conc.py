"""Precision pins for the hgconc rule families (HG7xx blocking-under-lock,
HG8xx thread/resource lifecycle, HG901 analyzer hygiene) plus the
``--diff-base`` scoped-report lane and the README docs-drift gate.

Three jobs:

1. pin the seeded fixtures exactly — rule AND line — so a precision
   regression in either direction (missed hazard, new false positive)
   fails loudly;
2. exercise the escape hatches (``*_locked`` leaves, used pragmas, the
   HG901 stale-suppression audit's carve-outs) and the changed-files
   report scoping;
3. act as the zero-baseline gate: ``hypergraphdb_tpu`` must carry NO
   HG7xx/HG8xx/HG9xx findings — concurrency hazards get fixed, not
   baselined.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.hglint import run_lint  # noqa: E402
from tools.hglint.model import DOC_ANCHORS, RULES, family  # noqa: E402

FIXTURES = Path(__file__).parent / "hglint_fixtures"


def _pins(findings):
    return sorted((f.rule, f.line) for f in findings)


# ------------------------------------------------------- blocking fixtures


def test_blocking_bad_exact_rule_and_line():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "blocking_bad.py")])
    assert _pins(findings) == [
        ("HG701", 16),   # time.sleep under the module lock
        ("HG701", 21),   # sock.sendall under the lock
        ("HG701", 26),   # Queue.get under the lock
        ("HG701", 32),   # cv.wait while ANOTHER lock stays held
        ("HG701", 56),   # Thread.join under the instance lock
        ("HG702", 41),   # transitive: tick -> _slow_helper -> time.sleep
        ("HG702", 72),   # arg-passed edge: prober(run_probe(_slow_helper))
        ("HG702", 77),   # blocking callable smuggled into an unresolvable
                         # receiver under the hold
        ("HG702", 86),   # dict-dispatch: OPS[kind]() can hit _slow_helper
        ("HG703", 52),   # sorted() under the instance lock
    ], "\n".join(f.render() for f in findings)


def test_blocking_transitive_names_the_witness_chain():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "blocking_bad.py")])
    (hit,) = [f for f in findings if f.rule == "HG702" and f.line == 41]
    assert "_slow_helper" in hit.message
    assert "time.sleep" in hit.message


def test_blocking_taint_follows_arg_passed_edges():
    # prober() never blocks by name — the taint arrives ONLY through the
    # callable it smuggles into run_probe's parameter
    findings = run_lint([str(FIXTURES / "bad_pkg" / "blocking_bad.py")])
    (hit,) = [f for f in findings if f.rule == "HG702" and f.line == 72]
    assert "prober" in hit.message and "time.sleep" in hit.message
    (smuggled,) = [f for f in findings
                   if f.rule == "HG702" and f.line == 77]
    assert "_slow_helper" in smuggled.message
    assert "passed while holding" in smuggled.message


def test_blocking_dispatch_table_members_flagged():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "blocking_bad.py")])
    (hit,) = [f for f in findings if f.rule == "HG702" and f.line == 86]
    assert "dispatch" in hit.message and "OPS" in hit.message


def test_blocking_clean_shapes_are_silent():
    findings = run_lint([str(FIXTURES / "clean_pkg" / "blocking_ok.py")])
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------------------ lifecycle fixtures


def test_lifecycle_bad_exact_rule_and_line():
    findings = run_lint([str(FIXTURES / "bad_pkg" / "lifecycle_bad.py")])
    assert _pins(findings) == [
        ("HG402", 21),   # the racy assign is ALSO an unlocked mutation
        ("HG801", 21),   # worker thread never joined, not daemon
        ("HG801", 49),   # fire-and-forget local thread
        ("HG801", 54),   # timer never cancelled
        ("HG802", 42),   # raising recv leaks the socket
        ("HG802", 59),   # tuple-unpacked conn from accept() leaks on recv
        ("HG802", 67),   # self._sock attribute target leaks on sendall
        ("HG803", 20),   # check-then-act start() without the lock
        ("HG804", 32),   # untimed cv.wait outside a predicate loop
        ("HG805", 37),   # raising handler kills the pump loop
        ("HG901", 8),    # stale disable=HG402 on a bare constant
    ], "\n".join(f.render() for f in findings)


def test_lifecycle_clean_shapes_are_silent():
    findings = run_lint([str(FIXTURES / "clean_pkg" / "lifecycle_ok.py")])
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------------- HG901 suppression audit


def _pkg(tmp_path, src):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "m.py").write_text(src)
    return pkg


_STALE = "import threading\n\n_CAP = 4  # hglint: disable=HG402\n"

_HAZARD_PLUS_STALE = (
    "import jax\n\n\n"
    "@jax.jit\n"
    "def f(x):\n"
    "    return x.item()\n\n\n"
    "_CAP = 4  # hglint: disable=HG402\n"
)


def test_stale_pragma_fires_hg901(tmp_path):
    findings = run_lint([str(_pkg(tmp_path, _STALE))])
    assert [(f.rule, f.line) for f in findings] == [("HG901", 3)]
    assert "stale suppression" in findings[0].message
    assert "disable=HG402" in findings[0].message


def test_used_pragma_is_not_stale(tmp_path):
    pkg = _pkg(tmp_path, (
        "import jax\n\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item()  # hglint: disable=HG101\n"
    ))
    assert run_lint([str(pkg)]) == []


def test_unknown_rule_id_is_not_audited(tmp_path):
    # disable=HG999 names no rule: useless but not "stale" — HG901 only
    # audits suppressions the analyzer could ever have honored
    pkg = _pkg(tmp_path, "_CAP = 4  # hglint: disable=HG999\n")
    assert run_lint([str(pkg)]) == []


def test_scoped_run_skips_the_audit(tmp_path):
    # `--only HG1` never ran HG402, so the pragma CAN'T be judged stale —
    # a scoped run must not spray HG901 noise
    pkg = _pkg(tmp_path, _HAZARD_PLUS_STALE)
    findings = run_lint([str(pkg)], only="HG1")
    assert [f.rule for f in findings] == ["HG101"]


def test_only_hg9_still_audits(tmp_path):
    # `--only HG9` has no runner of its own: every family runs for audit
    # material, but only the HG901 verdicts are reported
    pkg = _pkg(tmp_path, _HAZARD_PLUS_STALE)
    findings = run_lint([str(pkg)], only="HG9")
    assert [(f.rule, f.line) for f in findings] == [("HG901", 9)]


def test_disable_hg901_silences_the_audit(tmp_path):
    pkg = _pkg(tmp_path,
               "_CAP = 4  # hglint: disable=HG402,HG901\n")
    assert run_lint([str(pkg)]) == []


# --------------------------------------------------- changed-files scoping


_HOT_BAD = (
    "import threading\n\n"
    "lock = threading.Lock()\n\n\n"
    "def spin():\n"
    "    import time\n"
    "    with lock:\n"
    "        time.sleep(1)\n"
)


def test_run_lint_changed_files_scopes_the_report(tmp_path, monkeypatch):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "stable.py").write_text(
        "import socket\n\n\n"
        "def probe(host):\n"
        "    s = socket.create_connection((host, 80))\n"
        "    data = s.recv(8)\n"
        "    s.close()\n"
        "    return data\n"
    )
    (pkg / "hot.py").write_text(_HOT_BAD)
    monkeypatch.chdir(tmp_path)
    full = run_lint(["pkg"])
    assert {f.path.replace("\\", "/") for f in full} == {
        "pkg/stable.py", "pkg/hot.py",
    }
    scoped = run_lint(["pkg"], changed_files=["pkg/hot.py"])
    assert scoped and all(
        f.path.replace("\\", "/") == "pkg/hot.py" for f in scoped
    )


def _git(cwd, *argv):
    out = subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *argv],
        cwd=cwd, capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    return out.stdout


def test_cli_diff_base_reports_only_changed_files(tmp_path):
    import os
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "stable.py").write_text(_HOT_BAD)       # pre-existing hazard
    (pkg / "hot.py").write_text("VALUE = 1\n")
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "hot.py").write_text(_HOT_BAD)          # the NEW hazard

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    out = subprocess.run(
        [sys.executable, "-m", "tools.hglint", "pkg",
         "--diff-base", "HEAD", "--output", "json"],
        cwd=tmp_path, capture_output=True, text=True, env=env,
    )
    assert out.returncode == 1, out.stderr
    report = json.loads(out.stdout)
    assert report["diff_base"] == "HEAD"
    assert report["changed_files"] == ["pkg/hot.py"]
    paths = {f["path"].replace("\\", "/") for f in report["findings"]}
    assert paths == {"pkg/hot.py"}, "stable.py leaked into the scoped lane"

    # the full run still sees the pre-existing hazard: scoping narrows
    # the REPORT, never the analysis
    full = subprocess.run(
        [sys.executable, "-m", "tools.hglint", "pkg", "--json"],
        cwd=tmp_path, capture_output=True, text=True, env=env,
    )
    full_paths = {f["path"].replace("\\", "/")
                  for f in json.loads(full.stdout)}
    assert full_paths == {"pkg/hot.py", "pkg/stable.py"}


def test_cli_diff_base_usage_errors(tmp_path):
    import os
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO)
    # scoped run must never become the whole-tree baseline
    out = subprocess.run(
        [sys.executable, "-m", "tools.hglint", "--diff-base", "HEAD",
         "--write-baseline", str(tmp_path / "b.json")],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert out.returncode == 2
    assert "--write-baseline" in out.stderr
    # a rev git can't resolve is a usage error (exit 2), not a crash (3)
    bad = subprocess.run(
        [sys.executable, "-m", "tools.hglint", "--diff-base",
         "no-such-rev-xyz"],
        cwd=REPO, capture_output=True, text=True, env=env,
    )
    assert bad.returncode == 2


# ------------------------------------------------------- docs-drift gate


def _heading_slug(text):
    """GitHub-style anchor slug: lowercase, drop punctuation, spaces to
    hyphens (`&` vanishes, leaving a double hyphen)."""
    text = text.lower()
    text = "".join(c for c in text if c.isalnum() or c in " -_")
    return text.replace(" ", "-")


def test_readme_documents_every_rule_and_vice_versa():
    text = (REPO / "README.md").read_text()
    row_re = re.compile(
        r"^\|\s*\[[^\]]+\]\(#(hg\d[^)]*)\)\s*\|\s*"
        r"(HG\d{3,4})(?:–(HG\d{3,4}))?\s*\|", re.M,
    )
    documented, row_anchors = set(), {}
    for m in row_re.finditer(text):
        anchor, lo, hi = m.group(1), m.group(2), m.group(3) or m.group(2)
        for n in range(int(lo[2:]), int(hi[2:]) + 1):
            documented.add(f"HG{n}")
        row_anchors[family(lo)] = anchor

    missing = set(RULES) - documented
    assert not missing, f"rules with no README table row: {sorted(missing)}"
    phantom = documented - set(RULES)
    assert not phantom, f"README table rows for unknown rules: {sorted(phantom)}"

    # every family's table row links the anchor the diagnostics print...
    assert row_anchors == DOC_ANCHORS

    # ...and every anchor resolves to a real `### HGNxx:` section heading
    headings = {
        _heading_slug(m.group(1))
        for m in re.finditer(r"^### (.+)$", text, re.M)
    }
    dangling = set(DOC_ANCHORS.values()) - headings
    assert not dangling, f"anchors with no section heading: {sorted(dangling)}"


# ------------------------------------------------------ zero-baseline gate


def test_repo_carries_zero_concurrency_findings(monkeypatch):
    """The hgconc acceptance bar: HG7xx/HG8xx/HG9xx hold a ZERO baseline
    on the real tree — a new blocking-under-lock or lifecycle hazard (or
    a suppression going stale) fails tier-1 outright, no baselining."""
    monkeypatch.chdir(REPO)
    findings = run_lint(["hypergraphdb_tpu"], only="HG7,HG8,HG9")
    assert findings == [], (
        "concurrency findings must be FIXED, not baselined:\n"
        + "\n".join(f.render() for f in findings)
    )
