"""Pallas gather+OR kernel: interpret-mode semantics vs the XLA reference.

Real Mosaic compiles need a TPU; CPU CI runs the kernel through the Pallas
interpreter, which exercises the same grid/DMA/semaphore program.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hypergraphdb_tpu.ops import pallas_gather as pg


def _ref(values, idx, w):
    g = np.asarray(values)[np.asarray(idx)]
    return np.bitwise_or.reduce(g.reshape(-1, w, values.shape[1]), axis=1)


@pytest.mark.parametrize("w", [4, 8])
@pytest.mark.parametrize("n_out", [pg.G, pg.G * 3 + 17])
def test_gather_or_matches_xla(w, n_out):
    r = np.random.default_rng(0)
    S = 500
    values = jnp.asarray(
        r.integers(0, 2**32, size=(S, 128), dtype=np.uint64).astype(np.uint32)
    )
    idx = jnp.asarray(r.integers(0, S, size=n_out * w).astype(np.int32))
    out = pg.gather_or(values, idx, w, interpret=True)
    assert out.shape == (n_out, 128)
    assert np.array_equal(np.asarray(out), _ref(values, idx, w))


def test_gather_or_multi_segment(monkeypatch):
    # shrink SEG so the lax.scan path runs in-test
    monkeypatch.setattr(pg, "SEG", pg.G * 8 * 2)
    r = np.random.default_rng(1)
    S, w = 300, 8
    values = jnp.asarray(
        r.integers(0, 2**32, size=(S, 128), dtype=np.uint64).astype(np.uint32)
    )
    n_out = pg.G * 2 * 3 + 5  # 3 full segments + ragged tail
    idx = jnp.asarray(r.integers(0, S, size=n_out * w).astype(np.int32))
    out = pg.gather_or(values, idx, w, interpret=True)
    assert np.array_equal(np.asarray(out), _ref(values, idx, w))


def test_gather_or_rejects_bad_shapes():
    values = jnp.zeros((8, 64), jnp.uint32)  # 64 lanes unsupported
    with pytest.raises(ValueError):
        pg.gather_or(values, jnp.zeros((16,), jnp.int32), 8)
    values = jnp.zeros((8, 128), jnp.uint32)
    with pytest.raises(ValueError):
        pg.gather_or(values, jnp.zeros((15,), jnp.int32), 8)  # not %w


def test_pallas_ok_false_on_cpu():
    assert jax.default_backend() == "cpu"
    assert pg.pallas_ok() is False


def test_bfs_pull_wide_block_cpu_fallback(graph):
    """k_block=4096 on CPU: pallas preflight fails → XLA path, results must
    equal the narrow-block run."""
    from tests.conftest import make_random_hypergraph
    from hypergraphdb_tpu.ops.ellbfs import bfs_pull, visited_rows

    make_random_hypergraph(graph, n_nodes=300, n_links=600, seed=3)
    snap = graph.snapshot()
    seeds = np.arange(40, dtype=np.int32)
    wide = bfs_pull(snap, seeds, 3, k_block=4096)
    narrow = bfs_pull(snap, seeds, 3, k_block=32)
    assert np.array_equal(wide.edges_touched, narrow.edges_touched)
    rw = visited_rows(wide, snap.num_atoms)
    rn = visited_rows(narrow, snap.num_atoms)
    for a, b in zip(rw[: len(seeds)], rn[: len(seeds)]):
        assert np.array_equal(a, b)
