"""Front-door router: placement, per-replica breaker failover,
re-admission on rejoin — and the chaos failover soak the tier's
availability story is accepted on.

Soak contract (ISSUE 9): with the front door under seeded open-loop
load, killing one of two serving replicas yields ZERO caller-visible
errors for in-deadline requests (re-routed or primary-fallback, counted
in stats); the breaker re-admits the replica after it rejoins; a seeded
wire-drop schedule that exhausts the redelivery budget is detected by
contiguity tracking and repaired — final replica content exactly equals
the primary's; the redelivery journal matches its offline replay.
"""

from __future__ import annotations

import random
import threading
import time

import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.algorithms.traversals import HGBreadthFirstTraversal
from hypergraphdb_tpu.fault import CLOSED, OPEN, TransientFault, \
    global_faults
from hypergraphdb_tpu.obs.http import runtime_health
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.replica import (
    FrontDoor,
    LocalBackend,
    ReplicaConfig,
    ReplicaNode,
    RouterConfig,
    submit_payload,
)
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime


@pytest.fixture
def faults():
    f = global_faults()
    f.reset()
    yield f
    f.reset()
    f.disable()


def serve_cfg(**kw):
    kw.setdefault("max_linger_s", 0.001)
    kw.setdefault("prewarm_aot", False)
    return ServeConfig(**kw)


# ------------------------------------------------------------ unit: routing


class FakeBackend:
    """Scripted backend: submit returns a tagged dict or raises what the
    script says; health is injectable."""

    def __init__(self, backend_id, lag=0, healthy=True, queue_depth=0,
                 breaker_worst=0):
        self.id = backend_id
        self.lag = lag
        self.healthy = healthy
        self.queue_depth = queue_depth
        self.breaker_worst = breaker_worst
        self.fail_with = None
        self.calls = 0

    def submit(self, payload, timeout):
        self.calls += 1
        if self.fail_with is not None:
            raise self.fail_with
        return {"answered_by": self.id}

    def health(self):
        if not self.healthy:
            raise ConnectionError("down")
        return True, {"replication_lag": self.lag,
                      "queue_depth": self.queue_depth,
                      "breaker_worst": self.breaker_worst}


def make_router(replicas, **cfg_kw):
    cfg_kw.setdefault("poll_interval_s", 0)     # lazy refresh (tests)
    cfg_kw.setdefault("health_refresh_s", 0.0)  # always fresh
    primary = FakeBackend("primary")
    fd = FrontDoor(primary, replicas, RouterConfig(**cfg_kw))
    return fd, primary


def test_placement_spreads_across_equal_lag_replicas():
    r1, r2 = FakeBackend("r1"), FakeBackend("r2")
    fd, primary = make_router([r1, r2])
    routed = {fd.submit({"kind": "x"})["routed_to"] for _ in range(6)}
    assert routed == {"r1", "r2"}          # round-robin within the group
    assert primary.calls == 0


def test_placement_prefers_lower_lag():
    fresh, stale = FakeBackend("fresh", lag=0), FakeBackend("stale", lag=50)
    fd, _ = make_router([stale, fresh])
    for _ in range(4):
        assert fd.submit({"kind": "x"})["routed_to"] == "fresh"


def test_placement_load_tiebreak_at_equal_lag():
    """ROADMAP 3c: two equally-lagged replicas, one with a deep
    admission queue — the idle one wins every placement; load never
    overrides a LAG difference."""
    idle = FakeBackend("idle", lag=0, queue_depth=0)
    busy = FakeBackend("busy", lag=0, queue_depth=500)
    fd, _ = make_router([busy, idle])
    for _ in range(6):
        assert fd.submit({"kind": "x"})["routed_to"] == "idle"
    # lag-first stays the primary key: a fresher-but-busy replica still
    # beats a laggier idle one
    busy.lag, idle.lag = 0, 10
    fd.refresh_health()
    assert fd.submit({"kind": "x"})["routed_to"] == "busy"


def test_placement_breaker_penalty_sheds_degraded_replica():
    """A replica whose OWN serve breaker reports non-closed loses an
    equal-lag, equal-queue tie to a clean sibling."""
    clean = FakeBackend("clean", lag=0)
    degraded = FakeBackend("degraded", lag=0, breaker_worst=2)
    fd, _ = make_router([degraded, clean])
    for _ in range(6):
        assert fd.submit({"kind": "x"})["routed_to"] == "clean"
    # the load score rides the router's own health payload
    _, payload = fd.health_probe()()
    assert payload["backends"]["degraded"]["load_score"] > \
        payload["backends"]["clean"]["load_score"]


def test_dead_replica_trips_breaker_and_reroutes_with_zero_errors():
    r1, r2 = FakeBackend("r1"), FakeBackend("r2")
    fd, primary = make_router([r1, r2], breaker_threshold=2,
                              breaker_cooldown_s=60.0)
    r1.fail_with = TransientFault("dead")
    for _ in range(12):
        out = fd.submit({"kind": "x"})     # never raises
        assert out["routed_to"] in ("r2", "primary")
    # bounded probes: r1 ate exactly `threshold` failed submits, then
    # its OPEN gate re-routed everything without touching it
    assert r1.calls == 2
    assert fd.breaker.state_of("r1") == OPEN
    assert fd.metrics.counters.get("router.errors", 0) == 0
    assert fd.metrics.counters.get("router.rerouted", 0) == 2


def test_health_poll_readmits_rejoined_replica():
    r1, r2 = FakeBackend("r1"), FakeBackend("r2")
    fd, _ = make_router([r1, r2], breaker_threshold=1,
                        breaker_cooldown_s=60.0)
    # the death: health still answers while the first submit fails —
    # the breaker trips on that submit; the next poll sees it DOWN
    r1.fail_with = TransientFault("dying")
    for _ in range(4):                 # round-robin probes r1 within 2
        fd.submit({"kind": "x"})
        if r1.calls:
            break
    assert fd.breaker.state_of("r1") == OPEN
    r1.healthy = False
    fd.refresh_health()
    # rejoin: the unhealthy→healthy EDGE resets the gate immediately
    # (no cooldown wait — it was set to 60 s on purpose)
    r1.fail_with = None
    r1.healthy = True
    fd.refresh_health()
    assert fd.breaker.state_of("r1") == CLOSED
    assert fd.metrics.counters.get("router.readmissions", 0) == 1
    routed = {fd.submit({"kind": "x"})["routed_to"] for _ in range(4)}
    assert "r1" in routed


def test_http_deadline_exceeded_propagates_unstruck():
    """Over HTTP a 504 body must map back to typed DeadlineExceeded —
    read as TransientFault it would strike a healthy replica's breaker
    and retry a dead-on-arrival request across the whole tier."""
    from hypergraphdb_tpu.replica import HTTPBackend, SubmitServer
    from hypergraphdb_tpu.serve import DeadlineExceeded

    def expired(payload):
        raise DeadlineExceeded("budget spent in the queue")

    with SubmitServer(expired,
                      health=lambda: (True, {"replication_lag": 0})) as srv:
        be = HTTPBackend("r1", srv.url)
        with pytest.raises(DeadlineExceeded):
            be.submit({"kind": "bfs", "seed": 1}, timeout=10.0)
        fd = FrontDoor(FakeBackend("primary"), [be],
                       RouterConfig(poll_interval_s=0,
                                    health_refresh_s=0.0))
        with pytest.raises(DeadlineExceeded):
            fd.submit({"kind": "bfs", "seed": 1})
        # un-struck: the breaker stays CLOSED, nothing fell back
        assert fd.breaker.state_of("r1") == CLOSED
        assert fd.metrics.counters.get("router.rerouted", 0) == 0
        assert fd.metrics.counters.get("router.primary_fallbacks", 0) == 0
        assert fd.metrics.counters.get("router.errors", 0) == 1


def test_all_replicas_down_primary_answers():
    r1 = FakeBackend("r1", healthy=False)
    fd, primary = make_router([r1])
    out = fd.submit({"kind": "x"})
    assert out["routed_to"] == "primary"
    assert fd.metrics.counters.get("router.primary_fallbacks", 0) == 1


# --------------------------------------------------------- the chaos soak


class NodeBackend:
    """A LocalBackend whose node can be REPLACED (the rejoin path: a
    killed node's successor serves under the same backend id)."""

    def __init__(self, backend_id, get_node):
        self.id = backend_id
        self._get = get_node

    def submit(self, payload, timeout):
        return submit_payload(self._get().runtime, payload, timeout)

    def health(self):
        return self._get().health_probe()()


def bfs_truth_gids(g, seed_h, hops):
    reached = {int(a) for _, a in HGBreadthFirstTraversal(
        g, seed_h, max_distance=hops)}
    reached.add(int(seed_h))
    return {transfer.existing_gid(g, h) for h in reached}


def pattern_truth_gids(g, anchor_h):
    return {transfer.existing_gid(g, int(h))
            for h in g.find_all(c.Incident(int(anchor_h)))}


def wait_for(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_chaos_failover_soak(faults, tmp_path):
    SEED = 7
    rng = random.Random(SEED)
    net = LoopbackNetwork()

    # -- primary: a fixed main cluster (truths) + its own serve runtime
    gp = hg.HyperGraph()
    pp = HyperGraphPeer.loopback(gp, net, identity="primary")
    pp.replication.debounce_s = 0.005
    pp.replication.send_backoff_s = 0.001
    pp.replication.redelivery_interval_s = 0.01
    pp.replication.max_redeliveries = 2          # exhaustable budget
    # bound the dead-replica backlog: each queued message costs a probe
    # ladder to drop, and the soak's settle barriers must stay fast
    pp.replication.max_redelivery_backlog = 500
    pp.replication.journal_path = str(tmp_path / "primary.redelivery.jsonl")
    pp.start()
    nodes = [int(gp.add(f"m{i}")) for i in range(24)]
    for j in range(36):
        a, b = rng.sample(nodes, 2)
        gp.add_link((a, b), value=f"me{j}")

    # -- two serving replicas
    def new_replica(ident):
        gr = hg.HyperGraph()
        pr = HyperGraphPeer.loopback(gr, net, identity=ident)
        pr.replication.debounce_s = 0.005
        node = ReplicaNode(gr, pr, ReplicaConfig(
            primary="primary", anti_entropy_interval_s=0.1,
            serve=serve_cfg()))
        node.start()
        return node

    n1, n2 = new_replica("r1"), new_replica("r2")
    current = {"r1": n1, "r2": n2}
    assert pp.replication.flush()
    assert n1.wait_converged(timeout=30) and n2.wait_converged(timeout=30)
    assert wait_for(lambda: transfer.content_digest(gp)
                    == transfer.content_digest(n1.graph))
    assert wait_for(lambda: transfer.content_digest(gp)
                    == transfer.content_digest(n2.graph))

    # gid-addressed requests + truths (main cluster only, so the
    # concurrent ingest below can never invalidate them)
    gid_of = {h: transfer.gid_of(gp, h, "primary") for h in nodes}
    requests = []
    for _ in range(45):
        h = rng.choice(nodes)
        if rng.random() < 0.5:
            hops = rng.choice((1, 2))
            requests.append((
                {"kind": "bfs", "seed_gid": gid_of[h], "max_hops": hops,
                 "gids": True, "deadline_s": 10.0},
                lambda h=h, hops=hops: bfs_truth_gids(gp, h, hops),
            ))
        else:
            requests.append((
                {"kind": "pattern", "anchor_gids": [gid_of[h]],
                 "gids": True, "deadline_s": 10.0},
                lambda h=h: pattern_truth_gids(gp, h),
            ))

    prt = ServeRuntime(gp, serve_cfg())
    fd = FrontDoor(
        LocalBackend("primary", prt, runtime_health(prt), role="primary"),
        [NodeBackend("r1", lambda: current["r1"]),
         NodeBackend("r2", lambda: current["r2"])],
        # deterministic soak: NO background poll — the kill must be
        # discovered by failing submits (the breaker path), the rejoin
        # by an explicit health refresh (the re-admission edge)
        RouterConfig(breaker_threshold=2, breaker_cooldown_s=3600.0,
                     poll_interval_s=0, health_refresh_s=3600.0),
    ).start()

    # concurrent ingest into a DISCONNECTED fresh cluster (truths hold)
    stop_ingest = threading.Event()

    def ingest():
        prev = None
        while not stop_ingest.is_set():
            h = gp.add(f"fresh-{time.monotonic_ns()}")
            if prev is not None:
                gp.add_link([prev, h], value="fresh-e")
            prev = int(h)
            time.sleep(0.01)

    ing = threading.Thread(target=ingest, daemon=True)
    ing.start()

    answered = []
    try:
        def fire(req, truth_fn):
            out = fd.submit(dict(req))
            answered.append(out["routed_to"])
            if not out["truncated"]:
                got = {g for g in out["match_gids"] if g is not None}
                assert got == truth_fn(), f"wrong answer via " \
                    f"{out['routed_to']} for {req}"

        # phase 1: healthy tier — load spreads over the replicas
        for req, truth in requests[:15]:
            fire(req, truth)
        assert set(answered) <= {"r1", "r2"}
        assert len(set(answered)) == 2

        # phase 2: KILL r2 mid-load (no drain — a death, not a drain)
        n2.stop(drain=False)
        for req, truth in requests[15:30]:
            fire(req, truth)         # zero caller-visible errors
        assert fd.metrics.counters.get("router.errors", 0) == 0
        assert {a for a in answered[15:]} <= {"r1", "primary"}
        # the dead replica cost exactly `threshold` probes, then its
        # OPEN gate re-routed the rest without touching it
        assert fd.breaker.state_of("r2") == OPEN
        assert fd.metrics.counters.get("router.rerouted", 0) == 2
        fd.refresh_health()          # the poll observes the death

        # quiesce the open-loop ingest so the flush barriers below can
        # actually settle (an unbounded writer never lets flush() see
        # an empty pipeline)
        stop_ingest.set()
        ing.join(timeout=10)

        # the wire-drop schedule: eat ALL replication traffic to r1 so
        # pushes drop past the (size-2) redelivery budget, then heal —
        # contiguity tracking must detect the hole and repair it
        faults.enable(seed=SEED)
        faults.arm("peer.transport.send", prob=1.0,
                   when=lambda ctx: (ctx.get("target") == "r1" and
                                     ctx.get("activity") == "replication"))
        lost = gp.add("lost-under-drops")
        assert pp.replication.flush(timeout=30)
        faults.disarm("peer.transport.send")
        gp.add("after-drops")        # the later push that exposes the hole
        assert pp.replication.flush(timeout=30)
        assert wait_for(lambda: n1.graph.metrics.counters.get(
            "peer.gaps_detected", 0) >= 1)
        assert int(lost) > 0

        # phase 3: r2 REJOINS (same graph + identity, resume bootstrap)
        gr2 = n2.graph
        pr2b = HyperGraphPeer.loopback(gr2, net, identity="r2")
        pr2b.replication.debounce_s = 0.005
        n2b = ReplicaNode(gr2, pr2b, ReplicaConfig(
            primary="primary", anti_entropy_interval_s=0.1,
            serve=serve_cfg()))
        n2b.start()
        assert n2b.bootstrap_mode == "resume"
        current["r2"] = n2b
        assert n2b.wait_converged(timeout=30)  # lag back to 0 → the
        # placement's least-lagged group holds BOTH replicas again
        # the next health poll sees the unhealthy→healthy edge and
        # re-admits immediately (cooldown is 1 h on purpose: only the
        # edge reset can close the gate here)
        fd.refresh_health()
        assert fd.breaker.state_of("r2") == CLOSED
        assert fd.metrics.counters.get("router.readmissions", 0) >= 1
        for req, truth in requests[30:]:
            fire(req, truth)
        assert "r2" in set(answered[30:])   # load returned to the rejoiner

        # -- final convergence: settle, compare content
        assert pp.replication.flush(timeout=30)
        for node in (current["r1"], current["r2"]):
            assert wait_for(
                lambda n=node: transfer.content_digest(gp)
                == transfer.content_digest(n.graph), timeout=30), \
                "replica diverged from primary"

        # accounting: every request answered, none errored
        m = fd.metrics.counters
        assert m.get("router.submitted") == len(requests)
        assert (m.get("router.routed_replica", 0)
                + m.get("router.primary_fallbacks", 0)) == len(requests)
        assert m.get("router.errors", 0) == 0
        assert m.get("router.readmissions", 0) >= 1

        # journal == offline replay: the settled queue is empty and the
        # journal file replays to exactly that
        import json
        with open(pp.replication.journal_path, encoding="utf-8") as f:
            journal = [json.loads(line) for line in f if line.strip()]
        mem = [(pid, msg["content"]["seq"])
               for pid, dq in pp.replication._redelivery.items()
               for msg, _ in dq]
        assert [(r["pid"], r["message"]["content"]["seq"])
                for r in journal] == mem
    finally:
        stop_ingest.set()
        fd.stop()
        prt.close()
        for node in set(current.values()):
            node.stop()
        pp.stop()
        gp.close()


def test_router_health_probe_reflects_backend_state():
    """The router's own /healthz is the tier's truth: all backends dead
    must read unhealthy (a load balancer over several routers needs the
    dead-tier signal), any live replica or the primary reads healthy."""
    r1 = FakeBackend("r1", healthy=False)
    primary = FakeBackend("primary")
    fd = FrontDoor(primary, [r1],
                   RouterConfig(poll_interval_s=0, health_refresh_s=0.0))
    fd.refresh_health()
    probe = fd.health_probe()

    healthy, payload = probe()
    assert healthy and payload["primary_healthy"]  # primary carries it
    assert not payload["backends"]["r1"]["healthy"]

    primary.healthy = False
    healthy, payload = probe()
    assert not healthy and not payload["primary_healthy"]

    r1.healthy = True
    fd.refresh_health()
    healthy, payload = probe()
    assert healthy and payload["backends"]["r1"]["healthy"]


def test_refresh_health_probes_concurrently_and_deduplicates():
    """One sweep probes all replicas in parallel (wall time ~ the
    slowest single probe, not the sum) and concurrent sweeps collapse
    to one — a blackholed replica must not stack N x timeout onto the
    lazy-mode submit path."""
    import threading as _threading

    class SlowBackend(FakeBackend):
        probes = 0

        def health(self):
            SlowBackend.probes += 1
            time.sleep(0.3)
            return super().health()

    replicas = [SlowBackend(f"r{i}") for i in range(3)]
    fd = FrontDoor(FakeBackend("primary"), replicas,
                   RouterConfig(poll_interval_s=0, health_refresh_s=0.0))
    t0 = time.monotonic()
    fd.refresh_health()
    assert time.monotonic() - t0 < 0.75          # serial would be >= 0.9
    assert SlowBackend.probes == 3

    # dedup: a sweep already in flight makes the second call a no-op
    SlowBackend.probes = 0
    ts = [_threading.Thread(target=fd.refresh_health) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert SlowBackend.probes == 3               # one sweep, not two


def test_unknown_gid_permanent_on_primary_retryable_on_replica():
    """A gid miss is a replication race on a replica (re-route, no
    breaker strike) but a caller error on the primary (source of truth):
    the tier must answer 400-permanent, not 503-retry-forever."""
    from hypergraphdb_tpu.serve import AdmissionGated, Unservable

    gp = hg.HyperGraph()
    gp.add("anchor")
    prt = ServeRuntime(gp, serve_cfg())
    gr = hg.HyperGraph()
    rrt = ServeRuntime(gr, serve_cfg())
    try:
        replica = LocalBackend("r1", rrt)
        primary = LocalBackend("primary", prt, role="primary")
        with pytest.raises(AdmissionGated):
            replica.submit({"kind": "bfs", "seed_gid": "no-such"}, 5)
        with pytest.raises(Unservable):
            primary.submit({"kind": "bfs", "seed_gid": "no-such"}, 5)
        fd = FrontDoor(primary, [replica],
                       RouterConfig(poll_interval_s=0,
                                    health_refresh_s=0.0))
        with pytest.raises(Unservable):
            fd.submit({"kind": "bfs", "seed_gid": "no-such"})
        # the replica's miss re-routed without a breaker strike
        assert fd.breaker.state_of("r1") == CLOSED
        assert fd.metrics.counters.get("router.lag_rerouted", 0) == 1
    finally:
        prt.close()
        rrt.close()
        gp.close()
        gr.close()


def test_placement_peek_does_not_burn_half_open_probe():
    """Ranking candidates must not consume the one-probe-per-cooldown
    half-open token: a request answered before reaching the gated
    backend would otherwise starve that backend's actual recovery
    probe."""
    t = [0.0]
    r1 = FakeBackend("r1")
    fd, primary = make_router([r1], clock=lambda: t[0],
                              breaker_cooldown_s=1.0)
    r1.fail_with = TransientFault("down")
    fd.submit({"kind": "x"})                  # strike 1 (primary answers)
    fd.submit({"kind": "x"})                  # strike 2 → OPEN
    assert fd.breaker.state_of("r1") == OPEN
    t[0] += 10.0                               # past the cooldown
    for _ in range(5):
        assert fd._placement()                 # peeks only
    assert fd.breaker.state_of("r1") == OPEN   # no transition consumed
    r1.fail_with = None
    out = fd.submit({"kind": "x"})             # the real probe, intact
    assert out["routed_to"] == "r1"
