"""Fixture entry points for hgverify precision tests.

``build_bad_registry()`` seeds at least one finding in every HV rule
family on private :class:`hypergraphdb_tpu.verify.Registry` objects;
``build_clean_registry()`` holds the clean twins, which must verify
silent (HV4xx coverage is exercised separately through a temp costs
file). Private registries keep fixture entries out of the production
cost-budget gate.
"""

from __future__ import annotations

import numpy as np

from hypergraphdb_tpu.verify import Registry, sds

AX = "shard"


def _mesh(axis=AX):
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:8]), (axis,))


def build_bad_registry() -> Registry:
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    R = Registry()

    # -- HV100: exemplars that cannot trace -----------------------------------
    def _boom():
        raise ValueError("fixture exemplar explosion")

    @R.entry(name="fix.trace_fail", shapes=_boom)
    def trace_fail(x):
        return x

    # -- HV101/102/103: host callbacks inside the traced graph ----------------
    @R.entry(name="fix.pure_cb", shapes=lambda: (sds((8,), "float32"),))
    @jax.jit
    def pure_cb(x):
        y = jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct((8,), np.float32),
            x,
        )
        return y * 2

    @R.entry(name="fix.io_cb", shapes=lambda: (sds((8,), "float32"),))
    @jax.jit
    def io_cb(x):
        io_callback(lambda a: None, None, x)
        return x + 1

    @R.entry(name="fix.debug_cb", shapes=lambda: (sds((8,), "float32"),))
    @jax.jit
    def debug_cb(x):
        jax.debug.print("x sum {}", x.sum())
        return x + 1

    # -- HV201: collective axis vs the DECLARED deployment mesh ---------------
    @R.entry(name="fix.ghost_axis", shapes=lambda: (sds((8,), "float32"),),
             mesh=("rows",))
    def ghost_axis(x):
        return shard_map(
            lambda v: jax.lax.psum(v, AX),
            mesh=_mesh(AX), in_specs=(P(AX),), out_specs=P(),
            check_rep=False,
        )(x)

    # -- HV202: cond branches with mismatched collectives ---------------------
    @R.entry(name="fix.cond_mismatch",
             shapes=lambda: (sds((8,), "float32"),), mesh=(AX,))
    def cond_mismatch(x):
        def body(v):
            return jax.lax.cond(
                v[0] > 0,
                lambda u: jax.lax.psum(u, AX),
                lambda u: u * 2,
                v,
            )

        return shard_map(
            body, mesh=_mesh(AX), in_specs=(P(AX),), out_specs=P(AX),
            check_rep=False,
        )(x)

    # -- HV203: collectives with no declared mesh -----------------------------
    @R.entry(name="fix.undeclared_mesh",
             shapes=lambda: (sds((8,), "float32"),))
    def undeclared_mesh(x):
        return shard_map(
            lambda v: jax.lax.psum(v, AX),
            mesh=_mesh(AX), in_specs=(P(AX),), out_specs=P(),
            check_rep=False,
        )(x)

    # -- HV301: donation with no matching output ------------------------------
    _shrink = jax.jit(lambda x: x[:4] * 2, donate_argnums=(0,))

    @R.entry(name="fix.donate_unusable",
             shapes=lambda: (sds((8,), "float32"),), donate=True)
    def donate_unusable(x):
        return _shrink(x)   # (4,) output cannot reuse the (8,) buffer

    # -- HV302: donated buffer aliased into two outputs -----------------------
    _twice = jax.jit(lambda x: (x, x), donate_argnums=(0,))

    @R.entry(name="fix.donate_twice",
             shapes=lambda: (sds((8,), "float32"),), donate=True)
    def donate_twice(x):
        return _twice(x)

    # -- HV303: declared donation the traced jit does not perform -------------
    @R.entry(name="fix.donate_lost",
             shapes=lambda: (sds((8,), "float32"),), donate=True)
    @jax.jit
    def donate_lost(x):
        return x + 1

    # -- HV4xx probe: budget drift/coverage is driven by the test's costs file
    @R.entry(name="fix.cost_probe", shapes=lambda: (sds((64,), "float32"),))
    @jax.jit
    def cost_probe(x):
        return (x * 2 + 1).sum()

    return R


def build_clean_registry() -> Registry:
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    R = Registry()

    @R.entry(name="fix.pure_math", shapes=lambda: (sds((8,), "float32"),))
    @jax.jit
    def pure_math(x):
        return x * 2 + 1

    @R.entry(name="fix.matched_axis",
             shapes=lambda: (sds((8,), "float32"),), mesh=(AX,))
    def matched_axis(x):
        return shard_map(
            lambda v: jax.lax.psum(v, AX),
            mesh=_mesh(AX), in_specs=(P(AX),), out_specs=P(),
            check_rep=False,
        )(x)

    @R.entry(name="fix.cond_matched",
             shapes=lambda: (sds((8,), "float32"),), mesh=(AX,))
    def cond_matched(x):
        def body(v):
            return jax.lax.cond(
                v[0] > 0,
                lambda u: jax.lax.psum(u * 2, AX),
                lambda u: jax.lax.psum(u, AX),
                v,
            )

        return shard_map(
            body, mesh=_mesh(AX), in_specs=(P(AX),), out_specs=P(AX),
            check_rep=False,
        )(x)

    _honored = jax.jit(lambda x: x + 1, donate_argnums=(0,))

    @R.entry(name="fix.donate_honored",
             shapes=lambda: (sds((8,), "float32"),), donate=True)
    def donate_honored(x):
        return _honored(x)

    @R.entry(name="fix.cost_probe", shapes=lambda: (sds((64,), "float32"),))
    @jax.jit
    def cost_probe(x):
        return (x * 2 + 1).sum()

    return R
