"""Checkpoint/export/import, subgraph copy, and parameterized queries."""

import numpy as np
import pytest

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.ops.checkpoint import (
    copy_subgraph,
    export_graph,
    import_graph,
    load_snapshot,
    save_snapshot,
)
from hypergraphdb_tpu.query import dsl as q
from hypergraphdb_tpu.query.variables import prepare, substitute, var
from hypergraphdb_tpu.core.errors import QueryError

from conftest import make_random_hypergraph


# ---------------------------------------------------------------- snapshot ckpt


def test_snapshot_save_load_roundtrip(graph, tmp_path):
    make_random_hypergraph(graph, n_nodes=60, n_links=90, seed=5)
    snap = graph.snapshot()
    p = str(tmp_path / "snap.npz")
    save_snapshot(snap, p)
    back = load_snapshot(p)
    assert back.num_atoms == snap.num_atoms
    np.testing.assert_array_equal(back.inc_offsets, snap.inc_offsets)
    np.testing.assert_array_equal(back.inc_links, snap.inc_links)
    np.testing.assert_array_equal(back.value_rank, snap.value_rank)
    for k, v in snap.by_type.items():
        np.testing.assert_array_equal(back.by_type[k], v)
    # the reloaded snapshot serves kernels without a graph
    from hypergraphdb_tpu.ops.frontier import bfs_levels
    import jax.numpy as jnp

    seeds = jnp.asarray([0], dtype=jnp.int32)
    lv1, _ = bfs_levels(snap.device, seeds, 2)
    lv2, _ = bfs_levels(back.device, seeds, 2)
    np.testing.assert_array_equal(np.asarray(lv1), np.asarray(lv2))


# ---------------------------------------------------------------- logical dump


def test_export_import_roundtrip(graph, tmp_path):
    a = graph.add("alpha")
    b = graph.add(42)
    l = graph.add_link((a, b), value="edge")
    meta = graph.add_link((l,), value="meta")
    p = str(tmp_path / "dump.jsonl")
    n = export_graph(graph, p)
    assert n >= 4

    g2 = hg.HyperGraph()
    mapping = import_graph(g2, p)
    na, nb, nl = mapping[int(a)], mapping[int(b)], mapping[int(l)]
    assert g2.get(na) == "alpha"
    assert g2.get(nb) == 42
    assert g2.get(nl).targets == (na, nb)
    assert g2.get(mapping[int(meta)]).targets == (nl,)
    # queries work on the imported graph
    assert q.find_all(g2, q.value("edge")) == [nl]
    g2.close()


def test_copy_subgraph_closure(graph):
    a = graph.add("root")
    b = graph.add("reach")
    c = graph.add("unreached")
    lab = graph.add_link((a, b), value="ab")
    graph.add_link((c,), value="lonely")

    g2 = hg.HyperGraph()
    mapping = copy_subgraph(graph, g2, [int(a)])
    assert mapping[int(a)] is not None
    assert g2.get(mapping[int(b)]) == "reach"
    assert g2.get(mapping[int(lab)]).targets == (
        mapping[int(a)], mapping[int(b)]
    )
    assert int(c) not in mapping  # not reachable from a
    g2.close()


# ---------------------------------------------------------------- variables


def test_prepared_query_rebinds(graph):
    graph.add("hello")
    graph.add("world")
    pq = prepare(graph, q.and_(q.type_("string"), q.value(var("v"))))
    assert pq.variables == {"v"}
    r1 = pq.execute(v="hello")
    r2 = pq.execute(v="world")
    assert len(r1) == 1 and len(r2) == 1 and r1 != r2


def test_unbound_variable_raises(graph):
    pq = prepare(graph, q.value(var("x")))
    with pytest.raises(QueryError, match="unbound"):
        pq.execute()


def test_substitute_nested(graph):
    cond = q.or_(q.incident(var("t")), q.and_(q.value(var("v")), q.arity(2)))
    out = substitute(cond, {"t": 7, "v": "z"})
    assert out == q.or_(q.incident(7), q.and_(q.value("z"), q.arity(2)))


# ------------------------------------------- review regressions (round 4)


def test_var_in_link_targets(graph):
    a = graph.add("a")
    b = graph.add("b")
    l = graph.add_link((a, b))
    pq = prepare(graph, q.link(var("t"), int(b)))
    assert pq.execute(t=int(a)) == [int(l)]


def test_substitute_tree_with_link_and_var(graph):
    cond = q.and_(q.link(1, 2), q.value(var("v")))
    out = substitute(cond, {"v": "x"})
    assert out == q.and_(q.link(1, 2), q.value("x"))


def test_snapshot_path_without_extension(graph, tmp_path):
    graph.add("p")
    snap = graph.snapshot()
    p = str(tmp_path / "noext")
    save_snapshot(snap, p)
    back = load_snapshot(p)  # both sides normalize to .npz
    assert back.num_atoms == snap.num_atoms


def test_plans_persist_with_snapshot(tmp_path, graph):
    """save_snapshot(with_plans=True) writes a sidecar the loader attaches,
    and the restored plans drive bit-identical BFS results."""
    import numpy as np

    from tests.conftest import make_random_hypergraph
    from hypergraphdb_tpu.ops import checkpoint as cp
    from hypergraphdb_tpu.ops.ellbfs import bfs_pull, plans_for

    make_random_hypergraph(graph, n_nodes=150, n_links=300, seed=11)
    snap = graph.snapshot()
    path = str(tmp_path / "snap.npz")
    cp.save_snapshot(snap, path, with_plans=True)
    loaded = cp.load_snapshot(path)
    assert getattr(loaded, "_pull_plans", None) is not None  # no rebuild
    seeds = np.arange(24, dtype=np.int32)
    a = bfs_pull(snap, seeds, 3)
    b = bfs_pull(loaded, seeds, 3)
    assert np.array_equal(a.edges_touched, b.edges_touched)
    assert np.array_equal(np.asarray(a.visited_t), np.asarray(b.visited_t))
    # plan pyramids round-trip exactly
    p0, p1 = plans_for(snap), loaded._pull_plans
    assert p0.stage2_widths == p1.stage2_widths
    for x, y in zip(p0.stage1.levels, p1.stage1.levels):
        assert np.array_equal(x, y)
    assert np.array_equal(p0.out_map, p1.out_map)


# ------------------------------------------- crash-atomic saves (hgfault)


@pytest.fixture
def faults():
    from hypergraphdb_tpu.fault import global_faults

    f = global_faults()
    f.reset()
    yield f
    f.reset()
    f.disable()


def _two_snapshots(graph):
    make_random_hypergraph(graph, n_nodes=40, n_links=60, seed=3)
    snap_a = graph.snapshot()
    for i in range(25):
        graph.add(f"extra-{i}")
    snap_b = graph.snapshot()
    assert snap_b.num_atoms > snap_a.num_atoms
    return snap_a, snap_b


def test_crash_mid_npz_save_previous_checkpoint_survives(graph, tmp_path,
                                                         faults):
    from hypergraphdb_tpu.fault import InjectedCrash

    snap_a, snap_b = _two_snapshots(graph)
    p = str(tmp_path / "snap.npz")
    save_snapshot(snap_a, p)
    faults.enable(seed=0)
    faults.arm("ckpt.save_npz", at={1}, error=InjectedCrash)
    with pytest.raises(InjectedCrash):
        save_snapshot(snap_b, p)
    # the "kill" happened after the tmp write, before publish: the
    # previous checkpoint is fully loadable, never a torn file
    back = load_snapshot(p)
    assert back.num_atoms == snap_a.num_atoms
    np.testing.assert_array_equal(back.inc_offsets, snap_a.inc_offsets)
    # once the schedule clears, the next save publishes normally
    save_snapshot(snap_b, p)
    assert load_snapshot(p).num_atoms == snap_b.num_atoms


def test_crash_mid_plans_save_leaves_loadable_state(graph, tmp_path,
                                                    faults):
    from hypergraphdb_tpu.fault import InjectedCrash
    from hypergraphdb_tpu.ops.checkpoint import _plans_path

    snap_a, snap_b = _two_snapshots(graph)
    p = str(tmp_path / "snap.npz")
    save_snapshot(snap_a, p, with_plans=True)
    faults.enable(seed=0)
    faults.arm("ckpt.save_plans", at={1}, error=InjectedCrash)
    with pytest.raises(InjectedCrash):
        save_snapshot(snap_b, p, with_plans=True)
    # npz published (B), sidecar still A's: the fingerprint mismatch is
    # the DESIGNED stale shape — load succeeds, plans rebuild quietly
    back = load_snapshot(p)
    assert back.num_atoms == snap_b.num_atoms
    assert getattr(back, "_pull_plans", None) is None
    import os

    assert os.path.exists(_plans_path(p))  # old sidecar intact on disk
    save_snapshot(snap_b, p, with_plans=True)
    assert getattr(load_snapshot(p), "_pull_plans", None) is not None


def test_ordinary_save_failure_cleans_tmp(graph, tmp_path, faults):
    from hypergraphdb_tpu.fault import PermanentFault

    snap_a, snap_b = _two_snapshots(graph)
    p = str(tmp_path / "snap.npz")
    save_snapshot(snap_a, p)
    import os

    # a real (non-crash) failure between write and publish cleans up: the
    # Exception path unlinks the tmp, the BaseException crash path leaves
    # it (like a real kill would) — test the crash side leaves tmp behind
    from hypergraphdb_tpu.fault import InjectedCrash

    faults.enable(seed=0)
    faults.arm("ckpt.save_npz", at={1}, error=InjectedCrash)
    with pytest.raises(InjectedCrash):
        save_snapshot(snap_b, p)
    assert os.path.exists(p + ".tmp")
    faults.disarm("ckpt.save_npz")
    save_snapshot(snap_b, p)          # next save overwrites + publishes
    assert not os.path.exists(p + ".tmp")
    assert load_snapshot(p).num_atoms == snap_b.num_atoms
    with pytest.raises(PermanentFault):  # Exception path: tmp cleaned
        faults.arm("ckpt.save_npz", at={1}, error=PermanentFault)
        save_snapshot(snap_a, p)
    assert not os.path.exists(p + ".tmp")


def test_stale_sidecar_rebuilds_quietly_corrupt_sidecar_counts(
        graph, tmp_path, faults):
    """The load_snapshot triage: fingerprint mismatch (stale by design) is
    silent; an unreadable sidecar logs + bumps fault.sidecar_corrupt."""
    from hypergraphdb_tpu.ops.checkpoint import _plans_path
    from hypergraphdb_tpu.utils.metrics import global_metrics

    snap_a, snap_b = _two_snapshots(graph)
    pa_ = str(tmp_path / "a.npz")
    pb_ = str(tmp_path / "b.npz")
    save_snapshot(snap_a, pa_, with_plans=True)
    save_snapshot(snap_b, pb_, with_plans=True)

    c = global_metrics.registry.counter("fault.sidecar_corrupt")
    before = c.value

    # stale: b's npz with a's plans → quiet rebuild, counter untouched
    import shutil

    shutil.copyfile(_plans_path(pa_), _plans_path(pb_))
    back = load_snapshot(pb_)
    assert back.num_atoms == snap_b.num_atoms
    assert getattr(back, "_pull_plans", None) is None
    assert c.value == before

    # corrupt: garbage bytes → logged warning + counter, load still fine
    with open(_plans_path(pb_), "wb") as f:
        f.write(b"this is not an npz file at all")
    back = load_snapshot(pb_)
    assert back.num_atoms == snap_b.num_atoms
    assert getattr(back, "_pull_plans", None) is None
    assert c.value == before + 1


def test_plan_cache_env_roundtrip(tmp_path, graph, monkeypatch):
    import numpy as np

    from tests.conftest import make_random_hypergraph
    from hypergraphdb_tpu.ops import ellbfs as E

    make_random_hypergraph(graph, n_nodes=100, n_links=200, seed=5)
    snap = graph.snapshot()
    monkeypatch.setenv("HG_PLAN_CACHE", str(tmp_path / "plancache"))
    p0 = E.plans_for(snap)
    # a content-identical snapshot hits the disk cache, not the builder
    snap2 = graph.snapshot()
    calls = []
    monkeypatch.setattr(E, "build_pull_plans",
                        lambda *a, **k: calls.append(1))
    p1 = E.plans_for(snap2)
    assert not calls  # loaded, not rebuilt
    assert np.array_equal(p0.out_map, p1.out_map)
    for x, y in zip(p0.stage2_levels, p1.stage2_levels):
        assert np.array_equal(x, y)
