"""Traversal tests: BFS/DFS order, generators, classics, BFS query condition."""

import pytest

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.algorithms.traversals import (
    DefaultALGenerator,
    HGBreadthFirstTraversal,
    HGDepthFirstTraversal,
    HyperTraversal,
    SimpleALGenerator,
    dijkstra,
    has_cycles,
)
from hypergraphdb_tpu.query import dsl as hg


@pytest.fixture
def chain(graph):
    """a -> b -> c -> d via binary ordered links."""
    g = graph
    a, b, c, d = (g.add(x) for x in "abcd")
    ab = g.add_link((a, b))
    bc = g.add_link((b, c))
    cd = g.add_link((c, d))
    return g, (a, b, c, d), (ab, bc, cd)


def test_bfs_visits_all_reachable(chain):
    g, (a, b, c, d), links = chain
    visited = [atom for _, atom in HGBreadthFirstTraversal(g, a)]
    assert visited == [b, c, d]


def test_bfs_yields_parent_links(chain):
    g, (a, b, c, d), (ab, bc, cd) = chain
    pairs = list(HGBreadthFirstTraversal(g, a))
    assert pairs == [(ab, b), (bc, c), (cd, d)]


def test_bfs_max_distance(chain):
    g, (a, b, c, d), links = chain
    visited = [atom for _, atom in HGBreadthFirstTraversal(g, a, max_distance=2)]
    assert visited == [b, c]


def test_dfs_order(graph):
    g = graph
    root = g.add("root")
    k1, k2 = g.add("k1"), g.add("k2")
    k1a = g.add("k1a")
    g.add_link((root, k1))
    g.add_link((root, k2))
    g.add_link((k1, k1a))
    visited = [atom for _, atom in HGDepthFirstTraversal(g, root)]
    # depth-first: k1 branch fully explored before k2
    assert visited.index(k1a) < visited.index(k2) or visited.index(k2) < visited.index(k1)


def test_bfs_no_revisit_on_cycle(graph):
    g = graph
    a, b, c = (g.add(x) for x in "abc")
    g.add_link((a, b))
    g.add_link((b, c))
    g.add_link((c, a))
    visited = [atom for _, atom in HGBreadthFirstTraversal(g, a)]
    assert sorted(visited) == sorted([b, c])


def test_hyperedge_traversal(graph):
    """Arity-3 link: all siblings reachable in one hop."""
    g = graph
    a, b, c = (g.add(x) for x in "abc")
    g.add_link((a, b, c))
    visited = {atom for _, atom in HGBreadthFirstTraversal(g, a, max_distance=1)}
    assert visited == {b, c}


def test_default_generator_direction(chain):
    g, (a, b, c, d), links = chain
    # succeeding only: b sees c (b precedes c in (b,c)) but not a
    gen = DefaultALGenerator(g, return_preceeding=False)
    nbrs = {t for _, t in gen.generate(b)}
    assert nbrs == {c}
    gen = DefaultALGenerator(g, return_succeeding=False)
    nbrs = {t for _, t in gen.generate(b)}
    assert nbrs == {a}


def test_generator_link_predicate(graph):
    g = graph
    a, b, c = (g.add(x) for x in "abc")
    l1 = g.add_link((a, b), value="follow")
    l2 = g.add_link((a, c), value="skip")
    gen = DefaultALGenerator(g, link_predicate=lambda gr, l: gr.get(l).value == "follow")
    assert {t for _, t in gen.generate(a)} == {b}


def test_generator_sibling_predicate(graph):
    g = graph
    a = g.add("a")
    b, c = g.add(1), g.add("c")
    g.add_link((a, b))
    g.add_link((a, c))
    gen = DefaultALGenerator(
        g, sibling_predicate=lambda gr, t: isinstance(gr.get(t), int)
    )
    assert {t for _, t in gen.generate(a)} == {b}


def test_hyper_traversal_includes_links(chain):
    g, (a, b, c, d), (ab, bc, cd) = chain
    visited = {atom for _, atom in HyperTraversal(g, a)}
    assert {ab, b, bc, c, cd, d} <= visited


def test_dijkstra_path(chain):
    g, (a, b, c, d), links = chain
    assert dijkstra(g, a, d) == [a, b, c, d]
    e = g.add("e")  # disconnected
    assert dijkstra(g, a, e) is None


def test_dijkstra_weighted(graph):
    g = graph
    a, b, c = (g.add(x) for x in "abc")
    cheap1 = g.add_link((a, b), value=1)
    cheap2 = g.add_link((b, c), value=1)
    expensive = g.add_link((a, c), value=10)
    path = dijkstra(g, a, c, weight=lambda l: g.get(l).value)
    assert path == [a, b, c]


def test_has_cycles(graph):
    g = graph
    a, b, c = (g.add(x) for x in "abc")
    g.add_link((a, b))
    g.add_link((b, c))
    # undirected sibling adjacency always has back-edges via SimpleALGenerator;
    # use a directed generator (succeeding only) for a meaningful test
    gen = DefaultALGenerator(g, return_preceeding=False)
    assert not has_cycles(g, a, gen)
    g.add_link((c, a))
    gen = DefaultALGenerator(g, return_preceeding=False)
    assert has_cycles(g, a, gen)


def test_bfs_query_condition(chain):
    g, (a, b, c, d), links = chain
    res = set(g.find_all(hg.bfs(a)))
    # BFS over sibling adjacency reaches atoms AND the traversal yields only
    # atoms (links excluded since SimpleALGenerator yields targets)
    assert {b, c, d} <= res
    res2 = set(g.find_all(hg.bfs(a, max_distance=1)))
    assert b in res2 and d not in res2


def test_bfs_condition_intersects(chain):
    g, (a, b, c, d), links = chain
    res = g.find_all(hg.and_(hg.bfs(a), hg.eq("c")))
    assert res == [c]
