"""Storage SPI conformance suite.

The backend-agnostic contract suite, modeled on the reference's
``storage/teststorage/`` module (``StoreImplementationTestBase.java:16-24``,
``TopLevelStorageTests``, ``IndexInterfaceTests``, ``SortIndexTests``,
``BiIndexTests`` — SURVEY §4): any backend (memory, native C++) must pass
every test here. Parametrized over available backends.
"""

import numpy as np
import pytest

from hypergraphdb_tpu.storage.api import StorageBackend
from hypergraphdb_tpu.storage.memstore import MemStorage


def _backends():
    yield "memory"
    yield "partitioned"
    yield "partitioned-native"
    try:
        from hypergraphdb_tpu.storage.native import NativeStorage  # noqa: F401

        yield "native"
    except Exception:
        pass


@pytest.fixture(params=list(_backends()))
def store(request, tmp_path):
    if request.param == "memory":
        b = MemStorage()
    elif request.param == "partitioned":
        from hypergraphdb_tpu.storage.partitioned import PartitionedStorage

        b = PartitionedStorage(n_partitions=3)
    elif request.param == "partitioned-native":
        pytest.importorskip("hypergraphdb_tpu.storage.native")
        from hypergraphdb_tpu.storage.native import NativeStorage
        from hypergraphdb_tpu.storage.partitioned import PartitionedStorage

        b = PartitionedStorage(
            n_partitions=3,
            factory=lambda i: NativeStorage(str(tmp_path / f"part{i}")),
        )
    else:
        from hypergraphdb_tpu.storage.native import NativeStorage

        b = NativeStorage(str(tmp_path / "db"))
    b.startup()
    yield b
    b.shutdown()


# ---------------------------------------------------------------- links


def test_link_roundtrip(store: StorageBackend):
    store.store_link(1, (10, 20, 30))
    assert store.get_link(1) == (10, 20, 30)
    assert store.contains_link(1)
    assert store.get_link(2) is None
    assert not store.contains_link(2)


def test_link_empty_targets(store: StorageBackend):
    store.store_link(5, ())
    assert store.get_link(5) == ()
    assert store.contains_link(5)


def test_link_overwrite_and_remove(store: StorageBackend):
    store.store_link(1, (1, 2))
    store.store_link(1, (3,))
    assert store.get_link(1) == (3,)
    store.remove_link(1)
    assert store.get_link(1) is None
    store.remove_link(1)  # idempotent


# ---------------------------------------------------------------- data


def test_data_roundtrip(store: StorageBackend):
    store.store_data(7, b"hello")
    assert store.get_data(7) == b"hello"
    store.store_data(7, b"")
    assert store.get_data(7) == b""
    store.remove_data(7)
    assert store.get_data(7) is None


def test_data_large(store: StorageBackend):
    blob = bytes(range(256)) * 1000
    store.store_data(8, blob)
    assert store.get_data(8) == blob


# ---------------------------------------------------------------- incidence


def test_incidence_sorted_and_deduped(store: StorageBackend):
    for link in (5, 3, 9, 3, 7):
        store.add_incidence_link(100, link)
    rs = store.get_incidence_set(100)
    assert rs.array().tolist() == [3, 5, 7, 9]
    assert store.incidence_count(100) == 4
    assert 5 in rs
    assert 4 not in rs


def test_incidence_remove(store: StorageBackend):
    for link in (1, 2, 3):
        store.add_incidence_link(100, link)
    store.remove_incidence_link(100, 2)
    assert store.get_incidence_set(100).array().tolist() == [1, 3]
    store.remove_incidence_set(100)
    assert len(store.get_incidence_set(100)) == 0


def test_incidence_goto(store: StorageBackend):
    for link in (10, 20, 30):
        store.add_incidence_link(1, link)
    rs = store.get_incidence_set(1)
    assert rs.go_to(20) == 1
    assert rs.go_to(15) == -1
    assert rs.go_to(15, exact=False) == 1
    assert rs.go_to(31, exact=False) == -1


# ---------------------------------------------------------------- indices


def test_index_basic(store: StorageBackend):
    idx = store.get_index("test")
    idx.add_entry(b"a", 1)
    idx.add_entry(b"a", 2)
    idx.add_entry(b"b", 3)
    assert idx.find(b"a").array().tolist() == [1, 2]
    assert idx.find_first(b"a") == 1
    assert idx.count(b"a") == 2
    assert idx.key_count() == 2
    assert list(idx.scan_keys()) == [b"a", b"b"]
    assert sorted(idx.scan_values()) == [1, 2, 3]


def test_index_remove(store: StorageBackend):
    idx = store.get_index("test")
    idx.add_entry(b"k", 1)
    idx.add_entry(b"k", 2)
    idx.remove_entry(b"k", 1)
    assert idx.find(b"k").array().tolist() == [2]
    idx.remove_all_entries(b"k")
    assert len(idx.find(b"k")) == 0
    assert idx.key_count() == 0


def test_index_range(store: StorageBackend):
    idx = store.get_index("rng")
    for i, k in enumerate([b"a", b"c", b"e", b"g"]):
        idx.add_entry(k, i)
    assert idx.find_lt(b"e").array().tolist() == [0, 1]
    assert idx.find_lte(b"e").array().tolist() == [0, 1, 2]
    assert idx.find_gt(b"c").array().tolist() == [2, 3]
    assert idx.find_gte(b"c").array().tolist() == [1, 2, 3]
    assert idx.find_range(lo=b"c", hi=b"g").array().tolist() == [1, 2]


def test_index_bidirectional(store: StorageBackend):
    idx = store.get_index("bi")
    idx.add_entry(b"x", 1)
    idx.add_entry(b"y", 1)
    idx.add_entry(b"x", 2)
    assert idx.find_by_value(1) == [b"x", b"y"]
    assert idx.count_keys(1) == 2
    idx.remove_entry(b"x", 1)
    assert idx.find_by_value(1) == [b"y"]


def test_index_namespace(store: StorageBackend):
    a = store.get_index("a")
    b = store.get_index("b")
    a.add_entry(b"k", 1)
    assert len(b.find(b"k")) == 0
    assert set(store.index_names()) >= {"a", "b"}
    store.remove_index("a")
    assert "a" not in store.index_names()


def test_index_empty(store: StorageBackend):
    """EmtpyIndexTest [sic] analogue."""
    idx = store.get_index("empty")
    assert len(idx.find(b"nope")) == 0
    assert idx.find_first(b"nope") is None
    assert idx.count(b"nope") == 0
    assert idx.key_count() == 0
    assert list(idx.scan_keys()) == []


# ---------------------------------------------------------------- bulk


def test_bulk_links(store: StorageBackend):
    store.store_link(0, (1, 2))
    store.store_link(2, (3,))
    store.store_link(1, ())
    ids, offsets, flat = store.bulk_links()
    assert ids.tolist() == [0, 1, 2]
    assert offsets.tolist() == [0, 2, 2, 3]
    assert flat.tolist() == [1, 2, 3]


def test_max_handle(store: StorageBackend):
    assert store.max_handle() == 0
    store.store_link(41, ())
    store.store_data(7, b"x")
    assert store.max_handle() == 42


def test_index_count_range(store: StorageBackend):
    """count_range: exact entry counts over key windows, cap clamping —
    the planner's cardinality source (HGIndexStats.java:37 analogue)."""
    idx = store.get_index("cr")
    for i in range(20):
        key = bytes([i])
        for v in range(i % 3 + 1):  # 1..3 entries per key
            idx.add_entry(key, 100 * i + v)
    total = sum(i % 3 + 1 for i in range(20))
    assert idx.count_range() == total
    assert idx.count_range(lo=bytes([5]), hi=bytes([10])) == sum(
        i % 3 + 1 for i in range(5, 10)
    )
    assert idx.count_range(
        lo=bytes([5]), hi=bytes([10]), lo_inclusive=False, hi_inclusive=True
    ) == sum(i % 3 + 1 for i in range(6, 11))
    assert idx.count_range(cap=4) == 4
    assert idx.count_range(lo=bytes([19]), hi=None) == 19 % 3 + 1
    assert idx.count_range(lo=bytes([50])) == 0
