"""Multithreaded stress over the MVCC/storage stack (SURVEY §5 flags the
reference's thin concurrency coverage; this is the rebuild's heavier
counterpart). Invariants checked under contention:

- optimistic commits never produce torn structures (link targets and
  incidence sets stay mutually consistent),
- snapshot readers see internally consistent states mid-churn,
- the retry loop converges (no deadlock, bounded conflicts)."""

import threading

import numpy as np
import pytest

from hypergraphdb_tpu import HyperGraph


@pytest.fixture()
def g():
    graph = HyperGraph()
    yield graph
    graph.close()


def test_many_writers_counters_converge(g):
    """N threads each transfer 'value tokens' between two cells via
    read-modify-write transactions; the total must be conserved."""
    a = g.add(1000)
    b = g.add(1000)
    errors = []

    def mover(n):
        try:
            for _ in range(40):
                def step():
                    va = g.get(a)
                    vb = g.get(b)
                    g.replace(a, va - 1)
                    g.replace(b, vb + 1)
                g.txman.transact(step, retries=64)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ts = [threading.Thread(target=mover, args=(i,)) for i in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in ts), "writers deadlocked"
    assert not errors, errors
    assert g.get(a) + g.get(b) == 2000
    assert g.get(a) == 1000 - 6 * 40


def test_readers_see_consistent_link_structure(g):
    """Writers churn links while snapshot readers verify that every link
    they can see has its incidence entries — no torn commits."""
    nodes = [g.add(f"n{i}") for i in range(12)]
    stop = threading.Event()
    errors = []

    def writer():
        rng = np.random.default_rng(threading.get_ident() % 2**31)
        try:
            while not stop.is_set():
                i, j = rng.choice(12, size=2, replace=False)
                l = g.add_link((nodes[i], nodes[j]), value=int(rng.integers(1e6)))
                if rng.random() < 0.5:
                    g.remove(l)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def reader():
        try:
            for _ in range(200):
                def check():
                    # within one tx: every incident link of node 0 must
                    # still resolve and point back at node 0
                    inc = g.get_incidence_set(nodes[0]).array()
                    for l in inc.tolist():
                        atom = g.get(int(l))
                        assert int(nodes[0]) in [int(t) for t in atom.targets], (
                            "incidence entry without a matching target"
                        )
                g.txman.transact(check, readonly=True)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    ws = [threading.Thread(target=writer) for _ in range(3)]
    rs = [threading.Thread(target=reader) for _ in range(3)]
    for t in ws + rs:
        t.start()
    for t in rs:
        t.join(timeout=120)
    stop.set()
    for t in ws:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in ws + rs), "stress threads hung"
    assert not errors, errors


def test_history_bounded_under_churn(g):
    """MVCC pre-image chains must not leak while txs open/close rapidly."""
    a = g.add("cell")
    done = threading.Event()
    errors = []

    def churn():
        try:
            for i in range(300):
                g.replace(a, i)
        except Exception as e:  # pragma: no cover
            errors.append(e)
        finally:
            done.set()

    def read_loop():
        while not done.is_set():
            g.txman.transact(lambda: g.get(a), readonly=True)

    w = threading.Thread(target=churn)
    r = threading.Thread(target=read_loop)
    w.start()
    r.start()
    w.join(timeout=120)
    r.join(timeout=120)
    assert not errors, errors
    # one final commit GCs everything below the (now empty) active floor
    g.add("tick")
    assert len(g.txman._history) <= 2
